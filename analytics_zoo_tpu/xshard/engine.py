"""XShard ETL engine: hash-partitioned relational ops over shared-memory
blocks with a persistent forked worker pool.

The reference's analytics half runs XShards on Ray/Spark executors; our
seed-era :class:`~analytics_zoo_tpu.xshard.shard.DataShards` instead
pickles whole pandas shards through a throwaway ``ProcessPoolExecutor``
and funnels ``repartition``/``collect``/``to_featureset`` through a
full-dataset ``pd.concat`` in the driver. This module is the real tier:

- **blocks**: a partition is a column-major block (one aligned region per
  column, same layout math as the transform slabs) living either in a
  pooled ``multiprocessing.shared_memory`` slab or — when it outgrows the
  ``xshard.slab_mb`` budget — in a per-partition ``.mmap`` spill file,
  the same memmap tier FeatureSet's DISK mode uses;
- **workers**: a persistent forked fleet (:class:`EtlPool`, built on the
  transform pool's :class:`~analytics_zoo_tpu.feature.worker_pool.
  WorkerPoolBase` claim/done ledger, death sweep + respawn, task
  retries). Tasks ship as cloudpickle blobs; results are tiny
  :class:`BlockRef` descriptors — data NEVER transits the pipe, it moves
  by slab name;
- **shuffle**: ``groupby(...).agg`` and ``join`` run as two-stage
  hash-partitioned exchanges — stage A buckets each source partition by
  key hash (stable reorder + per-destination offset table, written
  straight into an exchange slab), stage B attaches every source's slab,
  slices its destination ranges and combines locally — pandas' own
  groupby kernel for aggregations (same values in the same order as the
  single-process reference, so even Kahan-compensated float sums are
  bit-identical) and a factorized-key ``searchsorted`` kernel for joins;
- **zero-copy handoff**: :meth:`XShard.to_featureset` lays out ONE
  exact-size feature/label segment, workers write their partition rows
  at row offsets, and the FeatureSet wraps the views directly — no
  intermediate DataFrame and no full-dataset concat ever exists in the
  driver.

Workers never touch jax (numpy/pandas only) and attach slabs UNTRACKED:
a child re-attaching by name must not register the segment with its own
``resource_tracker``, or the tracker would unlink the parent's live slab
at child exit (bpo-39959). All segments are created in the parent.
"""
from __future__ import annotations

import atexit
import itertools
import os
import pickle
import shutil
import signal
import tempfile
import time
import traceback
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import faults
from ..common import metrics as _metrics
from ..common.config import global_config
from ..common.pickling import pickler as _pickler
from ..common.utils import time_it
from ..feature.worker_pool import (_ALIGN, SlabKeepAlive, WorkerPoolBase,
                                   default_workers)

_M_TASK = _metrics.histogram(
    "xshard.task_seconds",
    "XShard ETL task latency (observed in the forked worker).")
_M_RESPAWN = _metrics.counter(
    "xshard.respawn_total",
    "XShard ETL workers respawned after dying mid-task (SIGKILL/OOM).")
_M_EXCHANGE = _metrics.counter(
    "xshard.exchange_bytes_total",
    "Bytes written to shuffle-exchange blocks (stage-A bucket reorders).")
_M_SPILL = _metrics.counter(
    "xshard.spill_bytes_total",
    "Block bytes that exceeded the xshard.slab_mb budget and spilled to "
    "per-partition memmap files.")
_M_HANDOFF = _metrics.counter(
    "xshard.handoff_bytes_total",
    "Bytes workers wrote directly into FeatureSet handoff segments "
    "(the zero-copy to_featureset path).")


class XShardWorkerError(RuntimeError):
    """An ETL task raised inside a worker process; carries the worker-side
    traceback so the failure reads as if it happened in the driver."""


# -- block descriptors and layout -------------------------------------------


class BlockRef:
    """Tiny picklable descriptor of one materialized partition block.

    ``kind`` is ``"shm"`` (name = slab segment), ``"mmap"`` (name = spill
    file path) or ``"empty"``; ``schema`` is a tuple of ``(column,
    dtype_str, shape_tail)``; ``meta`` carries small per-block extras
    (the exchange offset table). The data itself never rides the pipe.
    """

    __slots__ = ("kind", "name", "schema", "rows", "nbytes", "meta")

    def __init__(self, kind: str, name: str, schema, rows: int,
                 nbytes: int, meta=None):
        self.kind, self.name, self.schema = kind, name, schema
        self.rows, self.nbytes, self.meta = int(rows), int(nbytes), meta

    def __getstate__(self):
        return (self.kind, self.name, self.schema, self.rows, self.nbytes,
                self.meta)

    def __setstate__(self, state):
        (self.kind, self.name, self.schema, self.rows, self.nbytes,
         self.meta) = state


def _block_layout(schema, rows: int):
    """Column-major block layout: per column one contiguous ``rows ×
    cell`` region, starts aligned to ``_ALIGN``; final yield is the total
    size sentinel."""
    offset = 0
    for col, dtstr, tail in schema:
        offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        dt = np.dtype(dtstr)
        cell = dt.itemsize * int(np.prod(tail, dtype=np.int64))
        yield offset, col, dt, tuple(tail)
        offset += cell * rows
    yield offset, None, None, None


def _block_nbytes(schema, rows: int) -> int:
    return max(1, list(_block_layout(schema, rows))[-1][0])


def _block_views(buf, schema, rows: int) -> Dict[str, np.ndarray]:
    return {col: np.ndarray((rows,) + tail, dtype=dt, buffer=buf,
                            offset=off)
            for off, col, dt, tail in _block_layout(schema, rows)
            if col is not None}


def _schema_of(cols: Dict[str, np.ndarray]):
    schema = []
    for c, a in cols.items():
        if a.dtype.hasobject:
            raise ValueError(
                f"column {c!r} has object dtype; XShard blocks hold "
                f"fixed-width (numeric/bool/datetime) columns only")
        schema.append((c, a.dtype.str, tuple(a.shape[1:])))
    return tuple(schema)


# -- shared-memory attach (worker side, untracked) ---------------------------

_ATTACHED: Dict[str, shared_memory.SharedMemory] = {}


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach an existing segment WITHOUT resource-tracker registration:
    a tracked attach in a forked child unlinks the parent's live slab
    when the child exits (bpo-39959)."""
    try:
        return shared_memory.SharedMemory(name=name, create=False,
                                          track=False)  # 3.13+
    except TypeError:
        from multiprocessing import resource_tracker
        orig = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None
        try:
            return shared_memory.SharedMemory(name=name, create=False)
        finally:
            resource_tracker.register = orig


def _attach(name: str) -> shared_memory.SharedMemory:
    shm = _ATTACHED.get(name)
    if shm is None:
        shm = _attach_untracked(name)
        _ATTACHED[name] = shm
    return shm


def _detach_all() -> None:
    for shm in _ATTACHED.values():
        try:
            shm.close()
        except Exception:
            pass
    _ATTACHED.clear()


# -- block load/store --------------------------------------------------------


def _load_block(ref: BlockRef) -> Tuple[Dict[str, np.ndarray], Any]:
    """Map a block back into column views; the returned keepalive object
    must outlive the views (shm mapping or memmap buffer)."""
    if ref.kind == "empty" or ref.rows == 0:
        return ({col: np.empty((0,) + tail, dtype=dt)
                 for _, col, dt, tail in _block_layout(ref.schema, 0)
                 if col is not None}, None)
    if ref.kind == "shm":
        shm = _attach(ref.name)
        return _block_views(shm.buf, ref.schema, ref.rows), shm
    mm = np.memmap(ref.name, dtype=np.uint8, mode="r")
    return _block_views(mm, ref.schema, ref.rows), mm


def _alloc_block(schema, rows: int, slab: Optional[Tuple[str, int]],
                 spill_dir: str, tag: str
                 ) -> Tuple[Optional[Dict[str, np.ndarray]], BlockRef]:
    """Views + ref for a block about to be written: the assigned pooled
    slab when it fits the budget, a per-partition memmap spill file when
    it does not (the disk tier — same convention as FeatureSet's
    ``_spill_to_disk``)."""
    nbytes = _block_nbytes(schema, rows)
    if rows == 0:
        return None, BlockRef("empty", "", schema, 0, 0)
    if slab is not None and nbytes <= slab[1]:
        return (_block_views(_attach(slab[0]).buf, schema, rows),
                BlockRef("shm", slab[0], schema, rows, nbytes))
    path = os.path.join(spill_dir, tag + ".mmap")
    mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=(nbytes,))
    _M_SPILL.inc(nbytes)
    return (_block_views(mm, schema, rows),
            BlockRef("mmap", path, schema, rows, nbytes))


def _store_block(cols: Dict[str, np.ndarray], slab, spill_dir: str,
                 tag: str) -> BlockRef:
    schema = _schema_of(cols)
    rows = len(next(iter(cols.values()))) if cols else 0
    views, ref = _alloc_block(schema, rows, slab, spill_dir, tag)
    if views is not None:
        for c, a in cols.items():  # per-COLUMN loop; each copy vectorized
            views[c][...] = a
    return ref


def _take_cols_into(views: Dict[str, np.ndarray],
                    cols: Dict[str, np.ndarray], order: np.ndarray) -> None:
    for c, a in cols.items():  # per-COLUMN loop; gather itself vectorized
        np.take(a, order, axis=0, out=views[c])


def _cols_of(out) -> Dict[str, np.ndarray]:
    """Normalize a task function's result (DataFrame or dict of arrays)
    into contiguous column arrays."""
    if isinstance(out, dict):
        return {c: np.ascontiguousarray(v) for c, v in out.items()}
    return {c: np.ascontiguousarray(out[c].to_numpy())
            for c in out.columns}


def _frame_of(cols: Dict[str, np.ndarray]):
    import pandas as pd
    return pd.DataFrame(cols, copy=False)


# -- vectorized kernels (policed by the hot-path lint: loop-free, no
#    full-frame concats, no per-row Python) ---------------------------------

_MIX_MULT = np.uint64(0x9E3779B97F4A7C15)
_MIX_SEED = np.uint64(0x243F6A8885A308D3)


def _mix64(h: np.ndarray, a: np.ndarray) -> np.ndarray:
    """One splitmix64-style round folding key column ``a`` into ``h``."""
    a = np.ascontiguousarray(a)
    if a.dtype.itemsize != 8:
        a = a.astype(np.int64)
    with np.errstate(over="ignore"):
        v = a.view(np.uint64)
        h = h ^ (v * _MIX_MULT)
        h = (h ^ (h >> np.uint64(31))) * np.uint64(0xBF58476D1CE4E5B9)
        return h ^ (h >> np.uint64(27))


def _bucket_order(dest: np.ndarray, nparts: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Stable reorder by destination: ``order`` groups rows by dest
    (original order preserved within a dest), ``offsets[j]:offsets[j+1]``
    bounds dest ``j``'s rows in the reordered block."""
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=nparts)
    offsets = np.zeros(nparts + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return order, offsets


def _join_match(lcode: np.ndarray, rcode: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Inner-join row match on factorized keys: left order preserved,
    each left row's matches in right original order (pandas ``merge``
    row-order contract), duplicates expanded by arithmetic — no per-row
    Python."""
    order = np.argsort(rcode, kind="stable")
    rs = rcode[order]
    lo = np.searchsorted(rs, lcode, side="left")
    hi = np.searchsorted(rs, lcode, side="right")
    counts = hi - lo
    li = np.repeat(np.arange(lcode.shape[0]), counts)
    ends = np.cumsum(counts)
    within = np.arange(int(ends[-1]) if ends.shape[0] else 0) \
        - np.repeat(ends - counts, counts)
    ri = order[np.repeat(lo, counts) + within]
    return li, ri


def _stack_into(out: np.ndarray, row0: int, k: int,
                col: np.ndarray) -> None:
    """Scatter one feature column into the handoff matrix at its row
    offset (assignment casts to the matrix dtype, float32)."""
    out[row0:row0 + col.shape[0], k] = col


# -- key factorization (per-column loops live here, outside the policed
#    kernels — column count is schema-sized, never row-sized) ----------------


def _hash_keys(cols: Dict[str, np.ndarray], keys: Sequence[str],
               nparts: int) -> np.ndarray:
    n = len(cols[keys[0]])
    h = np.full(n, _MIX_SEED, dtype=np.uint64)
    for k in keys:
        h = _mix64(h, cols[k])
    return (h % np.uint64(nparts)).astype(np.int64)


def _factorize_two(lcols, rcols, keys
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Factorize join keys over the UNION of both sides so codes agree."""
    nl = len(lcols[keys[0]])
    codes, sizes = [], []
    for k in keys:
        both = np.concatenate([lcols[k], rcols[k]])
        _, inv = np.unique(both, return_inverse=True)
        codes.append(inv.astype(np.int64))
        sizes.append(int(inv.max()) + 1 if len(inv) else 1)
    combined = codes[0]
    for c, s in zip(codes[1:], sizes[1:]):
        combined = combined * s + c
    return combined[:nl], combined[nl:]


# -- worker task bodies ------------------------------------------------------


def _map_task(ref, blob, slab, spill_dir, tag):
    cols, keep = _load_block(ref)
    fn = pickle.loads(blob)
    out_cols = _cols_of(fn(_frame_of(cols)))
    del cols, keep
    return _store_block(out_cols, slab, spill_dir, tag)


def _filter_task(ref, blob, slab, spill_dir, tag):
    cols, keep = _load_block(ref)
    pred = pickle.loads(blob)
    idx = np.flatnonzero(np.ascontiguousarray(pred(_frame_of(cols))))
    views, out = _alloc_block(_schema_of(cols), len(idx), slab, spill_dir,
                              tag)
    if views is not None:
        _take_cols_into(views, cols, idx)
    del cols, keep
    return out


def _read_file_task(path, fmt, kwargs, slab, spill_dir, tag):
    import pandas as pd
    reader = {"csv": pd.read_csv, "json": pd.read_json,
              "parquet": pd.read_parquet}[fmt]
    return _store_block(_cols_of(reader(path, **kwargs)), slab, spill_dir,
                        tag)


def _exchange_task(ref, keys, nparts, slab, spill_dir, tag):
    """Stage A of a shuffle: bucket one source partition by key hash —
    stable reorder straight into the exchange block plus the
    per-destination offset table (carried in the ref's meta)."""
    cols, keep = _load_block(ref)
    if ref.rows == 0:
        out = BlockRef("empty", "", ref.schema, 0, 0)
        out.meta = {"offsets": np.zeros(nparts + 1, dtype=np.int64)}
        return out
    dest = _hash_keys(cols, keys, nparts)
    order, offsets = _bucket_order(dest, nparts)
    views, out = _alloc_block(tuple(ref.schema), ref.rows, slab, spill_dir,
                              tag)
    _take_cols_into(views, cols, order)
    out.meta = {"offsets": offsets}
    _M_EXCHANGE.inc(out.nbytes)
    del cols, keep
    return out


def _gather_dest(refs: Sequence[BlockRef], j: int
                 ) -> Dict[str, np.ndarray]:
    """Stage-B input: destination ``j``'s row ranges from every source's
    exchange block, concatenated per column (the only concat in the
    engine — per-destination slices, never the full dataset)."""
    parts = []
    keeps = []
    for ref in refs:
        if ref.rows == 0:
            continue
        cols, keep = _load_block(ref)
        off = ref.meta["offsets"]
        lo, hi = int(off[j]), int(off[j + 1])
        if hi > lo:
            parts.append({c: a[lo:hi] for c, a in cols.items()})
            keeps.append(keep)
    if not parts:
        return {col: np.empty((0,) + tail, dtype=dt)
                for _, col, dt, tail in _block_layout(refs[0].schema, 0)
                if col is not None}
    if len(parts) == 1:
        merged = {c: np.ascontiguousarray(a) for c, a in parts[0].items()}
    else:
        merged = {c: np.concatenate([p[c] for p in parts])
                  for c in parts[0]}
    del keeps
    return merged


def _groupby_task(refs, j, keys, spec, slab, spill_dir, tag):
    """Stage B of groupby-agg: local combine of destination ``j`` through
    pandas' OWN groupby kernel. Bit-parity with the single-process
    reference holds by construction: the hash shuffle puts every row of a
    group in one destination, the stable bucket reorder + source-order
    gather preserve each group's original row order, so pandas'
    (Kahan-compensated) accumulation sees the same values in the same
    order as it would on the whole frame."""
    cols = _gather_dest(refs, j)
    df = _frame_of(cols)
    out = df.groupby(list(keys), as_index=False, sort=True).agg(dict(spec))
    return _store_block(_cols_of(out), slab, spill_dir, tag)


def _join_task(lrefs, rrefs, j, keys, slab, spill_dir, tag):
    """Stage B of inner join: match destination ``j``'s left and right
    slices on factorized keys."""
    lcols = _gather_dest(lrefs, j)
    rcols = _gather_dest(rrefs, j)
    lcode, rcode = _factorize_two(lcols, rcols, keys)
    li, ri = _join_match(lcode, rcode)
    out_cols = {c: a[li] for c, a in lcols.items()}
    for c, a in rcols.items():
        if c not in keys:
            out_cols[c] = a[ri]
    return _store_block(out_cols, slab, spill_dir, tag)


def _handoff_task(ref, feature_cols, label_cols, out_name, row0, hschema,
                  total):
    """Write one partition's rows straight into the shared FeatureSet
    handoff segment at its row offset — the zero-copy lowering."""
    cols, keep = _load_block(ref)
    views = _block_views(_attach(out_name).buf, hschema, total)
    feats = views["__features__"]
    for k, c in enumerate(feature_cols):
        _stack_into(feats, row0, k, cols[c])
    nbytes = ref.rows * 4 * len(feature_cols)
    for c in label_cols:
        views[c][row0:row0 + ref.rows] = cols[c]
        nbytes += ref.rows * cols[c].dtype.itemsize
    _M_HANDOFF.inc(nbytes)
    del cols, keep
    return ref.rows


# -- worker loop + pool ------------------------------------------------------


def _etl_worker_main(wid, task_q, result_q) -> None:
    """Forked ETL worker loop: tasks arrive as ``(tid, cloudpickle
    blob)``, data moves by slab name. Same claim/done protocol as the
    transform workers (see ``worker_pool._worker_main``)."""
    from ..utils.trace import set_thread_label
    set_thread_label(f"xshard-{wid}")
    while True:
        task = task_q.get()
        if task is None:
            return
        tid, blob = task
        result_q.put(("claim", tid, wid))
        try:
            if faults.inject("xshard.kill"):
                os.kill(os.getpid(), signal.SIGKILL)
            faults.inject("xshard.task")
            t0 = time.perf_counter()
            fn, args = pickle.loads(blob)
            with time_it("xshard.task"):
                out = fn(*args)
            _M_TASK.observe(time.perf_counter() - t0)
            result_q.put(("done", tid, out, None))
        except BaseException:
            result_q.put(("done", tid, None, traceback.format_exc()))


class EtlPool(WorkerPoolBase):
    """Persistent forked ETL worker fleet. Unlike the transform pool,
    nothing task-specific is fork-inherited — tasks ship whole — so a
    respawned worker is immediately as capable as the one it replaces."""

    _kind = "xshard"
    _error_cls = XShardWorkerError
    _respawn_metric = _M_RESPAWN

    def __init__(self, num_workers: int):
        self._closed = True  # armed by _init_pool; keeps __del__ safe
        self._init_pool(num_workers)

    def _spawn_worker(self, wid: int):
        return self._fork_process(wid, _etl_worker_main,
                                  (wid, self._task_q, self._result_q))

    def run(self, calls: Sequence[Tuple[Any, tuple]]) -> List[Any]:
        """Submit ``(fn, args)`` tasks and collect results in order.
        Pending claim/done messages are drained between submits so a wide
        fan-out cannot wedge both pipes."""
        if not self._lock.acquire(blocking=False):
            raise RuntimeError(
                "EtlPool is already running a task wave; use one engine "
                "per concurrent driver thread")
        try:
            self._drain_outstanding()
            tids = []
            for fn, args in calls:
                tids.append(self._submit_payload(_pickler.dumps((fn, args))))
                while self._result_q._reader.poll(0):
                    self._pump(0)
            return [self._collect(tid) for tid in tids]
        finally:
            self._lock.release()


# -- slab pool (parent-owned, reused across task waves) ----------------------


class SlabPool:
    """Fixed-size reusable shared-memory slabs, ALL created in the parent
    (workers only ever attach by name, untracked). A slab is pinned while
    a live XShard's block occupies it and recycled when that shard is
    closed or collected."""

    def __init__(self, slab_bytes: int):
        self.slab_bytes = int(slab_bytes)
        self._all: Dict[str, shared_memory.SharedMemory] = {}
        self._free: List[str] = []

    def acquire(self) -> Tuple[str, int]:
        if self._free:
            return self._free.pop(), self.slab_bytes
        shm = shared_memory.SharedMemory(create=True, size=self.slab_bytes)
        self._all[shm.name] = shm
        return shm.name, self.slab_bytes

    def release(self, name: str) -> None:
        if name in self._all and name not in self._free:
            self._free.append(name)

    @property
    def total_bytes(self) -> int:
        return len(self._all) * self.slab_bytes

    def close(self) -> None:
        for shm in self._all.values():
            try:
                shm.close()
            except BufferError:
                pass  # a consumer still holds views; unlink below still
                # frees the NAME — memory goes when the views do
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        self._all = {}
        self._free = []


# -- engine ------------------------------------------------------------------


class EtlEngine:
    """One worker fleet + slab pool + spill directory; process-global via
    :func:`get_engine` (rebuilt when the ``xshard.*`` config changes)."""

    def __init__(self, num_workers: Optional[int] = None,
                 slab_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        cfg = global_config()
        if num_workers is None:
            num_workers = (int(cfg.get("xshard.num_workers") or 0)
                           or default_workers())
        if slab_bytes is None:
            slab_bytes = int(float(cfg.get("xshard.slab_mb") or 64.0)
                             * (1 << 20))
        if spill_dir is None:
            spill_dir = str(cfg.get("xshard.spill_dir") or "")
        self.num_workers = int(num_workers)
        self.slab_bytes = max(1, int(slab_bytes))
        self._own_spill = not spill_dir
        self.spill_dir = (spill_dir
                          or tempfile.mkdtemp(prefix="zoo_xshard_spill_"))
        os.makedirs(self.spill_dir, exist_ok=True)
        self.slabs = SlabPool(self.slab_bytes)
        self.pool = EtlPool(self.num_workers)
        self._tag_counter = itertools.count()
        self._closed = False
        self._cfg_sig: Any = None

    def run(self, calls) -> List[Any]:
        return self.pool.run(calls)

    def new_tag(self) -> str:
        return f"xshard-{os.getpid()}-{next(self._tag_counter)}"

    def release_block(self, ref: BlockRef) -> None:
        if ref.kind == "shm":
            self.slabs.release(ref.name)
        elif ref.kind == "mmap":
            try:
                os.remove(ref.name)
            except OSError:
                pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.pool.close()
        self.slabs.close()
        _detach_all()
        if self._own_spill:
            shutil.rmtree(self.spill_dir, ignore_errors=True)

    def __enter__(self) -> "EtlEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_engine: Optional[EtlEngine] = None


def _config_signature():
    cfg = global_config()
    return (int(cfg.get("xshard.num_workers") or 0),
            float(cfg.get("xshard.slab_mb") or 64.0),
            str(cfg.get("xshard.spill_dir") or ""))


def get_engine() -> EtlEngine:
    """The process-global ETL engine, rebuilt when its ``xshard.*``
    config signature changes (worker count, slab budget, spill dir)."""
    global _engine
    sig = _config_signature()
    if _engine is not None and _engine._cfg_sig != sig:
        _engine.close()
        _engine = None
    if _engine is None:
        _engine = EtlEngine()
        _engine._cfg_sig = sig
    return _engine


@atexit.register
def _close_engine() -> None:
    global _engine
    if _engine is not None:
        try:
            _engine.close()
        except Exception:
            pass
        _engine = None


# -- the user-facing shard -----------------------------------------------


class XShard:
    """A hash-partitionable distributed table: partitions are
    shared-memory (or spilled-memmap) blocks, ops are waves of tasks on
    the engine's persistent worker fleet."""

    def __init__(self, engine: EtlEngine, refs: Sequence[BlockRef]):
        self._engine = engine
        self._refs: List[BlockRef] = list(refs)
        self._closed = False

    # -- construction --------------------------------------------------------

    @classmethod
    def from_pandas(cls, df, npartitions: Optional[int] = None,
                    engine: Optional[EtlEngine] = None) -> "XShard":
        """Split a driver DataFrame into row-range partitions (the
        ``np.array_split`` size convention) stored as blocks."""
        eng = engine or get_engine()
        if npartitions is None:
            cfg = global_config()
            npartitions = (int(cfg.get("xshard.partitions") or 0)
                           or eng.num_workers)
        npartitions = max(1, int(npartitions))
        n = len(df)
        sizes = np.full(npartitions, n // npartitions, dtype=np.int64)
        sizes[:n % npartitions] += 1
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        cols_all = {c: np.ascontiguousarray(df[c].to_numpy())
                    for c in df.columns}
        refs = []
        for i in range(npartitions):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            part = {c: a[lo:hi] for c, a in cols_all.items()}
            refs.append(cls._store_parent(eng, part))
        return cls(eng, refs)

    @classmethod
    def from_shards(cls, dfs: Sequence[Any],
                    engine: Optional[EtlEngine] = None) -> "XShard":
        """One partition per DataFrame (the DataShards bridge)."""
        eng = engine or get_engine()
        return cls(eng, [cls._store_parent(eng, _cols_of(df))
                         for df in dfs])

    @classmethod
    def read_files(cls, paths: Sequence[str], fmt: str = "csv",
                   engine: Optional[EtlEngine] = None,
                   **pandas_kwargs) -> "XShard":
        """Distributed ingest: one partition per file, each loaded by a
        WORKER straight into its block — file bytes never materialize in
        the driver."""
        eng = engine or get_engine()
        slabs = [eng.slabs.acquire() for _ in paths]
        calls = [(_read_file_task,
                  (p, fmt, pandas_kwargs, slab, eng.spill_dir,
                   eng.new_tag()))
                 for p, slab in zip(paths, slabs)]
        refs = eng.run(calls)
        cls._release_unused(eng, slabs, refs)
        return cls(eng, refs)

    @classmethod
    def read_csv(cls, path: str, engine: Optional[EtlEngine] = None,
                 **pandas_kwargs) -> "XShard":
        from .shard import _expand
        return cls.read_files(_expand(path, [".csv"]), "csv", engine,
                              **pandas_kwargs)

    @staticmethod
    def _store_parent(eng: EtlEngine, cols: Dict[str, np.ndarray]
                      ) -> BlockRef:
        slab = eng.slabs.acquire()
        ref = _store_block(cols, slab, eng.spill_dir, eng.new_tag())
        if ref.kind != "shm":
            eng.slabs.release(slab[0])
        return ref

    @staticmethod
    def _release_unused(eng, slabs, refs) -> None:
        used = {r.name for r in refs if r is not None and r.kind == "shm"}
        for name, _ in slabs:
            if name not in used:
                eng.slabs.release(name)

    # -- introspection -------------------------------------------------------

    @property
    def schema(self):
        return self._refs[0].schema if self._refs else ()

    @property
    def columns(self) -> List[str]:
        return [c for c, _, _ in self.schema]

    def num_partitions(self) -> int:
        return len(self._refs)

    def count(self) -> int:
        return sum(r.rows for r in self._refs)

    # -- ops -----------------------------------------------------------------

    def _wave(self, make_call) -> List[BlockRef]:
        eng = self._engine
        slabs = [eng.slabs.acquire() for _ in self._refs]
        calls = [make_call(ref, slab) for ref, slab in
                 zip(self._refs, slabs)]
        refs = eng.run(calls)
        self._release_unused(eng, slabs, refs)
        return refs

    def map(self, fn) -> "XShard":
        """Apply ``fn(df) -> df`` per partition in the worker fleet."""
        blob = _pickler.dumps(fn)
        eng = self._engine
        return XShard(eng, self._wave(
            lambda ref, slab: (_map_task, (ref, blob, slab, eng.spill_dir,
                                           eng.new_tag()))))

    def filter(self, pred) -> "XShard":
        """Keep rows where ``pred(df)`` is True (vectorized take in the
        worker — no per-row Python, no intermediate frame)."""
        blob = _pickler.dumps(pred)
        eng = self._engine
        return XShard(eng, self._wave(
            lambda ref, slab: (_filter_task, (ref, blob, slab,
                                              eng.spill_dir,
                                              eng.new_tag()))))

    def groupby(self, keys) -> "_GroupedXShard":
        keys = [keys] if isinstance(keys, str) else list(keys)
        return _GroupedXShard(self, keys)

    def _exchange(self, keys: Sequence[str], nparts: int
                  ) -> List[BlockRef]:
        """Stage A: bucket every partition by key hash into exchange
        blocks (handed off by slab name, never concatenated)."""
        eng = self._engine
        slabs = [eng.slabs.acquire() for _ in self._refs]
        calls = [(_exchange_task, (ref, tuple(keys), nparts, slab,
                                   eng.spill_dir, eng.new_tag()))
                 for ref, slab in zip(self._refs, slabs)]
        refs = eng.run(calls)
        self._release_unused(eng, slabs, refs)
        return refs

    def join(self, other: "XShard", on, how: str = "inner") -> "XShard":
        """Hash-partitioned inner join (pandas ``merge`` row-order and
        column-order contract per destination partition; global row
        order is partition-major, as with any shuffle engine)."""
        if how != "inner":
            raise ValueError("XShard.join supports how='inner' only")
        if other._engine is not self._engine:
            raise ValueError("joined XShards must share an engine")
        keys = [on] if isinstance(on, str) else list(on)
        overlap = (set(self.columns) & set(other.columns)) - set(keys)
        if overlap:
            raise ValueError(
                f"non-key columns overlap: {sorted(overlap)}; rename "
                f"before joining (no suffix support)")
        eng = self._engine
        nparts = max(self.num_partitions(), other.num_partitions())
        lex = self._exchange(keys, nparts)
        rex = other._exchange(keys, nparts)
        slabs = [eng.slabs.acquire() for _ in range(nparts)]
        calls = [(_join_task, (tuple(lex), tuple(rex), j, tuple(keys),
                               slab, eng.spill_dir, eng.new_tag()))
                 for j, slab in enumerate(slabs)]
        refs = eng.run(calls)
        self._release_unused(eng, slabs, refs)
        for ref in lex + rex:
            eng.release_block(ref)
        return XShard(eng, refs)

    # -- materialization -----------------------------------------------------

    def collect(self) -> List[Any]:
        """Partitions as driver DataFrames (copied out of the slabs, so
        they survive slab recycling)."""
        import pandas as pd
        out = []
        for ref in self._refs:
            cols, keep = _load_block(ref)
            out.append(pd.DataFrame({c: np.array(a)
                                     for c, a in cols.items()}))
            del cols, keep
        return out

    def to_pandas(self):
        """Driver-side materialization (debug/interop — NOT the training
        path; ``to_featureset`` lowers without this concat)."""
        import pandas as pd
        frames = self.collect()
        if len(frames) == 1:
            return frames[0]
        return pd.concat(frames, ignore_index=True)

    def to_featureset(self, feature_cols, label_cols=None,
                      stack: bool = True, feature_shape=None, **kwargs):
        """Lower into a FeatureSet with ZERO full-dataset host copies:
        workers write partition rows straight into one exact-size shared
        feature/label segment and the FeatureSet wraps the views
        (``data.handoff='gather'`` switches to the eager
        concat-into-``from_dataframe`` baseline for A/B).

        ``feature_shape`` reshapes the ``[N, K]`` feature matrix to
        ``(N, *feature_shape)`` — a free view reshape, used by the Zouwu
        rolling-window path to feed ``(lookback, features)`` sequence
        models."""
        from ..feature.featureset import FeatureSet
        feature_cols = ([feature_cols] if isinstance(feature_cols, str)
                        else list(feature_cols))
        label_cols = ([label_cols] if isinstance(label_cols, str)
                      else list(label_cols or []))
        mode = str(global_config().get("data.handoff") or "slab")
        if mode == "gather" or not stack:
            return FeatureSet.from_dataframe(
                self.to_pandas(), feature_cols, label_cols or None,
                stack=stack, **kwargs)
        total = self.count()
        if total == 0:
            raise ValueError("cannot lower an empty XShard to a "
                             "FeatureSet")
        schema = {c: (dt, tail) for c, dt, tail in self.schema}
        for c in feature_cols + label_cols:
            if c not in schema:
                raise KeyError(f"column {c!r} not in shard schema "
                               f"{sorted(schema)}")
            if schema[c][1]:
                raise ValueError(f"column {c!r} is array-valued; the "
                                 f"slab handoff stacks scalar columns")
        hschema = ((("__features__", "<f4", (len(feature_cols),)),)
                   + tuple((c,) + schema[c] for c in label_cols))
        eng = self._engine
        shm = shared_memory.SharedMemory(
            create=True, size=_block_nbytes(hschema, total))
        try:
            calls, row0 = [], 0
            for ref in self._refs:
                if ref.rows:
                    calls.append((_handoff_task,
                                  (ref, tuple(feature_cols),
                                   tuple(label_cols), shm.name, row0,
                                   hschema, total)))
                row0 += ref.rows
            eng.run(calls)
        except BaseException:
            shm.close()
            shm.unlink()
            raise
        views = _block_views(shm.buf, hschema, total)
        feats = views["__features__"]
        if feature_shape is not None:
            feats = feats.reshape((total,) + tuple(feature_shape))
        labels: Any = tuple(views[c] for c in label_cols)
        if len(labels) == 1:
            labels = labels[0]
        return FeatureSet.from_slab_views(
            feats, labels if label_cols else None,
            keepalive=SlabKeepAlive([shm]), **kwargs)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Release this shard's blocks back to the slab pool (and delete
        its spill files). Also runs on GC."""
        if self._closed:
            return
        self._closed = True
        eng = self._engine
        if eng is not None and not eng._closed:
            for ref in self._refs:
                eng.release_block(ref)
        self._refs = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _GroupedXShard:
    """``xs.groupby(keys).agg({col: how})`` — the two-stage shuffle."""

    def __init__(self, xs: XShard, keys: List[str]):
        self._xs = xs
        self._keys = keys

    def agg(self, spec: Dict[str, str]) -> XShard:
        """Aggregate with pandas ``groupby(keys, as_index=False,
        sort=True).agg(spec)`` semantics per destination partition
        (sum/count/mean/min/max; accumulation order matches pandas so
        float sums are bit-identical)."""
        xs, keys = self._xs, self._keys
        eng = xs._engine
        nparts = xs.num_partitions()
        ex = xs._exchange(keys, nparts)
        slabs = [eng.slabs.acquire() for _ in range(nparts)]
        calls = [(_groupby_task, (tuple(ex), j, tuple(keys), dict(spec),
                                  slab, eng.spill_dir, eng.new_tag()))
                 for j, slab in enumerate(slabs)]
        refs = eng.run(calls)
        XShard._release_unused(eng, slabs, refs)
        for ref in ex:
            eng.release_block(ref)
        return XShard(eng, refs)
