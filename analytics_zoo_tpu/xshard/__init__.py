from .shard import DataShards, read_csv, read_json  # noqa: F401
