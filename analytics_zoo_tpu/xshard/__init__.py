from .shard import (  # noqa: F401
    DataShards, read_csv, read_json, read_parquet)
from .pod_shard import PodDataShards  # noqa: F401
from .engine import (  # noqa: F401
    EtlEngine, XShard, XShardWorkerError, get_engine)
