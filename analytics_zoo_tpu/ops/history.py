"""Metric history: fixed-size per-series rings over the shm registry.

The metrics registry (``common/metrics.py``) is a *point-in-time* plane:
gauges hold the current value, counters hold the running total, and a
scrape sees only "now". Burn-rate alerting and post-incident forensics
both need *windows* — "what was the shed rate over the last 60 seconds",
"what did p99 look like in the two minutes before the breaker tripped".
:class:`MetricHistory` closes that gap with a sampler thread that
snapshots the registry on a fixed cadence into bounded per-series rings:

- **Fixed-size.** Each ``(metric, label)`` series keeps the newest
  ``ops.history_depth`` samples in a ``deque`` — memory is bounded by
  ``series x depth`` regardless of run length.
- **Delta-aware for counters.** :meth:`delta` sums the *positive*
  increments between consecutive samples in a window, so a counter reset
  (process restart, ``zero_all`` between bench legs) contributes the
  post-reset value instead of a huge negative step — the same semantics
  as PromQL ``increase()``.
- **Histogram-aware.** Histogram samples carry the snapshot summary
  (``count``/``sum``/``p50``/``p90``/``p99``); window queries extract a
  key (``key="p99"``) and ``delta(key="count")`` gives windowed event
  counts for ratio rules.
- **Near-zero cost when off.** Nothing samples until :meth:`start`, and
  callers gate ``start()`` on ``ops.enabled`` (see
  ``ops.alerts.ensure_default``) — the disabled ops plane costs one
  boolean check at server startup and nothing per step.

All timestamps are wall-clock (:func:`~analytics_zoo_tpu.common.utils.
wall_clock`): history is a cross-process forensic artifact, bundled next
to events whose wall stamps bracket the cross-pid merge. Tests drive
:meth:`sample_once` with an explicit fake ``now`` instead of the thread.
"""
from __future__ import annotations

import collections
import logging
import threading
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..common import metrics as _metrics
from ..common.config import global_config
from ..common.utils import wall_clock

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["MetricHistory"]

Sample = Tuple[float, Any]  # (wall, value-or-histogram-summary)


class MetricHistory:
    """Sampler + ring store over one metrics registry (the process
    default unless a fresh test registry is passed)."""

    def __init__(self, registry: Optional[_metrics.Registry] = None,
                 depth: Optional[int] = None,
                 interval_s: Optional[float] = None):
        cfg = global_config()
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self.depth = int(depth if depth is not None
                         else cfg.get("ops.history_depth"))
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.get("ops.sample_interval_s"))
        self._series: Dict[Tuple[str, str], Deque[Sample]] = {}
        self._kinds: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling -------------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> float:
        """Take one registry snapshot into the rings. ``now`` is
        injectable for fake-clock tests; production sampling stamps
        :func:`wall_clock`."""
        t = wall_clock() if now is None else float(now)
        snap = self._reg.snapshot()
        with self._lock:
            for name, entry in snap.items():
                kind = entry.get("type", "untyped")
                self._kinds[name] = kind
                if "series" in entry:
                    items = entry["series"].items()
                elif kind == "histogram":
                    items = [("", entry.get("summary"))]
                else:
                    items = [("", entry.get("value"))]
                for label, val in items:
                    if val is None:
                        continue
                    dq = self._series.get((name, label))
                    if dq is None:
                        dq = self._series[(name, label)] = \
                            collections.deque(maxlen=self.depth)
                    dq.append((t, val))
        return t

    def start(self) -> "MetricHistory":
        """Start the daemon sampler thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _run() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:
                    logger.debug("metric history sample failed",
                                 exc_info=True)

        self._thread = threading.Thread(
            target=_run, name="zoo-ops-history", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    # -- queries --------------------------------------------------------------

    @staticmethod
    def _num(val: Any, key: Optional[str]) -> Optional[float]:
        if isinstance(val, dict):
            val = val.get(key or "count")
        if val is None:
            return None
        try:
            return float(val)
        except (TypeError, ValueError):
            return None

    def kind(self, name: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(name)

    def labels_for(self, name: str) -> List[str]:
        with self._lock:
            return sorted(l for (n, l) in self._series if n == name)

    def latest(self, name: str, label: str = "") -> Optional[Sample]:
        with self._lock:
            dq = self._series.get((name, label))
            return dq[-1] if dq else None

    def window(self, name: str, label: str = "",
               seconds: Optional[float] = None,
               now: Optional[float] = None) -> List[Sample]:
        """Samples of one series inside the trailing window (all retained
        samples when ``seconds`` is None)."""
        with self._lock:
            dq = list(self._series.get((name, label), ()))
        if not dq:
            return []
        if now is None:
            now = dq[-1][0]
        if seconds is None:
            return [(t, v) for t, v in dq if t <= now]
        lo = now - float(seconds)
        return [(t, v) for t, v in dq if lo <= t <= now]

    def delta(self, name: str, label: str = "",
              seconds: Optional[float] = None,
              now: Optional[float] = None,
              key: Optional[str] = None) -> Optional[float]:
        """Counter increase over the trailing window: the sum of positive
        consecutive increments, reset-tolerant (a decrease counts the
        post-reset value from zero). The last sample *before* the window
        seeds the baseline so the first in-window increment is not lost.
        Returns ``None`` when the series has no sample in the window."""
        with self._lock:
            dq = list(self._series.get((name, label), ()))
        if not dq:
            return None
        if now is None:
            now = dq[-1][0]
        lo = (now - float(seconds)) if seconds is not None else None
        prev: Optional[float] = None
        total = 0.0
        seen = False
        for t, val in dq:
            if t > now:
                break
            x = self._num(val, key)
            if x is None:
                continue
            if lo is not None and t < lo:
                prev = x  # pre-window baseline
                continue
            seen = True
            if prev is not None:
                d = x - prev
                if d > 0:
                    total += d
                elif d < 0:
                    total += x  # counter reset between samples
            prev = x
        return total if seen else None

    def rate(self, name: str, label: str = "", seconds: float = 60.0,
             now: Optional[float] = None,
             key: Optional[str] = None) -> Optional[float]:
        """Windowed per-second rate of a counter (``delta / seconds``)."""
        d = self.delta(name, label, seconds, now, key)
        if d is None or seconds <= 0:
            return None
        return d / float(seconds)

    def dump(self, seconds: Optional[float] = None,
             now: Optional[float] = None
             ) -> Dict[str, Dict[str, List[List[Any]]]]:
        """JSON-ready ``{metric: {label: [[wall, value], ...]}}`` of the
        trailing window — the "related metric history" an incident
        bundle seals."""
        with self._lock:
            keys = list(self._series)
        out: Dict[str, Dict[str, List[List[Any]]]] = {}
        for name, label in keys:
            win = self.window(name, label, seconds, now)
            if win:
                out.setdefault(name, {})[label] = \
                    [[t, v] for t, v in win]
        return out
