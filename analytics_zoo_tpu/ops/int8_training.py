"""int8 TRAINING convolution — the byte-cut lever past ResNet's bf16 HBM
floor.

The bf16 ResNet-50 step sits at 97-99% of the chip's HBM roofline
(bench roofline fields; analytic floor ~62-65GB/step), so further
throughput needs smaller bytes, not better schedules. This op is the
building block: a convolution whose forward runs on the int8 MXU path
(2x the bf16 peak on v5e) with dynamically-scaled activations and
per-output-channel weight scales, and whose backward is the standard
straight-through estimator — dx/dw computed in bf16 against the
DEQUANTIZED input, with the int8 tensor (half the bytes of bf16) as the
saved residual. Because the dynamic scale is max-based there is no
clipping, so the STE is exact up to rounding quantization noise.

Design notes for the full-network integration (round-5 work): the win
compounds when the int8 tensor is what flows BETWEEN layers (BN+relu
output quantized once, bf16 never round-tripping HBM); at the op level
the measurable wins are the int8 MXU forward and the halved wgrad
activation stream. Reference parity: the reference's int8 story is
OpenVINO inference-only (``examples/vnni/openvino/Perf.scala:1``) —
int8 TRAINING is a new TPU-native capability.
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _quantize_dynamic(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-tensor symmetric int8: scale = max|x|/127 (no clipping)."""
    s = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _quantize_weight_per_channel(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """HWIO kernel, per-O-channel symmetric scales."""
    wf = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=(0, 1, 2)), 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127)
    return q.astype(jnp.int8), s


def _conv_dims():
    return ("NHWC", "HWIO", "NHWC")


def _int8_conv_core(x, kernel, strides, padding, dilation, groups):
    """Quantize + int8 conv + rescale; the ONE implementation both the
    primal and the vjp-forward call (they must stay bit-identical)."""
    xq, sx = _quantize_dynamic(x)
    wq, sw = _quantize_weight_per_channel(kernel)
    acc = lax.conv_general_dilated(
        xq, wq, window_strides=tuple(strides), padding=padding,
        rhs_dilation=tuple(dilation), feature_group_count=groups,
        dimension_numbers=_conv_dims(),
        preferred_element_type=jnp.int32)
    y = (acc.astype(jnp.float32) * (sx * sw)).astype(x.dtype)
    return y, xq, sx


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def int8_train_conv(x: jax.Array, kernel: jax.Array,
                    strides: Sequence[int], padding,
                    dilation: Sequence[int], groups: int) -> jax.Array:
    """Forward: int8 x int8 convolution with int32 accumulation, rescaled
    to the input dtype. Backward (STE): bf16 dgrad/wgrad against the
    dequantized input; the residual activation is stored INT8."""
    y, _, _ = _int8_conv_core(x, kernel, strides, padding, dilation, groups)
    return y


def _fwd(x, kernel, strides, padding, dilation, groups):
    y, xq, sx = _int8_conv_core(x, kernel, strides, padding, dilation,
                                groups)
    # residuals: int8 activations + scale (HALF the bytes of a bf16 save,
    # a quarter of f32) and the small kernel; a zero-size array carries
    # x's dtype (a bare dtype object is not a JAX type)
    return y, (xq, sx, kernel, jnp.zeros((0,), x.dtype))


def _bwd(strides, padding, dilation, groups, residuals, g):
    xq, sx, kernel, x_proto = residuals
    x_dtype = x_proto.dtype
    x_deq = (xq.astype(jnp.float32) * sx).astype(jnp.bfloat16)

    def ref_conv(x_, k_):
        return lax.conv_general_dilated(
            x_, k_, window_strides=tuple(strides), padding=padding,
            rhs_dilation=tuple(dilation), feature_group_count=groups,
            dimension_numbers=_conv_dims())

    _, vjp = jax.vjp(ref_conv, x_deq, kernel.astype(jnp.bfloat16))
    dx, dk = vjp(g.astype(jnp.bfloat16))
    return dx.astype(x_dtype), dk.astype(kernel.dtype)


int8_train_conv.defvjp(_fwd, _bwd)
