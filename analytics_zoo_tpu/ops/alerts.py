"""SLO alerting: multi-window multi-burn-rate rules over metric history.

The alerting discipline is the Google SRE workbook's: an SLO with
objective ``o`` grants an error budget ``1 - o``; the **burn rate** of a
window is ``(bad / total) / (1 - o)`` — 1.0 means the budget burns
exactly at sustainable speed, 14.4 means a 30-day budget is gone in two
days. A :class:`BurnRateRule` fires only when the burn rate exceeds a
factor in BOTH a long and a short window:

- the **long window** gives significance (one shed request out of ten
  must not page anyone);
- the **short window** gives a fast reset (once the bleeding stops, the
  short window drains and the alert clears long before the long window
  forgets).

Several ``(long_s, short_s, factor)`` pairs per rule give the classic
fast-burn (page now) / slow-burn (ticket) split. :class:`ThresholdRule`
covers non-ratio signals (p99 latency, queue depth) with a sustained
``for_s`` qualifier. Comparisons are strict (``>``), so a series sitting
*exactly on* the boundary does not flap, and an active alert only clears
after ``clear_holds`` consecutive calm evaluations — hysteresis in the
same spirit as the brownout ladder's hold ticks.

A firing (or clearing) alert is itself an **event** (``ops.alert`` in
the structured event log), so alerts interleave with the transitions
that caused them on the incident timeline; the engine's ``on_fire`` hook
is where the incident correlator seals a bundle.

Everything evaluates against injectable wall-clock ``now`` values, so
the burn-rate math is testable on a fake clock with no sleeping.
"""
from __future__ import annotations

import logging
import threading
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from ..common.config import global_config
from ..common.utils import wall_clock
from . import events
from .history import MetricHistory

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = [
    "AlertEngine", "BurnRateRule", "Rule", "ThresholdRule",
    "active_alerts", "default_rules", "ensure_default",
    "shutdown_default",
]

_E_ALERT = events.event_type(
    "ops.alert",
    "Alert state transition (state=fire|clear) from the SLO rule engine, "
    "carrying the rule name and the evaluation detail that crossed the "
    "line.")

#: default multi-window pairs: (long_s, short_s, factor). The canonical
#: SRE-workbook shape scaled to this platform's second-scale SLO windows:
#: a fast burn pages on a minute of evidence, a slow burn on five.
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (60.0, 5.0, 14.4),   # fast burn
    (300.0, 30.0, 6.0),  # slow burn
)


class Rule:
    """One named alert rule. Subclasses implement :meth:`evaluate`
    against a :class:`~analytics_zoo_tpu.ops.history.MetricHistory` and
    an explicit wall-clock ``now``."""

    def __init__(self, name: str, clear_holds: int = 2):
        self.name = str(name)
        self.clear_holds = max(1, int(clear_holds))

    def evaluate(self, history: MetricHistory, now: float
                 ) -> Tuple[bool, Dict[str, Any]]:
        raise NotImplementedError


def _as_names(x) -> Tuple[str, ...]:
    return (x,) if isinstance(x, str) else tuple(x)


class BurnRateRule(Rule):
    """Multi-window multi-burn-rate SLO rule over counter deltas.

    ``bad`` and ``total`` are metric names (or tuples summed together);
    with ``label=None`` deltas aggregate across every label of each
    series (fleet-wide SLO), a specific label pins one instance. For
    histogram-backed series pass ``key="count"``.
    """

    def __init__(self, name: str, bad, total, objective: float = 0.999,
                 windows: Sequence[Tuple[float, float, float]]
                 = DEFAULT_WINDOWS,
                 label: Optional[str] = None,
                 key: Optional[str] = None,
                 min_total: float = 1.0,
                 clear_holds: int = 2):
        super().__init__(name, clear_holds)
        self.bad = _as_names(bad)
        self.total = _as_names(total)
        self.objective = float(objective)
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        self.windows = tuple((float(l), float(s), float(f))
                             for l, s, f in windows)
        self.label = label
        self.key = key
        self.min_total = float(min_total)

    def _delta(self, history: MetricHistory, names: Tuple[str, ...],
               seconds: float, now: float) -> Optional[float]:
        total = 0.0
        seen = False
        for n in names:
            labels = ([self.label] if self.label is not None
                      else (history.labels_for(n) or [""]))
            for lab in labels:
                d = history.delta(n, lab, seconds, now, key=self.key)
                if d is not None:
                    total += max(0.0, d)
                    seen = True
        return total if seen else None

    def burn_rate(self, history: MetricHistory, seconds: float,
                  now: float) -> Optional[float]:
        """The window's burn rate, or ``None`` when the window has no
        traffic to judge (no samples, or fewer than ``min_total``
        events — silence is not an SLO violation)."""
        bad = self._delta(history, self.bad, seconds, now)
        tot = self._delta(history, self.total, seconds, now)
        if tot is None or tot < self.min_total:
            return None
        budget = max(1e-9, 1.0 - self.objective)
        return ((bad or 0.0) / tot) / budget

    def evaluate(self, history: MetricHistory, now: float
                 ) -> Tuple[bool, Dict[str, Any]]:
        for long_s, short_s, factor in self.windows:
            burn_l = self.burn_rate(history, long_s, now)
            burn_s = self.burn_rate(history, short_s, now)
            if burn_l is None or burn_s is None:
                continue
            # strict >: a burn sitting exactly on the factor holds steady
            if burn_l > factor and burn_s > factor:
                return True, {
                    "rule": "burn_rate",
                    "objective": self.objective,
                    "window_s": [long_s, short_s],
                    "factor": factor,
                    "burn_long": round(burn_l, 3),
                    "burn_short": round(burn_s, 3),
                }
        return False, {}


class ThresholdRule(Rule):
    """Sustained threshold over one metric series (``above`` / ``below``
    strict comparisons). With ``for_s > 0`` every sample in the trailing
    window must breach AND the series must have history reaching back at
    least ``for_s`` — a single spiky sample cannot page. ``label=None``
    checks every label and fires on the worst offender."""

    def __init__(self, name: str, metric: str, key: Optional[str] = None,
                 label: Optional[str] = None,
                 above: Optional[float] = None,
                 below: Optional[float] = None,
                 for_s: float = 0.0, clear_holds: int = 2):
        super().__init__(name, clear_holds)
        if above is None and below is None:
            raise ValueError("ThresholdRule needs above= and/or below=")
        self.metric = metric
        self.key = key
        self.label = label
        self.above = above
        self.below = below
        self.for_s = float(for_s)

    def _breach(self, x: float) -> bool:
        if self.above is not None and not (x > self.above):
            return False
        if self.below is not None and not (x < self.below):
            return False
        return True

    def evaluate(self, history: MetricHistory, now: float
                 ) -> Tuple[bool, Dict[str, Any]]:
        labels = ([self.label] if self.label is not None
                  else (history.labels_for(self.metric) or [""]))
        for lab in labels:
            full = history.window(self.metric, lab, None, now)
            if not full:
                continue
            if self.for_s > 0:
                if full[0][0] > now - self.for_s:
                    continue  # not enough history to call it sustained
                win = [v for t, v in full if t >= now - self.for_s]
            else:
                win = [full[-1][1]]
            vals = [history._num(v, self.key) for v in win]
            vals = [v for v in vals if v is not None]
            if not vals:
                continue
            if all(self._breach(v) for v in vals):
                return True, {
                    "rule": "threshold", "metric": self.metric,
                    "label": lab, "key": self.key,
                    "value": round(vals[-1], 6),
                    "above": self.above, "below": self.below,
                    "for_s": self.for_s,
                }
        return False, {}


class AlertEngine:
    """Evaluates a rule set against a :class:`MetricHistory` on a
    cadence (or on demand with an injected clock) and tracks active
    alerts with clear-side hysteresis. Transitions are emitted as
    ``ops.alert`` events; ``on_fire(name, info, now)`` hooks incident
    sealing."""

    def __init__(self, history: MetricHistory,
                 rules: Iterable[Rule] = (),
                 log: Optional[events.EventLog] = None,
                 on_fire: Optional[Callable[[str, Dict[str, Any], float],
                                            Any]] = None,
                 interval_s: Optional[float] = None):
        cfg = global_config()
        self.history = history
        self.rules: List[Rule] = list(rules)
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.get("ops.eval_interval_s"))
        self.on_fire = on_fire
        self._log = log
        self._active: Dict[str, Dict[str, Any]] = {}
        self._calm: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- evaluation -----------------------------------------------------------

    def _emit(self, name: str, state: str,
              info: Dict[str, Any]) -> None:
        try:
            if self._log is not None:
                self._log.emit("ops.alert", alert=name, state=state,
                               info=info)
            else:
                _E_ALERT.emit(alert=name, state=state, info=info)
        except Exception:
            logger.debug("alert event emit failed", exc_info=True)

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the state transitions it caused
        (empty on a quiet pass). ``now`` is injectable for fake-clock
        tests."""
        t = wall_clock() if now is None else float(now)
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                firing, info = rule.evaluate(self.history, t)
            except Exception:
                logger.debug("rule %s evaluation failed", rule.name,
                             exc_info=True)
                continue
            name = rule.name
            with self._lock:
                active = name in self._active
                if firing:
                    self._calm[name] = 0
                    if active:
                        self._active[name]["info"] = info
                        continue
                    self._active[name] = {"since": t, "info": info}
                elif active:
                    calm = self._calm.get(name, 0) + 1
                    self._calm[name] = calm
                    if calm < rule.clear_holds:
                        continue
                    del self._active[name]
                    self._calm[name] = 0
                else:
                    continue
            state = "fire" if firing else "clear"
            self._emit(name, state, info)
            transitions.append({"name": name, "state": state,
                                "info": info, "wall": t})
            if firing and self.on_fire is not None:
                try:
                    self.on_fire(name, info, t)
                except Exception:
                    logger.warning("on_fire hook for alert %s failed",
                                   name, exc_info=True)
        return transitions

    def active_alerts(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: dict(v) for n, v in self._active.items()}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "AlertEngine":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _run() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.evaluate()
                except Exception:
                    logger.debug("alert evaluation pass failed",
                                 exc_info=True)

        self._thread = threading.Thread(
            target=_run, name="zoo-ops-alerts", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None


def default_rules() -> List[Rule]:
    """The stock serving SLO rule set: goodput burn (sheds + errors
    against answered traffic), deadline-miss burn, and sustained p99
    latency. Fleet-wide (label-aggregated); tune or replace per
    deployment by handing :class:`AlertEngine` your own list."""
    return [
        BurnRateRule(
            "goodput_burn",
            bad=("serving.shed_total", "serving.error_total"),
            total=("serving.records_total", "serving.shed_total",
                   "serving.error_total", "serving.expired_total"),
            objective=0.99),
        BurnRateRule(
            "deadline_miss_burn",
            bad=("serving.expired_total",),
            total=("serving.records_total", "serving.expired_total"),
            objective=0.999),
        ThresholdRule(
            "p99_latency_high", "serving.request_latency_seconds",
            key="p99", above=1.0, for_s=15.0),
    ]


# -- process-default engine ----------------------------------------------------

_default_engine: Optional[AlertEngine] = None
_default_history: Optional[MetricHistory] = None
_default_lock = threading.Lock()


def active_alerts() -> Dict[str, Dict[str, Any]]:
    """Active alerts of the process-default engine ({} when the ops
    plane is off) — the dict servers stamp into ``health.json``."""
    eng = _default_engine
    return eng.active_alerts() if eng is not None else {}


def ensure_default(registry=None) -> Optional[AlertEngine]:
    """Start the process-default ops plane — history sampler + alert
    engine over :func:`default_rules`, with incident sealing wired to
    alert fires — iff ``ops.enabled`` is set. Idempotent; returns the
    engine, or ``None`` while the ops plane is disabled (the one boolean
    check a disabled plane costs at server startup)."""
    global _default_engine, _default_history
    if _default_engine is not None:
        return _default_engine
    cfg = global_config()
    if not bool(cfg.get("ops.enabled")):
        return None
    with _default_lock:
        if _default_engine is not None:
            return _default_engine
        from . import incident as _incident
        hist = MetricHistory(registry).start()
        corr = _incident.IncidentCorrelator(history=hist)
        eng = AlertEngine(
            hist, default_rules(),
            on_fire=lambda name, info, t: corr.seal(
                reason=f"alert:{name}",
                alert={"name": name, "info": info, "wall": t}, now=t))
        eng.start()
        _default_history = hist
        _default_engine = eng
        return eng


def shutdown_default() -> None:
    """Stop and discard the process-default engine (tests/bench)."""
    global _default_engine, _default_history
    with _default_lock:
        if _default_engine is not None:
            _default_engine.stop()
            _default_engine = None
        if _default_history is not None:
            _default_history.stop()
            _default_history = None
