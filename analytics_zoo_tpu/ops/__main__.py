"""Incident CLI: ``python -m analytics_zoo_tpu.ops <command>``.

Reads a fleet's shared event spool (the directory every process was
pointed at via ``ops.dir``) without joining it — the CLI's
:class:`~analytics_zoo_tpu.ops.events.EventLog` is constructed disabled,
so it never appends a part file of its own.

Commands::

    # render the causally-ordered timeline of the last 10 minutes
    python -m analytics_zoo_tpu.ops timeline --events /tmp/fleet_ops --since-s 600

    # seal an on-demand incident bundle (events + health snapshots)
    python -m analytics_zoo_tpu.ops seal --events /tmp/fleet_ops \
        --reason manual-probe --health /tmp/fleet_health

    # re-render a sealed bundle
    python -m analytics_zoo_tpu.ops show /tmp/fleet_ops/incidents/incident-...
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import incident as _incident
from .events import EventLog


def _read_only_log(root: str) -> EventLog:
    # enabled=False: a forensic reader must never write the spool it reads
    return EventLog(root=root, enabled=False)


def _cmd_timeline(args: argparse.Namespace) -> int:
    log = _read_only_log(args.events)
    since = None
    if args.since_s is not None:
        newest = log.read()
        if newest:
            since = newest[-1].get("wall", 0.0) - float(args.since_s)
    evs = _incident.order_events(log.read(since_wall=since))
    sys.stdout.write(_incident.render_timeline(evs))
    return 0


def _cmd_seal(args: argparse.Namespace) -> int:
    log = _read_only_log(args.events)
    corr = _incident.IncidentCorrelator(
        log=log, out_dir=args.out, window_s=args.window_s,
        health_paths=args.health or ())
    path = corr.seal(reason=args.reason)
    sys.stdout.write(path + "\n")
    return 0


def _cmd_show(args: argparse.Namespace) -> int:
    bundle = _incident.load_bundle(args.bundle)
    if args.json:
        json.dump(bundle, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(_incident.render_timeline(
            bundle.get("events", []), reason=bundle.get("reason"),
            alert=bundle.get("alert")))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.ops",
        description="Incident correlator CLI over a shared event spool.")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("timeline",
                       help="render the causally-ordered event timeline")
    t.add_argument("--events", required=True,
                   help="event spool directory (the fleet's ops.dir)")
    t.add_argument("--since-s", type=float, default=None,
                   help="only the trailing N seconds (default: everything)")
    t.set_defaults(fn=_cmd_timeline)

    s = sub.add_parser("seal", help="seal an on-demand incident bundle")
    s.add_argument("--events", required=True,
                   help="event spool directory (the fleet's ops.dir)")
    s.add_argument("--out", default=None,
                   help="bundle output dir (default: <events>/incidents)")
    s.add_argument("--reason", default="manual")
    s.add_argument("--window-s", type=float, default=None,
                   help="event window to seal (default: ops.incident_window_s)")
    s.add_argument("--health", nargs="*", default=None,
                   help="health.json files or directories to freeze in")
    s.set_defaults(fn=_cmd_seal)

    w = sub.add_parser("show", help="re-render a sealed bundle")
    w.add_argument("bundle",
                   help="bundle directory or its bundle.json")
    w.add_argument("--json", action="store_true",
                   help="dump the raw bundle JSON instead of the timeline")
    w.set_defaults(fn=_cmd_show)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return int(args.fn(args))


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
