"""Incremental (KV-cached) attention decoding.

New TPU-native capability rounding out the long-context stack: training
runs the flash kernels (``ops/attention.py``), generation runs this cache.
The reference's only generation path is the host-side RNN loop in Seq2seq
(``models/seq2seq``); transformer decoding needs the KV cache to avoid
re-attending the whole prefix per step.

Design for XLA: the cache is a STATIC ``max_len`` buffer pair updated with
``lax.dynamic_update_slice`` — shapes never change, so the per-step program
compiles once; validity is a position mask derived from ``length``. The
whole generate loop is one ``lax.scan`` (single dispatch per sequence, the
only pattern that amortizes dispatch latency on remote-attached chips).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _NEG_INF

KVCache = Dict[str, Any]


def init_kv_cache(batch: int, heads: int, max_len: int, head_dim: int,
                  dtype=jnp.bfloat16) -> KVCache:
    """Empty cache: K/V buffers ``[B, H, max_len, D]`` + write position."""
    return {
        "k": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "v": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                     cache: KVCache, scale: Optional[float] = None
                     ) -> Tuple[jax.Array, KVCache]:
    """Append ``k_new``/``v_new`` (``[B, H, T, D]``, T = 1 for decode or the
    prompt length for prefill) at the cache's write position, then attend
    ``q`` against everything cached so far, causally within the new block.

    Returns ``(context [B, H, T, D], updated cache)``. jit-safe: static
    shapes, the step count lives in ``cache["length"]``.
    """
    b, h, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    start = cache["length"]
    # capacity guard: under eager execution (concrete length) overflowing
    # the static buffer raises here; under jit the caller owns the budget
    # (max_len - length tokens remain) — overflow would silently corrupt
    import jax.core as _core
    if not isinstance(start, _core.Tracer) and int(start) + t > max_len:
        raise ValueError(
            f"KV cache overflow: writing {t} tokens at position "
            f"{int(start)} exceeds max_len={max_len}")
    k_buf = lax.dynamic_update_slice(cache["k"], k_new.astype(cache["k"].dtype),
                                     (0, 0, start, 0))
    v_buf = lax.dynamic_update_slice(cache["v"], v_new.astype(cache["v"].dtype),
                                     (0, 0, start, 0))
    s = jnp.einsum("bhtd,bhkd->bhtk", q, k_buf,
                   preferred_element_type=jnp.float32) * scale
    # visibility: cached prefix [0, start) plus the causal part of the new
    # block [start, start+t)
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    row_pos = start + lax.broadcasted_iota(jnp.int32, (t, max_len), 0)
    visible = key_pos <= row_pos
    s = jnp.where(visible[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhtk,bhkd->bhtd", p.astype(v_buf.dtype), v_buf,
                     preferred_element_type=jnp.float32)
    new_cache = {"k": k_buf, "v": v_buf, "length": start + t}
    return ctx.astype(q.dtype), new_cache


# -- slot-based cache for continuous batching -------------------------------
#
# The generative scheduler (serving/server.py GenerativeServing) keeps S
# independent streams resident in ONE device-shaped cache so a single fused
# step advances every occupied slot. All shapes are static: joining,
# stepping and evicting only move traced indices/masks around, so the step
# program compiles exactly once (plus one prefill program per length
# bucket) no matter how streams come and go.

SlotCache = Dict[str, Any]


def init_slot_cache(slots: int, heads: int, max_len: int, head_dim: int,
                    dtype=jnp.float32) -> SlotCache:
    """Per-block K/V buffers ``[S, H, max_len, D]`` for S decode slots.

    Unlike :func:`init_kv_cache` there is no scalar write position: slots
    advance independently, so per-slot lengths live in the scheduler-wide
    slot STATE (:func:`init_slot_state`) shared across blocks."""
    return {"k": jnp.zeros((slots, heads, max_len, head_dim), dtype),
            "v": jnp.zeros((slots, heads, max_len, head_dim), dtype)}


def init_slot_state(slots: int) -> Dict[str, jax.Array]:
    """Scheduler-wide occupancy: per-slot fed-token counts + active mask."""
    return {"length": jnp.zeros((slots,), jnp.int32),
            "active": jnp.zeros((slots,), bool)}


def slot_join(state: Dict[str, jax.Array], slot, length
              ) -> Dict[str, jax.Array]:
    """Mark ``slot`` occupied with ``length`` tokens already fed. Both
    arguments may be traced values — joins never trigger a recompile."""
    length = jnp.asarray(length, jnp.int32)
    return {"length": state["length"].at[slot].set(length),
            "active": state["active"].at[slot].set(True)}


def slot_evict(state: Dict[str, jax.Array], mask) -> Dict[str, jax.Array]:
    """Vacate every slot where ``mask`` [S] is True — one vectorized call
    evicts any number of finished/expired slots per step."""
    mask = jnp.asarray(mask)
    return {"length": jnp.where(mask, 0, state["length"]),
            "active": state["active"] & ~mask}


def slot_insert(cache: SlotCache, slot, k_new: jax.Array, v_new: jax.Array
                ) -> SlotCache:
    """Write a prefilled K/V block ``[H, T, D]`` into ``slot`` at position
    0. ``slot`` may be traced; T is static (length-bucketed by the caller)
    so one compile per bucket covers every join at that bucket."""
    k_buf = lax.dynamic_update_slice(
        cache["k"], k_new[None].astype(cache["k"].dtype), (slot, 0, 0, 0))
    v_buf = lax.dynamic_update_slice(
        cache["v"], v_new[None].astype(cache["v"].dtype), (slot, 0, 0, 0))
    return {"k": k_buf, "v": v_buf}


def slot_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                   cache: SlotCache, lengths: jax.Array,
                   scale: Optional[float] = None
                   ) -> Tuple[jax.Array, SlotCache]:
    """One decode step over ALL slots: write each slot's new K/V at its own
    ``lengths[s]`` position, then attend each slot's query against its
    visible prefix. Mirrors :func:`cached_attention` arithmetic exactly —
    same contractions, mask and softmax — which is what keeps slot-batched
    token streams bit-identical to serial decode rows.

    ``q``/``k_new``/``v_new``: ``[S, H, 1, D]``; ``lengths``: [S] int32
    (tokens fed so far = this step's write position). Returns
    ``(ctx [S, H, 1, D], updated cache)``; the CALLER advances lengths once
    after every block has attended (all blocks see pre-increment lengths).
    """
    _, _, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    write = jax.vmap(
        lambda buf, new, pos: lax.dynamic_update_slice(buf, new,
                                                       (0, pos, 0)))
    k_buf = write(cache["k"], k_new.astype(cache["k"].dtype), lengths)
    v_buf = write(cache["v"], v_new.astype(cache["v"].dtype), lengths)
    s = jnp.einsum("bhtd,bhkd->bhtk", q, k_buf,
                   preferred_element_type=jnp.float32) * scale
    # visibility per slot: prefix [0, length] inclusive — the just-written
    # position IS visible, exactly as cached_attention's t=1 decode row
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    visible = key_pos[None] <= lengths[:, None, None]   # [S, 1, max_len]
    s = jnp.where(visible[:, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhtk,bhkd->bhtd", p.astype(v_buf.dtype), v_buf,
                     preferred_element_type=jnp.float32)
    return ctx.astype(q.dtype), {"k": k_buf, "v": v_buf}


def _decode_loop(step_fn, params, cache, prompt_last_token,
                 max_new_tokens, eos_id, select_fn, xs) -> jax.Array:
    """Shared scan scaffolding for greedy/sampled decoding: feed a token,
    select the next via ``select_fn(logits, x)``, force eos on finished
    rows. One dispatch for the whole sequence."""

    def body(carry, x):
        token, cache, done = carry
        logits, cache = step_fn(params, token, cache)
        nxt = select_fn(logits, x).astype(token.dtype)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, token.dtype), nxt)
            done = done | (nxt == eos_id)
        return (nxt, cache, done), nxt

    done0 = jnp.zeros(prompt_last_token.shape, bool)
    (_, _, _), tokens = lax.scan(
        body, (prompt_last_token, cache, done0), xs,
        length=None if xs is not None else max_new_tokens)
    return jnp.swapaxes(tokens, 0, 1)  # [B, max_new]


def greedy_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Single-dispatch greedy decoding loop.

    ``step_fn(params, token [B], cache) -> (logits [B, V], cache)`` is the
    user's per-token forward (typically built on :func:`cached_attention`).
    Each scan step FEEDS a token — i.e. appends its K/V and predicts the
    next — so prefill the prompt EXCLUDING its last token and pass that
    last token here; prefilling the whole prompt would insert the final
    token's K/V twice. The loop runs as ONE ``lax.scan`` of
    ``max_new_tokens`` steps; with ``eos_id``, finished rows keep emitting
    ``eos_id`` (output length stays static — XLA-friendly).

    Returns generated tokens ``[B, max_new_tokens]``.
    """
    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id,
                        lambda logits, _: jnp.argmax(logits, axis=-1), None)


def beam_generate(step_fn: Callable, params: Any, cache: Any,
                  prompt_last_token: jax.Array, max_new_tokens: int,
                  beam_size: int, eos_id: Optional[int] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decoding, one ``lax.scan`` dispatch.

    Same ``step_fn(params, token [N], cache) -> (logits [N, V], cache)``
    contract as :func:`greedy_generate`, where N is ``batch * beam_size``
    after tiling. Cache leaves whose leading axis equals the batch size are
    tiled ``beam_size``-fold and reordered by backpointer every step; a
    finished beam (emitted ``eos_id``) keeps its score and pads with eos.

    Returns ``(sequences [B, beam, max_new], scores [B, beam])`` sorted
    best-first by accumulated log-probability.
    """
    b = prompt_last_token.shape[0]
    k = beam_size

    def tile(a):
        if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b:
            return jnp.repeat(a, k, axis=0)
        return a

    caches = jax.tree_util.tree_map(tile, cache)
    tokens = jnp.repeat(prompt_last_token[:, None], k, axis=1)  # [B, K]
    # only beam 0 is live initially so the first expansion picks the top-k
    # distinct continuations instead of k copies of the argmax
    scores = jnp.tile(jnp.asarray([0.0] + [_NEG_INF] * (k - 1)), (b, 1))
    done = jnp.zeros((b, k), bool)
    seqbuf = jnp.zeros((b, k, max_new_tokens), prompt_last_token.dtype)

    def body(carry, i):
        tokens, scores, done, seqbuf, caches = carry
        logits, caches = step_fn(params, tokens.reshape(b * k), caches)
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1).reshape(b, k, v)
        if eos_id is not None:
            # a finished beam may only "continue" with eos at zero cost
            eos_row = jnp.full((v,), _NEG_INF).at[eos_id].set(0.0)
            logp = jnp.where(done[..., None], eos_row[None, None], logp)
        cand = (scores[..., None] + logp).reshape(b, k * v)
        scores, idx = lax.top_k(cand, k)                   # [B, K]
        parent = idx // v
        token = (idx % v).astype(tokens.dtype)

        def reorder(a):
            if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b * k:
                ak = a.reshape((b, k) + a.shape[1:])
                sel = jnp.take_along_axis(
                    ak, parent.reshape((b, k) + (1,) * (a.ndim - 1)), axis=1)
                return sel.reshape((b * k,) + a.shape[1:])
            return a

        caches = jax.tree_util.tree_map(reorder, caches)
        seqbuf = jnp.take_along_axis(seqbuf, parent[..., None], axis=1)
        seqbuf = lax.dynamic_update_slice(
            seqbuf, token[..., None], (0, 0, i))
        done = jnp.take_along_axis(done, parent, axis=1)
        if eos_id is not None:
            done = done | (token == eos_id)
        return (token, scores, done, seqbuf, caches), None

    (tokens, scores, done, seqbuf, caches), _ = lax.scan(
        body, (tokens, scores, done, seqbuf, caches),
        jnp.arange(max_new_tokens))
    return seqbuf, scores


def make_logit_filter(temperature: float = 1.0, top_k: Optional[int] = None,
                      top_p: Optional[float] = None
                      ) -> Callable[[jax.Array], jax.Array]:
    """Build the sampling logit filter shared by :func:`sample_generate`
    and the slot-batched generative scheduler (serving/server.py).

    Filters compose in the standard order: temperature scales logits,
    ``top_k`` keeps the k highest, ``top_p`` keeps the smallest prefix of
    the sorted distribution with cumulative probability >= top_p; sampling
    renormalizes over what survives. Both decode paths composing THIS
    filter (not a re-implementation) is part of what keeps slot-batched
    sampled streams bit-identical to serial runs.
    """
    if temperature <= 0:
        raise ValueError("temperature must be > 0 (use greedy_generate "
                         "for deterministic argmax decoding)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k} "
                         "(pass top_k=None to disable)")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p} "
                         "(pass top_p=None to disable)")

    def filter_logits(logits):
        logits = logits / temperature
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
        if top_p is not None:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix reaching top_p (always >= 1 token)
            cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1,
                                 keepdims=True) - 1
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, _NEG_INF, logits)
        return logits

    return filter_logits


def sample_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    rng: jax.Array, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Stochastic decoding (temperature / top-k / nucleus), one scan
    dispatch — same ``step_fn`` contract as :func:`greedy_generate`.
    Filter semantics: :func:`make_logit_filter`. Finished rows keep
    emitting ``eos_id``.
    """
    filter_logits = make_logit_filter(temperature, top_k, top_p)

    def select(logits, step_rng):
        return jax.random.categorical(
            step_rng, filter_logits(logits.astype(jnp.float32)), axis=-1)

    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id, select,
                        jax.random.split(rng, max_new_tokens))
