"""Incremental (KV-cached) attention decoding.

New TPU-native capability rounding out the long-context stack: training
runs the flash kernels (``ops/attention.py``), generation runs this cache.
The reference's only generation path is the host-side RNN loop in Seq2seq
(``models/seq2seq``); transformer decoding needs the KV cache to avoid
re-attending the whole prefix per step.

Design for XLA: the cache is a STATIC ``max_len`` buffer pair updated with
``lax.dynamic_update_slice`` — shapes never change, so the per-step program
compiles once; validity is a position mask derived from ``length``. The
whole generate loop is one ``lax.scan`` (single dispatch per sequence, the
only pattern that amortizes dispatch latency on remote-attached chips).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _NEG_INF

KVCache = Dict[str, Any]


def init_kv_cache(batch: int, heads: int, max_len: int, head_dim: int,
                  dtype=jnp.bfloat16) -> KVCache:
    """Empty cache: K/V buffers ``[B, H, max_len, D]`` + write position."""
    return {
        "k": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "v": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                     cache: KVCache, scale: Optional[float] = None
                     ) -> Tuple[jax.Array, KVCache]:
    """Append ``k_new``/``v_new`` (``[B, H, T, D]``, T = 1 for decode or the
    prompt length for prefill) at the cache's write position, then attend
    ``q`` against everything cached so far, causally within the new block.

    Returns ``(context [B, H, T, D], updated cache)``. jit-safe: static
    shapes, the step count lives in ``cache["length"]``.
    """
    b, h, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    start = cache["length"]
    # capacity guard: under eager execution (concrete length) overflowing
    # the static buffer raises here; under jit the caller owns the budget
    # (max_len - length tokens remain) — overflow would silently corrupt
    import jax.core as _core
    if not isinstance(start, _core.Tracer) and int(start) + t > max_len:
        raise ValueError(
            f"KV cache overflow: writing {t} tokens at position "
            f"{int(start)} exceeds max_len={max_len}")
    k_buf = lax.dynamic_update_slice(cache["k"], k_new.astype(cache["k"].dtype),
                                     (0, 0, start, 0))
    v_buf = lax.dynamic_update_slice(cache["v"], v_new.astype(cache["v"].dtype),
                                     (0, 0, start, 0))
    s = jnp.einsum("bhtd,bhkd->bhtk", q, k_buf,
                   preferred_element_type=jnp.float32) * scale
    # visibility: cached prefix [0, start) plus the causal part of the new
    # block [start, start+t)
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    row_pos = start + lax.broadcasted_iota(jnp.int32, (t, max_len), 0)
    visible = key_pos <= row_pos
    s = jnp.where(visible[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhtk,bhkd->bhtd", p.astype(v_buf.dtype), v_buf,
                     preferred_element_type=jnp.float32)
    new_cache = {"k": k_buf, "v": v_buf, "length": start + t}
    return ctx.astype(q.dtype), new_cache


def _decode_loop(step_fn, params, cache, prompt_last_token,
                 max_new_tokens, eos_id, select_fn, xs) -> jax.Array:
    """Shared scan scaffolding for greedy/sampled decoding: feed a token,
    select the next via ``select_fn(logits, x)``, force eos on finished
    rows. One dispatch for the whole sequence."""

    def body(carry, x):
        token, cache, done = carry
        logits, cache = step_fn(params, token, cache)
        nxt = select_fn(logits, x).astype(token.dtype)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, token.dtype), nxt)
            done = done | (nxt == eos_id)
        return (nxt, cache, done), nxt

    done0 = jnp.zeros(prompt_last_token.shape, bool)
    (_, _, _), tokens = lax.scan(
        body, (prompt_last_token, cache, done0), xs,
        length=None if xs is not None else max_new_tokens)
    return jnp.swapaxes(tokens, 0, 1)  # [B, max_new]


def greedy_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Single-dispatch greedy decoding loop.

    ``step_fn(params, token [B], cache) -> (logits [B, V], cache)`` is the
    user's per-token forward (typically built on :func:`cached_attention`).
    Each scan step FEEDS a token — i.e. appends its K/V and predicts the
    next — so prefill the prompt EXCLUDING its last token and pass that
    last token here; prefilling the whole prompt would insert the final
    token's K/V twice. The loop runs as ONE ``lax.scan`` of
    ``max_new_tokens`` steps; with ``eos_id``, finished rows keep emitting
    ``eos_id`` (output length stays static — XLA-friendly).

    Returns generated tokens ``[B, max_new_tokens]``.
    """
    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id,
                        lambda logits, _: jnp.argmax(logits, axis=-1), None)


def beam_generate(step_fn: Callable, params: Any, cache: Any,
                  prompt_last_token: jax.Array, max_new_tokens: int,
                  beam_size: int, eos_id: Optional[int] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decoding, one ``lax.scan`` dispatch.

    Same ``step_fn(params, token [N], cache) -> (logits [N, V], cache)``
    contract as :func:`greedy_generate`, where N is ``batch * beam_size``
    after tiling. Cache leaves whose leading axis equals the batch size are
    tiled ``beam_size``-fold and reordered by backpointer every step; a
    finished beam (emitted ``eos_id``) keeps its score and pads with eos.

    Returns ``(sequences [B, beam, max_new], scores [B, beam])`` sorted
    best-first by accumulated log-probability.
    """
    b = prompt_last_token.shape[0]
    k = beam_size

    def tile(a):
        if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b:
            return jnp.repeat(a, k, axis=0)
        return a

    caches = jax.tree_util.tree_map(tile, cache)
    tokens = jnp.repeat(prompt_last_token[:, None], k, axis=1)  # [B, K]
    # only beam 0 is live initially so the first expansion picks the top-k
    # distinct continuations instead of k copies of the argmax
    scores = jnp.tile(jnp.asarray([0.0] + [_NEG_INF] * (k - 1)), (b, 1))
    done = jnp.zeros((b, k), bool)
    seqbuf = jnp.zeros((b, k, max_new_tokens), prompt_last_token.dtype)

    def body(carry, i):
        tokens, scores, done, seqbuf, caches = carry
        logits, caches = step_fn(params, tokens.reshape(b * k), caches)
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1).reshape(b, k, v)
        if eos_id is not None:
            # a finished beam may only "continue" with eos at zero cost
            eos_row = jnp.full((v,), _NEG_INF).at[eos_id].set(0.0)
            logp = jnp.where(done[..., None], eos_row[None, None], logp)
        cand = (scores[..., None] + logp).reshape(b, k * v)
        scores, idx = lax.top_k(cand, k)                   # [B, K]
        parent = idx // v
        token = (idx % v).astype(tokens.dtype)

        def reorder(a):
            if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b * k:
                ak = a.reshape((b, k) + a.shape[1:])
                sel = jnp.take_along_axis(
                    ak, parent.reshape((b, k) + (1,) * (a.ndim - 1)), axis=1)
                return sel.reshape((b * k,) + a.shape[1:])
            return a

        caches = jax.tree_util.tree_map(reorder, caches)
        seqbuf = jnp.take_along_axis(seqbuf, parent[..., None], axis=1)
        seqbuf = lax.dynamic_update_slice(
            seqbuf, token[..., None], (0, 0, i))
        done = jnp.take_along_axis(done, parent, axis=1)
        if eos_id is not None:
            done = done | (token == eos_id)
        return (token, scores, done, seqbuf, caches), None

    (tokens, scores, done, seqbuf, caches), _ = lax.scan(
        body, (tokens, scores, done, seqbuf, caches),
        jnp.arange(max_new_tokens))
    return seqbuf, scores


def sample_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    rng: jax.Array, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Stochastic decoding (temperature / top-k / nucleus), one scan
    dispatch — same ``step_fn`` contract as :func:`greedy_generate`.

    Filters compose in the standard order: temperature scales logits,
    ``top_k`` keeps the k highest, ``top_p`` keeps the smallest prefix of
    the sorted distribution with cumulative probability >= top_p; sampling
    renormalizes over what survives. Finished rows keep emitting
    ``eos_id``.
    """
    if temperature <= 0:
        raise ValueError("temperature must be > 0 (use greedy_generate "
                         "for deterministic argmax decoding)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k} "
                         "(pass top_k=None to disable)")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p} "
                         "(pass top_p=None to disable)")

    def filter_logits(logits):
        logits = logits / temperature
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
        if top_p is not None:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix reaching top_p (always >= 1 token)
            cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1,
                                 keepdims=True) - 1
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, _NEG_INF, logits)
        return logits

    def select(logits, step_rng):
        return jax.random.categorical(
            step_rng, filter_logits(logits.astype(jnp.float32)), axis=-1)

    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id, select,
                        jax.random.split(rng, max_new_tokens))
