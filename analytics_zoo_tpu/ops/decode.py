"""Incremental (KV-cached) attention decoding.

New TPU-native capability rounding out the long-context stack: training
runs the flash kernels (``ops/attention.py``), generation runs this cache.
The reference's only generation path is the host-side RNN loop in Seq2seq
(``models/seq2seq``); transformer decoding needs the KV cache to avoid
re-attending the whole prefix per step.

Design for XLA: the cache is a STATIC ``max_len`` buffer pair updated with
``lax.dynamic_update_slice`` — shapes never change, so the per-step program
compiles once; validity is a position mask derived from ``length``. The
whole generate loop is one ``lax.scan`` (single dispatch per sequence, the
only pattern that amortizes dispatch latency on remote-attached chips).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _NEG_INF, masked_context
from .int8_dataflow import next_amax, quant_int8, scale_of_amax

KVCache = Dict[str, Any]


def init_kv_cache(batch: int, heads: int, max_len: int, head_dim: int,
                  dtype=jnp.bfloat16) -> KVCache:
    """Empty cache: K/V buffers ``[B, H, max_len, D]`` + write position."""
    return {
        "k": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "v": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                     cache: KVCache, scale: Optional[float] = None
                     ) -> Tuple[jax.Array, KVCache]:
    """Append ``k_new``/``v_new`` (``[B, H, T, D]``, T = 1 for decode or the
    prompt length for prefill) at the cache's write position, then attend
    ``q`` against everything cached so far, causally within the new block.

    Returns ``(context [B, H, T, D], updated cache)``. jit-safe: static
    shapes, the step count lives in ``cache["length"]``.
    """
    b, h, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    start = cache["length"]
    # capacity guard: under eager execution (concrete length) overflowing
    # the static buffer raises here; under jit ``length`` is a Tracer so
    # this check SILENTLY SKIPS — the caller owns the budget (max_len -
    # length tokens remain) and overflow would silently corrupt the tail.
    # Use :func:`checked_cached_attention` where the write position is
    # traced and a runtime-checkable guard is wanted.
    import jax.core as _core
    if not isinstance(start, _core.Tracer) and int(start) + t > max_len:
        raise ValueError(
            f"KV cache overflow: writing {t} tokens at position "
            f"{int(start)} exceeds max_len={max_len}")
    k_buf = lax.dynamic_update_slice(cache["k"], k_new.astype(cache["k"].dtype),
                                     (0, 0, start, 0))
    v_buf = lax.dynamic_update_slice(cache["v"], v_new.astype(cache["v"].dtype),
                                     (0, 0, start, 0))
    # visibility: cached prefix [0, start) plus the causal part of the new
    # block [start, start+t)
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    row_pos = start + lax.broadcasted_iota(jnp.int32, (t, max_len), 0)
    visible = key_pos <= row_pos
    ctx = masked_context(q, k_buf, v_buf, visible[None, None], scale)
    new_cache = {"k": k_buf, "v": v_buf, "length": start + t}
    return ctx, new_cache


def checked_cached_attention(q: jax.Array, k_new: jax.Array,
                             v_new: jax.Array, cache: KVCache,
                             scale: Optional[float] = None
                             ) -> Tuple[jax.Array, KVCache]:
    """:func:`cached_attention` with a RUNTIME-checkable capacity guard.

    The eager guard in :func:`cached_attention` is skipped whenever
    ``cache["length"]`` is a tracer (i.e. under ``jit`` — exactly where
    every production decode loop runs), so an overflowing write silently
    wraps into ``dynamic_update_slice``'s clamped behavior and corrupts
    the newest cache tail. This variant stages a ``checkify`` predicate
    that travels THROUGH jit and fires at runtime with the offending
    position. Use it by functionalizing the error with
    ``jax.experimental.checkify``::

        from jax.experimental import checkify
        step = jax.jit(checkify.checkify(decode_step))
        err, (ctx, cache) = step(q, k_new, v_new, cache)
        err.throw()   # raises on overflow, no-op otherwise

    The check is metadata riding the jitted program — the decode math and
    cache layout are bit-identical to :func:`cached_attention`.
    """
    from jax.experimental import checkify
    t = q.shape[2]
    max_len = cache["k"].shape[2]
    checkify.check(
        cache["length"] + t <= max_len,
        "KV cache overflow: writing {t} tokens at position {start} "
        "exceeds max_len={max_len}",
        t=jnp.asarray(t, jnp.int32), start=cache["length"],
        max_len=jnp.asarray(max_len, jnp.int32))
    return cached_attention(q, k_new, v_new, cache, scale)


# -- slot-based cache for continuous batching -------------------------------
#
# The generative scheduler (serving/server.py GenerativeServing) keeps S
# independent streams resident in ONE device-shaped cache so a single fused
# step advances every occupied slot. All shapes are static: joining,
# stepping and evicting only move traced indices/masks around, so the step
# program compiles exactly once (plus one prefill program per length
# bucket) no matter how streams come and go.

SlotCache = Dict[str, Any]


def init_slot_cache(slots: int, heads: int, max_len: int, head_dim: int,
                    dtype=jnp.float32) -> SlotCache:
    """Per-block K/V buffers ``[S, H, max_len, D]`` for S decode slots.

    Unlike :func:`init_kv_cache` there is no scalar write position: slots
    advance independently, so per-slot lengths live in the scheduler-wide
    slot STATE (:func:`init_slot_state`) shared across blocks."""
    return {"k": jnp.zeros((slots, heads, max_len, head_dim), dtype),
            "v": jnp.zeros((slots, heads, max_len, head_dim), dtype)}


def init_slot_state(slots: int) -> Dict[str, jax.Array]:
    """Scheduler-wide occupancy: per-slot fed-token counts + active mask."""
    return {"length": jnp.zeros((slots,), jnp.int32),
            "active": jnp.zeros((slots,), bool)}


def slot_join(state: Dict[str, jax.Array], slot, length
              ) -> Dict[str, jax.Array]:
    """Mark ``slot`` occupied with ``length`` tokens already fed. Both
    arguments may be traced values — joins never trigger a recompile."""
    length = jnp.asarray(length, jnp.int32)
    return {"length": state["length"].at[slot].set(length),
            "active": state["active"].at[slot].set(True)}


def slot_evict(state: Dict[str, jax.Array], mask) -> Dict[str, jax.Array]:
    """Vacate every slot where ``mask`` [S] is True — one vectorized call
    evicts any number of finished/expired slots per step."""
    mask = jnp.asarray(mask)
    return {"length": jnp.where(mask, 0, state["length"]),
            "active": state["active"] & ~mask}


def slot_insert(cache: SlotCache, slot, k_new: jax.Array, v_new: jax.Array
                ) -> SlotCache:
    """Write a prefilled K/V block ``[H, T, D]`` into ``slot`` at position
    0. ``slot`` may be traced; T is static (length-bucketed by the caller)
    so one compile per bucket covers every join at that bucket."""
    k_buf = lax.dynamic_update_slice(
        cache["k"], k_new[None].astype(cache["k"].dtype), (slot, 0, 0, 0))
    v_buf = lax.dynamic_update_slice(
        cache["v"], v_new[None].astype(cache["v"].dtype), (slot, 0, 0, 0))
    return {"k": k_buf, "v": v_buf}


def slot_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                   cache: SlotCache, lengths: jax.Array,
                   scale: Optional[float] = None
                   ) -> Tuple[jax.Array, SlotCache]:
    """One decode step over ALL slots: write each slot's new K/V at its own
    ``lengths[s]`` position, then attend each slot's query against its
    visible prefix. Mirrors :func:`cached_attention` arithmetic exactly —
    same contractions, mask and softmax — which is what keeps slot-batched
    token streams bit-identical to serial decode rows.

    ``q``/``k_new``/``v_new``: ``[S, H, 1, D]``; ``lengths``: [S] int32
    (tokens fed so far = this step's write position). Returns
    ``(ctx [S, H, 1, D], updated cache)``; the CALLER advances lengths once
    after every block has attended (all blocks see pre-increment lengths).
    """
    _, _, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    write = jax.vmap(
        lambda buf, new, pos: lax.dynamic_update_slice(buf, new,
                                                       (0, pos, 0)))
    k_buf = write(cache["k"], k_new.astype(cache["k"].dtype), lengths)
    v_buf = write(cache["v"], v_new.astype(cache["v"].dtype), lengths)
    # visibility per slot: prefix [0, length] inclusive — the just-written
    # position IS visible, exactly as cached_attention's t=1 decode row
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    visible = key_pos[None] <= lengths[:, None, None]   # [S, 1, max_len]
    ctx = masked_context(q, k_buf, v_buf, visible[:, None], scale)
    return ctx, {"k": k_buf, "v": v_buf}


# -- paged KV cache (block-granular allocation + per-slot page tables) ------
#
# The slot engine above reserves a contiguous [S, H, max_len, D] rectangle
# per block: HBM pays for max_len whether a stream uses it or not. The paged
# engine (vLLM's PagedAttention transplanted onto the traced-index slot
# machinery) replaces the rectangles with ONE global pool of fixed-size
# pages [P, H, page_len, D] plus a per-slot page TABLE [S, W] of pool
# indices in logical order — a stream only holds the pages its prompt +
# budget actually need, and identical prompt prefixes can share refcounted
# pages (copy-on-write, managed by the scheduler in serving/server.py).
#
# Page 0 is the NULL page: never allocated to a stream, it absorbs the
# writes of inactive slots and of positions past a slot's allocation (the
# same way inactive slots harmlessly write into their own rectangle in the
# contiguous engine). Bit-identity with the slot engine holds because
# attention gathers a slot's pages back into logical [max_len] order and
# runs the SAME masked_context arithmetic: garbage beyond a slot's length —
# null-page junk here, stale rectangle tail there — is masked to exactly
# _NEG_INF and contributes exact-zero terms either way.
#
# All shapes are static: tables, lengths and page ids are DATA, so joins,
# evictions and CoW copies never recompile the step program. The int8
# variant stores the pool as int8 plus a per-token-position f32 scale
# ([P, page_len]) using the delayed-scaling recipe from ops/int8_dataflow
# (quantize with the RUNNING amax — no max pass on the decode hot path).

PagedCache = Dict[str, Any]


def init_paged_pool(num_pages: int, heads: int, page_len: int,
                    head_dim: int, dtype=jnp.float32,
                    int8: bool = False) -> PagedCache:
    """Global K/V page pool ``[P, H, page_len, D]`` (per transformer
    block). Page 0 is reserved as the null page — allocators hand out ids
    ``1..P-1``. With ``int8=True`` the pool stores int8 payloads plus a
    per-position f32 scale ``[P, page_len]`` and per-pool running amax
    scalars (delayed scaling, seeded at 1.0 so the cold-start scale is
    sane for layer-normed activations)."""
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (page 0 is the reserved "
                         f"null page), got {num_pages}")
    if page_len < 1:
        raise ValueError(f"page_len must be >= 1, got {page_len}")
    if int8:
        return {"k": jnp.zeros((num_pages, heads, page_len, head_dim),
                               jnp.int8),
                "v": jnp.zeros((num_pages, heads, page_len, head_dim),
                               jnp.int8),
                "scale_k": jnp.zeros((num_pages, page_len), jnp.float32),
                "scale_v": jnp.zeros((num_pages, page_len), jnp.float32),
                "amax_k": jnp.ones((), jnp.float32),
                "amax_v": jnp.ones((), jnp.float32)}
    return {"k": jnp.zeros((num_pages, heads, page_len, head_dim), dtype),
            "v": jnp.zeros((num_pages, heads, page_len, head_dim), dtype)}


#: mesh axis the paged pool's page dimension shards over
KV_SHARD_AXIS = "kv"


def shard_paged_pool(caches, n_shard: int,
                     axis_name: str = KV_SHARD_AXIS):
    """Spread each block's page pool across ``n_shard`` devices along the
    PAGE axis (contiguous blocks of ``num_pages/n_shard`` pages per
    device) — the sharded-KV serving tier for models whose cache exceeds
    one device's HBM budget.

    Pure placement, no program change: the decode step's page gather
    pulls each stream's pages to the compute device and the attention
    arithmetic runs on the gathered buffer exactly as it does over a
    single-device pool, so decoded tokens are bit-identical to
    ``n_shard=1`` (asserted by the serving parity tests). Scalars (int8
    running amax) stay replicated. Allocators should hand out pages
    round-robin across shards so writes spread evenly (serving/server.py
    does)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if n_shard < 1 or len(devs) % n_shard:
        raise ValueError(f"kv shard count {n_shard} must divide the local "
                         f"device count {len(devs)}")
    num_pages = caches[0]["k"].shape[0]
    if num_pages % n_shard:
        raise ValueError(f"num_pages {num_pages} must be divisible by the "
                         f"kv shard count {n_shard}")
    import numpy as _np
    # the mesh spans ALL local devices (jit needs one device set across
    # the pool, params, and tables); pages split over the first axis and
    # replicate over the remainder
    mesh = Mesh(_np.asarray(devs).reshape(n_shard, -1),
                (axis_name, "kv_repl"))

    def put(leaf):
        spec = (P(axis_name, *([None] * (leaf.ndim - 1)))
                if leaf.ndim >= 1 and leaf.shape[0] == num_pages else P())
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return [jax.tree_util.tree_map(put, c) for c in caches]


def page_table_set(table: jax.Array, slot, row: jax.Array) -> jax.Array:
    """Install ``row`` [W] as ``slot``'s page table. Both may be traced —
    joins never recompile."""
    return lax.dynamic_update_slice(table, row[None].astype(table.dtype),
                                    (slot, 0))


def page_table_clear(table: jax.Array, mask) -> jax.Array:
    """Zero (→ null page) every table row where ``mask`` [S] is True — the
    paged twin of :func:`slot_evict`, one vectorized call for any number
    of evictions."""
    return jnp.where(jnp.asarray(mask)[:, None], 0, table)


def page_copy(cache: PagedCache, src, dst) -> PagedCache:
    """Copy page ``src`` into page ``dst`` (copy-on-write: a stream that
    would append into a shared, partially-filled prefix tail page gets a
    private copy instead). Indices may be traced."""
    new = {"k": cache["k"].at[dst].set(cache["k"][src]),
           "v": cache["v"].at[dst].set(cache["v"][src])}
    if "scale_k" in cache:
        new["scale_k"] = cache["scale_k"].at[dst].set(cache["scale_k"][src])
        new["scale_v"] = cache["scale_v"].at[dst].set(cache["scale_v"][src])
        new["amax_k"] = cache["amax_k"]
        new["amax_v"] = cache["amax_v"]
    return new


def _page_positions(table: jax.Array, positions: jax.Array, page_len: int
                    ) -> Tuple[jax.Array, jax.Array]:
    """Map logical token ``positions`` [S, T] through per-slot ``table``
    [S, W] rows to (pool page ids, in-page offsets). Positions past a
    table's width land on the null page (id 0)."""
    w = table.shape[1]
    idx = positions // page_len
    page = jnp.take_along_axis(table, jnp.minimum(idx, w - 1), axis=1)
    page = jnp.where(idx < w, page, 0)
    return page, positions % page_len


def _paged_write(cache: PagedCache, pages: jax.Array, offs: jax.Array,
                 k_rows: jax.Array, v_rows: jax.Array,
                 inline_amax: bool) -> PagedCache:
    """Scatter token rows (``[..., H, D]``, leading dims matching
    ``pages``/``offs``) into the pool. int8 pools quantize on the way in:
    ``inline_amax=True`` (prefill/join path, off the token hot loop) folds
    the block's own amax into the scale; ``inline_amax=False`` (decode hot
    path) uses the DELAYED running scale — no max pass over the write."""
    if "scale_k" not in cache:
        return {"k": cache["k"].at[pages, :, offs, :].set(
                    k_rows.astype(cache["k"].dtype)),
                "v": cache["v"].at[pages, :, offs, :].set(
                    v_rows.astype(cache["v"].dtype))}
    kf = k_rows.astype(jnp.float32)
    vf = v_rows.astype(jnp.float32)
    seen_k = jnp.max(jnp.abs(kf))
    seen_v = jnp.max(jnp.abs(vf))
    amax_k = (jnp.maximum(cache["amax_k"], seen_k) if inline_amax
              else cache["amax_k"])
    amax_v = (jnp.maximum(cache["amax_v"], seen_v) if inline_amax
              else cache["amax_v"])
    sk = scale_of_amax(amax_k)
    sv = scale_of_amax(amax_v)
    return {"k": cache["k"].at[pages, :, offs, :].set(quant_int8(kf, sk)),
            "v": cache["v"].at[pages, :, offs, :].set(quant_int8(vf, sv)),
            "scale_k": cache["scale_k"].at[pages, offs].set(
                jnp.broadcast_to(sk, pages.shape)),
            "scale_v": cache["scale_v"].at[pages, offs].set(
                jnp.broadcast_to(sv, pages.shape)),
            "amax_k": next_amax(cache["amax_k"], seen_k),
            "amax_v": next_amax(cache["amax_v"], seen_v)}


def paged_gather(cache: PagedCache, table: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """Gather per-slot pages back into logical order: ``table`` [S, C] →
    K/V ``[S, H, C*page_len, D]`` (dequantized to f32 for int8 pools).
    This materializes the logical view as a TRANSIENT activation — the
    persistent HBM footprint is the pool; a production TPU kernel would
    fuse the gather into the attention read (pallas follow-up)."""
    k = jnp.take(cache["k"], table, axis=0)   # [S, C, H, page_len, D]
    v = jnp.take(cache["v"], table, axis=0)
    if "scale_k" in cache:
        sk = jnp.take(cache["scale_k"], table, axis=0)  # [S, C, page_len]
        sv = jnp.take(cache["scale_v"], table, axis=0)
        k = k.astype(jnp.float32) * sk[:, :, None, :, None]
        v = v.astype(jnp.float32) * sv[:, :, None, :, None]
    s, c, h, pl, d = k.shape
    k = k.transpose(0, 2, 1, 3, 4).reshape(s, h, c * pl, d)
    v = v.transpose(0, 2, 1, 3, 4).reshape(s, h, c * pl, d)
    return k, v


def paged_insert(cache: PagedCache, table_row: jax.Array, k_new: jax.Array,
                 v_new: jax.Array, start: int = 0) -> PagedCache:
    """Write a prefilled K/V block ``[H, T, D]`` into the pages named by
    ``table_row`` [W] at logical positions ``start..start+T-1`` — the
    paged twin of :func:`slot_insert`. T is static (length-bucketed), so
    one compile per bucket covers every join; positions past the row's
    width (bucket padding beyond the stream's allocation) fall onto the
    null page. ``start`` is a static offset for shared-prefix suffix
    prefills."""
    t = k_new.shape[1]
    positions = start + lax.broadcasted_iota(jnp.int32, (1, t), 1)
    pages, offs = _page_positions(table_row[None], positions,
                                  cache["k"].shape[2])
    return _paged_write(cache, pages, offs,
                        k_new.transpose(1, 0, 2)[None],
                        v_new.transpose(1, 0, 2)[None], inline_amax=True)


def paged_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                    cache: PagedCache, table: jax.Array,
                    lengths: jax.Array, max_len: int,
                    scale: Optional[float] = None
                    ) -> Tuple[jax.Array, PagedCache]:
    """One decode step over ALL slots through the page pool — the paged
    twin of :func:`slot_attention`, bit-identical to it: write each slot's
    new K/V at its own ``lengths[s]`` position (scattered to the owning
    page), gather the first ``max_len // page_len`` table columns back
    into a logical ``[S, H, max_len, D]`` view, then run the SAME
    :func:`~..attention.masked_context` arithmetic over the SAME key
    length and visibility mask.

    ``q``/``k_new``/``v_new``: ``[S, H, 1, D]``; ``lengths``: [S] int32.
    The caller advances lengths once after every block attended, exactly
    as with the contiguous engine."""
    _, _, t, d = q.shape
    page_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    pages, offs = _page_positions(table, lengths[:, None], page_len)
    cache = _paged_write(cache, pages, offs, k_new.transpose(0, 2, 1, 3),
                         v_new.transpose(0, 2, 1, 3), inline_amax=False)
    k_buf, v_buf = paged_gather(cache, table[:, :max_len // page_len])
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    visible = key_pos[None] <= lengths[:, None, None]   # [S, 1, max_len]
    ctx = masked_context(q, k_buf, v_buf, visible[:, None], scale)
    return ctx, cache


def paged_verify_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, cache: PagedCache,
                           table: jax.Array, lengths: jax.Array,
                           scale: Optional[float] = None
                           ) -> Tuple[jax.Array, PagedCache]:
    """Speculative VERIFY step: feed T = k+1 tokens per slot in one pass —
    write their K/V at logical positions ``lengths[s]..lengths[s]+T-1``
    (crossing page boundaries as needed; transient positions past the
    allocation fall onto the null page) and attend causally within the new
    block on top of each slot's visible prefix. Same masked_context
    arithmetic as everywhere else; the extra gathered slack columns past
    ``max_len`` are masked to exact zeros. Per-row contexts match serial
    decode rows to float-reduction tolerance (the T-batched matmul may
    vectorize differently than T=1), which is why speculative parity is a
    TOKEN-identity guarantee, not a bit-identity one.

    ``q``/``k_new``/``v_new``: ``[S, H, T, D]``. Lengths advance by the
    caller-side ACCEPTED count, not T — rejected positions hold stale K/V
    that the next round overwrites at the same positions."""
    _, _, t, d = q.shape
    page_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    positions = (lengths[:, None]
                 + lax.broadcasted_iota(jnp.int32, (q.shape[0], t), 1))
    pages, offs = _page_positions(table, positions, page_len)
    cache = _paged_write(cache, pages, offs, k_new.transpose(0, 2, 1, 3),
                         v_new.transpose(0, 2, 1, 3), inline_amax=False)
    k_buf, v_buf = paged_gather(cache, table)
    kcols = table.shape[1] * page_len
    key_pos = lax.broadcasted_iota(jnp.int32, (t, kcols), 1)
    row_pos = lax.broadcasted_iota(jnp.int32, (t, kcols), 0)
    visible = key_pos[None] <= lengths[:, None, None] + row_pos[None]
    ctx = masked_context(q, k_buf, v_buf, visible[:, None], scale)
    return ctx, cache


def _decode_loop(step_fn, params, cache, prompt_last_token,
                 max_new_tokens, eos_id, select_fn, xs) -> jax.Array:
    """Shared scan scaffolding for greedy/sampled decoding: feed a token,
    select the next via ``select_fn(logits, x)``, force eos on finished
    rows. One dispatch for the whole sequence."""

    def body(carry, x):
        token, cache, done = carry
        logits, cache = step_fn(params, token, cache)
        nxt = select_fn(logits, x).astype(token.dtype)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, token.dtype), nxt)
            done = done | (nxt == eos_id)
        return (nxt, cache, done), nxt

    done0 = jnp.zeros(prompt_last_token.shape, bool)
    (_, _, _), tokens = lax.scan(
        body, (prompt_last_token, cache, done0), xs,
        length=None if xs is not None else max_new_tokens)
    return jnp.swapaxes(tokens, 0, 1)  # [B, max_new]


def greedy_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Single-dispatch greedy decoding loop.

    ``step_fn(params, token [B], cache) -> (logits [B, V], cache)`` is the
    user's per-token forward (typically built on :func:`cached_attention`).
    Each scan step FEEDS a token — i.e. appends its K/V and predicts the
    next — so prefill the prompt EXCLUDING its last token and pass that
    last token here; prefilling the whole prompt would insert the final
    token's K/V twice. The loop runs as ONE ``lax.scan`` of
    ``max_new_tokens`` steps; with ``eos_id``, finished rows keep emitting
    ``eos_id`` (output length stays static — XLA-friendly).

    Returns generated tokens ``[B, max_new_tokens]``.
    """
    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id,
                        lambda logits, _: jnp.argmax(logits, axis=-1), None)


def beam_generate(step_fn: Callable, params: Any, cache: Any,
                  prompt_last_token: jax.Array, max_new_tokens: int,
                  beam_size: int, eos_id: Optional[int] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decoding, one ``lax.scan`` dispatch.

    Same ``step_fn(params, token [N], cache) -> (logits [N, V], cache)``
    contract as :func:`greedy_generate`, where N is ``batch * beam_size``
    after tiling. Cache leaves whose leading axis equals the batch size are
    tiled ``beam_size``-fold and reordered by backpointer every step; a
    finished beam (emitted ``eos_id``) keeps its score and pads with eos.

    Returns ``(sequences [B, beam, max_new], scores [B, beam])`` sorted
    best-first by accumulated log-probability.
    """
    b = prompt_last_token.shape[0]
    k = beam_size

    def tile(a):
        if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b:
            return jnp.repeat(a, k, axis=0)
        return a

    caches = jax.tree_util.tree_map(tile, cache)
    tokens = jnp.repeat(prompt_last_token[:, None], k, axis=1)  # [B, K]
    # only beam 0 is live initially so the first expansion picks the top-k
    # distinct continuations instead of k copies of the argmax
    scores = jnp.tile(jnp.asarray([0.0] + [_NEG_INF] * (k - 1)), (b, 1))
    done = jnp.zeros((b, k), bool)
    seqbuf = jnp.zeros((b, k, max_new_tokens), prompt_last_token.dtype)

    def body(carry, i):
        tokens, scores, done, seqbuf, caches = carry
        logits, caches = step_fn(params, tokens.reshape(b * k), caches)
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1).reshape(b, k, v)
        if eos_id is not None:
            # a finished beam may only "continue" with eos at zero cost
            eos_row = jnp.full((v,), _NEG_INF).at[eos_id].set(0.0)
            logp = jnp.where(done[..., None], eos_row[None, None], logp)
        cand = (scores[..., None] + logp).reshape(b, k * v)
        scores, idx = lax.top_k(cand, k)                   # [B, K]
        parent = idx // v
        token = (idx % v).astype(tokens.dtype)

        def reorder(a):
            if hasattr(a, "ndim") and a.ndim > 0 and a.shape[0] == b * k:
                ak = a.reshape((b, k) + a.shape[1:])
                sel = jnp.take_along_axis(
                    ak, parent.reshape((b, k) + (1,) * (a.ndim - 1)), axis=1)
                return sel.reshape((b * k,) + a.shape[1:])
            return a

        caches = jax.tree_util.tree_map(reorder, caches)
        seqbuf = jnp.take_along_axis(seqbuf, parent[..., None], axis=1)
        seqbuf = lax.dynamic_update_slice(
            seqbuf, token[..., None], (0, 0, i))
        done = jnp.take_along_axis(done, parent, axis=1)
        if eos_id is not None:
            done = done | (token == eos_id)
        return (token, scores, done, seqbuf, caches), None

    (tokens, scores, done, seqbuf, caches), _ = lax.scan(
        body, (tokens, scores, done, seqbuf, caches),
        jnp.arange(max_new_tokens))
    return seqbuf, scores


def make_logit_filter(temperature: float = 1.0, top_k: Optional[int] = None,
                      top_p: Optional[float] = None
                      ) -> Callable[[jax.Array], jax.Array]:
    """Build the sampling logit filter shared by :func:`sample_generate`
    and the slot-batched generative scheduler (serving/server.py).

    Filters compose in the standard order: temperature scales logits,
    ``top_k`` keeps the k highest, ``top_p`` keeps the smallest prefix of
    the sorted distribution with cumulative probability >= top_p; sampling
    renormalizes over what survives. Both decode paths composing THIS
    filter (not a re-implementation) is part of what keeps slot-batched
    sampled streams bit-identical to serial runs.
    """
    if temperature <= 0:
        raise ValueError("temperature must be > 0 (use greedy_generate "
                         "for deterministic argmax decoding)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k} "
                         "(pass top_k=None to disable)")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p} "
                         "(pass top_p=None to disable)")

    def filter_logits(logits):
        logits = logits / temperature
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
        if top_p is not None:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix reaching top_p (always >= 1 token)
            cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1,
                                 keepdims=True) - 1
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, _NEG_INF, logits)
        return logits

    return filter_logits


def sample_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    rng: jax.Array, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Stochastic decoding (temperature / top-k / nucleus), one scan
    dispatch — same ``step_fn`` contract as :func:`greedy_generate`.
    Filter semantics: :func:`make_logit_filter`. Finished rows keep
    emitting ``eos_id``.
    """
    filter_logits = make_logit_filter(temperature, top_k, top_p)

    def select(logits, step_rng):
        return jax.random.categorical(
            step_rng, filter_logits(logits.astype(jnp.float32)), axis=-1)

    return _decode_loop(step_fn, params, cache, prompt_last_token,
                        max_new_tokens, eos_id, select,
                        jax.random.split(rng, max_new_tokens))


# -- speculative decoding (draft proposes k, target verifies in one pass) ---
#
# Leviathan et al.: the decode step is memory-bandwidth-bound, so a small
# DRAFT model proposes k tokens serially and the TARGET verifies all k in
# ONE batched pass through its (paged) cache — one target dispatch emits
# between 1 and k+1 tokens. The accept/resample rule preserves the target
# distribution exactly; with greedy decoding it degenerates to "accept
# while the draft matches the target argmax", which makes speculative
# greedy TOKEN-IDENTICAL to serial greedy (the parity anchor the tests
# hold). Rejected draft positions leave stale K/V past the accepted
# length — invisible under the length mask and overwritten at the same
# positions next round, so the cache never needs a rollback copy.


def spec_accept_greedy(drafts: jax.Array, target_logits: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
    """Greedy accept rule. ``drafts`` [S, k] are the draft proposals;
    ``target_logits`` [S, k+1, V] are the verify-pass logits (row j
    predicts the token AFTER feeding draft j). Returns ``(emitted [S,
    k+1], n [S])``: the target argmax row per position and how many lead
    entries are valid — ``n = 1 + (leading draft/argmax matches)``, so a
    fully-accepted round emits k+1 tokens (the free "bonus" token)."""
    g = jnp.argmax(target_logits, axis=-1)              # [S, k+1]
    match = (drafts == g[:, :-1]).astype(jnp.int32)
    lead = jnp.cumprod(match, axis=1)
    n = 1 + jnp.sum(lead, axis=1)
    return g, n


def _spec_accept_sampled(drafts, draft_logits, target_logits, key,
                         filter_logits):
    """Standard stochastic accept/resample rule: accept draft token d_i
    with probability min(1, p_i(d_i)/q_i(d_i)); at the first rejection
    resample from norm(max(p - q, 0)); when every draft survives, sample
    the bonus token from the target's k-th distribution (q := 0 there, so
    the residual IS p). Output-distribution-preserving, not run-identical
    to a serial sampled run (different rng consumption)."""
    s, k = drafts.shape
    p = jax.nn.softmax(filter_logits(target_logits.astype(jnp.float32)),
                       axis=-1)                          # [S, k+1, V]
    q = jax.nn.softmax(filter_logits(draft_logits.astype(jnp.float32)),
                       axis=-1)                          # [S, k, V]
    pd = jnp.take_along_axis(p[:, :k], drafts[..., None], axis=-1)[..., 0]
    qd = jnp.take_along_axis(q, drafts[..., None], axis=-1)[..., 0]
    key_u, key_x = jax.random.split(key)
    u = jax.random.uniform(key_u, (s, k))
    accept = (u * qd < pd).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)     # [S] in [0, k]
    q_pad = jnp.concatenate([q, jnp.zeros_like(p[:, :1])], axis=1)
    sel = m[:, None, None]
    pm = jnp.take_along_axis(p, jnp.broadcast_to(sel, (s, 1, p.shape[-1])),
                             axis=1)[:, 0]               # p_{m}  [S, V]
    qm = jnp.take_along_axis(q_pad,
                             jnp.broadcast_to(sel, (s, 1, p.shape[-1])),
                             axis=1)[:, 0]
    resid = jnp.maximum(pm - qm, 0.0)
    total = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(total > 0, resid, pm)  # p == q: residual undefined
    x = jax.random.categorical(
        key_x, jnp.where(resid > 0, jnp.log(resid), _NEG_INF), axis=-1)
    j = lax.broadcasted_iota(jnp.int32, (s, k + 1), 1)
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((s, 1), drafts.dtype)], axis=1)
    emitted = jnp.where(j < m[:, None], drafts_pad,
                        jnp.where(j == m[:, None], x[:, None].astype(
                            drafts.dtype), 0))
    return emitted, m + 1


def speculative_generate(draft_step_fn: Callable, verify_fn: Callable,
                         draft_params: Any, target_params: Any,
                         draft_cache: Any, target_cache: Any,
                         prompt_last_token: jax.Array, lengths: jax.Array,
                         max_new_tokens: int, spec_k: int,
                         eos_id: Optional[int] = None,
                         rng: Optional[jax.Array] = None,
                         temperature: float = 1.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> jax.Array:
    """Speculative decoding driver: one ``lax.scan`` of at most
    ``max_new_tokens`` rounds, each round = ``spec_k`` serial DRAFT steps
    + ONE batched target VERIFY + vectorized accept.

    Contracts (lengths are PER-ROW, slot/paged style):

    - ``draft_step_fn(draft_params, tokens [B], lengths [B], draft_cache)
      -> (logits [B, V], draft_cache)``
    - ``verify_fn(target_params, block [B, k+1], lengths [B],
      target_cache) -> (logits [B, k+1, V], target_cache)``

    Greedy when ``rng is None`` (token-identical to serial greedy);
    otherwise samples with the standard accept/resample rule through the
    shared :func:`make_logit_filter` chain. Finished rows (eos / budget)
    freeze and the output pads with ``eos_id``. Returns ``[B,
    max_new_tokens]``."""
    b = prompt_last_token.shape[0]
    sampling = rng is not None
    filter_logits = (make_logit_filter(temperature, top_k, top_p)
                     if sampling else None)

    def round_body(carry, key):
        last, lengths, dcache, tcache, out, cursor, done = carry
        if sampling:
            subkeys = jax.random.split(key, spec_k + 1)

        def draft_body(c, i):
            tok, ln, dc = c
            logits, dc = draft_step_fn(draft_params, tok, ln, dc)
            if sampling:
                nxt = jax.random.categorical(
                    subkeys[i], filter_logits(logits.astype(jnp.float32)),
                    axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            nxt = nxt.astype(tok.dtype)
            return (nxt, ln + 1, dc), (nxt, logits)

        (_, _, dcache), (drafts, dlogits) = lax.scan(
            draft_body, (last, lengths, dcache), jnp.arange(spec_k))
        drafts = jnp.swapaxes(drafts, 0, 1)              # [B, k]
        block = jnp.concatenate([last[:, None], drafts], axis=1)
        tlogits, tcache = verify_fn(target_params, block, lengths, tcache)
        if sampling:
            emitted, n = _spec_accept_sampled(
                drafts, jnp.swapaxes(dlogits, 0, 1), tlogits,
                subkeys[spec_k], filter_logits)
        else:
            emitted, n = spec_accept_greedy(drafts, tlogits)
        emitted = emitted.astype(last.dtype)
        n = jnp.where(done, 0, n)
        n = jnp.minimum(n, max_new_tokens - cursor)       # budget clamp
        j = lax.broadcasted_iota(jnp.int32, (b, spec_k + 1), 1)
        if eos_id is not None:
            iseos = (emitted == eos_id) & (j < n[:, None])
            first = jnp.min(jnp.where(iseos, j, spec_k + 1), axis=1)
            n = jnp.minimum(n, first + 1)
            done = done | jnp.any(iseos, axis=1)
        valid = j < n[:, None]
        pos = jnp.where(valid, cursor[:, None] + j, max_new_tokens)
        rows = lax.broadcasted_iota(jnp.int32, (b, spec_k + 1), 0)
        out = out.at[rows, pos].set(emitted, mode="drop")
        last = jnp.where(
            n > 0,
            jnp.take_along_axis(emitted, jnp.maximum(n - 1, 0)[:, None],
                                axis=1)[:, 0],
            last)
        lengths = lengths + n
        cursor = cursor + n
        done = done | (cursor >= max_new_tokens)
        return (last, lengths, dcache, tcache, out, cursor, done), n

    fill = eos_id if eos_id is not None else 0
    out0 = jnp.full((b, max_new_tokens), fill, prompt_last_token.dtype)
    carry0 = (prompt_last_token, jnp.asarray(lengths, jnp.int32),
              draft_cache, target_cache, out0,
              jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool))
    xs = (jax.random.split(rng, max_new_tokens) if sampling
          else jnp.zeros((max_new_tokens,), jnp.uint32))
    (_, _, _, _, out, _, _), _ = lax.scan(round_body, carry0, xs)
    return out
