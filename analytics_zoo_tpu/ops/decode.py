"""Incremental (KV-cached) attention decoding.

New TPU-native capability rounding out the long-context stack: training
runs the flash kernels (``ops/attention.py``), generation runs this cache.
The reference's only generation path is the host-side RNN loop in Seq2seq
(``models/seq2seq``); transformer decoding needs the KV cache to avoid
re-attending the whole prefix per step.

Design for XLA: the cache is a STATIC ``max_len`` buffer pair updated with
``lax.dynamic_update_slice`` — shapes never change, so the per-step program
compiles once; validity is a position mask derived from ``length``. The
whole generate loop is one ``lax.scan`` (single dispatch per sequence, the
only pattern that amortizes dispatch latency on remote-attached chips).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _NEG_INF

KVCache = Dict[str, Any]


def init_kv_cache(batch: int, heads: int, max_len: int, head_dim: int,
                  dtype=jnp.bfloat16) -> KVCache:
    """Empty cache: K/V buffers ``[B, H, max_len, D]`` + write position."""
    return {
        "k": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "v": jnp.zeros((batch, heads, max_len, head_dim), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                     cache: KVCache, scale: Optional[float] = None
                     ) -> Tuple[jax.Array, KVCache]:
    """Append ``k_new``/``v_new`` (``[B, H, T, D]``, T = 1 for decode or the
    prompt length for prefill) at the cache's write position, then attend
    ``q`` against everything cached so far, causally within the new block.

    Returns ``(context [B, H, T, D], updated cache)``. jit-safe: static
    shapes, the step count lives in ``cache["length"]``.
    """
    b, h, t, d = q.shape
    max_len = cache["k"].shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    start = cache["length"]
    # capacity guard: under eager execution (concrete length) overflowing
    # the static buffer raises here; under jit the caller owns the budget
    # (max_len - length tokens remain) — overflow would silently corrupt
    import jax.core as _core
    if not isinstance(start, _core.Tracer) and int(start) + t > max_len:
        raise ValueError(
            f"KV cache overflow: writing {t} tokens at position "
            f"{int(start)} exceeds max_len={max_len}")
    k_buf = lax.dynamic_update_slice(cache["k"], k_new.astype(cache["k"].dtype),
                                     (0, 0, start, 0))
    v_buf = lax.dynamic_update_slice(cache["v"], v_new.astype(cache["v"].dtype),
                                     (0, 0, start, 0))
    s = jnp.einsum("bhtd,bhkd->bhtk", q, k_buf,
                   preferred_element_type=jnp.float32) * scale
    # visibility: cached prefix [0, start) plus the causal part of the new
    # block [start, start+t)
    key_pos = lax.broadcasted_iota(jnp.int32, (t, max_len), 1)
    row_pos = start + lax.broadcasted_iota(jnp.int32, (t, max_len), 0)
    visible = key_pos <= row_pos
    s = jnp.where(visible[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhtk,bhkd->bhtd", p.astype(v_buf.dtype), v_buf,
                     preferred_element_type=jnp.float32)
    new_cache = {"k": k_buf, "v": v_buf, "length": start + t}
    return ctx.astype(q.dtype), new_cache


def greedy_generate(step_fn: Callable, params: Any, cache: Any,
                    prompt_last_token: jax.Array, max_new_tokens: int,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Single-dispatch greedy decoding loop.

    ``step_fn(params, token [B], cache) -> (logits [B, V], cache)`` is the
    user's per-token forward (typically built on :func:`cached_attention`).
    Each scan step FEEDS a token — i.e. appends its K/V and predicts the
    next — so prefill the prompt EXCLUDING its last token and pass that
    last token here; prefilling the whole prompt would insert the final
    token's K/V twice. The loop runs as ONE ``lax.scan`` of
    ``max_new_tokens`` steps; with ``eos_id``, finished rows keep emitting
    ``eos_id`` (output length stays static — XLA-friendly).

    Returns generated tokens ``[B, max_new_tokens]``.
    """

    def body(carry, _):
        token, cache, done = carry
        logits, cache = step_fn(params, token, cache)
        nxt = jnp.argmax(logits, axis=-1).astype(token.dtype)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, token.dtype), nxt)
            done = done | (nxt == eos_id)
        return (nxt, cache, done), nxt

    done0 = jnp.zeros(prompt_last_token.shape, bool)
    (_, _, _), tokens = lax.scan(
        body, (prompt_last_token, cache, done0), None,
        length=max_new_tokens)
    return jnp.swapaxes(tokens, 0, 1)  # [B, max_new]
