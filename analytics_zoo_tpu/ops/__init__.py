"""TPU hot-op kernels (pallas) with XLA fallbacks, plus the ops plane.

The reference's hot loops are MKL kernels inside BigDL layers and TF JNI
``Session.run`` (SURVEY §3.2/§3.3). Here the hot ops are implemented directly
for the TPU: pallas kernels where hand-tiling beats XLA fusion (attention),
plain jnp everywhere XLA already does the right thing.

The package also hosts the **operational plane** (stdlib-only, imported
explicitly rather than re-exported here): :mod:`.events` (structured
event log), :mod:`.history` (metric history sampler), :mod:`.alerts`
(multi-window burn-rate SLO rules), :mod:`.incident` (incident bundles +
timelines), and the ``python -m analytics_zoo_tpu.ops`` incident CLI.
"""
from .attention import (  # noqa: F401
    dot_product_attention,
    blockwise_attention,
    flash_attention,
    flash_attention_lse,
)
from .decode import (  # noqa: F401
    beam_generate,
    cached_attention,
    greedy_generate,
    init_kv_cache,
    sample_generate,
)
from .embedding_kernels import (  # noqa: F401
    fused_enabled,
    gather_pool,
    gather_pool_int8,
    gather_rows,
    gather_rows_clip,
    int8_error_bound,
    multi_table_lookup,
    quantize_table,
    scatter_rows,
    segment_grads,
)
