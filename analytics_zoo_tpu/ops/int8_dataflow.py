"""Quantized-DATAFLOW int8 ResNet backbone — int8 tensors BETWEEN layers.

Round-4 measured that inserting int8 inside individual convs is
byte-NEGATIVE on a memory-bound ResNet (82.8GB/step vs 77.2 bf16): the
dynamic-quantize max pass re-reads the bf16 activation and BN still
materializes bf16. The win requires the int8 tensor to be what FLOWS —
this module implements that:

- every inter-layer activation is an ``int8`` array + a host-level delayed
  scale (updated from the previous step's amax, the FP8 "delayed scaling"
  recipe — no extra max pass over the tensor in the hot loop);
- conv consumes int8 and runs on the int8 MXU path (int32 accumulation,
  2x the bf16 peak on v5e); its f32 result is quantized to int8 *in the
  conv's output fusion* (elementwise, delayed per-channel scale), with the
  batch-norm statistics and the amax riding the same multi-output fusion —
  the f32/bf16 tensor never reaches HBM;
- BN apply + relu reads the int8 pre-activation and writes the int8 output
  (1 byte in, 1 byte out where the bf16 flow moves 2+2);
- residual adds dequantize → add → requantize in one fused elementwise op.

Autodiff: int8 graph edges carry no JAX cotangents, so the WHOLE backbone
is one ``custom_vjp`` with a hand-written backward walking a residual tape
in reverse (straight-through estimator through every quantizer; BN backward
in closed form; dgrad/wgrad via ``jax.linear_transpose`` of the bf16 conv —
no wasted primal evaluation). Gradients stay bf16; weight masters stay
f32/bf16. The saved activations are the int8 tensors themselves — half the
residual bytes of a bf16 save.

Reference parity: the reference's int8 story is OpenVINO inference-only
(``zoo/.../examples/vnni/openvino/Perf.scala``); int8 TRAINING dataflow is
a new TPU-native capability beyond it.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_EPS = 1e-5
_AMAX_DECAY = 0.99  # fast-rise / slow-decay running amax


# ---------------------------------------------------------------------------
# quantize helpers (elementwise — XLA fuses them into producer/consumer)
# ---------------------------------------------------------------------------


def _quant(f: jax.Array, scale: jax.Array) -> jax.Array:
    """Symmetric int8 with a DELAYED scale (scalar or per-channel [C] for
    NHWC). No max pass over ``f`` — clipping at +/-127 is absorbed by the
    running-amax update for the next step."""
    return jnp.clip(jnp.round(f / scale), -127, 127).astype(jnp.int8)


def _deq(q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _amax(f: jax.Array, per_channel: bool) -> jax.Array:
    a = jnp.abs(f.astype(jnp.float32))
    return jnp.max(a, axis=(0, 1, 2)) if per_channel else jnp.max(a)


def _next_amax(running: jax.Array, seen: jax.Array) -> jax.Array:
    return jnp.maximum(_AMAX_DECAY * running, seen)


def _scale_of(running_amax: jax.Array) -> jax.Array:
    return jnp.maximum(running_amax, 1e-6) / 127.0


#: Public aliases for the delayed-scaling recipe. The paged int8 KV cache
#: (``ops/decode.py``) reuses these on the bandwidth-bound decode read path:
#: same symmetric quantizer, same fast-rise/slow-decay running amax, applied
#: per cached token position instead of per inter-layer activation — so the
#: ResNet dataflow and the KV cache stay one quantization story.
quant_int8 = _quant
dequant_int8 = _deq
next_amax = _next_amax
scale_of_amax = _scale_of


def _quantize_weight_pc(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """HWIO kernel → per-O-channel symmetric int8 (computed per step from
    the float master; weight tensors are ~100x smaller than activations)."""
    wf = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=(0, 1, 2)), 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return q, s


def _conv_dims():
    return ("NHWC", "HWIO", "NHWC")


def _int8_conv(xq, wq, strides, padding):
    return lax.conv_general_dilated(
        xq, wq, window_strides=tuple(strides), padding=padding,
        dimension_numbers=_conv_dims(), preferred_element_type=jnp.int32)


def _bf16_conv(x, w, strides, padding):
    # uniformly bf16 in/out so jax.linear_transpose stays dtype-consistent;
    # the MXU accumulates bf16 dots in f32 internally regardless
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(strides), padding=padding,
        dimension_numbers=_conv_dims())


# ---------------------------------------------------------------------------
# per-op forward/backward pairs (the tape entries)
# ---------------------------------------------------------------------------
# Forward fns return (outputs..., residuals) with residuals a flat tuple of
# arrays; backward fns take (residuals, upstream bf16 cotangent wrt the
# DEQUANTIZED op output — STE through the output quantizer) and return the
# cotangent wrt the op's dequantized input plus param grads.


def _conv_bn_fwd(xq, sx, w, gamma, beta, s_mid_run, relu, strides, padding):
    """conv(int8) → [stats + quantize in the conv fusion] → BN apply + relu
    → int8 out. Returns (yq, aux, residuals)."""
    wq, sw = _quantize_weight_pc(w)
    acc = _int8_conv(xq, wq, strides, padding)
    f = acc.astype(jnp.float32) * (sx * sw)  # true conv output, per-channel
    mean = jnp.mean(f, axis=(0, 1, 2))
    var = jnp.maximum(jnp.mean(f * f, axis=(0, 1, 2)) - mean * mean, 0.0)
    amax_mid = _amax(f, per_channel=True)
    s_mid = _scale_of(s_mid_run)  # DELAYED: last step's running amax
    q_mid = _quant(f, s_mid)
    # apply pass: int8 in, int8 out (bf16 never stored)
    inv = lax.rsqrt(var + _EPS)
    fh = q_mid.astype(jnp.float32) * s_mid
    z = (fh - mean) * inv * gamma + beta
    y = jnp.maximum(z, 0.0) if relu else z
    amax_out = jnp.max(jnp.abs(y))
    residuals = (xq, sx, w, gamma, q_mid, s_mid, mean, inv)
    aux = (amax_mid, amax_out, mean, var)
    return y, aux, residuals


def _conv_bn_bwd(residuals, relu, strides, padding, yq, dy):
    """Closed-form BN backward + conv transposes. ``dy`` is bf16, the
    cotangent wrt the dequantized output (STE through the out-quantizer);
    the relu mask comes from the saved int8 output ``yq``."""
    xq, sx, w, gamma, q_mid, s_mid, mean, inv = residuals
    dz = dy.astype(jnp.float32)
    if relu:
        dz = dz * (yq > 0)
    fh = q_mid.astype(jnp.float32) * s_mid
    xhat = (fh - mean) * inv
    dgamma = jnp.sum(dz * xhat, axis=(0, 1, 2))
    dbeta = jnp.sum(dz, axis=(0, 1, 2))
    dxhat = dz * gamma
    df = inv * (dxhat - jnp.mean(dxhat, axis=(0, 1, 2))
                - xhat * jnp.mean(dxhat * xhat, axis=(0, 1, 2)))
    df = df.astype(jnp.bfloat16)
    x_deq = _deq(xq, sx)
    wb = w.astype(jnp.bfloat16)
    # linear_transpose: exact dgrad/wgrad without evaluating the primal
    dx = jax.linear_transpose(
        lambda t: _bf16_conv(t, wb, strides, padding), x_deq)(df)[0]
    dw = jax.linear_transpose(
        lambda t: _bf16_conv(x_deq, t, strides, padding), wb)(df)[0]
    return (dx, dw.astype(w.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


def _add_relu_fwd(aq, sa, bq, sb):
    y = aq.astype(jnp.float32) * sa + bq.astype(jnp.float32) * sb
    y = jnp.maximum(y, 0.0)
    return y, jnp.max(jnp.abs(y))


def _maxpool_q(q, window, strides, padding):
    """Max-pool directly on int8: max commutes with the (positive-scale)
    dequantize, so pooling the codes equals pooling the values."""
    return lax.reduce_window(
        q, jnp.int8(-128), lax.max, (1,) + tuple(window) + (1,),
        (1,) + tuple(strides) + (1,), padding)


def _maxpool_bwd(q, s, window, strides, padding, dy):
    """Gradient routing via the float maxpool's transpose on the dequantized
    input (select-and-scatter; the input read is the saved int8)."""
    x = _deq(q, s, jnp.float32)
    _, vjp = jax.vjp(
        lambda t: lax.reduce_window(
            t, -jnp.inf, lax.max, (1,) + tuple(window) + (1,),
            (1,) + tuple(strides) + (1,), padding), x)
    return vjp(dy.astype(jnp.float32))[0].astype(jnp.bfloat16)


# ---------------------------------------------------------------------------
# backbone builder
# ---------------------------------------------------------------------------

# canonical stage table lives with the model zoo; imported lazily so this
# op module never imports the models package at load time


class _ConvSpec:
    def __init__(self, name, k, cin, cout, stride, relu):
        self.name, self.k = name, k
        self.cin, self.cout = cin, cout
        self.stride, self.relu = stride, relu
        self.strides = (stride, stride)
        self.padding = "SAME"


def _resnet_plan(depth: int, in_channels: int = 3):
    """Static op plan: list of ('conv', spec) / ('pool',) / ('block', ...)
    entries the tape walker follows. Returns (plan, out_channels)."""
    from ..models.image.imageclassification import RESNET_BLOCKS
    if depth not in RESNET_BLOCKS:
        raise ValueError(f"unsupported depth {depth}")
    blocks = RESNET_BLOCKS[depth]
    bottleneck = depth >= 50
    plan: List[Tuple] = [("conv", _ConvSpec("stem", 7, in_channels, 64, 2,
                                            True)),
                         ("pool",)]
    c_in = 64
    filters = 64
    for stage, nblocks in enumerate(blocks):
        for i in range(nblocks):
            stride = 2 if (i == 0 and stage > 0) else 1
            nm = f"s{stage + 1}b{i + 1}"
            if bottleneck:
                convs = [_ConvSpec(f"{nm}_a", 1, c_in, filters, 1, True),
                         _ConvSpec(f"{nm}_b", 3, filters, filters, stride,
                                   True),
                         _ConvSpec(f"{nm}_c", 1, filters, filters * 4, 1,
                                   False)]
                c_out = filters * 4
            else:
                convs = [_ConvSpec(f"{nm}_a", 3, c_in, filters, stride,
                                   True),
                         _ConvSpec(f"{nm}_b", 3, filters, filters, 1,
                                   False)]
                c_out = filters
            short = (None if stride == 1 and c_in == c_out else
                     _ConvSpec(f"{nm}_sc", 1, c_in, c_out, stride, False))
            plan.append(("block", convs, short))
            c_in = c_out
        filters *= 2
    return plan, c_in


def _iter_convs(plan):
    for entry in plan:
        if entry[0] == "conv":
            yield entry[1]
        elif entry[0] == "block":
            for c in entry[1]:
                yield c
            if entry[2] is not None:
                yield entry[2]


class Int8ResNetDataflow:
    """Functional int8-dataflow ResNet backbone.

    ``init(rng)`` → (params, state); ``apply(params, state, x, training)``
    → (features bf16 [N,H',W',C'], new_state). Scales live in ``state`` as
    running amaxes (delayed scaling); BN running stats ride along for eval.
    """

    def __init__(self, depth: int = 50,
                 input_shape: Tuple[int, int, int] = (224, 224, 3)):
        self.depth = depth
        self.input_shape = tuple(input_shape)
        self.plan, self.out_channels = _resnet_plan(depth, input_shape[-1])
        self._train_fn = self._build_train_fn()

    # -- params / state -----------------------------------------------------

    def init(self, rng: jax.Array):
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {"in_amax": jnp.asarray(4.0, jnp.float32)}
        for spec in _iter_convs(self.plan):
            rng, k1 = jax.random.split(rng)
            fan_in = spec.k * spec.k * spec.cin
            params[spec.name] = {
                "kernel": (jax.random.normal(
                    k1, (spec.k, spec.k, spec.cin, spec.cout), jnp.float32)
                    * np.sqrt(2.0 / fan_in)),
                "gamma": jnp.ones((spec.cout,), jnp.float32),
                "beta": jnp.zeros((spec.cout,), jnp.float32),
            }
            state[spec.name] = {
                "mid_amax": jnp.full((spec.cout,), 8.0, jnp.float32),
                "out_amax": jnp.asarray(8.0, jnp.float32),
                "running_mean": jnp.zeros((spec.cout,), jnp.float32),
                "running_var": jnp.ones((spec.cout,), jnp.float32),
            }
        for entry in self.plan:
            if entry[0] == "block":
                nm = entry[1][0].name.rsplit("_", 1)[0]
                state[f"{nm}_add"] = {"out_amax": jnp.asarray(8.0,
                                                             jnp.float32)}
        return params, state

    # -- forward pieces shared by train fwd and eval ------------------------

    def _run_conv(self, params, state_in, name_updates, spec, xq, sx, tape,
                  training):
        """``state_in`` is always the PRE-step state (delayed scaling:
        this step quantizes with last step's running amaxes)."""
        p = params[spec.name]
        st = state_in[spec.name]
        if training:
            y, aux, res = _conv_bn_fwd(
                xq, sx, p["kernel"], p["gamma"], p["beta"], st["mid_amax"],
                spec.relu, spec.strides, spec.padding)
            amax_mid, amax_out, mean, var = aux
            s_out = _scale_of(st["out_amax"])
            yq = _quant(y, s_out)
            if tape is not None:
                tape.append((res, yq, s_out))
            name_updates[spec.name] = {
                "mid_amax": _next_amax(st["mid_amax"], amax_mid),
                "out_amax": _next_amax(st["out_amax"], amax_out),
                "running_mean": 0.9 * st["running_mean"] + 0.1 * mean,
                "running_var": 0.9 * st["running_var"] + 0.1 * var,
            }
            return yq, s_out
        # eval: running stats, same int8 flow
        wq, sw = _quantize_weight_pc(p["kernel"])
        acc = _int8_conv(xq, wq, spec.strides, spec.padding)
        f = acc.astype(jnp.float32) * (sx * sw)
        inv = lax.rsqrt(st["running_var"] + _EPS)
        z = (f - st["running_mean"]) * inv * p["gamma"] + p["beta"]
        y = jnp.maximum(z, 0.0) if spec.relu else z
        s_out = _scale_of(st["out_amax"])
        return _quant(y, s_out), s_out

    def _forward(self, params, state, x, training, tape):
        """Shared int8 walk. Returns (features, state_updates, tape)."""
        updates: Dict[str, Any] = {}
        s_in = _scale_of(state["in_amax"])
        if training:
            updates["in_amax"] = _next_amax(state["in_amax"],
                                            jnp.max(jnp.abs(x)))
        xq = _quant(x.astype(jnp.float32), s_in)
        if tape is not None:
            tape.append((jnp.zeros((0,), x.dtype),))  # input dtype proto
        sx = s_in
        for entry in self.plan:
            if entry[0] == "conv":
                xq, sx = self._run_conv(params, state, updates, entry[1],
                                        xq, sx, tape, training)
            elif entry[0] == "pool":
                if tape is not None:
                    tape.append((xq, sx))
                xq = _maxpool_q(xq, (3, 3), (2, 2), "SAME")
            else:  # residual block
                _, convs, short = entry
                nm = convs[0].name.rsplit("_", 1)[0]
                block_in_q, block_in_s = xq, sx
                yq, sy = xq, sx
                for spec in convs:
                    yq, sy = self._run_conv(params, state, updates, spec,
                                            yq, sy, tape, training)
                if short is not None:
                    scq, scs = self._run_conv(params, state, updates, short,
                                              block_in_q, block_in_s, tape,
                                              training)
                else:
                    scq, scs = block_in_q, block_in_s
                add_st = state[f"{nm}_add"]
                y, amax = _add_relu_fwd(yq, sy, scq, scs)
                s_out = _scale_of(add_st["out_amax"])
                out_q = _quant(y, s_out)
                if training:
                    updates[f"{nm}_add"] = {
                        "out_amax": _next_amax(add_st["out_amax"], amax)}
                if tape is not None:
                    tape.append((out_q,))
                xq, sx = out_q, s_out
        features = _deq(xq, sx)
        return features, updates

    # -- custom_vjp train function ------------------------------------------

    def _build_train_fn(self):
        plan = self.plan

        @jax.custom_vjp
        def train_fn(params, state, x):
            feats, updates = self._forward(params, state, x, True, None)
            return feats, updates

        def fwd(params, state, x):
            tape: List[Tuple] = []
            feats, updates = self._forward(params, state, x, True, tape)
            return (feats, updates), (tape, params, state)

        def bwd(saved, cots):
            g, _ = cots  # updates carry no cotangent
            tape, params, state = saved
            g = g.astype(jnp.bfloat16)
            dparams = {name: {"kernel": None, "gamma": None, "beta": None}
                       for name in params}
            ti = len(tape) - 1

            def take():
                nonlocal ti
                e = tape[ti]
                ti -= 1
                return e

            def conv_back(spec, dy):
                res, yq, _s_out = take()
                dx, dw, dgam, dbet = _conv_bn_bwd(
                    res, spec.relu, spec.strides, spec.padding, yq, dy)
                dparams[spec.name] = {"kernel": dw, "gamma": dgam,
                                      "beta": dbet}
                return dx

            dy = g
            for entry in reversed(plan):
                if entry[0] == "conv":
                    dy = conv_back(entry[1], dy)
                elif entry[0] == "pool":
                    q, s = take()
                    dy = _maxpool_bwd(q, s, (3, 3), (2, 2), "SAME", dy)
                else:
                    _, convs, short = entry
                    (out_q,) = take()
                    mask = (out_q > 0)
                    d_branch = (dy.astype(jnp.float32) * mask
                                ).astype(jnp.bfloat16)
                    if short is not None:
                        d_sc = conv_back(short, d_branch)
                    else:
                        d_sc = d_branch
                    d_main = d_branch
                    for spec in reversed(convs):
                        d_main = conv_back(spec, d_main)
                    dy = (d_main.astype(jnp.float32)
                          + d_sc.astype(jnp.float32)).astype(jnp.bfloat16)
            (x_proto,) = take()
            assert ti == -1
            dx = dy.astype(x_proto.dtype)  # STE through the input quantizer
            zero_state = jax.tree_util.tree_map(jnp.zeros_like, state)
            return dparams, zero_state, dx

        train_fn.defvjp(fwd, bwd)
        return train_fn

    # -- float reference (tests: quantization-free mirror of the same math) --

    def apply_float(self, params, x):
        """Pure-float forward of the identical architecture/batch-stat math,
        fully differentiable by JAX autodiff — the ground truth the custom
        backward's STE gradients are validated against in tests."""
        def conv_bn(spec, h):
            p = params[spec.name]
            f = lax.conv_general_dilated(
                h, p["kernel"], window_strides=spec.strides,
                padding=spec.padding, dimension_numbers=_conv_dims())
            mean = jnp.mean(f, axis=(0, 1, 2))
            var = jnp.maximum(jnp.mean(f * f, axis=(0, 1, 2)) - mean * mean,
                              0.0)
            z = (f - mean) * lax.rsqrt(var + _EPS) * p["gamma"] + p["beta"]
            return jnp.maximum(z, 0.0) if spec.relu else z

        h = x.astype(jnp.float32)
        for entry in self.plan:
            if entry[0] == "conv":
                h = conv_bn(entry[1], h)
            elif entry[0] == "pool":
                h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1),
                                      (1, 2, 2, 1), "SAME")
            else:
                _, convs, short = entry
                y = h
                for spec in convs:
                    y = conv_bn(spec, y)
                sc = conv_bn(short, h) if short is not None else h
                h = jnp.maximum(y + sc, 0.0)
        return h

    # -- public apply -------------------------------------------------------

    def apply(self, params, state, x, training: bool):
        if training:
            feats, updates = self._train_fn(params, state, x)
            new_state = dict(state)
            for k, v in updates.items():
                new_state[k] = v
            return feats, new_state
        feats, _ = self._forward(params, state, x, False, None)
        return feats, state
