"""Structured event log: typed, fork-safe, crash-tolerant state history.

The platform's state machines — brownout rungs, circuit breakers, pod
generations, autoscaler actuations, promotions, fault fires — surface as
instantaneous gauges (``serving.brownout_level``, ``fleet.breaker_state``)
that say *where* the system is, never *how it got there*. This module is
the missing third telemetry plane next to the metrics registry and the
span tracer: an append-only log of **typed events**, one per state
transition, that the incident correlator (``ops/incident.py``) replays
into a causally-ordered timeline after the fact.

Design, deliberately mirroring the two existing planes:

- **Typed and registered once.** An event type is declared at module
  scope by exactly one module via :func:`event_type` (the zoolint
  ``event-names`` pass lints literalness, uniqueness and documentation,
  exactly like ``metric-names``). Emitting an unregistered type raises —
  a typo'd event name must not silently vanish from every timeline.
- **Fork-safe and crash-tolerant.** Every process appends JSONL lines to
  its own ``<root>/<pid>.jsonl`` part file (the ``utils/trace.py`` spool
  pattern), flushed per event: a SIGKILLed child loses at most a torn
  final line, which readers skip. :meth:`EventLog.read` merges all part
  files, so a forked worker's transitions land in the parent's view.
- **Bounded in memory.** Each process additionally keeps the newest
  events in a fixed-size ring (:meth:`EventLog.tail`) for cheap
  in-process queries with zero file IO.
- **Two clocks per event.** Every event carries a ``wall`` stamp
  (:func:`~analytics_zoo_tpu.common.utils.wall_clock`, the only clock
  two processes share) AND a ``mono`` stamp (``perf_counter``): within
  one pid the monotonic stamps give exact causal order even when NTP
  steps the wall clock; across pids the wall stamps bracket the merge
  (see ``ops/incident.py``).
- **Near-zero cost when off.** With ``ops.enabled`` false (the default)
  an emit is an attribute load and a boolean check; no spool directory
  is ever created.

Usage::

    from analytics_zoo_tpu.ops import events

    _E_RUNG = events.event_type(
        "serving.brownout_rung", "Brownout ladder rung change.")
    _E_RUNG.emit(label="srv0", level_from=1, level_to=2, pressure=0.84)

    events.set_enabled(True)          # or ops.enabled / ZOO_TPU_OPS_ENABLED
    for ev in events.read_events():   # merged across pids, wall-ordered
        print(ev["type"], ev["wall"], ev["pid"])

Point ``ops.dir`` at a shared directory and every process of a fleet
(supervisor, servers, forked workers) appends to the same spool, giving
the incident CLI one place to read the whole story from.
"""
from __future__ import annotations

import atexit
import collections
import glob
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Deque, Dict, Iterable, List, Optional

from ..common import utils as _utils
from ..common.config import global_config

__all__ = [
    "EventLog", "EventType", "RESERVED_FIELDS", "default_log",
    "event_type", "registered_types", "read_events", "reset_default",
    "set_enabled", "enabled",
]

#: field names the log stamps onto every event — ``emit(**fields)``
#: payloads may not collide with them
RESERVED_FIELDS = ("type", "wall", "mono", "seq", "pid", "label",
                   "trace_id")


class EventType:
    """One registered event type; :meth:`emit` appends to the process
    default log. Registration is process-global (a type is a *name*, not
    a sink) — tests route emission into private :class:`EventLog`
    instances via ``EventLog.emit(name, ...)``."""

    __slots__ = ("name", "help")

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help

    def emit(self, label: str = "", trace_id: Optional[int] = None,
             **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one event to the default log (no-op returning ``None``
        while the ops plane is disabled)."""
        return default_log().emit(self.name, label=label,
                                  trace_id=trace_id, **fields)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"EventType({self.name!r})"


_types: Dict[str, EventType] = {}
_types_lock = threading.Lock()


def event_type(name: str, help: str = "") -> EventType:
    """Register (idempotently) and return an event type. One module owns
    each name — the ``event-names`` zoolint pass enforces literal names,
    single registration and a docs/observability.md row, mirroring the
    metric-names contract."""
    if not isinstance(name, str) or "." not in name:
        raise ValueError(
            f"event type {name!r} must be a dotted 'subsystem.noun' "
            f"string")
    with _types_lock:
        et = _types.get(name)
        if et is None:
            et = _types[name] = EventType(name, help)
        return et


def registered_types() -> Dict[str, str]:
    """``{name: help}`` of every registered event type."""
    with _types_lock:
        return {n: t.help for n, t in sorted(_types.items())}


class EventLog:
    """One event sink: a bounded in-memory ring plus per-pid JSONL part
    files under ``root``. The default instance (:func:`default_log`) is
    what :meth:`EventType.emit` writes to; tests and the incident CLI
    construct private ones over explicit directories."""

    def __init__(self, root: Optional[str] = None,
                 ring: Optional[int] = None,
                 enabled: Optional[bool] = None):
        cfg = global_config()
        if enabled is None:
            enabled = bool(cfg.get("ops.enabled"))
        if ring is None:
            ring = int(cfg.get("ops.ring_events"))
        self._enabled = bool(enabled)
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(int(ring), 1))
        self._configured_root = (str(root) if root
                                 else str(cfg.get("ops.dir") or ""))
        self._root: Optional[str] = None
        self._owns_root = False
        self._owner_pid = os.getpid()
        self._lock = threading.Lock()
        self._fh = None
        self._fh_pid = -1
        self._seq = 0
        if self._enabled:
            # resolve the spool BEFORE any fork so children share it
            self._ensure_root()

    # -- sink resolution ------------------------------------------------------

    def _ensure_root(self) -> str:
        if self._root is None:
            if self._configured_root:
                os.makedirs(self._configured_root, exist_ok=True)
                self._root = self._configured_root
            else:
                self._root = tempfile.mkdtemp(prefix="zoo_ops_events_")
                self._owns_root = True
        return self._root

    @property
    def root(self) -> str:
        """The spool directory (created on first need)."""
        return self._ensure_root()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, v: bool) -> None:
        self._enabled = bool(v)
        if self._enabled:
            self._ensure_root()

    # -- append path ----------------------------------------------------------

    def emit(self, type_name: str, label: str = "",
             trace_id: Optional[int] = None,
             **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one typed event. Raises on an unregistered type or a
        reserved-field collision (both are programming errors that would
        otherwise corrupt every downstream timeline); returns the event
        dict, or ``None`` when this log is disabled."""
        if not self._enabled:
            return None
        with _types_lock:
            known = type_name in _types
        if not known:
            raise ValueError(
                f"event type {type_name!r} was never registered via "
                f"events.event_type(...) — a typo'd type would vanish "
                f"from every timeline")
        for k in fields:
            if k in RESERVED_FIELDS:
                raise ValueError(
                    f"event field {k!r} collides with a reserved stamp "
                    f"({', '.join(RESERVED_FIELDS)})")
        ev: Dict[str, Any] = {
            "type": type_name,
            "wall": _utils.wall_clock(),
            "mono": time.perf_counter(),
            "pid": os.getpid(),
            "label": str(label or ""),
        }
        if trace_id is not None:
            ev["trace_id"] = int(trace_id)
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            self._append_line(ev)
        return ev

    def _append_line(self, ev: Dict[str, Any]) -> None:
        """Crash-tolerant append to this pid's part file. The handle is
        re-resolved after any fork (pid changed under us, like
        trace.py's spool); a torn final line from a killed process is
        skipped by :meth:`read`."""
        pid = ev["pid"]
        if self._fh is None or self._fh_pid != pid:
            try:
                self._fh = open(
                    os.path.join(self._ensure_root(), f"{pid}.jsonl"),
                    "a")
                self._fh_pid = pid
            except OSError:
                self._fh = None
                return
        try:
            self._fh.write(json.dumps(ev, default=str) + "\n")
            self._fh.flush()
        except (OSError, ValueError, TypeError):
            pass

    # -- read path ------------------------------------------------------------

    def tail(self, n: int = 50) -> List[Dict[str, Any]]:
        """The newest ``n`` events emitted BY THIS PROCESS (ring only, no
        file IO)."""
        with self._lock:
            return list(self._ring)[-int(n):]

    def read(self, since_wall: Optional[float] = None,
             types: Optional[Iterable[str]] = None,
             label: Optional[str] = None) -> List[Dict[str, Any]]:
        """Merge every pid's part file into one wall-ordered list (stable
        tie-break by pid then per-pid seq). Torn final lines of killed
        processes are skipped, exactly like the trace spool merge."""
        wanted = set(types) if types is not None else None
        out: List[Dict[str, Any]] = []
        for part in sorted(glob.glob(
                os.path.join(self._ensure_root(), "*.jsonl"))):
            try:
                with open(part) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue  # torn final line of a killed pid
                        if not isinstance(ev, dict) or "type" not in ev:
                            continue
                        if since_wall is not None \
                                and ev.get("wall", 0.0) < since_wall:
                            continue
                        if wanted is not None \
                                and ev["type"] not in wanted:
                            continue
                        if label is not None \
                                and ev.get("label") != label:
                            continue
                        out.append(ev)
            except OSError:
                pass
        out.sort(key=lambda e: (e.get("wall", 0.0), e.get("pid", 0),
                                e.get("seq", 0)))
        return out

    def clear(self) -> None:
        """Drop the ring and every part file (bench/test resets)."""
        with self._lock:
            self._ring.clear()
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            if self._root is not None:
                for part in glob.glob(os.path.join(self._root,
                                                   "*.jsonl")):
                    try:
                        os.remove(part)
                    except OSError:
                        pass

    def close(self) -> None:
        """Close the part-file handle; the CREATING process also removes
        an owned temp spool (children must never delete the shared dir
        out from under the parent)."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            if (self._owns_root and self._root is not None
                    and os.getpid() == self._owner_pid):
                shutil.rmtree(self._root, ignore_errors=True)
                self._root = None


# -- process-global default log -----------------------------------------------

_default: Optional[EventLog] = None
_default_lock = threading.Lock()


def default_log() -> EventLog:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = EventLog()
    return _default


def reset_default(root: Optional[str] = None, ring: Optional[int] = None,
                  enabled: Optional[bool] = None) -> EventLog:
    """Swap in a fresh default log (tests/bench A-B legs); the previous
    one is closed. Returns the new log."""
    global _default
    with _default_lock:
        old = _default
        _default = EventLog(root=root, ring=ring, enabled=enabled)
        if old is not None:
            old.close()
    return _default


def set_enabled(v: bool) -> None:
    default_log().set_enabled(v)


def enabled() -> bool:
    return default_log().enabled


def read_events(**kw: Any) -> List[Dict[str, Any]]:
    return default_log().read(**kw)


@atexit.register
def _close_default() -> None:
    # interpreter exit must not leak temp spools (metrics slab pattern)
    if _default is not None:
        try:
            _default.close()
        except Exception:
            pass
