"""Incident correlator: seal event windows into causally-ordered bundles.

When an alert fires (or an operator asks), the correlator freezes the
evidence before it scrolls away: the trailing event window from the
structured log, the related metric history rings, and any ``health.json``
snapshots it was pointed at, written together as one **incident bundle**
directory (``bundle.json`` + a rendered ``timeline.txt``).

The timeline ordering problem: events carry two clocks. Within one pid
the monotonic stamps (``mono``) give exact causal order even when NTP
steps the wall clock mid-incident; across pids only the wall stamps are
comparable, and they are comparable only approximately. So
:func:`order_events` orders **by monotonic stamp within each pid** and
**brackets across pids by wall clock**: events are grouped per pid,
each group sorted by ``(mono, seq)``, and the groups merged by always
taking the group whose *head* event has the smallest wall stamp. The
result never reorders two events of the same process (causality within
a pid is exact) and interleaves processes as faithfully as wall clocks
allow — a chaos run reads as "ramp → rung L2 → breaker open on inst-c →
scale-out → recovery" instead of a wall-clock shuffle.

Sealing an incident is itself an ``ops.incident`` event, so a later
incident's timeline shows the earlier one's seal point.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..common.config import global_config
from ..common.utils import wall_clock
from . import events
from .history import MetricHistory

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = [
    "IncidentCorrelator", "last_incident", "load_bundle",
    "order_events", "render_timeline",
]

_E_INCIDENT = events.event_type(
    "ops.incident",
    "An incident bundle was sealed (reason=alert:<name>|manual), "
    "carrying the bundle path and event count.")

_last: Optional[Dict[str, Any]] = None
_last_lock = threading.Lock()


def last_incident() -> Optional[Dict[str, Any]]:
    """Summary of the most recently sealed incident in this process
    (``None`` when there is none) — what servers stamp into
    ``health.json`` so ``read_health()`` consumers see it."""
    with _last_lock:
        return dict(_last) if _last is not None else None


def order_events(evs: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Causal order: exact ``(mono, seq)`` order within each pid,
    wall-clock-bracketed merge across pids (always advance the group
    whose head event carries the smallest wall stamp)."""
    groups: Dict[int, List[Dict[str, Any]]] = {}
    for ev in evs:
        groups.setdefault(int(ev.get("pid", 0)), []).append(ev)
    for g in groups.values():
        g.sort(key=lambda e: (e.get("mono", 0.0), e.get("seq", 0)))
    heads = {pid: 0 for pid in groups}
    out: List[Dict[str, Any]] = []
    while heads:
        pid = min(heads,
                  key=lambda p: (groups[p][heads[p]].get("wall", 0.0), p))
        out.append(groups[pid][heads[pid]])
        heads[pid] += 1
        if heads[pid] >= len(groups[pid]):
            del heads[pid]
    return out


def _fields_str(ev: Dict[str, Any]) -> str:
    parts = []
    for k in sorted(ev):
        if k in events.RESERVED_FIELDS:
            continue
        v = ev[k]
        if isinstance(v, dict):
            v = json.dumps(v, sort_keys=True, default=str)
        parts.append(f"{k}={v}")
    return " ".join(parts)


def render_timeline(evs: Sequence[Dict[str, Any]],
                    reason: Optional[str] = None,
                    alert: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable timeline of already-causally-ordered events:
    one ``+offset  [pid/label]  type  fields`` line per event, offsets
    relative to the first event's wall stamp."""
    lines: List[str] = []
    if reason:
        lines.append(f"incident: {reason}")
    if alert:
        lines.append(
            f"triggering alert: {alert.get('name')} "
            f"{json.dumps(alert.get('info', {}), sort_keys=True, default=str)}")
    if not evs:
        lines.append("(no events in window)")
        return "\n".join(lines) + "\n"
    t0 = float(evs[0].get("wall", 0.0))
    lines.append(f"t0 = {t0:.3f} (wall)")
    for ev in evs:
        dt = float(ev.get("wall", t0)) - t0
        who = f"{ev.get('pid', '?')}/{ev.get('label') or '-'}"
        extra = _fields_str(ev)
        line = f"+{dt:8.3f}s  [{who}]  {ev.get('type', '?')}"
        if extra:
            line += f"  {extra}"
        lines.append(line)
    return "\n".join(lines) + "\n"


def load_bundle(path: str) -> Dict[str, Any]:
    """Load a sealed bundle back (``path`` is the bundle directory or
    its ``bundle.json``)."""
    if os.path.isdir(path):
        path = os.path.join(path, "bundle.json")
    with open(path) as f:
        return json.load(f)


class IncidentCorrelator:
    """Seals incident bundles from an event log + metric history.

    ``health_paths`` may list ``health.json`` files (or directories of
    them) whose current contents should be frozen into each bundle.
    """

    def __init__(self, log: Optional[events.EventLog] = None,
                 history: Optional[MetricHistory] = None,
                 out_dir: Optional[str] = None,
                 window_s: Optional[float] = None,
                 health_paths: Sequence[str] = ()):
        cfg = global_config()
        self._log = log
        self.history = history
        self.window_s = float(window_s if window_s is not None
                              else cfg.get("ops.incident_window_s"))
        self._out_dir = (str(out_dir) if out_dir
                         else str(cfg.get("ops.incident_dir") or ""))
        self.health_paths = list(health_paths)
        self._seal_lock = threading.Lock()

    @property
    def log(self) -> events.EventLog:
        return self._log if self._log is not None else events.default_log()

    def _resolve_out_dir(self) -> str:
        if self._out_dir:
            return self._out_dir
        return os.path.join(self.log.root, "incidents")

    def _health_snapshots(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        paths: List[str] = []
        for p in self.health_paths:
            if os.path.isdir(p):
                for fn in sorted(os.listdir(p)):
                    if fn.endswith(".json"):
                        paths.append(os.path.join(p, fn))
            else:
                paths.append(p)
        for p in paths:
            try:
                with open(p) as f:
                    out[p] = json.load(f)
            except (OSError, ValueError):
                out[p] = None  # frozen as unreadable — that IS evidence
        return out

    def seal(self, reason: str = "manual",
             alert: Optional[Dict[str, Any]] = None,
             now: Optional[float] = None) -> str:
        """Seal one bundle: trailing event window (causally ordered),
        metric history dump, health snapshots, rendered timeline.
        Returns the bundle directory path."""
        global _last
        t = wall_clock() if now is None else float(now)
        with self._seal_lock:
            raw = self.log.read(since_wall=t - self.window_s)
            ordered = order_events(raw)
            hist = (self.history.dump(self.window_s, t)
                    if self.history is not None else {})
            health = self._health_snapshots()
            slug = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)[:48]
            out_root = self._resolve_out_dir()
            bdir = os.path.join(out_root,
                                f"incident-{int(t * 1000)}-{slug}")
            os.makedirs(bdir, exist_ok=True)
            bundle = {
                "version": 1,
                "sealed_wall": t,
                "reason": reason,
                "alert": alert,
                "window_s": self.window_s,
                "events": ordered,
                "history": hist,
                "health": health,
            }
            timeline = render_timeline(ordered, reason=reason, alert=alert)
            try:
                with open(os.path.join(bdir, "bundle.json"), "w") as f:
                    json.dump(bundle, f, default=str)
                with open(os.path.join(bdir, "timeline.txt"), "w") as f:
                    f.write(timeline)
            except OSError:
                logger.warning("incident bundle write failed at %s",
                               bdir, exc_info=True)
            summary = {"path": bdir, "reason": reason, "wall": t,
                       "events": len(ordered)}
            with _last_lock:
                _last = summary
            try:
                self.log.emit("ops.incident", reason=reason, path=bdir,
                              events=len(ordered))
            except Exception:
                logger.debug("ops.incident event emit failed",
                             exc_info=True)
            logger.info("sealed incident bundle %s (%d events, %s)",
                        bdir, len(ordered), reason)
            return bdir
