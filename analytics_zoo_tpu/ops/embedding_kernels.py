"""Fused embedding kernels for the recsys hot path (NCF / Wide&Deep).

The gather/scatter-bound embedding path is the measured utilization floor
of the recommendation workloads (bench r02/r03: widedeep MFU 0.0001, ncf
0.0075 — judged by ``hbm_roofline_fraction``, not MFU, since the step does
almost no matmul work). This module collapses the per-table op chains into
single passes:

* **forward** — :func:`gather_pool`: table gather + padding mask + bag
  pooling (sum/mean/sqrtn) in one sweep; :func:`multi_table_lookup` runs
  every table of a tower in one traced call so XLA fuses the per-table
  chains and the feature concat into one dispatch (the unfused layer path
  materializes one intermediate per table).
* **backward** — :func:`segment_grads` + :func:`scatter_rows`: the fused
  segment-sum / scatter-add pair ``parallel/embedding.py`` runs after the
  gradient all-to-all. The cotangent stays the row-subset ``[rows_per_
  shard, dim]`` shard block the sparse row updates expect — never a dense
  ``[vocab, dim]`` materialization, never a one-hot matmul.
* **int8** — :func:`quantize_table` / :func:`gather_pool_int8`: tables
  live symmetric-int8 in HBM using the ``ops/int8_dataflow`` delayed-
  scaling recipe (same running-amax, same scale math), halving the bytes
  the gather actually moves; rows dequantize in-kernel (TPU) or right at
  the gather (fallback). Bound: ``|deq - f32| <= scale / 2`` per element,
  ``<= bag * scale / 2`` after sum pooling (:func:`int8_error_bound`).

On TPU the per-row work runs as pallas kernels (scalar-prefetched ids
driving double-buffered row DMAs out of HBM, VMEM accumulators for the
pooling — see docs/embeddings.md "Fused kernels" for the tiling scheme).
Everywhere else — and whenever the table shape misses the TPU lane tiling
(dim % 128) — the SAME functions trace the exact lax ops of the historical
unfused layers, in the same order, so the fused path is bit-identical
(f32) to the reference by construction; tests/test_fused_embedding.py
asserts that through real Estimator training, sharded and unsharded.

Everything here is gated by the ``kernels.fused_embedding`` config knob
(docs/configuration.md); the unfused layer code stays in-tree as the
bit-parity reference. The per-row bodies below are policed by
``scripts/check_hot_path_syncs.py`` — no host syncs, no ``one_hot``
densification, no per-row Python loops.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .int8_dataflow import (dequant_int8, next_amax, quant_int8,
                            scale_of_amax)

#: rows gathered per pallas grid step (the scalar-prefetch block); clamped
#: down to a divisor of the id count at call time.
DEFAULT_GATHER_BLOCK = 256

#: pallas scatter-add keeps the whole output shard in VMEM; above this
#: many bytes the lax scatter (XLA's native s32 scatter-add) runs instead.
SCATTER_VMEM_BYTES = 8 * 1024 * 1024


def fused_enabled() -> bool:
    """The ``kernels.fused_embedding`` config knob (True by default). Off
    means every caller traces the historical unfused op chain — the
    bit-parity reference the fused path is tested against."""
    from ..common.config import global_config
    return bool(global_config().get("kernels.fused_embedding"))


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _lane_ok(table) -> bool:
    """TPU kernels want the feature dim lane-aligned; anything else takes
    the lax fallback (documented in docs/embeddings.md)."""
    return table.ndim == 2 and table.shape[1] % 128 == 0


def _use_pallas(table) -> bool:
    return _on_tpu() and _lane_ok(table)


def _largest_divisor_leq(n: int, cap: int) -> int:
    for c in range(min(n, cap), 0, -1):
        if n % c == 0:
            return c
    return 1


def _vma_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying the input's varying-manual-axes so
    pallas_call outputs satisfy shard_map's vma check (the sharded lookup
    runs these kernels inside shard_map)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _int_zeros(x):
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


# ---------------------------------------------------------------------------
# pallas TPU kernels (never traced off-TPU; ids ride scalar prefetch and
# drive double-buffered per-row DMAs out of HBM)
# ---------------------------------------------------------------------------


def _gather_kernel(ids_ref, table_ref, out_ref, scratch_ref, sem_ref, *,
                   block: int, clip: bool):
    """One grid step gathers ``block`` rows: the next row's HBM->VMEM DMA
    is in flight while the current one lands (2-slot scratch). ``clip``
    mirrors ``jnp.take``'s default mode; otherwise out-of-range ids (the
    SENTINEL, negative padding) write zero rows — fill semantics."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nrows = table_ref.shape[0]
    base = pl.program_id(0) * block

    def _dma(slot, j):
        row = jnp.clip(ids_ref[base + j], 0, nrows - 1)
        return pltpu.make_async_copy(table_ref.at[pl.ds(row, 1), :],
                                     scratch_ref.at[slot],
                                     sem_ref.at[slot])

    _dma(0, 0).start()

    def _step(j, carry):
        slot = j % 2

        @pl.when(j + 1 < block)
        def _prefetch():
            _dma((j + 1) % 2, j + 1).start()

        _dma(slot, j).wait()
        if clip:
            out_ref[j, :] = scratch_ref[slot, 0]
        else:
            row = ids_ref[base + j]
            ok = (row >= 0) & (row < nrows)
            out_ref[j, :] = jnp.where(ok, scratch_ref[slot, 0],
                                      jnp.zeros_like(scratch_ref[slot, 0]))
        return carry

    lax.fori_loop(0, block, _step, 0)


def _gather_int8_kernel(ids_ref, table_ref, scale_ref, out_ref, scratch_ref,
                        sem_ref, *, block: int):
    """int8 row gather with dequant-in-kernel: the DMA moves 1 byte per
    element out of HBM (half the f32/bf16 bytes — the real roofline for
    gather-bound steps); the ``q * scale`` upcast happens on the row
    already sitting in VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nrows = table_ref.shape[0]
    base = pl.program_id(0) * block

    def _dma(slot, j):
        row = jnp.clip(ids_ref[base + j], 0, nrows - 1)
        return pltpu.make_async_copy(table_ref.at[pl.ds(row, 1), :],
                                     scratch_ref.at[slot],
                                     sem_ref.at[slot])

    _dma(0, 0).start()

    def _step(j, carry):
        slot = j % 2

        @pl.when(j + 1 < block)
        def _prefetch():
            _dma((j + 1) % 2, j + 1).start()

        _dma(slot, j).wait()
        row = ids_ref[base + j]
        ok = (row >= 0) & (row < nrows)
        deq = scratch_ref[slot, 0].astype(jnp.float32) * scale_ref[0, 0]
        out_ref[j, :] = jnp.where(ok, deq, jnp.zeros_like(deq))
        return carry

    lax.fori_loop(0, block, _step, 0)


def _gather_pool_kernel(ids_ref, table_ref, out_ref, acc_ref, cnt_ref,
                        scratch_ref, sem_ref, *, block: int, bag: int,
                        combiner: str):
    """Fused gather + segment pooling: each output row accumulates its
    ``bag`` gathered rows in a VMEM f32 accumulator (padding ids masked,
    valid count kept for mean/sqrtn) and writes once — the unfused
    ``[..., bag, dim]`` intermediate never exists."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nrows = table_ref.shape[0]
    base = pl.program_id(0) * block
    total = block * bag

    def _dma(slot, j):
        b = j // bag
        k = j - b * bag
        row = jnp.clip(ids_ref[base + b, k], 0, nrows - 1)
        return pltpu.make_async_copy(table_ref.at[pl.ds(row, 1), :],
                                     scratch_ref.at[slot],
                                     sem_ref.at[slot])

    _dma(0, 0).start()

    def _step(j, carry):
        slot = j % 2
        b = j // bag
        k = j - b * bag

        @pl.when(j + 1 < total)
        def _prefetch():
            _dma((j + 1) % 2, j + 1).start()

        _dma(slot, j).wait()
        row = ids_ref[base + b, k]
        ok = (row >= 0) & (row < nrows)

        @pl.when(k == 0)
        def _reset():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            cnt_ref[0, 0] = 0.0

        acc_ref[:] = acc_ref[:] + jnp.where(
            ok, scratch_ref[slot].astype(jnp.float32),
            jnp.zeros_like(acc_ref))
        cnt_ref[0, 0] = cnt_ref[0, 0] + jnp.where(ok, 1.0, 0.0)

        @pl.when(k == bag - 1)
        def _emit():
            denom = jnp.maximum(cnt_ref[0, 0], 1.0)
            if combiner == "mean":
                out_ref[b, :] = (acc_ref[0] / denom).astype(out_ref.dtype)
            elif combiner == "sqrtn":
                out_ref[b, :] = (acc_ref[0]
                                 / jnp.sqrt(denom)).astype(out_ref.dtype)
            else:
                out_ref[b, :] = acc_ref[0].astype(out_ref.dtype)
        return carry

    lax.fori_loop(0, total, _step, 0)


def _scatter_add_kernel(rows_ref, g_ref, out_ref, *, n: int):
    """Row-subset scatter-add: the output shard block lives in VMEM for
    the whole pass; out-of-range rows (SENTINEL markers) drop."""
    from jax.experimental import pallas as pl  # noqa: F401 (grid idiom)

    out_ref[:] = jnp.zeros_like(out_ref)
    limit = out_ref.shape[0]

    def _step(j, carry):
        row = rows_ref[j]
        ok = (row >= 0) & (row < limit)
        safe = jnp.clip(row, 0, limit - 1)
        add = jnp.where(ok, g_ref[j, :], jnp.zeros_like(g_ref[j, :]))
        out_ref[safe, :] = out_ref[safe, :] + add
        return carry

    lax.fori_loop(0, n, _step, 0)


# ---------------------------------------------------------------------------
# pallas_call plumbing


def _gather_call(table, flat_ids, clip: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = flat_ids.shape[0], table.shape[1]
    block = _largest_divisor_leq(n, DEFAULT_GATHER_BLOCK)
    return pl.pallas_call(
        functools.partial(_gather_kernel, block=block, clip=clip),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec((block, dim), lambda i, *_: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((2, 1, dim), table.dtype),
                            pltpu.SemaphoreType.DMA((2,))]),
        out_shape=_vma_struct((n, dim), table.dtype, table),
    )(flat_ids.astype(jnp.int32), table)


def _gather_int8_call(qtable, scale, flat_ids):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = flat_ids.shape[0], qtable.shape[1]
    block = _largest_divisor_leq(n, DEFAULT_GATHER_BLOCK)
    scale2 = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    return pl.pallas_call(
        functools.partial(_gather_int8_kernel, block=block),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((block, dim), lambda i, *_: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((2, 1, dim), qtable.dtype),
                            pltpu.SemaphoreType.DMA((2,))]),
        out_shape=_vma_struct((n, dim), jnp.float32, qtable),
    )(flat_ids.astype(jnp.int32), qtable, scale2)


def _gather_pool_call(table, ids2d, combiner: str):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, bag = ids2d.shape
    dim = table.shape[1]
    block = _largest_divisor_leq(n, DEFAULT_GATHER_BLOCK)
    return pl.pallas_call(
        functools.partial(_gather_pool_kernel, block=block, bag=bag,
                          combiner=combiner),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec((block, dim), lambda i, *_: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((1, dim), jnp.float32),
                            pltpu.SMEM((1, 1), jnp.float32),
                            pltpu.VMEM((2, 1, dim), table.dtype),
                            pltpu.SemaphoreType.DMA((2,))]),
        out_shape=_vma_struct((n, dim), table.dtype, table),
    )(ids2d.astype(jnp.int32), table)


def _scatter_call(g_flat, rows, num_rows: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = g_flat.shape
    return pl.pallas_call(
        functools.partial(_scatter_add_kernel, n=n),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec((n, dim), lambda *_: (0, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((num_rows, dim), lambda *_: (0, 0),
                                   memory_space=pltpu.VMEM)),
        out_shape=_vma_struct((num_rows, dim), g_flat.dtype, g_flat),
    )(rows.astype(jnp.int32), g_flat)


# ---------------------------------------------------------------------------
# fused primitives (the API the engine / layers / bench wire against).
# Off-TPU these trace EXACTLY the unfused reference ops, in the same order
# — bit-parity by construction. Policed: no host syncs, no one_hot, no
# per-row Python loops.
# ---------------------------------------------------------------------------


def gather_rows(table, flat_ids):
    """Fill-mode row gather (out-of-range -> zero row): the local-gather
    half of ``parallel.embedding._lookup_body`` after the id exchange.
    Not differentiated — the sharded lookup owns its backward."""
    if _use_pallas(table):
        return _gather_call(table, flat_ids, clip=False)
    return jnp.take(table, flat_ids, axis=0, mode="fill", fill_value=0)


def gather_rows_clip(table, ids):
    """Clip-mode row gather (``jnp.take`` default, any ``ids`` shape): the
    dense unsharded lookup. Differentiable: off-TPU it IS ``jnp.take``
    (native autodiff); on TPU a custom_vjp pairs the pallas gather with
    the same scatter-add XLA's take-transpose emits."""
    if _use_pallas(table):
        return _gather_clip_tpu(table, ids)
    return jnp.take(table, ids, axis=0)


def segment_grads(g, inv, d, slot, shards):
    """Fused backward half 1: segment-sum the output cotangent per unique
    id straight into its (destination, slot) cell of the request-shaped
    exchange buffer (``parallel.embedding._lookup_bwd_body``)."""
    n = inv.shape[0]
    g_u = jax.ops.segment_sum(g, inv, num_segments=n)
    return jnp.zeros((shards, n, g.shape[-1]), g.dtype).at[d, slot].set(g_u)


def scatter_rows(g_flat, rows, num_rows):
    """Fused backward half 2: scatter-add the exchanged per-unique grads
    into the touched rows of the local shard block. The result IS the
    row-subset cotangent the sparse row updates consume — ``[rows_per_
    shard, dim]``, never a dense ``[vocab, dim]``; SENTINEL rows drop."""
    if _on_tpu() and num_rows * g_flat.shape[-1] * 4 <= SCATTER_VMEM_BYTES \
            and _lane_ok(g_flat):
        return _scatter_call(g_flat, rows, num_rows)
    return jnp.zeros((num_rows, g_flat.shape[-1]), g_flat.dtype).at[
        rows].add(g_flat, mode="drop")


def _gather_pool_ref(table, idx, combiner, mask_negative):
    """The bit-parity reference: verbatim the op chain of the unfused
    ``SparseEmbedding.call`` (mask_negative) / ``_WideLinear.call``
    (pre-validated ids) — same ops, same order, same dtypes."""
    if mask_negative:
        valid = (idx >= 0).astype(table.dtype)[..., None]
        emb = jnp.take(table, jnp.maximum(idx, 0), axis=0) * valid
    else:
        valid = None
        emb = jnp.take(table, idx, axis=0)
    if combiner is None:
        return emb
    total = jnp.sum(emb, axis=-2)
    if combiner == "sum":
        return total
    if valid is not None:
        n = jnp.maximum(jnp.sum(valid, axis=-2), 1.0)
    else:
        n = jnp.full(total.shape[:-1] + (1,), 1.0 * idx.shape[-1],
                     table.dtype)
    if combiner == "mean":
        return total / n
    return total / jnp.sqrt(n)  # sqrtn


def gather_pool(table, idx, combiner=None, mask_negative=True):
    """Fused gather + padding mask + bag pooling over the trailing axis of
    ``idx``. ``mask_negative`` treats negative ids as padding (zero rows,
    excluded from mean/sqrtn counts) exactly like ``SparseEmbedding``;
    with it off, ids must be pre-validated (the ``_WideLinear`` contract).
    Differentiable both ways; pooled variants require ``idx.ndim >= 2``."""
    if _use_pallas(table):
        return _gather_pool_tpu(table, idx, combiner, mask_negative)
    return _gather_pool_ref(table, idx, combiner, mask_negative)


def gather_pool_int8(qtable, scale, idx, combiner=None, mask_negative=True):
    """:func:`gather_pool` over a :func:`quantize_table` table resident
    int8 in HBM. Rows dequantize in-kernel on TPU (the DMA moves 1 byte
    per element); the fallback dequantizes right at the gather. Forward
    only (quantized serving/eval path). Error vs the f32 table:
    ``<= scale/2`` per element, ``<= bag * scale/2`` after sum pooling."""
    if _on_tpu() and _lane_ok(qtable) and combiner is None:
        flat = idx.reshape(-1)
        rows = _gather_int8_call(qtable, scale, flat)
        out = rows.reshape(idx.shape + (qtable.shape[1],))
        if mask_negative:
            out = out * (idx >= 0).astype(out.dtype)[..., None]
        return out
    if mask_negative:
        valid = (idx >= 0).astype(jnp.float32)[..., None]
        q_rows = jnp.take(qtable, jnp.maximum(idx, 0), axis=0)
        emb = dequant_int8(q_rows, scale, jnp.float32) * valid
    else:
        valid = None
        emb = dequant_int8(jnp.take(qtable, idx, axis=0), scale,
                           jnp.float32)
    if combiner is None:
        return emb
    total = jnp.sum(emb, axis=-2)
    if combiner == "sum":
        return total
    if valid is not None:
        n = jnp.maximum(jnp.sum(valid, axis=-2), 1.0)
    else:
        n = jnp.full(total.shape[:-1] + (1,), 1.0 * idx.shape[-1],
                     jnp.float32)
    if combiner == "mean":
        return total / n
    return total / jnp.sqrt(n)  # sqrtn


# -- wrappers (multi-table dispatch + quantization; not per-row code) -------


def multi_table_lookup(tables: Sequence, indices: Sequence,
                       combiners: Optional[Sequence] = None,
                       mask_negative: bool = True):
    """One traced pass over a whole tower of embedding tables: per-table
    fused gather+pool, then the feature concat — a single dispatch where
    the unfused path pays one per table plus the concat. Pooled tables
    contribute ``[..., dim]``; un-pooled (combiner None) tables must share
    their index shape with the others for the concat to line up."""
    if combiners is None:
        combiners = (None,) * len(tables)
    parts = [gather_pool(t, i, c, mask_negative)
             for t, i, c in zip(tables, indices, combiners)]
    return jnp.concatenate(parts, axis=-1)


def quantize_table(table, running_amax=None):
    """Symmetric int8 quantization of an embedding table with the
    ``ops/int8_dataflow`` delayed-scaling recipe: fast-rise/slow-decay
    running amax (when carried across steps), ``scale = amax / 127``.
    Returns ``(qtable int8, scale, amax)`` — stash ``amax`` and feed it
    back as ``running_amax`` to requantize with delayed scales."""
    seen = jnp.max(jnp.abs(table.astype(jnp.float32)))
    amax = seen if running_amax is None else next_amax(running_amax, seen)
    scale = scale_of_amax(amax)
    return quant_int8(table, scale), scale, amax


def int8_error_bound(scale, bag_size: int = 1):
    """Documented worst-case absolute error of the int8 gather vs the f32
    table: half a quantization step per element, times the bag size for
    sum-pooled bags (mean/sqrtn divide it back down)."""
    return 0.5 * scale * bag_size


# ---------------------------------------------------------------------------
# TPU custom_vjp shims (pallas forward, reference-arithmetic backward) —
# never traced off-TPU.
# ---------------------------------------------------------------------------


@jax.custom_vjp
def _gather_clip_tpu(table, ids):
    rows = _gather_call(table, ids.reshape(-1), clip=True)
    return rows.reshape(ids.shape + (table.shape[1],))


def _gather_clip_tpu_fwd(table, ids):
    return _gather_clip_tpu(table, ids), (table, ids)


def _gather_clip_tpu_bwd(res, g):
    table, ids = res
    safe = jnp.clip(ids.reshape(-1), 0, table.shape[0] - 1)
    ct = jnp.zeros_like(table).at[safe].add(
        g.reshape(-1, table.shape[-1]).astype(table.dtype))
    return ct, _int_zeros(ids)


_gather_clip_tpu.defvjp(_gather_clip_tpu_fwd, _gather_clip_tpu_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _gather_pool_tpu(table, idx, combiner, mask_negative):
    if combiner is None:
        flat = idx.reshape(-1)
        if mask_negative:
            rows = _gather_call(table, flat, clip=False)  # fill == masked
        else:
            rows = _gather_call(table, flat, clip=True)
        return rows.reshape(idx.shape + (table.shape[1],))
    ids2d = idx.reshape(-1, idx.shape[-1])
    if not mask_negative:
        ids2d = jnp.clip(ids2d, 0, table.shape[0] - 1)
    pooled = _gather_pool_call(table, ids2d, combiner)
    return pooled.reshape(idx.shape[:-1] + (table.shape[1],))


def _gather_pool_tpu_fwd(table, idx, combiner, mask_negative):
    return _gather_pool_tpu(table, idx, combiner, mask_negative), (table, idx)


def _gather_pool_tpu_bwd(combiner, mask_negative, res, g):
    table, idx = res
    if mask_negative:
        valid = (idx >= 0).astype(table.dtype)[..., None]
        safe = jnp.maximum(idx, 0)
    else:
        valid = jnp.ones(idx.shape + (1,), table.dtype)
        safe = idx
    if combiner is None:
        gk = g * valid
    else:
        if combiner in ("mean", "sqrtn"):
            n = jnp.maximum(jnp.sum(valid, axis=-2), 1.0)
            g = g / (n if combiner == "mean" else jnp.sqrt(n))
        gk = g[..., None, :] * valid
    ct = jnp.zeros_like(table).at[safe.reshape(-1)].add(
        gk.reshape(-1, table.shape[-1]).astype(table.dtype))
    return ct, _int_zeros(idx)


_gather_pool_tpu.defvjp(_gather_pool_tpu_fwd, _gather_pool_tpu_bwd)
