"""Attention kernels.

Three tiers, one contract (``[batch, heads, seq, head_dim]`` tensors):

- :func:`dot_product_attention` — plain XLA. The materialized ``[q, kv]``
  score matrix is fine at short lengths; XLA fuses the softmax chain.
- :func:`blockwise_attention` — flash-style streaming softmax over KV chunks
  via ``lax.scan`` (never materializes ``[q, kv]``). Runs everywhere (CPU
  tests, TPU), is differentiable through the scan, and is the building block
  ring attention reuses per hop (``parallel/ring_attention.py``).
- :func:`flash_attention` — pallas TPU kernels for BOTH directions: the
  forward (tiled q/kv blocks in VMEM, running max/denominator in scratch,
  bf16 MXU matmuls with f32 accumulation, per-row logsumexp residual) and a
  two-pass backward (dq grid, then dk/dv grid) that recomputes attention
  probabilities from the saved logsumexp — measured ~6x over autodiff
  through the blockwise scan at seq 4096 on v5e. Falls back to blockwise
  (scan autodiff) off-TPU and for the key-bias variant.

The reference has no long-context machinery (SURVEY §5: absent); this is the
new TPU-native capability that backs ``TransformerLayer``/``BERT`` and the
sequence-parallel mesh axis.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# v5e-tuned (scripts/sweep_flash_blocks.py, seq 4096 fwd+bwd train step,
# dispatch-cancelled differenced timing): 512/1024 is the consistent best
# across sweeps; q blocks >= 2048 overflow VMEM/registers in the exp2
# kernels. Shorter or indivisible sequences clamp via _largest_divisor_leq.
DEFAULT_Q_BLOCK = 512
DEFAULT_KV_BLOCK = 1024
_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() grads finite
_LOG2E = 1.4426950408889634  # the pallas kernels run softmax in exp2 space:
# scale*log2(e) folds into q OUTSIDE the kernel, turning the per-element
# `s*scale` multiply + `exp` into a bare `exp2` — at head_dim 64 the kernels
# are VPU-bound (softmax ops per element rival the 2·64 MXU flops), so every
# elementwise op removed is direct wall-clock
_LN2 = 1.0 / _LOG2E


def _largest_divisor_leq(n: int, cap: int) -> int:
    for c in range(min(n, cap), 0, -1):
        if n % c == 0:
            return c
    return 1


def masked_context(q: jax.Array, k_buf: jax.Array, v_buf: jax.Array,
                   visible: jax.Array, scale: float) -> jax.Array:
    """THE decode-cache attention arithmetic, shared verbatim by every KV
    engine (``ops/decode.py``: ``cached_attention``, ``slot_attention``,
    ``paged_attention`` and the speculative verify path).

    ``softmax(q k^T * scale  masked to `visible`) v`` with f32 score/context
    accumulation. One shared body is what makes the engines' bit-identity
    guarantees structural rather than coincidental: invisible positions are
    forced to exactly ``_NEG_INF`` so their softmax probability underflows
    to exactly 0.0 — the masked tail contributes exact-zero terms to the
    context sum, which is why buffers that differ only in masked positions
    (contiguous garbage vs paged-pool garbage vs right-padding) still
    produce bit-identical contexts.

    ``q``: ``[B, H, T, D]``; ``k_buf``/``v_buf``: ``[B, H, K, D]``;
    ``visible`` broadcasts against scores ``[B, H, T, K]``.
    """
    s = jnp.einsum("bhtd,bhkd->bhtk", q, k_buf,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(visible, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhtk,bhkd->bhtd", p.astype(v_buf.dtype), v_buf,
                     preferred_element_type=jnp.float32)
    return ctx.astype(q.dtype)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          bias: Optional[jax.Array] = None,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """Reference attention: softmax(q k^T / sqrt(d) + bias) v, with optional
    attention-probability dropout (training regularizer)."""
    *_, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    scores = jnp.einsum("...qd,...kd->...qk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
        ki = lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
        scores = jnp.where(qi >= ki, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0)
    return jnp.einsum("...qk,...kd->...qd", probs.astype(v.dtype), v)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        bias: Optional[jax.Array] = None,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        q_block: int = DEFAULT_Q_BLOCK,
                        kv_block: int = DEFAULT_KV_BLOCK,
                        dropout_rate: float = 0.0,
                        dropout_rng: Optional[jax.Array] = None,
                        return_lse: bool = False):
    """Streaming-softmax attention over KV chunks; O(seq) memory.

    ``bias`` broadcasts against ``[batch, heads, q_len, kv_len]``.
    Attention-probability dropout is applied per KV block (the mask derives
    from ``fold_in(rng, block_index)``, so the full [q, kv] probability
    matrix never materializes); the streaming denominator accumulates the
    UNDROPPED weights, making the result exactly standard post-softmax
    dropout. ``return_lse`` also returns the per-row logsumexp
    ``[b, h, q_len]`` (partial-attention merging, ring hops).
    """
    b, h, q_len, d = q.shape
    kv_len = k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = _largest_divisor_leq(q_len, q_block)
    bk = _largest_divisor_leq(kv_len, kv_block)
    n_q, n_kv = q_len // bq, kv_len // bk

    if bias is not None:
        bias = jnp.broadcast_to(bias, (b, h, q_len, kv_len))

    q = q.reshape(b, h, n_q, bq, d)
    k_chunks = k.reshape(b, h, n_kv, bk, d).transpose(2, 0, 1, 3, 4)
    v_chunks = v.reshape(b, h, n_kv, bk, d).transpose(2, 0, 1, 3, 4)
    dropping = dropout_rate > 0.0 and dropout_rng is not None

    def one_q_chunk(args):
        qc, qi = args  # qc: [b, h, bq, d]

        def kv_step(carry, inp):
            acc, m, l = carry
            kc, vc, ki = inp
            s = jnp.einsum("bhqd,bhkd->bhqk", qc, kc,
                           preferred_element_type=jnp.float32) * scale
            if bias is not None:
                bslice = lax.dynamic_slice(
                    bias, (0, 0, qi * bq, ki * bk), (b, h, bq, bk))
                s = s + bslice
            if causal:
                rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                s = jnp.where(rows >= cols, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            # the softmax DENOMINATOR accumulates the undropped weights, so
            # the result equals standard post-softmax dropout exactly:
            # (Σ dropped_p·v) / (Σ p) = Σ dropout(softmax(s))·v
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            if dropping:
                block_rng = jax.random.fold_in(dropout_rng, qi * n_kv + ki)
                keep = jax.random.bernoulli(block_rng, 1.0 - dropout_rate,
                                            p.shape)
                p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            # p drops to the storage dtype for the MXU (bf16 multiplies with
            # f32 accumulation); f32xf32 would run ~8x slower on v5e
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc,
                preferred_element_type=jnp.float32)
            return (acc_new, m_new, l_new), None

        # init derives from qc*0 so it inherits qc's varying-axis type when
        # this runs inside shard_map (ulysses/ring sequence parallelism)
        zero_q = qc.astype(jnp.float32) * 0.0
        init = (zero_q, zero_q[..., :1] + _NEG_INF, zero_q[..., :1])
        (acc, m, l), _ = lax.scan(
            kv_step, init, (k_chunks, v_chunks, jnp.arange(n_kv)))
        o = (acc / jnp.maximum(l, 1e-30)).astype(v.dtype)
        if return_lse:
            return o, (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
        return o

    mapped = lax.map(one_q_chunk,
                     (q.transpose(2, 0, 1, 3, 4), jnp.arange(n_q)))
    if return_lse:
        out, lse = mapped
        return (out.transpose(1, 2, 0, 3, 4).reshape(b, h, q_len, d),
                lse.transpose(1, 2, 0, 3).reshape(b, h, q_len))
    return mapped.transpose(1, 2, 0, 3, 4).reshape(b, h, q_len, d)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, scale: float, causal: bool,
                      bq: int, bk: int, has_bias: bool,
                      has_lse: bool = False):
    from jax.experimental import pallas as pl

    lse_ref = None
    if has_bias:
        bias_ref, o_ref, acc_ref, m_ref, l_ref = rest
    elif has_lse:
        bias_ref = None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        bias_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = True
    if causal:
        # skip fully-masked blocks (query rows all before kv cols)
        run = (qi + 1) * bq > ki * bk

    @pl.when(run)
    def _step():
        # inputs stay in their storage dtype (bf16 on the fast path): the
        # MXU natively multiplies bf16 with f32 accumulation — upcasting
        # first would force 8x-slower f32 matmul passes. q arrives
        # pre-multiplied by scale*log2(e), so s/m/l live in exp2 space.
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk], exp2 domain
        if bias_ref is not None:
            # per-key additive bias (padding mask), broadcast over query
            # rows; the bias is natural-log units → exp2 domain
            s = s + bias_ref[0].astype(jnp.float32) * _LOG2E  # [1, bk]
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp residual for the backward kernels, converted
            # back to natural-log units (the ring-merge contract)
            lse_ref[0, 0, :] = (m_ref[:, 0] * _LN2
                                + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30)))


def _keybias_block(kv_len: int, kv_block: int) -> Optional[int]:
    """KV block size usable for the bias operand: its (1, bk) VMEM tile must
    have bk divisible by 128 or equal to kv_len (TPU lane tiling). Returns
    None when no such block exists within reasonable VMEM."""
    for c in range(min(kv_len, kv_block), 127, -128):
        if kv_len % c == 0 and c % 128 == 0:
            return c
    if kv_len <= 4096:
        return kv_len  # single block: tiny bias row, k/v tiles still fit
    return None


def _vma_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying the input's varying-manual-axes so
    pallas_call outputs satisfy shard_map's vma check (ulysses/ring run the
    kernel inside shard_map)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_fwd_pallas(q, k, v, scale: float, causal: bool,
                      q_block: int, kv_block: int,
                      key_bias: Optional[jax.Array] = None,
                      return_lse: bool = False):
    """``key_bias``: optional [batch, kv_len] additive per-key bias (the
    padding-mask form) applied inside the kernel. ``return_lse`` also
    returns the per-row logsumexp ``[bh, q_len]`` (the backward kernels'
    residual); only supported without ``key_bias``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, q_len, d = q.shape
    kv_len = k.shape[-2]
    bq = _largest_divisor_leq(q_len, q_block)
    bk = _largest_divisor_leq(kv_len, kv_block)
    if key_bias is not None:
        bk = _keybias_block(kv_len, kv_block)
        assert bk is not None  # dispatch checks before routing here
        # bias rides as [b, 1, kv_len] so its block's trailing dims obey the
        # (8, 128) tiling rules with a unit sublane
        key_bias = key_bias.reshape(b, 1, kv_len)
    bh = b * h
    # scale*log2e folds into q here — XLA fuses it into the preceding
    # producer, and the kernel's softmax runs in exp2 space with no
    # per-element multiplies
    qf = (q * (scale * _LOG2E)).astype(q.dtype).reshape(bh, q_len, d)
    kf = k.reshape(bh, kv_len, d)
    vf = v.reshape(bh, kv_len, d)

    grid = (bh, q_len // bq, kv_len // bk)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, has_bias=key_bias is not None,
                               has_lse=return_lse)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda a, i, j: (a, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), lambda a, i, j: (a, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), lambda a, i, j: (a, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qf, kf, vf]
    if key_bias is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, bk), lambda a, i, j, h=h: (a // h, 0, j),
                         memory_space=pltpu.VMEM))
        operands.append(key_bias)
    out_shape = _vma_struct((bh, q_len, d), q.dtype, q)
    out_specs = pl.BlockSpec((1, bq, d), lambda a, i, j: (a, i, 0),
                             memory_space=pltpu.VMEM)
    if return_lse:
        # ride as [bh, 1, q_len]: the (1, bq) trailing block dims satisfy
        # the TPU (8, 128) tiling rules via a unit sublane
        out_shape = (out_shape,
                     _vma_struct((bh, 1, q_len), jnp.float32, q))
        out_specs = (out_specs,
                     pl.BlockSpec((1, 1, bq), lambda a, i, j: (a, 0, i),
                                  memory_space=pltpu.VMEM))
    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        # batch·head and query blocks are independent; only the kv axis
        # carries the streaming-softmax accumulator — telling Mosaic lets it
        # overlap DMA and compute across the parallel axes
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(*operands)
    if return_lse:
        o, lse = out
        return o.reshape(b, h, q_len, d), lse.reshape(bh, q_len)
    return out.reshape(b, h, q_len, d)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                         gl_ref, dq_ref, dq_acc, *, scale: float,
                         causal: bool, bq: int, bk: int):
    """dq = Σ_k ds @ K with ds = p * (dO V^T − D + glse), where glse is the
    cotangent of the lse output (zero when only the attention output is
    used). q arrives pre-scaled by scale*log2e so p = exp2(qk − lse·log2e)
    with no per-element multiplies; the deferred ds·scale lands on the
    [bq, d] result at finalize. Grid (bh, n_q, n_kv); accumulates over the
    innermost kv axis."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (qi + 1) * bq > ki * bk

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk], exp2 domain
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp2(s - lse_ref[0, 0][:, None] * _LOG2E)  # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - dd_ref[0, 0][:, None]
                  + gl_ref[0, 0][:, None])  # scale deferred to finalize
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                          gl_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                          scale: float, causal: bool, bq: int, bk: int):
    """dv = Σ_q p^T dO; dk = Σ_q ds^T q. Grid (bh, n_kv, n_q); accumulates
    over the innermost query axis."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi + 1) * bq > ki * bk

    @pl.when(run)
    def _step():
        q = q_ref[0]  # pre-scaled by scale*log2e
        k = k_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk], exp2 domain
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp2(s - lse_ref[0, 0][:, None] * _LOG2E)  # [bq, bk]
        pt = p.astype(do.dtype)
        dv_acc[:] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        # ds against the PRE-SCALED q accumulates scale*log2e·(true dk); one
        # ln2 multiply on the [bk, d] result at finalize undoes the log2e
        # (the caller's q carried the scale, so dk keeps the bare `scale`)
        ds = (p * (dp - dd_ref[0, 0][:, None]
                   + gl_ref[0, 0][:, None])).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[:] * _LN2).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                            gl_ref, dq_ref, dk_ref, dv_ref, dq_acc, *,
                            scale: float, causal: bool, bq: int, bk: int,
                            kv_len: int):
    """Single-pass flash backward: K and V ride fully VMEM-resident per
    batch·head; dk/dv accumulate in the f32 output refs across the q sweep
    (their block index is constant within a batch·head, so Mosaic keeps the
    window in VMEM — the standard matmul-accumulator pattern); dq finishes
    within one program via an inner KV loop. Each probability tile is
    computed ONCE (the two-pass design recomputes s and dp in both grids:
    7 matmul passes vs 5 here) and q/k/v/do stream from HBM once instead of
    twice. Causal trip count is bounded per q block, preserving the
    skip-masked-blocks saving."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    n_q = pl.num_programs(1)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    dq_acc[:] = jnp.zeros_like(dq_acc)
    q = q_ref[0]  # [bq, d], pre-scaled by scale*log2e
    do = do_ref[0]  # [bq, d]
    lse2 = lse_ref[0, 0][:, None] * _LOG2E  # exp2 domain
    dd = dd_ref[0, 0][:, None]
    gl = gl_ref[0, 0][:, None]
    n_kv = kv_len // bk
    if causal:
        # kv blocks strictly above the diagonal contribute nothing
        j_hi = jnp.minimum(((qi + 1) * bq + bk - 1) // bk, n_kv)
    else:
        j_hi = n_kv

    def body(j, _):
        kc = k_ref[0, pl.ds(j * bk, bk), :]  # [bk, d]
        vc = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, kc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk], exp2 domain
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp2(s - lse2)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, vc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - dd + gl)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kc.dtype), kc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        pt = p.astype(do.dtype)
        dv_ref[0, pl.ds(j * bk, bk), :] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]
        dsc = ds.astype(q.dtype)
        # against the PRE-SCALED q: carries scale*log2e·(true dk); one ln2
        # multiply at the very end restores bare `scale` (see two-pass note)
        dk_ref[0, pl.ds(j * bk, bk), :] += jax.lax.dot_general(
            dsc, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return 0

    lax.fori_loop(0, j_hi, body, 0, unroll=False)
    dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)

    @pl.when(qi == n_q - 1)
    def _scale_dk():
        dk_ref[...] = dk_ref[...] * _LN2


# VMEM budget for the fused single-pass backward: K/V (storage dtype) +
# dk/dv f32 accumulators resident per batch·head = 2*itemsize + 8 bytes per
# kv·d element; capping the residents at ~6.6MB leaves room for q/do/dq
# tiles and the [bq, bk] f32 loop temporaries inside 16MB. Above it (e.g.
# s=8192 d=128 bf16, or s=4096 d=128 f32) the two-pass design takes over.
_FUSED_BWD_MAX_RESIDENT_BYTES = 6_600_000


def _fused_bwd_applicable(q_len: int, kv_len: int, d: int,
                          q_block: int, itemsize: int = 2) -> bool:
    bq = _largest_divisor_leq(q_len, q_block)
    resident = kv_len * d * (2 * itemsize + 8)
    return (resident <= _FUSED_BWD_MAX_RESIDENT_BYTES
            and (bq % 128 == 0 or bq == q_len))


def _flash_bwd_inputs(q, k, v, o, lse, g, scale, glse):
    """Shared backward-input preamble (fused AND two-pass kernels — they
    must stay interchangeable under the same entry point): q pre-scaled by
    scale*log2e, [bh, ...] reshapes, the D_i = Σ dO·O row reduction, and
    the lse-cotangent row (zero when only the attention output is used)."""
    b, h, q_len, d = q.shape
    kv_len = k.shape[-2]
    bh = b * h
    qf = (q * (scale * _LOG2E)).astype(q.dtype).reshape(bh, q_len, d)
    kf = k.reshape(bh, kv_len, d)
    vf = v.reshape(bh, kv_len, d)
    dof = g.reshape(bh, q_len, d).astype(q.dtype)
    dd = jnp.sum(g.reshape(bh, q_len, d).astype(jnp.float32)
                 * o.reshape(bh, q_len, d).astype(jnp.float32),
                 axis=-1).reshape(bh, 1, q_len)
    lse = lse.reshape(bh, 1, q_len)
    gl = (jnp.zeros((bh, 1, q_len), jnp.float32) if glse is None
          else glse.astype(jnp.float32).reshape(bh, 1, q_len))
    return qf, kf, vf, dof, dd, lse, gl


def _flash_bwd_fused(q, k, v, o, lse, g, scale: float, causal: bool,
                     q_block: int, kv_block: int, glse=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, q_len, d = q.shape
    kv_len = k.shape[-2]
    bq = _largest_divisor_leq(q_len, q_block)
    # inner KV block capped at 512: the loop body holds ~6 live [bq, bk] f32
    # temporaries (s, p, dp, ds, causal iotas); 512x512x4B each keeps them
    # inside the VMEM left over by the resident K/V + dk/dv accumulators
    bk = _largest_divisor_leq(kv_len, min(kv_block, 512))
    bh = b * h
    qf, kf, vf, dof, dd, lse, gl = _flash_bwd_inputs(q, k, v, o, lse, g,
                                                     scale, glse)

    q_spec = pl.BlockSpec((1, bq, d), lambda a, i: (a, i, 0),
                          memory_space=pltpu.VMEM)
    kv_full = pl.BlockSpec((1, kv_len, d), lambda a, i: (a, 0, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, bq), lambda a, i: (a, 0, i),
                            memory_space=pltpu.VMEM)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, scale=scale,
                          causal=causal, bq=bq, bk=bk, kv_len=kv_len),
        out_shape=(_vma_struct((bh, q_len, d), q.dtype, q),
                   _vma_struct((bh, kv_len, d), jnp.float32, k),
                   _vma_struct((bh, kv_len, d), jnp.float32, v)),
        grid=(bh, q_len // bq),
        in_specs=[q_spec, kv_full, kv_full, q_spec, row_spec, row_spec,
                  row_spec],
        out_specs=(q_spec, kv_full, kv_full),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qf, kf, vf, dof, lse, dd, gl)
    return (dq.reshape(b, h, q_len, d),
            dk.astype(k.dtype).reshape(b, h, kv_len, d),
            dv.astype(v.dtype).reshape(b, h, kv_len, d))


def _flash_bwd_pallas(q, k, v, o, lse, g, scale: float, causal: bool,
                      q_block: int, kv_block: int, glse=None):
    """Full flash backward on TPU. Preferred path: the fused single-pass
    kernel (:func:`_flash_bwd_fused`) whenever K/V + accumulators fit VMEM;
    otherwise recomputes p from the saved logsumexp in two gridded passes
    (dq; dk+dv), all matmuls in the storage dtype with f32 accumulation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if _fused_bwd_applicable(q.shape[-2], k.shape[-2], q.shape[-1], q_block,
                             q.dtype.itemsize):
        return _flash_bwd_fused(q, k, v, o, lse, g, scale, causal,
                                q_block, kv_block, glse=glse)

    b, h, q_len, d = q.shape
    kv_len = k.shape[-2]
    bq = _largest_divisor_leq(q_len, q_block)
    bk = _largest_divisor_leq(kv_len, kv_block)
    bh = b * h
    qf, kf, vf, dof, dd, lse, gl = _flash_bwd_inputs(q, k, v, o, lse, g,
                                                     scale, glse)

    q_spec = pl.BlockSpec((1, bq, d), lambda a, i, j: (a, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, d), lambda a, i, j: (a, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, bq), lambda a, i, j: (a, 0, i),
                            memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        out_shape=_vma_struct((bh, q_len, d), q.dtype, q),
        grid=(bh, q_len // bq, kv_len // bk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf, dof, lse, dd, gl)

    # second pass swaps the roles of the two block axes
    q_spec2 = pl.BlockSpec((1, bq, d), lambda a, i, j: (a, j, 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, bk, d), lambda a, i, j: (a, i, 0),
                            memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, 1, bq), lambda a, i, j: (a, 0, j),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        out_shape=(_vma_struct((bh, kv_len, d), k.dtype, k),
                   _vma_struct((bh, kv_len, d), v.dtype, v)),
        grid=(bh, kv_len // bk, q_len // bq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                  row_spec2, row_spec2],
        out_specs=(kv_spec2, kv_spec2),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf, dof, lse, dd, gl)
    return (dq.reshape(b, h, q_len, d), dk.reshape(b, h, kv_len, d),
            dv.reshape(b, h, kv_len, d))


# ---------------------------------------------------------------------------
# Fused short-sequence attention (BERT-class shapes)
# ---------------------------------------------------------------------------
#
# At seq <= ~256 the whole [s, s] score matrix fits VMEM, so streaming
# softmax is pure overhead — but XLA's fused path still materializes the f32
# probability chain in HBM several times across fwd+bwd (measured 2.15 GB
# per BERT-base block at b128 s128; the step is HBM-bound). These kernels
# keep the probabilities entirely in VMEM: one program per (batch*head)
# computes exact softmax forward, and ONE backward program recomputes the
# probabilities and emits dq, dk, dv together. Optional per-key bias
# (padding mask) and in-kernel dropout (pltpu PRNG, identically re-seeded in
# the backward so the recomputed mask matches the forward's).


def _fused_short_fwd_kernel(*refs, has_bias: bool, rate: float,
                            causal: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = 0
    seed_ref = None
    if rate > 0.0:
        seed_ref = refs[i]; i += 1
    q_ref, k_ref, v_ref = refs[i:i + 3]; i += 3
    bias_ref = None
    if has_bias:
        bias_ref = refs[i]; i += 1
    o_ref = refs[i]

    # blocks are [G, s, d]: G (batch·head) pairs per program, batched dots
    # (amortizes per-program overhead — G=1 measured 2.8x slower than XLA)
    q = q_ref[...]
    s_ = jax.lax.dot_general(
        q, k_ref[...], (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)  # [G, s, s], exp2 domain
    if bias_ref is not None:
        # pre-broadcast [G, s, s] bf16, already in exp2 units (gridded
        # sub-3D broadcasts crash Mosaic's layout pass)
        s_ = s_ + bias_ref[...].astype(jnp.float32)
    if causal:
        # diagonal stays visible, so no row is ever fully masked and the
        # running max below stays finite
        row = jax.lax.broadcasted_iota(jnp.int32, s_.shape[1:], 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s_.shape[1:], 1)
        s_ = jnp.where((col > row)[None], _NEG_INF, s_)
    m = jnp.max(s_, axis=-1, keepdims=True)
    p = jnp.exp2(s_ - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    if rate > 0.0:
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.prng_random_bits(p.shape)
        thresh = min(int(rate * 4294967296.0), 4294967295)
        keep = bits.astype(jnp.uint32) >= jnp.uint32(thresh)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    o_ref[...] = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[...], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _fused_short_bwd_kernel(*refs, scale2: float, has_bias: bool,
                            rate: float, causal: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = 0
    seed_ref = None
    if rate > 0.0:
        seed_ref = refs[i]; i += 1
    q_ref, k_ref, v_ref, do_ref = refs[i:i + 4]; i += 4
    bias_ref = None
    if has_bias:
        bias_ref = refs[i]; i += 1
    dq_ref, dk_ref, dv_ref = refs[i:i + 3]

    q = q_ref[...]
    k = k_ref[...]
    do = do_ref[...]
    s_ = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)  # [G, s, s]
    if bias_ref is not None:
        s_ = s_ + bias_ref[...].astype(jnp.float32)  # [G, s, s], exp2 units
    if causal:
        # masking the recomputed scores suffices for the whole backward:
        # p = 0 above the diagonal, so ds, dv and dk contributions vanish
        row = jax.lax.broadcasted_iota(jnp.int32, s_.shape[1:], 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s_.shape[1:], 1)
        s_ = jnp.where((col > row)[None], _NEG_INF, s_)
    m = jnp.max(s_, axis=-1, keepdims=True)
    p = jnp.exp2(s_ - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)  # pre-dropout probabilities
    if rate > 0.0:
        # identical seeding to the forward → identical mask
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.prng_random_bits(p.shape)
        thresh = min(int(rate * 4294967296.0), 4294967295)
        keep = bits.astype(jnp.uint32) >= jnp.uint32(thresh)
        inv = 1.0 / (1.0 - rate)
        pd = jnp.where(keep, p * inv, 0.0)  # dropped probs (fwd's p)
    else:
        pd = p
    dv_ref[...] = jax.lax.dot_general(
        pd.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dpd = jax.lax.dot_general(
        do, v_ref[...], (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)  # [G, s, s]
    if rate > 0.0:
        dp = jnp.where(keep, dpd * inv, 0.0)
    else:
        dp = dpd
    # softmax vjp on the NATURAL-domain probabilities (ds carries no ln2:
    # the exp2 fold is compensated in the dq/dk output scales below)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    ds_c = ds.astype(q.dtype)
    # q is pre-scaled by scale·log2e: dq_true = scale·(ds @ k);
    # dk_true = ds^T @ (q·scale·log2e) · ln2/(scale·log2e)·scale = ln2·(ds^T @ q)
    dq_ref[...] = (jax.lax.dot_general(
        ds_c, k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale2).astype(dq_ref.dtype)
    dk_ref[...] = (jax.lax.dot_general(
        ds_c, q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * _LN2).astype(dk_ref.dtype)


def _fused_short_call(q, k, v, key_bias, scale, rate, seed, causal=False,
                      fwd=True, do=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    bh = b * h
    # G (batch·head) pairs per program: biggest divisor of bh whose [G, s, s]
    # f32 score block keeps the backward's ~7 live copies (s_, p, pd, dpd,
    # dp, ds, mask) plus double-buffered DMAs inside the 16MB VMEM; G=64
    # also fails a Mosaic batched-dot layout check
    G = _largest_divisor_leq(bh, max(1, min(16, (1 << 20) // (s * s * 4))))
    qf = (q * (scale * _LOG2E)).astype(q.dtype).reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    tile = pl.BlockSpec((G, s, d), lambda a: (a, 0, 0),
                        memory_space=pltpu.VMEM)
    in_specs = []
    operands = []
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(seed, jnp.int32).reshape(1))
    in_specs += [tile, tile, tile]
    operands += [qf, kf, vf]
    if do is not None:
        in_specs.append(tile)
        operands.append(do.reshape(bh, s, d).astype(q.dtype))
    has_bias = key_bias is not None
    if has_bias:
        # the bias ships PRE-BROADCAST [bh, s, s] in bf16 and pre-scaled to
        # exp2 units: in-grid sub-3D broadcasts crash Mosaic's layout pass,
        # and a bf16 mask read per program is still ~95% less traffic than
        # the XLA path's f32 probability chain
        kb = (key_bias.astype(jnp.float32) * _LOG2E).astype(jnp.bfloat16)
        kb_full = jnp.broadcast_to(
            jnp.repeat(kb.reshape(b, 1, s), h, axis=0).reshape(bh, 1, s),
            (bh, s, s))
        in_specs.append(pl.BlockSpec((G, s, s), lambda a: (a, 0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(kb_full)
    compiler_params = pltpu.CompilerParams(
        dimension_semantics=("parallel",))
    if fwd:
        out = pl.pallas_call(
            functools.partial(_fused_short_fwd_kernel,
                              has_bias=has_bias, rate=rate, causal=causal),
            out_shape=_vma_struct((bh, s, d), q.dtype, q),
            grid=(bh // G,), in_specs=in_specs, out_specs=tile,
            compiler_params=compiler_params)(*operands)
        return out.reshape(b, h, s, d)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_fused_short_bwd_kernel, scale2=scale,
                          has_bias=has_bias, rate=rate, causal=causal),
        out_shape=(_vma_struct((bh, s, d), q.dtype, q),
                   _vma_struct((bh, s, d), k.dtype, k),
                   _vma_struct((bh, s, d), v.dtype, v)),
        grid=(bh // G,), in_specs=in_specs, out_specs=(tile, tile, tile),
        compiler_params=compiler_params)(*operands)
    return (dq.reshape(b, h, s, d), dk.reshape(b, h, s, d),
            dv.reshape(b, h, s, d))


# seed rides as a (traced) int32 array argument — it cannot be a
# nondiff_argnum (those must be static) — and gets a None cotangent
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused_short(q, k, v, key_bias, seed, scale, rate, causal):
    return _fused_short_call(q, k, v, key_bias, scale, rate, seed,
                             causal=causal, fwd=True)


def _fused_short_fwd(q, k, v, key_bias, seed, scale, rate, causal):
    out = _fused_short_call(q, k, v, key_bias, scale, rate, seed,
                            causal=causal, fwd=True)
    return out, (q, k, v, key_bias, seed)


def _fused_short_bwd(scale, rate, causal, residuals, g):
    q, k, v, key_bias, seed = residuals
    dq, dk, dv = _fused_short_call(q, k, v, key_bias, scale, rate, seed,
                                   causal=causal, fwd=False, do=g)
    dbias = None if key_bias is None else jnp.zeros_like(key_bias)
    return dq, dk, dv, dbias, None


_fused_short.defvjp(_fused_short_fwd, _fused_short_bwd)

# VMEM budget for the fused kernel's [s, s] f32 score block (plus q/k/v/do
# tiles); 512x512 f32 = 1 MB — comfortably resident
FUSED_SHORT_MAX_SEQ = 512


def fused_short_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          key_bias: Optional[jax.Array] = None,
                          scale: Optional[float] = None,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None,
                          causal: bool = False) -> jax.Array:
    """Exact (non-streaming) fused attention for short sequences:
    probabilities never leave VMEM in either direction, and the backward is
    a single kernel emitting dq/dk/dv. ``key_bias``: optional
    ``[batch, kv_len]`` additive per-key bias (padding mask). ``causal``
    applies the in-kernel lower-triangular mask (the generative prefill
    path — the whole score block is already resident, so the mask is one
    VPU select, not a second kernel). Attention dropout runs in-kernel on
    the TPU PRNG, deterministically re-seeded in the backward pass. The
    bias is a PADDING MASK, not a trained quantity — its gradient is zero
    (same contract as the flash key-bias path); use the XLA paths for
    trainable biases."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    seed = jnp.zeros((), jnp.int32)
    rate = 0.0
    if dropout_rate > 0.0 and dropout_rng is not None:
        rate = float(dropout_rate)  # zoolint: disable=jit-host-sync — static Python hyperparameter, not a tracer
        seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)
    return _fused_short(q, k, v, key_bias, seed, scale, rate, causal)


def fused_short_applicable(q_len: int, kv_len: int, causal: bool) -> bool:
    del causal  # the kernel masks in-VMEM since the generative-serving PR
    return (_on_tpu() and q_len == kv_len
            and kv_len <= FUSED_SHORT_MAX_SEQ)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, q_block, kv_block):
    if _on_tpu():
        return _flash_fwd_pallas(q, k, v, scale, causal, q_block, kv_block)
    return blockwise_attention(q, k, v, None, causal, scale, q_block, kv_block)


def _lse_tile_ok(q_len: int, q_block: int) -> bool:
    """The lse/D row tiles are (1, 1, bq): legal only when bq is a multiple
    of 128 or spans the whole row (same lane-tiling rule _keybias_block
    enforces for the bias tile)."""
    bq = _largest_divisor_leq(q_len, q_block)
    return bq == q_len or bq % 128 == 0


def _flash_fwd(q, k, v, scale, causal, q_block, kv_block):
    if _on_tpu() and _lse_tile_ok(q.shape[-2], q_block):
        out, lse = _flash_fwd_pallas(q, k, v, scale, causal, q_block,
                                     kv_block, return_lse=True)
        return out, (q, k, v, out, lse)
    out = (_flash_fwd_pallas(q, k, v, scale, causal, q_block, kv_block)
           if _on_tpu() else
           blockwise_attention(q, k, v, None, causal, scale, q_block,
                               kv_block))
    return out, (q, k, v, None, None)


def _flash_bwd(scale, causal, q_block, kv_block, residuals, g):
    q, k, v, o, lse = residuals
    if lse is not None:
        return _flash_bwd_pallas(q, k, v, o, lse, g, scale, causal,
                                 q_block, kv_block)
    # off-TPU: recompute-based backward through the blockwise path
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, None, causal, scale, q_block, kv_block), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, scale, causal, q_block, kv_block):
    return _flash_lse_fwd(q, k, v, scale, causal, q_block, kv_block)[0]


def _flash_lse_fwd(q, k, v, scale, causal, q_block, kv_block):
    b, h, q_len, _ = q.shape
    if _on_tpu() and _lse_tile_ok(q_len, q_block):
        out, lse = _flash_fwd_pallas(q, k, v, scale, causal, q_block,
                                     kv_block, return_lse=True)
        return ((out, lse.reshape(b, h, q_len)),
                (q, k, v, out, lse, True))
    out, lse = blockwise_attention(q, k, v, None, causal, scale, q_block,
                                   kv_block, return_lse=True)
    # the fallback backward recomputes via vjp: only q/k/v are needed, so
    # don't pin the forward activations in the residuals
    return (out, lse), (q, k, v, None, None, False)


def _flash_lse_bwd(scale, causal, q_block, kv_block, residuals, gs):
    q, k, v, o, lse, used_pallas = residuals
    go, glse = gs
    if used_pallas:
        return _flash_bwd_pallas(q, k, v, o, lse, go, scale, causal,
                                 q_block, kv_block, glse=glse)
    # off-TPU: autodiff through the blockwise lse path
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, None, causal, scale, q_block, kv_block,
            return_lse=True), q, k, v)
    return vjp((go, glse))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        q_block: int = DEFAULT_Q_BLOCK,
                        kv_block: int = DEFAULT_KV_BLOCK):
    """Fused attention that ALSO returns the per-row logsumexp
    ``[batch, heads, q_len]`` — the sufficient statistic for merging partial
    attentions over disjoint KV shards (ring hops):

        lse_c = logaddexp(lse_a, lse_b)
        out_c = out_a * exp(lse_a - lse_c) + out_b * exp(lse_b - lse_c)

    Jointly differentiable in both outputs: on TPU the lse cotangent folds
    into the backward kernels' ``ds`` term, off-TPU autodiff flows through
    the blockwise scan."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_lse(q, k, v, scale, causal, q_block, kv_block)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_keybias(q, k, v, key_bias, scale, causal, q_block, kv_block):
    if _on_tpu():
        return _flash_fwd_pallas(q, k, v, scale, causal, q_block, kv_block,
                                 key_bias=key_bias)
    return blockwise_attention(q, k, v, key_bias[:, None, None, :], causal,
                               scale, q_block, kv_block)


def _flash_keybias_fwd(q, k, v, key_bias, scale, causal, q_block, kv_block):
    return (_flash_keybias(q, k, v, key_bias, scale, causal, q_block,
                           kv_block), (q, k, v, key_bias))


def _flash_keybias_bwd(scale, causal, q_block, kv_block, residuals, g):
    q, k, v, key_bias = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, key_bias[:, None, None, :], causal, scale,
            q_block, kv_block), q, k, v)
    dq, dk, dv = vjp(g)
    # the bias is a padding mask, not a trained quantity
    return dq, dk, dv, jnp.zeros_like(key_bias)


_flash_keybias.defvjp(_flash_keybias_fwd, _flash_keybias_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: Optional[jax.Array] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    q_block: int = DEFAULT_Q_BLOCK,
                    kv_block: int = DEFAULT_KV_BLOCK) -> jax.Array:
    """Fused attention: pallas kernel on TPU, blockwise XLA elsewhere.

    A per-key padding bias in the UNAMBIGUOUS ``[b, 1, 1, kv]`` form (what
    the mask layers build) runs inside the pallas kernel; any other bias
    shape (including 2-D, which has always meant a broadcast ``[q, kv]``
    matrix) falls back to the blockwise path.
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if bias is not None:
        kv_len = k.shape[-2]
        if bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1 \
                and bias.shape[0] == q.shape[0] and bias.shape[3] == kv_len \
                and _keybias_block(kv_len, kv_block) is not None:
            return _flash_keybias(q, k, v, bias[:, 0, 0, :], scale, causal,
                                  q_block, kv_block)
        return blockwise_attention(q, k, v, bias, causal, scale,
                                   q_block, kv_block)
    return _flash(q, k, v, scale, causal, q_block, kv_block)
