"""RedisQueue wire contract, exercised against an in-memory fake that
implements the redis-stream subset the queue uses (XADD/XREADGROUP/XACK/
XAUTOCLAIM/XINFO GROUPS/HSET/HGETALL/XLEN/XTRIM) — the reference's Redis
contract (``serving/queues.py`` RedisQueue) previously had no test at all.

The fake models the PEL faithfully (owning consumer + idle clock): the
at-most-once fix — ACK only after the result lands, XAUTOCLAIM reclaims
entries from consumers that died mid-batch — is asserted against it."""
import sys
import time as _time
import types

import numpy as np
import pytest


class FakeRedis:
    """Minimal StrictRedis stand-in: one stream + hash keyspace, with the
    byte-typed responses the real client returns."""

    instances = {}

    def __new__(cls, host="localhost", port=6379, db=0):
        key = (host, port, db)
        if key not in cls.instances:
            inst = super().__new__(cls)
            inst.streams = {}
            inst.groups = {}
            inst.hashes = {}
            inst.next_id = 1
            cls.instances[key] = inst
        return cls.instances[key]

    # -- streams ------------------------------------------------------------
    def xadd(self, stream, fields):
        entries = self.streams.setdefault(stream, [])
        eid = f"{self.next_id}-0".encode()
        self.next_id += 1
        entries.append((eid, {k.encode() if isinstance(k, str) else k:
                              v.encode() if isinstance(v, str) else v
                              for k, v in fields.items()}))
        return eid

    def xgroup_create(self, stream, group, id="$", mkstream=False):
        if stream not in self.streams:
            if not mkstream:
                raise RuntimeError("NOGROUP no such stream")
            self.streams[stream] = []
        # pel: eid -> [consumer, last_delivery_monotonic] — the real PEL's
        # ownership + idle-time fields, which XAUTOCLAIM keys on
        self.groups.setdefault((stream, group), {"delivered": 0, "pel": {}})

    def xreadgroup(self, group, consumer, streams, count=None, block=None):
        out = []
        for stream, cursor in streams.items():
            g = self.groups.get((stream, group))
            if g is None:
                raise RuntimeError("NOGROUP")
            entries = self.streams.get(stream, [])
            fresh = entries[g["delivered"]:]
            if count is not None:
                fresh = fresh[:count]
            g["delivered"] += len(fresh)
            now = _time.monotonic()
            for eid, _ in fresh:
                g["pel"][eid] = [consumer, now]
            if fresh:
                out.append((stream.encode(), list(fresh)))
        return out

    def xack(self, stream, group, *ids):
        g = self.groups[(stream, group)]
        n = 0
        for eid in ids:
            if g["pel"].pop(eid, None) is not None:
                n += 1
        return n

    def xautoclaim(self, stream, group, consumer, min_idle_time=0,
                   start_id="0-0", count=None):
        """Reassign PEL entries idle past ``min_idle_time`` ms to
        ``consumer`` (redis >= 6.2 semantics, (next, entries, deleted)
        response shape)."""
        g = self.groups[(stream, group)]
        now = _time.monotonic()
        out = []
        for eid, meta in sorted(g["pel"].items()):
            if (now - meta[1]) * 1000.0 < min_idle_time:
                continue
            fields = next((f for e, f in self.streams.get(stream, [])
                           if e == eid), None)
            if fields is None:
                continue  # trimmed out from under the PEL
            meta[0] = consumer
            meta[1] = now
            out.append((eid, fields))
            if count is not None and len(out) >= count:
                break
        return (b"0-0", out, [])

    def xinfo_groups(self, stream):
        out = []
        for (s, group), g in self.groups.items():
            if s != stream:
                continue
            out.append({"name": group.encode(),
                        "pending": len(g["pel"]),
                        "lag": max(0, len(self.streams.get(stream, []))
                                   - g["delivered"])})
        return out

    def xinfo_consumers(self, stream, group):
        g = self.groups.get((stream, group))
        if g is None:
            raise RuntimeError("NOGROUP")
        now = _time.monotonic()
        counts, idle = {}, {}
        for eid, (consumer, ts) in g["pel"].items():
            counts[consumer] = counts.get(consumer, 0) + 1
            idle[consumer] = min(idle.get(consumer, float("inf")),
                                 (now - ts) * 1000.0)
        return [{"name": c.encode() if isinstance(c, str) else c,
                 "pending": n, "idle": int(idle[c])}
                for c, n in sorted(counts.items())]

    def xlen(self, stream):
        return len(self.streams.get(stream, []))

    def xtrim(self, stream, maxlen):
        entries = self.streams.get(stream, [])
        drop = max(0, len(entries) - maxlen)
        if drop:
            self.streams[stream] = entries[drop:]
            for (s, _), g in self.groups.items():
                if s == stream:
                    g["delivered"] = max(0, g["delivered"] - drop)
        return drop

    # -- hashes -------------------------------------------------------------
    def hset(self, key, mapping):
        h = self.hashes.setdefault(key, {})
        for k, v in mapping.items():
            h[k.encode() if isinstance(k, str) else k] = (
                v.encode() if isinstance(v, str) else v)
        return len(mapping)

    def hgetall(self, key):
        return dict(self.hashes.get(key, {}))


@pytest.fixture()
def fake_redis(monkeypatch):
    FakeRedis.instances.clear()
    mod = types.ModuleType("redis")
    mod.StrictRedis = FakeRedis
    monkeypatch.setitem(sys.modules, "redis", mod)
    return FakeRedis


class TestRedisQueueContract:
    def test_enqueue_claim_ack_roundtrip(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue("testhost", 6379)
        q.enqueue("a", {"tensor": [1.0, 2.0]})
        q.enqueue("b", {"tensor": [3.0]})
        assert q.pending_count() == 2
        batch = q.claim_batch(10)
        assert [uri for uri, _ in batch] == ["a", "b"]
        assert batch[0][1]["tensor"] == [1.0, 2.0]
        # claimed entries are ACKed: a second read returns nothing
        assert q.claim_batch(10) == []

    def test_claim_respects_max_items(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(5):
            q.enqueue(f"u{i}", {"tensor": [i]})
        assert len(q.claim_batch(2)) == 2
        assert len(q.claim_batch(10)) == 3

    def test_result_roundtrip(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        q.put_result("u1", {"value": [0.1, 0.9], "class": 1})
        res = q.get_result("u1")
        assert res == {"value": [0.1, 0.9], "class": 1}
        assert q.get_result("missing") is None

    def test_trim_backpressure(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(10):
            q.enqueue(f"u{i}", {"tensor": [i]})
        dropped = q.trim(4)
        assert dropped == 6
        assert q.pending_count() == 4

    def test_make_queue_hostport_routes_to_redis(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue, make_queue
        q = make_queue("somehost:6379")
        assert isinstance(q, RedisQueue)


class TestAtMostOnceFix:
    """The claim→result window must not lose requests: XACK happens only
    AFTER put_result lands, and entries stranded in a dead consumer's PEL
    are XAUTOCLAIMed back onto a live one."""

    def _pel(self):
        inst = FakeRedis.instances[("localhost", 6379, 0)]
        return inst.groups[("image_stream", "serving")]["pel"]

    def test_ack_only_after_result_lands(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        q.enqueue("a", {"tensor": [1.0]})
        batch = q.claim_batch(10)
        assert [u for u, _ in batch] == ["a"]
        # claimed but unanswered: the entry is still pending (NOT acked)
        assert len(self._pel()) == 1
        q.put_result("a", {"value": [1.0]})
        assert self._pel() == {}  # result landed → ack closed the loop

    def test_crash_between_claim_and_result_redelivers(self, fake_redis):
        """A server that claims a batch and dies before posting results
        must NOT drop it forever: once the lease expires, another consumer
        reclaims the pending entry and serves it."""
        from analytics_zoo_tpu.serving.queues import RedisQueue
        qa = RedisQueue(claim_lease_s=0.05)
        qa.enqueue("a", {"tensor": [1.0]})
        assert [u for u, _ in qa.claim_batch(10)] == ["a"]
        # qa "crashes" here: no put_result, no ack
        qb = RedisQueue(claim_lease_s=0.05)
        assert qb.consumer != qa.consumer
        assert qb.claim_batch(10) == []  # lease still live: no steal
        import time
        time.sleep(0.08)
        got = qb.claim_batch(10)  # lease expired: XAUTOCLAIM redelivers
        assert [u for u, _ in got] == ["a"]
        qb.put_result("a", {"value": [1.0]})
        assert self._pel() == {}
        assert qb.claim_batch(10) == []  # settled: nothing redelivers

    def test_pending_count_is_undelivered_backlog(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(4):
            q.enqueue(f"u{i}", {"tensor": [i]})
        assert q.pending_count() == 4
        q.claim_batch(2)
        # claimed-but-unacked entries are in flight, not queue backlog —
        # admission control must not shed phantom load
        assert q.pending_count() == 2

    def test_shed_posts_error_results(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(10):
            q.enqueue(f"u{i}", {"tensor": [i]})
        dropped = q.shed(4)
        assert dropped == [f"u{i}" for i in range(6)]  # oldest first
        for u in dropped:
            assert "overloaded" in q.get_result(u)["error"]
        assert self._pel() == {}  # shed entries are settled, not pending
        # the newest max_pending survive and serve normally
        assert [u for u, _ in q.claim_batch(10)] == ["u6", "u7", "u8", "u9"]


class TestPerConsumerPending:
    """XINFO CONSUMERS surfaces the true per-instance backlog — what each
    consumer has claimed and not yet answered (the fleet router's
    placement signal) — where group lag only shows undelivered work."""

    def test_per_consumer_pending_counts(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        qa = RedisQueue()
        qb = RedisQueue()
        for i in range(5):
            qa.enqueue(f"u{i}", {"tensor": [i]})
        assert [u for u, _ in qa.claim_batch(3)] == ["u0", "u1", "u2"]
        assert [u for u, _ in qb.claim_batch(10)] == ["u3", "u4"]
        assert qa.consumer_pending() == {qa.consumer: 3, qb.consumer: 2}
        # answering settles the claim: the consumer's count drops
        qa.put_result("u0", {"value": [0]})
        qa.put_result("u1", {"value": [1]})
        assert qa.consumer_pending()[qa.consumer] == 1
        assert qa.consumer_pending()[qb.consumer] == 2

    def test_degrades_to_empty_without_xinfo_consumers(self, fake_redis,
                                                       monkeypatch):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        q.enqueue("a", {"tensor": [1]})
        q.claim_batch(10)
        monkeypatch.delattr(FakeRedis, "xinfo_consumers")
        assert q.consumer_pending() == {}


class TestServingOverFakeRedis:
    def test_end_to_end_serve(self, fake_redis, tmp_path):
        """Full engine loop on the redis backend: enqueue → serve_once →
        results, same flow the FileQueue test covers."""
        from analytics_zoo_tpu.models import NeuralCF
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        ncf = NeuralCF(20, 15, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=2)
        ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 20, 64), rs.randint(1, 15, 64)], 1) \
            .astype(np.float32)
        ncf.fit(x, (rs.rand(64) > 0.5).astype(np.float32), batch_size=32,
                nb_epoch=1)
        model_path = str(tmp_path / "model")
        ncf.save_model(model_path)

        cfg = ServingConfig(model_path=model_path, model_type="zoo",
                            data_src="fakeredis:6379", batch_size=4)
        serving = ClusterServing(cfg)
        inq = InputQueue("fakeredis:6379")
        outq = OutputQueue("fakeredis:6379")
        for i in range(6):
            inq.enqueue_tensor(f"req-{i}", x[i])
        served = 0
        while served < 6:
            n = serving.serve_once()
            assert n > 0, "engine made no progress"
            served += n
        for i in range(6):
            res = outq.query(f"req-{i}")
            assert res is not None and "value" in res
