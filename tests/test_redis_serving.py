"""RedisQueue wire contract, exercised against an in-memory fake that
implements the redis-stream subset the queue uses (XADD/XREADGROUP/XACK/
HSET/HGETALL/XLEN/XTRIM) — the reference's Redis contract
(``serving/queues.py`` RedisQueue) previously had no test at all."""
import sys
import types

import numpy as np
import pytest


class FakeRedis:
    """Minimal StrictRedis stand-in: one stream + hash keyspace, with the
    byte-typed responses the real client returns."""

    instances = {}

    def __new__(cls, host="localhost", port=6379, db=0):
        key = (host, port, db)
        if key not in cls.instances:
            inst = super().__new__(cls)
            inst.streams = {}
            inst.groups = {}
            inst.hashes = {}
            inst.next_id = 1
            cls.instances[key] = inst
        return cls.instances[key]

    # -- streams ------------------------------------------------------------
    def xadd(self, stream, fields):
        entries = self.streams.setdefault(stream, [])
        eid = f"{self.next_id}-0".encode()
        self.next_id += 1
        entries.append((eid, {k.encode() if isinstance(k, str) else k:
                              v.encode() if isinstance(v, str) else v
                              for k, v in fields.items()}))
        return eid

    def xgroup_create(self, stream, group, id="$", mkstream=False):
        if stream not in self.streams:
            if not mkstream:
                raise RuntimeError("NOGROUP no such stream")
            self.streams[stream] = []
        self.groups.setdefault((stream, group), {"delivered": 0, "pel": set()})

    def xreadgroup(self, group, consumer, streams, count=None, block=None):
        out = []
        for stream, cursor in streams.items():
            g = self.groups.get((stream, group))
            if g is None:
                raise RuntimeError("NOGROUP")
            entries = self.streams.get(stream, [])
            fresh = entries[g["delivered"]:]
            if count is not None:
                fresh = fresh[:count]
            g["delivered"] += len(fresh)
            g["pel"].update(eid for eid, _ in fresh)
            if fresh:
                out.append((stream.encode(), list(fresh)))
        return out

    def xack(self, stream, group, *ids):
        g = self.groups[(stream, group)]
        n = 0
        for eid in ids:
            if eid in g["pel"]:
                g["pel"].discard(eid)
                n += 1
        return n

    def xlen(self, stream):
        return len(self.streams.get(stream, []))

    def xtrim(self, stream, maxlen):
        entries = self.streams.get(stream, [])
        drop = max(0, len(entries) - maxlen)
        if drop:
            self.streams[stream] = entries[drop:]
            for (s, _), g in self.groups.items():
                if s == stream:
                    g["delivered"] = max(0, g["delivered"] - drop)
        return drop

    # -- hashes -------------------------------------------------------------
    def hset(self, key, mapping):
        h = self.hashes.setdefault(key, {})
        for k, v in mapping.items():
            h[k.encode() if isinstance(k, str) else k] = (
                v.encode() if isinstance(v, str) else v)
        return len(mapping)

    def hgetall(self, key):
        return dict(self.hashes.get(key, {}))


@pytest.fixture()
def fake_redis(monkeypatch):
    FakeRedis.instances.clear()
    mod = types.ModuleType("redis")
    mod.StrictRedis = FakeRedis
    monkeypatch.setitem(sys.modules, "redis", mod)
    return FakeRedis


class TestRedisQueueContract:
    def test_enqueue_claim_ack_roundtrip(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue("testhost", 6379)
        q.enqueue("a", {"tensor": [1.0, 2.0]})
        q.enqueue("b", {"tensor": [3.0]})
        assert q.pending_count() == 2
        batch = q.claim_batch(10)
        assert [uri for uri, _ in batch] == ["a", "b"]
        assert batch[0][1]["tensor"] == [1.0, 2.0]
        # claimed entries are ACKed: a second read returns nothing
        assert q.claim_batch(10) == []

    def test_claim_respects_max_items(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(5):
            q.enqueue(f"u{i}", {"tensor": [i]})
        assert len(q.claim_batch(2)) == 2
        assert len(q.claim_batch(10)) == 3

    def test_result_roundtrip(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        q.put_result("u1", {"value": [0.1, 0.9], "class": 1})
        res = q.get_result("u1")
        assert res == {"value": [0.1, 0.9], "class": 1}
        assert q.get_result("missing") is None

    def test_trim_backpressure(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue
        q = RedisQueue()
        for i in range(10):
            q.enqueue(f"u{i}", {"tensor": [i]})
        dropped = q.trim(4)
        assert dropped == 6
        assert q.pending_count() == 4

    def test_make_queue_hostport_routes_to_redis(self, fake_redis):
        from analytics_zoo_tpu.serving.queues import RedisQueue, make_queue
        q = make_queue("somehost:6379")
        assert isinstance(q, RedisQueue)


class TestServingOverFakeRedis:
    def test_end_to_end_serve(self, fake_redis, tmp_path):
        """Full engine loop on the redis backend: enqueue → serve_once →
        results, same flow the FileQueue test covers."""
        from analytics_zoo_tpu.models import NeuralCF
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        ncf = NeuralCF(20, 15, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=2)
        ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 20, 64), rs.randint(1, 15, 64)], 1) \
            .astype(np.float32)
        ncf.fit(x, (rs.rand(64) > 0.5).astype(np.float32), batch_size=32,
                nb_epoch=1)
        model_path = str(tmp_path / "model")
        ncf.save_model(model_path)

        cfg = ServingConfig(model_path=model_path, model_type="zoo",
                            data_src="fakeredis:6379", batch_size=4)
        serving = ClusterServing(cfg)
        inq = InputQueue("fakeredis:6379")
        outq = OutputQueue("fakeredis:6379")
        for i in range(6):
            inq.enqueue_tensor(f"req-{i}", x[i])
        served = 0
        while served < 6:
            n = serving.serve_once()
            assert n > 0, "engine made no progress"
            served += n
        for i in range(6):
            res = outq.query(f"req-{i}")
            assert res is not None and "value" in res
