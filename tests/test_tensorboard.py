"""Tests for the self-contained TensorBoard event writer/reader."""
import struct

from analytics_zoo_tpu.utils.tensorboard import (
    SummaryWriter, crc32c, decode_event, encode_scalar_event, frame_record,
    masked_crc32c, read_events, read_scalars)


def test_crc32c_known_vectors():
    # Known CRC32C test vectors (RFC 3720 / iSCSI)
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_masked_crc_roundtrip():
    data = b"hello tensorboard"
    framed = frame_record(data)
    length = struct.unpack("<Q", framed[:8])[0]
    assert length == len(data)
    assert struct.unpack("<I", framed[8:12])[0] == masked_crc32c(framed[:8])
    assert framed[12:12 + length] == data


def test_event_encode_decode():
    raw = encode_scalar_event("Loss", 0.25, step=7, wall_time=123.5)
    event = decode_event(raw)
    assert event["step"] == 7
    assert abs(event["wall_time"] - 123.5) < 1e-9
    assert event["scalars"] == [("Loss", 0.25)]


def test_writer_reader_roundtrip(tmp_path):
    logdir = str(tmp_path / "train")
    with SummaryWriter(logdir) as w:
        for step in range(5):
            w.add_scalar("Loss", 1.0 / (step + 1), step)
            w.add_scalar("Throughput", 100.0 + step, step)
        w.flush()
    losses = read_scalars(logdir, "Loss")
    assert [s for s, _ in losses] == [0, 1, 2, 3, 4]
    assert abs(losses[2][1] - 1.0 / 3) < 1e-6
    tp = read_scalars(logdir, "Throughput")
    assert len(tp) == 5
    # file_version header present
    fname = [f for f in (tmp_path / "train").iterdir()][0]
    events = read_events(str(fname))
    assert events[0].get("file_version") == "brain.Event:2"


def test_truncated_tail_is_eof(tmp_path):
    logdir = str(tmp_path / "t")
    with SummaryWriter(logdir) as w:
        w.add_scalar("Loss", 1.0, 0)
        w.flush()
    fname = str(next((tmp_path / "t").iterdir()))
    with open(fname, "ab") as f:
        f.write(b"\x10\x00\x00")  # partial frame at tail (file still being written)
    scalars = read_scalars(logdir, "Loss")
    assert scalars == [(0, 1.0)]
