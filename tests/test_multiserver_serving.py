"""N-server serving: two ClusterServing consumers against ONE queue must
serve every record exactly once and scale (the reference's cluster serving
is inherently multi-executor, ``ClusterServing.scala:160-259``).

File queue: two REAL processes (the FileQueue's cross-process claim is the
whole point). Redis: two server instances over one locked fake broker
(delivery atomicity is the broker's job; the fake models it faithfully).
"""
import json
import multiprocessing as mp
import sys
import threading
import time
import types

import numpy as np
import pytest


def _file_server_proc(root: str, n_records: int, stall_s: float,
                      tag: str, done_q):
    """Subprocess: serve from the shared file-queue spool until the done
    flag file appears; report every uri served."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig

    def fwd(p, x):
        return x.reshape(x.shape[0], -1).mean(1, keepdims=True)

    im = InferenceModel().load_jax(fwd, {})

    class StallModel:
        """Wraps predict with a host stall so a single server cannot drain
        the queue before the second one claims anything."""

        def predict(self, x):
            time.sleep(stall_s)
            return im.predict(x)

        def predict_async(self, x):
            f = im.predict_async(x)

            def fetch():
                time.sleep(stall_s)
                return f()
            return fetch

    cfg = ServingConfig(data_src=f"dir://{root}", batch_size=4,
                        batch_wait_ms=2, input_dtype="float32")
    srv = ClusterServing(cfg, model=StallModel())
    served = []
    orig_writeback = srv._writeback

    def writeback(uris, probs, elapsed):
        served.extend(uris)
        return orig_writeback(uris, probs, elapsed)

    srv._writeback = writeback
    import os
    with open(os.path.join(root, f"READY_{tag}"), "w") as f:
        f.write("1")  # model built + queue open: measurement may begin
    deadline = time.time() + 60
    while time.time() < deadline:
        n = srv.serve_once()
        if not n:
            if os.path.exists(root + "/DONE"):
                break
            time.sleep(0.01)
    done_q.put((tag, served))


class TestTwoProcessFileQueue:
    def test_exactly_once_across_two_processes(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        root = str(tmp_path / "spool")
        q = FileQueue(root)  # creates dirs
        n = 48
        inq = InputQueue(f"dir://{root}")
        for i in range(n):
            inq.enqueue_tensor(f"rec{i}", np.full((4,), float(i),
                                                  np.float32))
        ctx = mp.get_context("spawn")
        done_q = ctx.Queue()
        procs = [ctx.Process(target=_file_server_proc,
                             args=(root, n, 0.05, f"s{k}", done_q))
                 for k in range(2)]
        for p in procs:
            p.start()
        outq = OutputQueue(f"dir://{root}")
        deadline = time.time() + 120
        while time.time() < deadline:
            if len(outq.dequeue()) >= n:
                break
            time.sleep(0.2)
        (tmp_path / "spool" / "DONE").write_text("1")
        reports = {}
        for _ in procs:
            tag, served = done_q.get(timeout=60)
            reports[tag] = served
        for p in procs:
            p.join(timeout=30)

        all_served = [u for served in reports.values() for u in served]
        expect = {f"rec{i}" for i in range(n)}
        # exactly once: no record served twice, none lost
        assert len(all_served) == len(set(all_served)), "double-served!"
        assert set(all_served) == expect, \
            f"lost: {expect - set(all_served)}"
        # and BOTH servers did real work (the stall guarantees overlap)
        assert all(len(s) > 0 for s in reports.values()), reports
        # results all present
        results = outq.dequeue()
        assert set(results) == expect

    def test_two_server_throughput_scales(self, tmp_path):
        """Aggregate 2-server throughput ≥ 1.5x single-server on a stalling
        model (the stall dominates, so perfect scaling would be 2x)."""
        from analytics_zoo_tpu.serving import FileQueue
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        def run(n_servers: int, root: str) -> float:
            import pathlib
            q = FileQueue(root)
            n = 24
            ctx = mp.get_context("spawn")
            done_q = ctx.Queue()
            procs = [ctx.Process(target=_file_server_proc,
                                 args=(root, n, 0.25, f"s{k}", done_q))
                     for k in range(n_servers)]
            for p in procs:
                p.start()
            # measurement starts only once every server is warm (jax import
            # + model build take seconds and would swamp the serving time)
            deadline = time.time() + 120
            while time.time() < deadline:
                if all(pathlib.Path(root, f"READY_s{k}").exists()
                       for k in range(n_servers)):
                    break
                time.sleep(0.05)
            inq = InputQueue(f"dir://{root}")
            start = time.time()
            for i in range(n):
                inq.enqueue_tensor(f"rec{i}",
                                   np.full((4,), float(i), np.float32))
            outq = OutputQueue(f"dir://{root}")
            deadline = time.time() + 120
            while time.time() < deadline:
                if len(outq.dequeue()) >= n:
                    break
                time.sleep(0.02)
            elapsed = time.time() - start
            pathlib.Path(root, "DONE").write_text("1")
            for _ in procs:
                done_q.get(timeout=60)
            for p in procs:
                p.join(timeout=30)
            return n / elapsed

        r1 = run(1, str(tmp_path / "one"))
        r2 = run(2, str(tmp_path / "two"))
        assert r2 >= 1.5 * r1, f"single {r1:.2f} rec/s, dual {r2:.2f} rec/s"


class TestDrainAndReloadMultiServer:
    def test_reload_then_drain_leaves_nothing_behind(self, tmp_path):
        """Two in-process servers on one spool: hot-reload one mid-traffic
        (zero dropped requests across the swap), then drain both — every
        uri answered with a value, no claim state or serve threads left."""
        import os

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (ClusterServing, FileQueue,
                                               InputQueue, OutputQueue,
                                               ServingConfig)

        def sum_model():
            return InferenceModel().load_jax(
                lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True),
                {})

        root = str(tmp_path / "spool")
        FileQueue(root)
        src = f"dir://{root}"
        # only THESE servers' threads are the drain contract (earlier
        # tests' decode pools die on GC, asynchronously)
        pre = set(threading.enumerate())
        servers = [ClusterServing(
            ServingConfig(data_src=src, image_shape=(4,), batch_size=4,
                          batch_wait_ms=5), model=sum_model())
            for _ in range(2)]
        for s in servers:
            s.start()
        inq, outq = InputQueue(src), OutputQueue(src)
        try:
            for i in range(16):
                inq.enqueue_tensor(f"pre{i}", np.full(4, 1.0))
            for i in range(16):
                assert outq.query(f"pre{i}", timeout_s=20.0) is not None
            # hot swap server 0 while server 1 keeps serving the old model
            servers[0].reload_model(model=InferenceModel().load_jax(
                lambda p, x: x.reshape(x.shape[0], -1).mean(
                    1, keepdims=True), {}))
            for i in range(16):
                inq.enqueue_tensor(f"post{i}", np.full(4, 1.0))
            for i in range(16):
                res = outq.query(f"post{i}", timeout_s=20.0)
                assert res is not None and "value" in res
                # whichever server answered, the value is a VALID model's
                # output (sum=4 or mean=1) — never garbage mid-swap
                assert res["value"][0] in (
                    pytest.approx(4.0), pytest.approx(1.0))
        finally:
            for s in servers:
                s.drain(timeout_s=30.0)
        results = outq.dequeue()
        assert len(results) == 32
        assert all("value" in r for r in results.values())  # drain: no errors
        assert servers[0].counters["reloads"] == 1
        assert servers[0].queue.pending_count() == 0
        assert file_io.listdir(file_io.join(root, "claimed")) == []
        leaked = [t.name for t in threading.enumerate()
                  if t not in pre and t.name.startswith("zoo-serving")]
        assert not leaked
        for s in servers:
            assert s.health_snapshot()["state"] == "drained"


class TestTwoServerRedis:
    def test_exactly_once_two_instances_one_stream(self, monkeypatch):
        """Two RedisQueue consumers (distinct consumer names, one group) on
        one stream: XREADGROUP '>' must deliver each entry exactly once
        across both, under concurrent claiming."""
        from tests.test_redis_serving import FakeRedis

        lock = threading.Lock()
        orig = FakeRedis.xreadgroup

        def locked_xreadgroup(self, *a, **k):
            with lock:  # the real broker pops atomically; model that
                return orig(self, *a, **k)

        monkeypatch.setattr(FakeRedis, "xreadgroup", locked_xreadgroup)
        fake_mod = types.ModuleType("redis")
        fake_mod.StrictRedis = FakeRedis
        monkeypatch.setitem(sys.modules, "redis", fake_mod)
        FakeRedis.instances.clear()

        from analytics_zoo_tpu.serving.queues import RedisQueue
        qa = RedisQueue("twosrv", 6379)
        qb = RedisQueue("twosrv", 6379)
        assert qa.consumer != qb.consumer
        n = 200
        for i in range(n):
            qa.enqueue(f"rec{i}", {"tensor": [i]})

        claims = {"a": [], "b": []}

        def drain(q, key):
            while True:
                batch = q.claim_batch(7)
                if not batch:
                    break
                claims[key].extend(u for u, _ in batch)

        ta = threading.Thread(target=drain, args=(qa, "a"))
        tb = threading.Thread(target=drain, args=(qb, "b"))
        ta.start(); tb.start()
        ta.join(30); tb.join(30)
        got = claims["a"] + claims["b"]
        assert len(got) == n
        assert len(set(got)) == n, "double delivery"
        assert set(got) == {f"rec{i}" for i in range(n)}


class TestRemoteSpoolClaims:
    def test_remote_claim_uses_exclusive_marker(self):
        """On a scheme:// spool, claims go through create_exclusive
        markers; a marker that exists means the claim is lost."""
        from fsspec.implementations.memory import MemoryFileSystem

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.serving import FileQueue
        import uuid as _uuid
        file_io.register_filesystem("spoolfs", MemoryFileSystem())
        try:
            root = f"spoolfs://q-{_uuid.uuid4().hex[:8]}"
            q1 = FileQueue(root)
            q2 = FileQueue(root)
            q1.enqueue("u1", {"tensor": [1]})
            q1.enqueue("u2", {"tensor": [2]})
            a = q1.claim_batch(10)
            b = q2.claim_batch(10)
            got = [u for u, _ in a] + [u for u, _ in b]
            assert sorted(got) == ["u1", "u2"]
            # claims are exclusive: nothing left to claim
            assert q1.claim_batch(10) == [] and q2.claim_batch(10) == []
        finally:
            file_io.unregister_filesystem("spoolfs")

    def test_expired_remote_claim_is_reaped(self):
        """A consumer that died between claim and cleanup must not wedge
        the record forever: once the lease expires another consumer
        reclaims it (the redis XAUTOCLAIM stance)."""
        from fsspec.implementations.memory import MemoryFileSystem

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.serving import FileQueue
        import uuid as _uuid
        file_io.register_filesystem("spoolfs2", MemoryFileSystem())
        try:
            root = f"spoolfs2://q-{_uuid.uuid4().hex[:8]}"
            q1 = FileQueue(root, claim_lease_s=0.2)
            q1.enqueue("u1", {"tensor": [1]})
            # simulate a dead consumer: claim then never clean up
            name = [n for n in file_io.listdir(
                f"{root}/requests", refresh=True)
                if not n.startswith(".")][0]
            assert q1._claim_one(name) is not None
            q2 = FileQueue(root, claim_lease_s=0.2)
            assert q2.claim_batch(10) == []  # lease still live
            time.sleep(0.3)
            got = q2.claim_batch(10)  # expired: reaped + reclaimed
            assert [u for u, _ in got] == ["u1"]
        finally:
            file_io.unregister_filesystem("spoolfs2")

    def test_reap_lock_serializes_reapers(self):
        """Reaping an expired claim is remove+recreate — not atomic — so it
        is guarded by an exclusive-create reap lock: while another consumer
        holds the lock, a racing reaper must claim NOTHING (this is the
        interleaving where two reapers could otherwise both win); a STALE
        lock (reaper died mid-reap) is cleared so a later pass recovers."""
        from fsspec.implementations.memory import MemoryFileSystem

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.serving import FileQueue
        import uuid as _uuid
        file_io.register_filesystem("spoolfs3", MemoryFileSystem())
        try:
            root = f"spoolfs3://q-{_uuid.uuid4().hex[:8]}"
            q1 = FileQueue(root, claim_lease_s=0.2)
            q1.enqueue("u1", {"tensor": [1]})
            name = [n for n in file_io.listdir(
                f"{root}/requests", refresh=True)
                if not n.startswith(".")][0]
            assert q1._claim_one(name) is not None  # dead consumer
            time.sleep(0.3)  # lease expires
            # another consumer is mid-reap: fresh reap lock held
            marker = file_io.join(f"{root}/claimed", name + ".claim")
            file_io.create_exclusive(marker + ".reap",
                                     repr(time.time()).encode())
            q2 = FileQueue(root, claim_lease_s=0.2)
            assert q2.claim_batch(10) == []  # must not double-claim
            assert file_io.exists(marker + ".reap")  # fresh lock untouched
            # now the lock itself goes stale (its holder died mid-reap);
            # clearing requires the conservative 2x-lease margin: one pass
            # clears it, the next reclaims the record
            time.sleep(0.45)
            assert q2.claim_batch(10) == []
            assert not file_io.exists(marker + ".reap")
            got = q2.claim_batch(10)
            assert [u for u, _ in got] == ["u1"]
        finally:
            file_io.unregister_filesystem("spoolfs3")

    def test_reap_revalidates_marker_under_lock(self, monkeypatch):
        """Two reapers that both read the same expired stamp must not both
        reclaim: the second one re-reads the marker AFTER winning the reap
        lock and must back off when it finds a fresh claim (simulated here
        by serving it a fresh stamp on the re-validation read)."""
        import io

        from fsspec.implementations.memory import MemoryFileSystem

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.serving import FileQueue
        import uuid as _uuid
        file_io.register_filesystem("spoolfs4", MemoryFileSystem())
        try:
            root = f"spoolfs4://q-{_uuid.uuid4().hex[:8]}"
            q1 = FileQueue(root, claim_lease_s=0.2)
            q1.enqueue("u1", {"tensor": [1]})
            name = [n for n in file_io.listdir(
                f"{root}/requests", refresh=True)
                if not n.startswith(".")][0]
            assert q1._claim_one(name) is not None  # dead consumer
            time.sleep(0.3)  # lease expires
            marker = file_io.join(f"{root}/claimed", name + ".claim")
            orig_fopen = file_io.fopen
            marker_reads = []

            def fake_fopen(path, mode="r", **kw):
                if path == marker and "r" in str(mode):
                    marker_reads.append(1)
                    if len(marker_reads) == 2:
                        # re-validation read: another reaper reclaimed it
                        # a moment ago — the stamp is fresh now
                        return io.BytesIO(repr(time.time()).encode())
                return orig_fopen(path, mode, **kw)

            monkeypatch.setattr(file_io, "fopen", fake_fopen)
            q2 = FileQueue(root, claim_lease_s=0.2)
            assert q2._claim_one(name) is None  # backed off under the lock
            assert len(marker_reads) == 2
            assert file_io.exists(marker)  # the fresh claim survived
            assert not file_io.exists(marker + ".reap")  # lock released
        finally:
            file_io.unregister_filesystem("spoolfs4")
