"""Calibrated int8 quantization (reference OpenVINO calibrated-int8 role,
``OpenVinoInferenceSupportive.scala:64``): activation observers over a
calibration set produce per-tensor activation scales; the quantized model's
accuracy must stay within 1% top-1 of fp32."""
import numpy as np
import pytest


def _blobs(n, seed=0):
    """Linearly separable 3-class image blobs a small CNN learns quickly."""
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 3, n)
    x = rs.randn(n, 8, 8, 3).astype(np.float32) * 0.3
    for i, c in enumerate(y):
        x[i, :, :, c] += 1.5  # class = dominant channel
    return x, y.astype(np.float32)


@pytest.fixture(scope="module")
def trained():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras.layers import (
        Activation, Convolution2D, Dense, Flatten, GlobalAveragePooling2D)
    from analytics_zoo_tpu.feature import FeatureSet
    model = Sequential([
        Convolution2D(8, 3, 3, border_mode="same", name="c1"),
        Activation("relu"),
        Convolution2D(16, 3, 3, border_mode="same", name="c2"),
        Activation("relu"),
        GlobalAveragePooling2D(name="gap"),
        Dense(16, activation="relu", name="d1"),
        Dense(3, activation="softmax", name="head")])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    x, y = _blobs(512)
    model.fit(FeatureSet.from_ndarrays(x, y, shuffle=True), batch_size=64,
              nb_epoch=6)
    return model


class TestCalibratedInt8:
    def test_observer_scales_collected(self, ctx, trained):
        from analytics_zoo_tpu.inference.quantize import (
            observe_activation_scales)
        est = trained.get_estimator()
        params = est.get_params()
        state = {k: np.asarray(v) for k, v in (est.model_state or {}).items()}
        x, _ = _blobs(64, seed=1)
        scales = observe_activation_scales(trained, params, est.model_state,
                                           [x[i:i + 16] for i in range(0, 64, 16)])
        assert set(scales) == {"c1", "c2", "d1", "head"}
        assert all(s > 0 for s in scales.values())
        # observers must be REMOVED afterwards
        for l in [l for l in trained.layers]:
            assert "wrapped" not in repr(getattr(l, "call", None))

    def test_int8_within_1pct_top1_of_fp32(self, ctx, trained):
        from analytics_zoo_tpu.inference import InferenceModel
        xe, ye = _blobs(512, seed=2)
        im = InferenceModel().load_keras(trained)
        fp32_top1 = np.argmax(np.asarray(im.predict(xe)), -1)
        fp32_acc = float((fp32_top1 == ye).mean())
        assert fp32_acc > 0.9, "fixture failed to train"

        xc, _ = _blobs(128, seed=3)
        im8 = InferenceModel().load_keras(trained).quantize(
            "int8", calibration_data=[xc[i:i + 32] for i in range(0, 128, 32)])
        int8_top1 = np.argmax(np.asarray(im8.predict(xe)), -1)
        agreement = float((int8_top1 == fp32_top1).mean())
        int8_acc = float((int8_top1 == ye).mean())
        assert agreement >= 0.99, f"top-1 agreement {agreement}"
        assert abs(fp32_acc - int8_acc) <= 0.01

    def test_act_scales_ride_in_params(self, ctx, trained):
        import jax
        from analytics_zoo_tpu.inference import InferenceModel
        xc, _ = _blobs(64, seed=4)
        im8 = InferenceModel().load_keras(trained).quantize(
            "int8", calibration_data=[xc])
        leaves = jax.tree_util.tree_leaves(
            im8._params, is_leaf=lambda t: isinstance(t, dict) and "q" in t)
        qleaves = [l for l in leaves if isinstance(l, dict) and "q" in l]
        assert len(qleaves) == 4  # c1, c2, d1, head kernels
        assert all("act_scale" in l for l in qleaves)
        assert all(l["q"].dtype == np.int8 for l in qleaves)

    def test_weight_only_int8_still_works(self, ctx, trained):
        from analytics_zoo_tpu.inference import InferenceModel
        xe, _ = _blobs(32, seed=5)
        im = InferenceModel().load_keras(trained)
        ref = np.asarray(im.predict(xe))
        im8 = InferenceModel().load_keras(trained).quantize("int8")
        got = np.asarray(im8.predict(xe))
        assert np.mean(np.argmax(got, -1) == np.argmax(ref, -1)) >= 0.95

    def test_load_zoo_calibration_path(self, ctx, tmp_path):
        # calibrated int8 must work for models loaded from disk, not just
        # in-memory load_keras handles
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.models import NeuralCF
        m = NeuralCF(20, 10, 2, user_embed=4, item_embed=4,
                     hidden_layers=[8], mf_embed=4)
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.save_model(str(tmp_path / "zoo"))
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 21, 16), rs.randint(1, 11, 16)],
                     1).astype(np.float32)
        im = InferenceModel().load_zoo(str(tmp_path / "zoo"))
        ref = np.asarray(im.predict(x))
        im8 = InferenceModel().load_zoo(str(tmp_path / "zoo"))
        im8.quantize("int8", calibration_data=[x[:8]])
        got = np.asarray(im8.predict(x))
        assert got.shape == ref.shape
        assert np.mean(np.argmax(got, -1) == np.argmax(ref, -1)) >= 0.9

    def test_opaque_forward_rejects_calibration(self, ctx):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_jax(lambda p, x: x @ p["w"],
                                      {"w": jnp.eye(4)})
        with pytest.raises(ValueError, match="keras-graph"):
            im.quantize("int8", calibration_data=[np.zeros((2, 4))])
