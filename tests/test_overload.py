"""Overload survival tier (docs/serving.md "Overload survival").

Three layers under test: the server-side brownout ladder (degrade answer
quality before answer existence), the ``retriable`` contract on terminal
errors (shed = retry me; deadline/validation = don't), and the chaos
capstone — a 3-instance fleet driven at 3x its capacity with one
injected-slow instance must keep critical-class goodput, degrade total
goodput monotonically (no congestion cliff), hold client retry
amplification under the budget, and never lose or duplicate a terminal.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.utils import wall_clock
from analytics_zoo_tpu.serving import (FleetInstance, FleetRouter,
                                       GenerativeServing, ServingConfig)
from analytics_zoo_tpu.serving.client import (InputQueue, OutputQueue,
                                              ResilientClient)
from analytics_zoo_tpu.serving.fleet import instance_queue
from analytics_zoo_tpu.serving.queues import FileQueue
from analytics_zoo_tpu.serving.server import SHED_ERROR, _Brownout

from tests.test_generative_serving import _drive, _lm, _src


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestBrownoutLadder:
    """The hysteretic controller in isolation: degrade fast, recover
    cautiously, cap token budgets only at the deeper rungs."""

    def test_degrades_fast_recovers_cautiously(self):
        b = _Brownout()  # defaults: high 0.75, low 0.35, hold 3
        assert b.tick(0.9) == 1          # one rung per hot tick
        assert b.tick(0.9) == 2
        assert b.tick(0.9) == 3
        assert b.tick(0.9) == 3          # clamped at MAX_LEVEL
        assert b.tick(0.1) == 3          # calm tick 1: hold
        assert b.tick(0.1) == 3          # calm tick 2: hold
        assert b.tick(0.1) == 2          # 3 consecutive calm ticks: -1
        assert b.tick(0.1) == 2
        assert b.tick(0.1) == 2
        assert b.tick(0.1) == 1          # another full hold window

    def test_mid_band_pressure_resets_the_calm_streak(self):
        b = _Brownout()
        b.tick(0.9)
        assert b.level == 1
        b.tick(0.1)
        b.tick(0.1)
        b.tick(0.5)                      # between low and high: not calm
        b.tick(0.1)
        b.tick(0.1)
        assert b.level == 1              # the streak restarted
        assert b.tick(0.1) == 0

    def test_rung_levers(self):
        b = _Brownout()
        assert b.token_cap(100) == 100 and b.batch_window_ms(10.0) == 10.0
        b.level = 1
        assert b.token_cap(100) == 100   # L1 never touches budgets
        assert b.batch_window_ms(10.0) == 20.0
        assert b.stream_stride(2) == 8
        b.level = 2
        assert b.token_cap(100) == 50    # 2 x token_frac
        assert b.batch_window_ms(10.0) == 40.0
        b.level = 3
        assert b.token_cap(100) == 25    # token_frac
        assert b.token_cap(1) == 1       # never capped to zero
        assert b.stream_stride(0) == 0   # "every step" stays every step


class TestBrownoutServing:
    """The ladder wired into a live generative server: queue pressure
    raises the rung (exported in health), and a browned-out server joins
    new streams with a capped budget — still token-identical to serial
    generate() under that budget."""

    def test_queue_pressure_raises_level_and_health_reports_it(
            self, ctx, tmp_path):
        lm = _lm()
        srv = GenerativeServing(
            ServingConfig(data_src=_src(tmp_path), slots=1, max_pending=4,
                          max_new_tokens=4), lm)
        inq = InputQueue(srv.config.data_src)
        rs = np.random.RandomState(3)
        for i in range(10):
            inq.enqueue_prompt(f"p{i}", rs.randint(0, 16, (4,)).tolist())
        srv._last_shed_m = -1e18      # force the shed/brownout cadence
        srv._shed()                   # sheds to 4 pending; fill 1.0 > high
        assert srv.health_snapshot()["brownout_level"] == 1
        srv._last_shed_m = -1e18
        srv._shed()
        assert srv.health_snapshot()["brownout_level"] == 2
        # drain the queue: pressure collapses, recovery needs a full
        # hold window of calm ticks
        srv.queue.claim_batch(100)
        for _ in range(6):
            srv._last_shed_m = -1e18
            srv._shed()
        assert srv.health_snapshot()["brownout_level"] == 0

    def test_browned_out_join_caps_budget_token_identically(
            self, ctx, tmp_path):
        lm = _lm()
        prompt = np.random.RandomState(5).randint(0, 16, (5,)).tolist()
        # L3 caps an 8-token budget to 2; the capped stream must be
        # exactly serial generate() at that shorter budget
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=2)[0].tolist()
        srv = GenerativeServing(
            ServingConfig(data_src=_src(tmp_path), slots=1,
                          max_new_tokens=8), lm)
        srv._brownout.level = 3
        inq, outq = InputQueue(srv.config.data_src), \
            OutputQueue(srv.config.data_src)
        inq.enqueue_prompt("b0", prompt, max_new_tokens=8)
        _drive(srv)
        res = outq.query("b0", timeout_s=5)
        assert res is not None and res.get("done") is True
        assert res["value"] == want

    def test_shed_terminal_is_retriable_deadline_is_not(self, ctx,
                                                        tmp_path):
        lm = _lm()
        srv = GenerativeServing(
            ServingConfig(data_src=_src(tmp_path), slots=1, max_pending=1,
                          max_new_tokens=4), lm)
        inq, outq = InputQueue(srv.config.data_src), \
            OutputQueue(srv.config.data_src)
        rs = np.random.RandomState(7)
        for i in range(4):
            inq.enqueue_prompt(f"s{i}", rs.randint(0, 16, (4,)).tolist())
        srv._last_shed_m = -1e18
        srv._shed()
        shed = [outq.query(f"s{i}") for i in range(4)]
        shed = [r for r in shed if r is not None and "error" in r]
        assert shed, "expected shed terminals"
        for r in shed:
            assert r["error"] == SHED_ERROR and r["retriable"] is True
        # an expired request answers a non-retriable deadline error
        inq.enqueue_prompt("dl", rs.randint(0, 16, (4,)).tolist(),
                           deadline_ms=1)
        time.sleep(0.02)
        _drive(srv)
        res = outq.query("dl", timeout_s=5)
        assert res is not None and res["error"] == "deadline exceeded"
        assert res["retriable"] is False


class _MiniInstance:
    """A synthetic serving instance: claims from its spool, spends
    ``service_s`` of wall time per request, posts the result, and keeps
    its health file fresh (advertising ``ewma_s`` as its service time).
    Enough surface for the router's placement, admission and breaker
    machinery — no model, so the chaos capstone stays tier-1 fast."""

    def __init__(self, name, queue, health_path, service_s, ewma_s):
        self.name = name
        self.queue = queue
        self.health_path = health_path
        self.service_s = service_s
        self.ewma_s = ewma_s
        self.served = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.write_health()

    def write_health(self):
        snap = {"state": "running", "time": wall_clock(),
                "queue_pending": self.queue.pending_count(),
                "in_flight": 0, "service_time_s_ewma": self.ewma_s}
        tmp = self.health_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(snap))
        os.replace(tmp, self.health_path)

    def _run(self):
        while not self._stop.is_set():
            self.write_health()
            try:
                batch = self.queue.claim_batch(8)
            except OSError:
                batch = []
            if not batch:
                time.sleep(0.002)
                continue
            for uri, rec in batch:
                time.sleep(self.service_s)
                self.queue.put_result(
                    uri, {"value": [sum(rec.get("tensor") or [0])]})
                self.served += 1

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


class TestOverloadCapstone:
    """The acceptance scenario: ramp one fleet shape through 1x/2x/3x of
    its deadline-bounded capacity with instance ``c`` injected-slow."""

    #: offered requests per 1x of the ramp
    BASE = 50

    @staticmethod
    def _lane_of(i):
        r = i % 10  # 3 critical / 4 default / 3 sheddable per 10
        return ("critical" if r < 3 else
                "default" if r < 7 else "sheddable")

    def _run_phase(self, tmp_path, mult):
        root = str(tmp_path / f"fleet{mult}")
        front = FileQueue(root)
        insts, workers = [], []
        # two healthy instances and one injected-slow one (>10x service
        # time, honestly advertised — the latency breaker takes it out)
        for name, svc, ewma in (("a", 0.003, 0.02), ("b", 0.003, 0.02),
                                ("c", 0.05, 0.09)):
            q = instance_queue(root, name)
            hp = str(tmp_path / f"h{mult}{name}.json")
            workers.append(_MiniInstance(name, q, hp, svc, ewma))
            insts.append(FleetInstance(name, q, hp))
        router = FleetRouter(front, insts, stale_after_s=5.0,
                             health_refresh_s=0.01)
        n = self.BASE * mult
        uris = {"critical": [], "default": [], "sheddable": []}
        client = ResilientClient(root, budget_ratio=0.1, attempts=2,
                                 backoff_s=0.005)
        inq = InputQueue(root)
        results, lock, threads = {}, threading.Lock(), []

        def _call(uri, hedged):
            def enq(attempt_uri):
                inq.enqueue_tensor(attempt_uri, [1], deadline_ms=800,
                                   criticality="critical")
            if hedged:
                # hedge fires only for genuinely stuck requests (delay
                # well past the healthy completion time, inside the
                # deadline); the loser's terminal is reaped, not lost
                res = client.query_any(uri, enq, timeout_s=15.0,
                                       hedge_delay_s=0.15)
            else:
                res = client.call(uri, enq, timeout_s=15.0)
            with lock:
                results[uri] = res

        # offer the whole phase up front, THEN open the fleet: the
        # router's first claims see a mixed backlog and must drain it in
        # lane-priority order, so the critical class is placed while the
        # completion estimates are still low
        for i in range(n):
            uri = f"q{mult}-{i}"
            lane = self._lane_of(i)
            uris[lane].append(uri)
            if lane == "critical":
                # every third critical request rides the hedged path, so
                # the exactly-one-terminal audit spans hedges too
                t = threading.Thread(target=_call,
                                     args=(uri, i % 10 == 0))
                t.start()
                threads.append(t)
            else:
                inq.enqueue_tensor(uri, [1], deadline_ms=800,
                                   criticality=lane)
        time.sleep(0.05)  # let the critical threads' enqueues land
        for w in workers:
            w.start()
        router.start()
        outq = OutputQueue(root)
        for lane in ("default", "sheddable"):
            for uri in uris[lane]:
                results[uri] = outq.query(uri, timeout_s=15.0)
        for t in threads:
            t.join(timeout=20.0)
        client.reap_pending()
        router.stop()
        for w in workers:
            w.stop()
        missing = [u for us in uris.values() for u in us
                   if results.get(u) is None]
        assert not missing, f"requests without a terminal: {missing[:5]}"
        good = {lane: sum(1 for u in us
                          if "value" in (results[u] or {}))
                for lane, us in uris.items()}
        return uris, good, client

    def test_ramp_survival(self, tmp_path, monkeypatch):
        # audit every terminal post fleet-wide: exactly one per uri
        posts, plock = {}, threading.Lock()
        real_put = FileQueue.put_result

        def audited(self, uri, value):
            with plock:
                posts[uri] = posts.get(uri, 0) + 1
            return real_put(self, uri, value)

        monkeypatch.setattr(FileQueue, "put_result", audited)
        goodput = {}
        for mult in (1, 2, 3):
            uris, good, client = self._run_phase(tmp_path, mult)
            goodput[mult] = good
            # retry amplification stays inside the token-bucket budget
            # (+1 for the bootstrap token), even while being shed
            assert client.attempts_sent <= (
                client.requests_sent * 1.1 + 1), (
                mult, client.attempts_sent, client.requests_sent)
            # the critical class keeps >= 90% of its offered goodput at
            # every point of the ramp — overload lands on the other lanes
            assert good["critical"] >= 0.9 * len(uris["critical"]), (
                mult, good, {k: len(v) for k, v in uris.items()})
        # no congestion cliff: total goodput must not collapse as offered
        # load ramps past capacity (sheds answer fast; they don't
        # thrash). The slack absorbs scheduling noise on a loaded host —
        # a genuine cliff (retry storms, shed thrash) halves goodput,
        # which both bounds still catch
        totals = {m: sum(goodput[m].values()) for m in (1, 2, 3)}
        assert totals[2] >= totals[1] * 0.85, (totals, goodput)
        assert totals[3] >= totals[2] * 0.75, (totals, goodput)
        # zero dropped, zero duplicated terminals across the whole ramp
        dupes = {u: c for u, c in posts.items() if c != 1}
        assert not dupes, f"duplicated terminals: {dupes}"

    def test_ops_plane_incident_timeline(self, tmp_path):
        """The observability acceptance scenario: the same 3x overload
        phase with the ops plane enabled must yield an incident bundle
        whose causally-ordered timeline contains the brownout rung climb,
        the breaker trip on the slow instance and the recovery — with the
        triggering burn-rate alert attached."""
        from analytics_zoo_tpu.common import metrics
        from analytics_zoo_tpu.ops import alerts, events, incident
        from analytics_zoo_tpu.ops.history import MetricHistory

        log = events.reset_default(root=str(tmp_path / "ops_spool"),
                                   enabled=True)
        hist = MetricHistory(metrics.default_registry(), depth=512,
                             interval_s=0.05)
        # the fleet's shed/expired fraction against placed traffic: at 3x
        # offered load the admission controller sheds, and any shed
        # fraction past 1% of the 99% objective burns > 1x
        rule = alerts.BurnRateRule(
            "capstone_shed_burn",
            bad=("fleet.shed_total", "fleet.expired_total"),
            total=("fleet.routed_total", "fleet.shed_total",
                   "fleet.expired_total"),
            objective=0.99, windows=((8.0, 1.0, 1.0),), min_total=5.0)
        fired = []
        engine = alerts.AlertEngine(
            hist, [rule], interval_s=0.05,
            on_fire=lambda name, info, t: fired.append(
                {"name": name, "info": info, "wall": t}))
        ladder = _Brownout("capstone")
        try:
            hist.start()
            engine.start()
            # the backlog pressure a real server would feed its ladder:
            # two hot ticks climb to L2 before the fleet opens
            ladder.tick(1.0)
            ladder.tick(1.0)
            self._run_phase(tmp_path, 3)
            for _ in range(100):  # the engine thread evaluates at 50ms
                if fired:
                    break
                time.sleep(0.05)
            # workload drained: a full hold window of calm ticks per rung
            # walks the ladder back down — the recovery side
            for _ in range(8):
                ladder.tick(0.0)
        finally:
            engine.stop()
            hist.stop()
        try:
            assert fired, "burn-rate alert never fired during the ramp"
            corr = incident.IncidentCorrelator(
                log=log, history=hist,
                out_dir=str(tmp_path / "incidents"), window_s=120.0)
            bdir = corr.seal(reason=f"alert:{fired[0]['name']}",
                             alert=fired[0])
            bundle = incident.load_bundle(bdir)
            assert bundle["alert"]["name"] == "capstone_shed_burn"
            assert bundle["alert"]["info"]["rule"] == "burn_rate"
            evs = bundle["events"]
            climb = next(i for i, e in enumerate(evs)
                         if e["type"] == "serving.brownout_rung"
                         and e["level_to"] > e["level_from"])
            trip = next(i for i, e in enumerate(evs)
                        if e["type"] == "fleet.breaker"
                        and e["state"] == "open" and e["label"] == "c")
            alert_i = next(i for i, e in enumerate(evs)
                           if e["type"] == "ops.alert"
                           and e["state"] == "fire")
            recovery = next(i for i, e in enumerate(evs)
                            if e["type"] == "serving.brownout_rung"
                            and e["level_to"] == 0)
            assert climb < trip < recovery, (climb, trip, recovery)
            assert climb < alert_i < recovery, (climb, alert_i, recovery)
            # the sealed history carries the fleet series behind the burn
            assert "fleet.routed_total" in bundle["history"]
            with open(os.path.join(bdir, "timeline.txt")) as f:
                tl = f.read()
            assert "triggering alert: capstone_shed_burn" in tl
            assert tl.index("serving.brownout_rung") \
                < tl.index("fleet.breaker")
        finally:
            events.reset_default(enabled=False)
