"""The fused embedding kernels (``ops/embedding_kernels.py``) against
their bit-parity contract (ISSUE 16): off-TPU the fused wrappers must
trace EXACTLY the unfused reference op chain — same ops, same order, same
dtypes — so toggling ``kernels.fused_embedding`` is a jaxpr no-op and
N-step Estimator training lands bit-identical params with the knob on or
off, sharded and unsharded. The int8 variant must stay inside its
documented ``int8_error_bound``. The bench-side fused A/B helper must
publish ``embedding_fused_speedup`` only behind a passing parity fence.
"""
import contextlib
import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import objectives
from analytics_zoo_tpu.keras.layers.embedding import (Embedding,
                                                      SparseEmbedding)
from analytics_zoo_tpu.keras.optimizers import SGD
from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF
from analytics_zoo_tpu.ops import embedding_kernels as ek

KNOB = "kernels.fused_embedding"
USERS, ITEMS, B = 40, 36, 16


@contextlib.contextmanager
def _knob(value):
    cfg = global_config()
    had = KNOB in cfg._overrides
    saved = cfg.get(KNOB)
    cfg.set(KNOB, value)
    try:
        yield
    finally:
        if had:
            cfg.set(KNOB, saved)
        else:
            cfg.unset(KNOB)


def _ragged_idx(rs, rows, bag, vocab):
    """Bag indices with ragged tails: -1 padding of varying lengths,
    including an all-padding row (the count-clamp edge case)."""
    idx = rs.randint(0, vocab, (rows, bag)).astype(np.int32)
    for i in range(rows):
        idx[i, bag - (i % (bag + 1)):] = -1
    idx[0, :] = -1
    return jnp.asarray(idx)


def _ref_pool(table, idx, combiner):
    """The unfused SparseEmbedding op chain, restated independently."""
    valid = (idx >= 0).astype(table.dtype)[..., None]
    emb = jnp.take(table, jnp.maximum(idx, 0), axis=0) * valid
    if combiner is None:
        return emb
    total = jnp.sum(emb, axis=-2)
    if combiner == "sum":
        return total
    n = jnp.maximum(jnp.sum(valid, axis=-2), 1.0)
    if combiner == "mean":
        return total / n
    return total / jnp.sqrt(n)  # sqrtn


class TestKernelParity:
    @pytest.mark.parametrize("combiner", [None, "sum", "mean", "sqrtn"])
    def test_gather_pool_forward_bitwise(self, combiner):
        rs = np.random.RandomState(0)
        table = jnp.asarray(rs.randn(64, 8).astype(np.float32))
        idx = _ragged_idx(rs, 10, 5, 64)
        got = ek.gather_pool(table, idx, combiner)
        want = _ref_pool(table, idx, combiner)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
    def test_gather_pool_backward_bitwise(self, combiner):
        rs = np.random.RandomState(1)
        table = jnp.asarray(rs.randn(32, 4).astype(np.float32))
        idx = _ragged_idx(rs, 8, 3, 32)

        def loss_fused(t):
            return jnp.sum(ek.gather_pool(t, idx, combiner) ** 2)

        def loss_ref(t):
            return jnp.sum(_ref_pool(t, idx, combiner) ** 2)

        gf = jax.grad(loss_fused)(table)
        gr = jax.grad(loss_ref)(table)
        np.testing.assert_array_equal(np.asarray(gf), np.asarray(gr))

    def test_multi_table_lookup_matches_per_table_concat(self):
        rs = np.random.RandomState(2)
        tables = [jnp.asarray(rs.randn(40, d).astype(np.float32))
                  for d in (4, 8, 4)]
        indices = [_ragged_idx(rs, 6, 3, 40) for _ in range(3)]
        combiners = ["sum", "mean", "sqrtn"]
        got = ek.multi_table_lookup(tables, indices, combiners)
        want = jnp.concatenate(
            [_ref_pool(t, i, c)
             for t, i, c in zip(tables, indices, combiners)], axis=-1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_gather_and_scatter_primitives_match_engine_ops(self):
        rs = np.random.RandomState(3)
        table = jnp.asarray(rs.randn(16, 8).astype(np.float32))
        # gather_rows: fill-mode OOB semantics (the _lookup_body contract)
        flat = jnp.asarray(
            np.array([0, 5, 15, 16, 255], np.int32))  # 16+ are OOB
        got = ek.gather_rows(table, flat)
        want = jnp.take(table, flat, axis=0, mode="fill", fill_value=0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # scatter_rows: drop-mode OOB semantics (the _lookup_bwd_body ct)
        g = jnp.asarray(rs.randn(5, 8).astype(np.float32))
        got = ek.scatter_rows(g, flat, 16)
        want = jnp.zeros((16, 8), g.dtype).at[flat].add(g, mode="drop")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # segment_grads: per-shard slot layout of the all-to-all request
        inv = jnp.asarray(np.array([0, 1, 1, 2, 0], np.int32))
        d = jnp.asarray(np.array([0, 0, 1, 1, 0], np.int32))
        slot = jnp.asarray(np.array([0, 1, 2, 0, 3], np.int32))
        gu = jax.ops.segment_sum(g, inv, num_segments=5)
        want = jnp.zeros((2, 5, 8), g.dtype).at[d, slot].set(gu)
        got = ek.segment_grads(g, inv, d, slot, 2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestInt8Variant:
    def test_pooled_lookup_stays_inside_documented_bound(self):
        rs = np.random.RandomState(4)
        table = jnp.asarray((rs.randn(128, 16) * 0.3).astype(np.float32))
        bag = 6
        idx = jnp.asarray(rs.randint(0, 128, (32, bag)).astype(np.int32))
        qtable, scale, amax = ek.quantize_table(table)
        got = ek.gather_pool_int8(qtable, scale, idx, "sum")
        want = ek.gather_pool(table, idx, "sum")
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - want.astype(jnp.float32))))
        bound = float(ek.int8_error_bound(scale, bag_size=bag))
        assert err <= bound, f"int8 err {err} exceeds bound {bound}"
        assert qtable.dtype == jnp.int8  # half the gather bytes vs bf16

    def test_delayed_scaling_follows_running_amax(self):
        from analytics_zoo_tpu.ops.int8_dataflow import (next_amax,
                                                         scale_of_amax)
        table = jnp.asarray(np.full((4, 4), 0.5, np.float32))
        running = jnp.asarray(np.float32(2.0))
        _q, scale, amax = ek.quantize_table(table, running_amax=running)
        want_amax = next_amax(running, jnp.float32(0.5))
        np.testing.assert_allclose(np.asarray(amax), np.asarray(want_amax))
        np.testing.assert_allclose(np.asarray(scale),
                                   np.asarray(scale_of_amax(want_amax)))


def _mesh4():
    return Mesh(np.asarray(jax.devices()[:4]), ("data",))


def _ncf_fs(n=64):
    rs = np.random.default_rng(0)
    x = np.stack([rs.integers(1, USERS + 1, size=(n,)),
                  rs.integers(1, ITEMS + 1, size=(n,))], 1).astype(np.int32)
    y = rs.integers(0, 2, size=(n,)).astype(np.int32)
    return FeatureSet.from_ndarrays(x, y, shuffle=False)


def _train_ncf(shard, mesh, epochs=2):
    model = NeuralCF(USERS, ITEMS, 2, user_embed=8, item_embed=8,
                     hidden_layers=(16, 8), mf_embed=8,
                     shard_embeddings=shard).build_model()
    est = Estimator(model=model,
                    loss_fn=objectives.get(
                        "sparse_categorical_crossentropy"),
                    optimizer=SGD(0.1), mesh=mesh, seed=7)
    est.train(_ncf_fs(), batch_size=B, epochs=epochs)
    return jax.tree_util.tree_map(np.asarray, est.params)


def _assert_trees_bitwise(a, b):
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(x, y)


class TestEstimatorParity:
    def test_unsharded_training_bitwise_knob_on_vs_off(self, ctx):
        mesh = _mesh4()
        with _knob(True):
            fused = _train_ncf(False, mesh)
        with _knob(False):
            ref = _train_ncf(False, mesh)
        _assert_trees_bitwise(fused, ref)

    def test_sharded_training_bitwise_knob_on_vs_off(self, ctx):
        mesh = _mesh4()
        with _knob(True):
            fused = _train_ncf(True, mesh)
        with _knob(False):
            ref = _train_ncf(True, mesh)
        _assert_trees_bitwise(fused, ref)

    def test_knob_off_is_the_old_path_byte_identical(self, ctx):
        """Byte-level: the SparseEmbedding trace with the knob off must be
        the same jaxpr STRING as with it on (the fused wrappers branch at
        trace time and replay the identical op chain off-TPU)."""
        layer = SparseEmbedding(12, 4, combiner="mean", name="t")
        params, state = layer.build(jax.random.PRNGKey(0), (None, 3))
        idx = jnp.asarray(
            np.array([[0, 5, -1], [11, -1, -1]], np.int32))

        def fwd(p, i):
            out, _ = layer.call(p, state, i)
            return out

        with _knob(True):
            on = str(jax.make_jaxpr(fwd)(params, idx))
        with _knob(False):
            off = str(jax.make_jaxpr(fwd)(params, idx))
        assert on == off

    def test_layer_level_fused_override_beats_the_knob(self, ctx):
        with _knob(True):
            assert Embedding(8, 4, name="a", fused=False) \
                ._fused_kernels() is None
        with _knob(False):
            assert Embedding(8, 4, name="b", fused=True) \
                ._fused_kernels() is not None
            assert Embedding(8, 4, name="c")._fused_kernels() is None


_BENCH_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("zoo_bench_fused",
                                                  _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchFusedAB:
    def test_ab_publishes_parity_gated_speedup(self, ctx):
        """The bench A/B helper must land the embedding_fused_speedup
        detail keys with the parity fence passing — through a real (tiny)
        Estimator + the differenced N-step scan."""
        bench = _load_bench()
        from analytics_zoo_tpu.parallel.mesh import shard_batch
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, USERS + 1, 64),
                      rs.randint(1, ITEMS + 1, 64)], 1).astype(np.float32)
        y = rs.randint(0, 2, 64).astype(np.float32)

        def make_est():
            model = NeuralCF(USERS, ITEMS, 2, user_embed=8, item_embed=8,
                             hidden_layers=(16, 8), mf_embed=8
                             ).build_model()
            return Estimator(
                model=model,
                loss_fn=objectives.get("sparse_categorical_crossentropy"),
                optimizer=SGD(0.1), seed=7)

        est = make_est()
        bx, by = shard_batch(est.mesh, (x, y))
        ab = bench._embedding_fused_ab(make_est, bx, by, steps=25)
        assert ab["embedding_fused_parity_ok"] is True
        assert ab["embedding_fused_speedup"] > 0
        assert ab["embedding_fused_step_ms"] > 0
        assert ab["embedding_unfused_step_ms"] > 0
