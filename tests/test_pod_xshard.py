"""PodDataShards: distributed pandas shards over pod workers (reference
``RayDataShards``/``SparkDataShards``, ``pyzoo/zoo/xshard/shard.py:42,103``)."""
import os

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.xshard import DataShards, PodDataShards, read_csv


def _write_csvs(tmp_path, n_files=4, rows=20):
    rs = np.random.RandomState(0)
    for i in range(n_files):
        pd.DataFrame({"a": rs.randint(0, 10, rows),
                      "b": rs.rand(rows)}).to_csv(
            tmp_path / f"part{i}.csv", index=False)
    return str(tmp_path)


def _double_a(df):
    df = df.copy()
    df["a"] = df["a"] * 2
    return df


def _tag_pid(df):
    df = df.copy()
    df["pid"] = os.getpid()
    return df


class TestPodDataShards:
    def test_matches_local_shards(self, tmp_path):
        path = _write_csvs(tmp_path)
        local = read_csv(path).apply(_double_a).concat_to_pandas()
        dist = PodDataShards.read_csv(path, num_workers=2, timeout=300) \
            .transform_shard(_double_a).concat_to_pandas()
        pd.testing.assert_frame_equal(dist, local)

    def test_shards_processed_in_distinct_workers(self, tmp_path):
        path = _write_csvs(tmp_path)
        shards = PodDataShards.read_csv(path, num_workers=2, timeout=300) \
            .transform_shard(_tag_pid).collect()
        pids = {int(s["pid"].iloc[0]) for s in shards}
        assert os.getpid() not in pids
        assert len(pids) == 2, "files must spread over 2 pod workers"
        assert len(shards) == 4  # one shard per file, file order preserved

    def test_to_featureset(self, tmp_path, ctx):
        path = _write_csvs(tmp_path)
        fs = PodDataShards.read_csv(path, num_workers=2, timeout=300) \
            .to_featureset(["a", "b"], None)
        batch = next(fs.eval_iterator(8, pad_remainder=True))
        assert batch[0].shape == (8, 2)

    def test_lambda_transform_works_via_cloudpickle(self, tmp_path):
        # cloudpickle ships lambdas/closures to workers (Ray ergonomics)
        path = _write_csvs(tmp_path)
        out = PodDataShards.read_csv(path, num_workers=2, timeout=300) \
            .transform_shard(lambda df: df.assign(z=1)).collect()
        assert all("z" in s.columns for s in out)

    def test_unserializable_transform_rejected(self, tmp_path):
        import threading
        path = _write_csvs(tmp_path)
        lock = threading.Lock()  # not serializable by any pickler
        dist = PodDataShards.read_csv(path, num_workers=2) \
            .transform_shard(lambda df, l: df, lock)
        with pytest.raises(ValueError, match="serializable"):
            dist.collect()

    def test_empty_input_rejected(self):
        with pytest.raises(ValueError, match="no input files|format"):
            PodDataShards([], "csv")
