"""AutoML + Zouwu tests (reference test strategy: recipes + transformer unit
tests, small end-to-end searches)."""
import numpy as np
import pandas as pd
import pytest


def make_ts_df(n=120, freq_h=1):
    t = pd.date_range("2025-01-01", periods=n, freq="h")
    value = (np.sin(np.arange(n) / 8) * 5 + 20
             + np.random.RandomState(0).rand(n) * 0.1)
    return pd.DataFrame({"datetime": t, "value": value})


class TestMetrics:
    def test_evaluator(self):
        from analytics_zoo_tpu.automl import Evaluator
        y = np.array([1.0, 2.0, 3.0])
        p = np.array([1.0, 2.0, 4.0])
        assert Evaluator.evaluate("mse", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("rmse", y, p) == pytest.approx(
            np.sqrt(1 / 3))
        assert Evaluator.evaluate("mae", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("r2", y, y) == pytest.approx(1.0)
        assert Evaluator.get_metric_mode("r2") == "max"
        assert Evaluator.get_metric_mode("mse") == "min"
        with pytest.raises(ValueError):
            Evaluator.evaluate("nope", y, p)


class TestFeatureTransformer:
    def test_fit_transform_shapes_and_unscale(self):
        from analytics_zoo_tpu.automl.feature import (
            TimeSequenceFeatureTransformer)
        df = make_ts_df(50)
        ft = TimeSequenceFeatureTransformer(future_seq_len=2)
        x, y = ft.fit_transform(df, past_seq_len=5,
                                selected_features=["hour", "is_weekend"])
        assert x.shape == (44, 5, 3)  # target + 2 features
        assert y.shape == (44, 2)
        # unscale round-trips the target
        raw = ft.post_processing(df, y, is_train=False)
        np.testing.assert_allclose(raw[0, 0], df["value"].iloc[5], atol=1e-4)

    def test_save_restore(self, tmp_path):
        from analytics_zoo_tpu.automl.feature import (
            TimeSequenceFeatureTransformer)
        df = make_ts_df(30)
        ft = TimeSequenceFeatureTransformer()
        ft.fit_transform(df, past_seq_len=4, selected_features=["hour"])
        path = str(tmp_path / "ft.json")
        ft.save(path)
        ft2 = TimeSequenceFeatureTransformer().restore(path)
        x1, y1 = ft.transform(df)
        x2, y2 = ft2.transform(df)
        np.testing.assert_allclose(x1, x2)

    def test_test_mode_windows(self):
        from analytics_zoo_tpu.automl.feature import (
            TimeSequenceFeatureTransformer)
        df = make_ts_df(20)
        ft = TimeSequenceFeatureTransformer()
        ft.fit_transform(df, past_seq_len=4)
        xt = ft.transform(df, is_train=False)
        assert xt.shape == (17, 4, 1)


class TestSearchEngine:
    def test_grid_and_random_expansion(self, ctx):
        from analytics_zoo_tpu.automl import hp
        from analytics_zoo_tpu.automl.config.recipe import Recipe
        from analytics_zoo_tpu.automl.search import LocalSearchEngine

        class ToyRecipe(Recipe):
            num_samples = 2

            def search_space(self, feats):
                return {"a": hp.grid_search([1, 2]),
                        "b": hp.uniform(0.0, 1.0), "c": 7}

        seen = []

        def fit_fn(config, data):
            seen.append(config)
            return (config["a"] - 1.5) ** 2 + config["b"]

        eng = LocalSearchEngine(seed=1)
        eng.compile(data=None, model_create_fn=None, recipe=ToyRecipe(),
                    metric="mse", fit_fn=fit_fn)
        trials = eng.run()
        assert len(trials) == 4  # 2 grid points x 2 samples
        assert all(t.config["c"] == 7 for t in trials)
        best = eng.get_best_trials(1)[0]
        assert best.metric == min(t.metric for t in trials)

    def test_bayes_engine(self, ctx):
        from analytics_zoo_tpu.automl import hp
        from analytics_zoo_tpu.automl.config.recipe import Recipe
        from analytics_zoo_tpu.automl.search import LocalSearchEngine

        class BayesToy(Recipe):
            num_samples = 8

            def search_space(self, feats):
                return {"x": hp.uniform(-2.0, 2.0)}

            def search_algorithm(self):
                return "bayes"

        def fit_fn(config, data):
            return (config["x"] - 1.0) ** 2

        eng = LocalSearchEngine(seed=2)
        eng.compile(data=None, model_create_fn=None, recipe=BayesToy(),
                    metric="mse", fit_fn=fit_fn)
        trials = eng.run()
        assert len(trials) == 8
        assert eng.get_best_trials(1)[0].metric < 1.0


class TestTimeSequencePredictor:
    def test_smoke_fit_predict_evaluate(self, ctx):
        from analytics_zoo_tpu.automl import (
            SmokeRecipe, TimeSequencePredictor)
        df = make_ts_df(80)
        tsp = TimeSequencePredictor(future_seq_len=1)
        pipeline = tsp.fit(df, recipe=SmokeRecipe(), metric="mse")
        res = pipeline.evaluate(df, metrics=["mse", "smape"])
        assert "mse" in res and "smape" in res
        preds = pipeline.predict(df)
        assert len(preds) > 0

    def test_pipeline_save_load(self, ctx, tmp_path):
        from analytics_zoo_tpu.automl import (
            SmokeRecipe, TimeSequencePipeline, TimeSequencePredictor)
        df = make_ts_df(60)
        tsp = TimeSequencePredictor(future_seq_len=1)
        pipeline = tsp.fit(df, recipe=SmokeRecipe(), metric="mse")
        p1 = pipeline.predict(df)
        path = str(tmp_path / "pipe")
        pipeline.save(path)
        loaded = TimeSequencePipeline.load(path)
        p2 = loaded.predict(df)
        np.testing.assert_allclose(p1, p2, atol=1e-4)


class TestForecasters:
    def roll(self, n=80, past=8, future=1):
        rs = np.random.RandomState(0)
        series = np.sin(np.arange(n) / 6).astype(np.float32)
        idx = np.arange(past)[None, :] + np.arange(n - past - future + 1)[:, None]
        x = series[idx][:, :, None]
        y = series[idx[:, -1] + future][:, None]
        return x, y

    def test_lstm_forecaster(self, ctx):
        from analytics_zoo_tpu.zouwu import LSTMForecaster
        x, y = self.roll()
        f = LSTMForecaster(target_dim=1, feature_dim=1, lstm_1_units=8,
                           lstm_2_units=4)
        score = f.fit(x, y, batch_size=16, epochs=2)
        assert np.isfinite(score)
        assert f.predict(x).shape == (len(x), 1)

    def test_mtnet_forecaster(self, ctx):
        from analytics_zoo_tpu.zouwu import MTNetForecaster
        x, y = self.roll(past=8)
        f = MTNetForecaster(target_dim=1, feature_dim=1, long_series_num=3,
                            series_length=2, ar_window_size=2, cnn_height=2)
        score = f.fit(x, y, batch_size=16, epochs=2)
        assert np.isfinite(score)
        assert f.predict(x).shape == (len(x), 1)

    def test_seq2seq_forecaster(self, ctx):
        from analytics_zoo_tpu.zouwu import Seq2SeqForecaster
        x, y = self.roll(future=1)
        f = Seq2SeqForecaster(future_seq_len=1, feature_dim=1, latent_dim=8)
        score = f.fit(x, y, batch_size=16, epochs=2)
        assert np.isfinite(score)
        assert f.predict(x).shape == (len(x), 1)


class TestAnomaly:
    def test_threshold_estimator_and_detector(self):
        from analytics_zoo_tpu.zouwu import (
            ThresholdDetector, ThresholdEstimator)
        rs = np.random.RandomState(0)
        y = rs.rand(100, 1)
        yhat = y.copy()
        yhat[7] += 5.0  # one big forecast miss
        th = ThresholdEstimator().fit(y, yhat, ratio=0.01)
        det = ThresholdDetector()
        hits = det.detect(y, yhat, threshold=th)
        assert 7 in hits and len(hits) == 1
        # range mode
        hits2 = det.detect(np.array([[0.5], [9.0], [0.2]]),
                           threshold=(0.0, 1.0))
        assert hits2.tolist() == [1]


class TestAutoTS:
    def test_autots_trainer(self, ctx, tmp_path):
        from analytics_zoo_tpu.zouwu import AutoTSTrainer, TSPipeline
        df = make_ts_df(70)
        trainer = AutoTSTrainer(horizon=1)
        pipe = trainer.fit(df)
        res = pipe.evaluate(df, metrics=["mse"])
        assert "mse" in res
        path = str(tmp_path / "ts")
        pipe.save(path)
        loaded = TSPipeline.load(path)
        assert len(loaded.predict(df)) > 0
