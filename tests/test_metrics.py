"""Tests for the unified telemetry plane: the fork-safe metrics registry
(common/metrics.py) — value semantics, labels, fork visibility, histogram
percentile accuracy vs a numpy reference, Prometheus exposition, and the
disabled-registry zero-overhead contract."""
import math
import multiprocessing as mp
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common import metrics as zoo_metrics
from analytics_zoo_tpu.common.metrics import (
    BUCKET_BOUNDS, BUCKET_REL_ERROR, Registry)


@pytest.fixture()
def reg():
    r = Registry(capacity=8192)
    yield r
    r.close()


class TestCore:
    def test_counter_gauge_roundtrip(self, reg):
        c = reg.counter("t.requests_total", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        g = reg.gauge("t.depth", "g")
        g.set(17)
        assert g.value() == 17.0
        g.inc(-3)
        assert g.value() == 14.0

    def test_labels_isolate_series(self, reg):
        c = reg.counter("t.by_shard_total", "c", labels=("shard",))
        c.labels(shard="a").inc(2)
        c.labels(shard="b").inc(5)
        assert c.labels(shard="a").value() == 2
        assert c.labels(shard="b").value() == 5
        with pytest.raises(ValueError):
            c.labels(wrong="x")
        with pytest.raises(ValueError):
            c.inc()  # labeled family needs .labels() first

    def test_reregistration_idempotent_or_loud(self, reg):
        c1 = reg.counter("t.same_total", "h")
        c2 = reg.counter("t.same_total", "h")
        assert c1 is c2
        with pytest.raises(ValueError):
            reg.gauge("t.same_total")  # kind mismatch
        with pytest.raises(ValueError):
            reg.counter("t.same_total", labels=("x",))  # label mismatch

    def test_zero_keeps_allocations(self, reg):
        c = reg.counter("t.z_total", "h")
        h = reg.histogram("t.z_seconds", "h")
        c.inc(9)
        h.observe(0.1)
        reg.zero()
        assert c.value() == 0
        assert h.count() == 0
        c.inc()  # bound child still valid after zero()
        assert c.value() == 1

    def test_disabled_registry_records_nothing(self, reg):
        c = reg.counter("t.off_total", "h")
        h = reg.histogram("t.off_seconds", "h")
        reg.set_enabled(False)
        c.inc(5)
        h.observe(1.0)
        assert c.value() == 0 and h.count() == 0
        reg.set_enabled(True)
        c.inc()
        assert c.value() == 1


class TestForkSafety:
    def test_child_increment_visible_in_parent(self, reg):
        """THE fork contract: a counter incremented / histogram observed
        in a forked child is visible to the parent (shared slab pages)."""
        if "fork" not in mp.get_all_start_methods():
            pytest.skip("no fork on this platform")
        c = reg.counter("t.fork_total", "h")
        h = reg.histogram("t.fork_seconds", "h")
        lc = reg.counter("t.fork_labeled_total", "h", labels=("who",))
        child_combo = lc.labels(who="child")  # pre-fork, parent-visible
        ctx = mp.get_context("fork")

        def child():
            c.inc(7)
            h.observe(0.25)
            child_combo.inc(3)

        procs = [ctx.Process(target=child) for _ in range(2)]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=30)
            assert p.exitcode == 0
        assert c.value() == 14
        assert h.count() == 2
        assert abs(h.sum() - 0.5) < 1e-9
        assert child_combo.value() == 6

    def test_concurrent_children_do_not_lose_updates(self, reg):
        """The fork-inherited value lock makes += read-modify-write safe
        across processes — N children × M increments land exactly."""
        if "fork" not in mp.get_all_start_methods():
            pytest.skip("no fork on this platform")
        c = reg.counter("t.race_total", "h")
        ctx = mp.get_context("fork")

        def child():
            for _ in range(200):
                c.inc()

        procs = [ctx.Process(target=child) for _ in range(4)]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=60)
        assert c.value() == 800


class TestHistogramPercentiles:
    def test_accuracy_vs_numpy_reference(self, reg):
        """Percentiles from the fixed log-spaced buckets must track an
        exact numpy quantile within the documented per-bucket relative
        error bound (with a small slack for the rank-vs-midpoint
        convention difference)."""
        h = reg.histogram("t.acc_seconds", "h")
        rs = np.random.RandomState(7)
        vals = rs.lognormal(mean=-4.0, sigma=1.2, size=8000)
        for v in vals:
            h.observe(float(v))
        for q in (0.5, 0.9, 0.99):
            est = h.percentile(q)
            ref = float(np.quantile(vals, q))
            assert est is not None
            assert abs(est - ref) / ref < 2 * BUCKET_REL_ERROR + 0.02, (
                q, est, ref)

    def test_monotone_and_bounded(self, reg):
        h = reg.histogram("t.mono_seconds", "h")
        for v in (1e-4, 3e-3, 0.02, 0.02, 1.5):
            h.observe(v)
        p50, p90, p99 = (h.percentile(q) for q in (0.5, 0.9, 0.99))
        assert p50 <= p90 <= p99
        # overflow + underflow land in the edge buckets, not crash
        h.observe(0.0)
        h.observe(-1.0)
        h.observe(1e9)
        assert h.count() == 8
        assert h.percentile(0.0) is not None

    def test_empty_histogram_is_null_not_zero(self, reg):
        """The documented null contract: no observations → None, never a
        fake 0.0 (health_snapshot and the bench rely on this)."""
        h = reg.histogram("t.empty_seconds", "h")
        assert h.percentile(0.5) is None
        assert h.percentile(0.99) is None
        assert h.count() == 0

    def test_bucket_layout_is_shared_and_log_spaced(self):
        ratios = {round(b2 / b1, 6) for b1, b2
                  in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:])}
        assert len(ratios) == 1  # constant log spacing
        assert abs(next(iter(ratios)) - 10 ** 0.1) < 1e-6
        assert math.isclose(BUCKET_REL_ERROR, 10 ** 0.05 - 1.0)


class TestExposition:
    def test_prometheus_text_golden(self, reg):
        """Exposition-format golden: exact text for a tiny known registry
        (cumulative buckets, _sum/_count, labels, HELP/TYPE headers)."""
        c = reg.counter("gold.requests_total", "Requests seen.",
                        labels=("code",))
        c.labels(code="200").inc(3)
        c.labels(code="500").inc()
        g = reg.gauge("gold.depth", "Depth.")
        g.set(4)
        text = reg.expose_text()
        expected_lines = [
            "# HELP gold_depth Depth.",  # no zoo_ prefix? see below
        ]
        # exact golden on the non-histogram families
        assert "# HELP zoo_gold_requests_total Requests seen." in text
        assert "# TYPE zoo_gold_requests_total counter" in text
        assert 'zoo_gold_requests_total{code="200"} 3' in text
        assert 'zoo_gold_requests_total{code="500"} 1' in text
        assert "# TYPE zoo_gold_depth gauge" in text
        assert "zoo_gold_depth 4" in text
        del expected_lines

    def test_histogram_exposition_cumulative(self, reg):
        h = reg.histogram("gold.lat_seconds", "Latency.")
        h.observe(2e-5)   # bucket index 2-ish
        h.observe(0.5)
        h.observe(1e9)    # overflow
        text = reg.expose_text()
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("zoo_gold_lat_seconds")]
        bucket_lines = [ln for ln in lines if "_bucket" in ln]
        assert len(bucket_lines) == len(BUCKET_BOUNDS) + 1
        # cumulative counts are monotone and end at the total on +Inf
        counts = [float(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
        assert counts == sorted(counts)
        assert bucket_lines[-1].startswith(
            'zoo_gold_lat_seconds_bucket{le="+Inf"}')
        assert counts[-1] == 3
        assert any(ln.startswith("zoo_gold_lat_seconds_count") and
                   ln.endswith(" 3") for ln in lines)

    def test_snapshot_structure(self, reg):
        c = reg.counter("snap.n_total", "h")
        c.inc(2)
        ls = reg.gauge("snap.depth", "h", labels=("k",))
        ls.labels(k="x").set(5)
        h = reg.histogram("snap.lat_seconds", "h")
        h.observe(0.01)
        s = reg.snapshot()
        assert s["snap.n_total"] == {"type": "counter", "value": 2}
        assert s["snap.depth"]["series"] == {"k=x": 5}
        summ = s["snap.lat_seconds"]["summary"]
        assert summ["count"] == 1 and summ["p50"] is not None

    def test_default_registry_helpers(self):
        c = zoo_metrics.default_registry().counter(
            "t.default_total", "via module helpers")
        before = c.value()
        c.inc()
        snap = zoo_metrics.metrics_snapshot()
        assert snap["t.default_total"]["value"] == before + 1
        assert "zoo_t_default_total" in zoo_metrics.expose_text()


class TestZeroOverhead:
    def test_disabled_registry_under_1us_per_time_it_span(self):
        """The hot-path contract: with the registry disabled, adding an
        observe to a ``time_it`` span costs < 1µs extra (it is an
        attribute load + boolean check). Median-of-5 to dodge scheduler
        noise."""
        from analytics_zoo_tpu.common.utils import time_it
        r = Registry(capacity=256)
        h = r.histogram("t.probe_seconds", "h")
        r.set_enabled(False)
        n = 2000

        def bare():
            t0 = time.perf_counter()
            for _ in range(n):
                with time_it("zoo.overhead_probe"):
                    pass
            return (time.perf_counter() - t0) / n

        def with_observe():
            t0 = time.perf_counter()
            for _ in range(n):
                with time_it("zoo.overhead_probe"):
                    pass
                h.observe(0.001)
            return (time.perf_counter() - t0) / n

        try:
            bare_s = sorted(bare() for _ in range(5))[2]
            obs_s = sorted(with_observe() for _ in range(5))[2]
        finally:
            r.close()
        added = obs_s - bare_s
        assert added < 1e-6, f"disabled observe added {added * 1e9:.0f}ns"

    def test_span_hook_snapshot_survives_concurrent_mutation(self):
        """Satellite: ``time_it`` iterates a snapshot of span_hooks, so a
        hook registered/removed from another thread mid-exit cannot break
        an in-flight span."""
        import threading
        from analytics_zoo_tpu.common import utils as zutils

        stop = threading.Event()
        errors = []

        def churn():
            def hook(name, start, elapsed):
                pass
            while not stop.is_set():
                zutils.span_hooks.append(hook)
                zutils.span_hooks.remove(hook)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            for _ in range(3000):
                try:
                    with zutils.time_it("t.churn"):
                        pass
                except RuntimeError as e:  # list mutated during iteration
                    errors.append(e)
        finally:
            stop.set()
            t.join(timeout=5)
        assert not errors
