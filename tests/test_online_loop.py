"""The online learning loop end-to-end: streaming ingest off a queue
(watermark/epoch semantics, backpressure, bit-reproducible data_state
resume), continual training (train_online), and trainer→server promotion
(canary → fleet, model_version verified live, chaos rollback).

Capstone: a sharded NCF retrains on simulated click feedback *while
serving it* — ISSUE 15 / ROADMAP item 3."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import analytics_zoo_tpu
from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.serving.queues import FileQueue, make_queue

REPO = os.path.dirname(os.path.dirname(
    os.path.abspath(analytics_zoo_tpu.__file__)))

USERS, ITEMS = 40, 36


def _click(rs):
    return {"x": [int(rs.integers(1, USERS + 1)),
                  int(rs.integers(1, ITEMS + 1))],
            "y": int(rs.integers(0, 2)), "ts": 0.0}


def _clicks(n, seed=0):
    rs = np.random.default_rng(seed)
    return [(f"c{i}", _click(rs)) for i in range(n)]


def _stream(q, root, tag="j", **kw):
    kw.setdefault("watermark_s", 0.0)
    kw.setdefault("poll_interval_s", 0.005)
    kw.setdefault("epoch_records", 16)
    return FeatureSet.from_queue(q, os.path.join(root, tag), **kw)


def _ncf(shard=True):
    from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF
    return NeuralCF(USERS, ITEMS, 2, user_embed=8, item_embed=8,
                    hidden_layers=(16, 8), mf_embed=8,
                    shard_embeddings=shard)


def _estimator(model, mesh=None):
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives
    from analytics_zoo_tpu.keras.optimizers import SGD
    return Estimator(model=model,
                     loss_fn=objectives.get(
                         "sparse_categorical_crossentropy"),
                     optimizer=SGD(0.1), mesh=mesh, seed=7)


def _params_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestQueueFeatureSet:
    def test_batches_replay_and_digest(self, tmp_path):
        """Journal order is the data order: a fresh consumer rewound to a
        saved data_state replays the same bytes; a tampered digest is
        rejected; skip_batches fast-forwards identically."""
        root = str(tmp_path)
        q = make_queue(f"dir://{root}/q")
        q.enqueue_many(_clicks(64))
        fs = _stream(q, root)
        list(fs.train_iterator(4))  # epoch 1
        st = fs.data_state()
        epoch2 = list(fs.train_iterator(4))
        assert len(epoch2) == 4

        fs2 = _stream(q, root)
        fs2.set_data_state(st)
        replay = list(fs2.train_iterator(4))
        for (xa, ya), (xb, yb) in zip(epoch2, replay):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

        bad = json.loads(st)
        bad["crc"] ^= 1
        with pytest.raises(ValueError, match="digest"):
            fs2.set_data_state(json.dumps(bad))

        fs3 = _stream(q, root)
        fs3.set_data_state(st)
        tail = list(fs3.train_iterator(4, skip_batches=2))
        assert len(tail) == 2
        np.testing.assert_array_equal(tail[0][0], epoch2[2][0])
        for f in (fs, fs2, fs3):
            f.close()

    def test_throwaway_iterator_loses_nothing(self, tmp_path):
        """The Estimator draws one batch from an abandoned iterator for
        model init; an uncommitted read position dies with its iterator,
        so the real epoch sees every record."""
        root = str(tmp_path)
        q = make_queue(f"dir://{root}/q")
        q.enqueue_many(_clicks(32))
        fs = _stream(q, root)
        sample = next(fs.train_iterator(4))
        first = list(fs.train_iterator(4))[0]
        np.testing.assert_array_equal(sample[0], first[0])
        fs.close()

    def test_watermark_holds_future_records(self, tmp_path):
        """Records younger than the watermark stay out of the journal
        (claimed, buffered, unreleased); old records flow through."""
        from analytics_zoo_tpu.common.utils import wall_clock
        root = str(tmp_path)
        q = make_queue(f"dir://{root}/q")
        rs = np.random.default_rng(1)
        old = [(f"o{i}", _click(rs)) for i in range(8)]
        future = []
        for i in range(4):
            rec = _click(rs)
            rec["ts"] = wall_clock() + 3600.0
            future.append((f"f{i}", rec))
        q.enqueue_many(old + future)
        fs = _stream(q, root, epoch_records=8, watermark_s=1.0,
                     buffer_records=64)
        got = list(fs.train_iterator(4))
        assert len(got) == 2
        # the 4 future records must not have been released
        deadline = time.monotonic() + 2.0
        while q.pending_count() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fs._journal_records == 8
        fs.close()

    def test_buffer_full_forces_release_and_backpressure(self, tmp_path):
        """A full buffer (a) force-releases past the watermark so a
        quiet stream never deadlocks, and (b) stops claiming, so
        backpressure shows up as queue depth."""
        from analytics_zoo_tpu.common.utils import wall_clock
        root = str(tmp_path)
        q = make_queue(f"dir://{root}/q")
        rs = np.random.default_rng(2)
        items = []
        for i in range(12):
            rec = _click(rs)
            rec["ts"] = wall_clock() + 3600.0  # all behind the watermark
            items.append((f"b{i}", rec))
        q.enqueue_many(items)
        fs = _stream(q, root, epoch_records=8, watermark_s=1.0,
                     buffer_records=4)
        fs._ensure_ingest()
        # buffer fills to 4, force-releases them, then stops claiming
        deadline = time.monotonic() + 5.0
        while fs._journal_records < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fs._journal_records == 4
        time.sleep(0.1)  # ingest gets every chance to over-claim
        assert q.pending_count() == 8, "backpressure did not hold"
        # consuming drains the backlog and re-opens the claim window
        got = list(fs.train_iterator(4))
        assert len(got) == 2
        fs.close()

    def test_resume_against_wrong_journal_fails(self, tmp_path):
        root = str(tmp_path)
        q = make_queue(f"dir://{root}/q")
        q.enqueue_many(_clicks(32))
        fs = _stream(q, root)
        list(fs.train_iterator(4))
        st = fs.data_state()
        fs.close()
        q2 = make_queue(f"dir://{root}/q2")
        q2.enqueue_many(_clicks(32, seed=9))
        other = _stream(q2, root, tag="j2")
        list(other.train_iterator(4))
        with pytest.raises(ValueError):
            other.set_data_state(st)
        other.close()


class TestOnlineNCFLoop:
    """Capstone: sharded NCF retrains on a click stream WHILE serving it,
    a promotion lands fleet-wide with model_version verified live, and an
    injected canary failure rolls back cleanly."""

    def _servers(self, root, export, names=("canary", "replica")):
        from analytics_zoo_tpu.serving.server import (ClusterServing,
                                                      ServingConfig)
        out = {}
        for name in names:
            cfg = ServingConfig(data_src=f"dir://{root}/srv-{name}",
                                model_path=export, model_type="zoo",
                                image_shape=(2,), batch_size=4,
                                batch_wait_ms=5)
            out[name] = ClusterServing(cfg)
        return out

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_train_serve_promote_rollback(self, ctx, tmp_path):
        import jax
        from jax.sharding import Mesh

        from analytics_zoo_tpu.online import (Promoter, PromotionError,
                                              export_servable)
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        root = str(tmp_path)
        clicks = make_queue(f"dir://{root}/clicks")
        clicks.enqueue_many(_clicks(400))

        mesh = Mesh(np.asarray(jax.devices()[:4]), ("data",))
        ncf = _ncf(shard=True)
        est = _estimator(ncf.build_model(), mesh=mesh)
        fs = _stream(clicks, root, epoch_records=64)

        # v1: a first round of continual training, exported and served
        est.train_online(fs, batch_size=16, max_steps=4,
                         snapshot_interval_s=3600)
        assert est._embed_plan(), "online NCF did not take the sparse path"
        v1 = export_servable(ncf, est, f"{root}/exports/v1")
        servers = self._servers(root, v1)
        for s in servers.values():
            assert s.model_version == "v1"
            assert s.health_snapshot()["model_version"] == "v1"

        # keep training off the stream WHILE the fleet serves it
        inq = InputQueue(f"dir://{root}/srv-canary")
        outq = OutputQueue(f"dir://{root}/srv-canary")
        served = []
        for i in range(6):
            inq.enqueue_tensor(f"u{i}",
                               np.array([1.0 + i % USERS, 2.0], np.float32))
        est.train_online(fs, batch_size=16, max_steps=12,
                         snapshot_interval_s=3600)
        while servers["canary"].serve_once():
            pass
        for i in range(6):
            r = outq.query(f"u{i}", timeout_s=20.0)
            assert r is not None
            served.append(r)
        assert len(served) == 6
        assert est.global_step == 12

        # promotion: canary first, fleet-wide, verified live
        v2 = export_servable(ncf, est, f"{root}/exports/v2")
        prom = Promoter(servers, canary="canary")
        assert prom.promote(v2) == "v2"
        for s in servers.values():
            assert s.health_snapshot()["model_version"] == "v2"
        # the promoted fleet still answers, with the new params
        inq.enqueue_tensor("after", np.array([3.0, 5.0], np.float32))
        while servers["canary"].serve_once():
            pass
        assert outq.query("after", timeout_s=20.0) is not None

        # injected canary failure: nothing may move off v2
        v3 = export_servable(ncf, est, f"{root}/exports/v3")
        faults.reset()
        faults.arm("online.promote", at=1)  # 1-based: dies at the canary
        try:
            with pytest.raises(PromotionError):
                prom.promote(v3)
        finally:
            faults.reset()
        for s in servers.values():
            assert s.model_version == "v2"
            assert s.config.model_path == v2
        fs.close()

    def test_mid_rollout_chaos_rolls_back_with_zero_drops(self, ctx,
                                                          tmp_path):
        """``online.promote`` fires at the second instance: the canary
        (already on the new version) must roll BACK to the prior
        model_version, and every request routed through the fleet during
        the failed rollout still gets exactly one terminal result."""
        from analytics_zoo_tpu.online import Promoter, PromotionError
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
        from analytics_zoo_tpu.serving.fleet import (FleetInstance,
                                                     FleetRouter,
                                                     instance_queue)
        from analytics_zoo_tpu.serving.server import (ClusterServing,
                                                      ServingConfig)

        root = str(tmp_path / "fleet")
        ncf = _ncf(shard=False)
        ncf.compile(optimizer="adam",
                    loss="sparse_categorical_crossentropy")
        exports = {}
        for v in ("v1", "v2"):
            ncf.save_model(f"{root}/exports/{v}")
            exports[v] = f"{root}/exports/{v}"

        front = FileQueue(root)
        servers, insts = {}, []
        for name in ("a", "b"):
            qi = instance_queue(root, name)
            hp = str(tmp_path / f"{name}.json")
            cfg = ServingConfig(data_src=root, model_path=exports["v1"],
                                model_type="zoo", image_shape=(2,),
                                batch_size=4, batch_wait_ms=5,
                                health_path=hp, health_interval_s=0.0)
            servers[name] = ClusterServing(cfg, queue=qi)
            insts.append(FleetInstance(name, qi, hp))
        router = FleetRouter(front, insts, stale_after_s=30.0,
                             health_refresh_s=0.0)
        for s in servers.values():
            s._write_health()  # router needs live gauges to place on

        def pump():
            router.route_once()
            moved = 1
            while moved:
                moved = sum(s.serve_once() for s in servers.values())

        inq, outq = InputQueue(root), OutputQueue(root)
        uris = []
        for i in range(4):
            uris.append(f"pre{i}")
            inq.enqueue_tensor(f"pre{i}",
                               np.array([1.0 + i, 2.0], np.float32))
        pump()

        prom = Promoter(servers, canary="a")
        faults.reset()
        faults.arm("online.promote", at=2)  # dies rolling out to "b"
        try:
            with pytest.raises(PromotionError):
                prom.promote(exports["v2"])
        finally:
            faults.reset()
        # fleet consistent on the PRIOR version
        for s in servers.values():
            assert s.model_version == "v1"
            assert s.health_snapshot()["model_version"] == "v1"
        # traffic enqueued across the failed rollout all terminates
        for i in range(4):
            uris.append(f"post{i}")
            inq.enqueue_tensor(f"post{i}",
                               np.array([2.0 + i, 3.0], np.float32))
        pump()
        results = {u: outq.query(u, timeout_s=20.0) for u in uris}
        missing = [u for u, r in results.items() if r is None]
        assert not missing, f"dropped requests: {missing}"
        reloads = sum(s.counters.get("reloads", 0)
                      for s in servers.values())
        assert reloads == 2  # canary forward + canary rollback


_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from analytics_zoo_tpu.common.context import init_tpu_context, reset_context
reset_context(); init_tpu_context(force_reinit=True)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from online_child_common import build_estimator, build_stream

root = sys.argv[1]
est = build_estimator()
est.set_checkpoint(os.path.join(root, "ckpt"))
fs = build_stream(root)
open(os.path.join(root, "child_up"), "w").write("1")
# more steps than the queue can feed: the child blocks on the stream
# until the parent SIGKILLs it
est.train_online(fs, batch_size=8, max_steps=40, snapshot_interval_s=0.05)
"""

_CHILD_COMMON = r"""
import os
import numpy as np
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import objectives
from analytics_zoo_tpu.keras.optimizers import SGD
from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF
from analytics_zoo_tpu.serving.queues import make_queue


def build_estimator():
    model = NeuralCF(40, 36, 2, user_embed=8, item_embed=8,
                     hidden_layers=(16, 8), mf_embed=8,
                     shard_embeddings=False).build_model()
    return Estimator(model=model,
                     loss_fn=objectives.get(
                         "sparse_categorical_crossentropy"),
                     optimizer=SGD(0.1), seed=7)


def build_stream(root):
    q = make_queue(f"dir://{root}/q")
    return FeatureSet.from_queue(q, os.path.join(root, "j"),
                                 epoch_records=16, watermark_s=0.0,
                                 poll_interval_s=0.005)
"""


class TestSigkillResume:
    def test_killed_consumer_resumes_bit_identically(self, tmp_path):
        """SIGKILL the stream consumer mid-run; restart from data_state +
        latest snapshot; final params bit-identical to an uninterrupted
        run over the same click sequence."""
        root = str(tmp_path)
        total_clicks = _clicks(320, seed=3)  # 40 steps of 8
        child_dir = os.path.join(root, "child")
        ref_dir = os.path.join(root, "ref")
        os.makedirs(child_dir)
        os.makedirs(ref_dir)
        with open(os.path.join(root, "online_child_common.py"), "w") as f:
            f.write(_CHILD_COMMON)
        with open(os.path.join(root, "child.py"), "w") as f:
            f.write(_CHILD)

        # the child gets only the first 240 clicks: it can never reach
        # max_steps=40, so the SIGKILL always lands mid-run
        q = make_queue(f"dir://{child_dir}/q")
        q.enqueue_many(total_clicks[:240])
        # the child must see the SAME virtual device mesh as the parent
        # (conftest's XLA_FLAGS ride along in os.environ): different
        # data-parallel widths reduce losses in different float orders
        # and the bitwise comparison would be meaningless
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, os.path.join(root, "child.py"), child_dir],
            env=env, cwd=root, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        try:
            ckpt = os.path.join(child_dir, "ckpt")
            deadline = time.monotonic() + 300.0
            while time.monotonic() < deadline:
                snaps = ([d for d in os.listdir(ckpt)
                          if d.startswith("snapshot-")]
                         if os.path.isdir(ckpt) else [])
                if snaps:  # snapshots publish atomically: listed == whole
                    break
                if proc.poll() is not None:
                    raise AssertionError(
                        f"child exited early with {proc.returncode}")
                time.sleep(0.05)
            else:
                raise AssertionError("child never published a snapshot")
            time.sleep(0.3)  # let a few more steps/snapshots land
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.wait(timeout=60)

        sys.path.insert(0, root)
        try:
            import online_child_common as cc
        finally:
            sys.path.remove(root)

        # resume: feed the remaining clicks, restore snapshot + journal
        # cursor, run to the SAME total step count
        q.enqueue_many(total_clicks[240:])
        est_r = cc.build_estimator()
        est_r.set_checkpoint(os.path.join(child_dir, "ckpt"))
        # the kill may have torn an in-flight async write: restore the
        # newest snapshot that passes checksum validation
        snap = est_r._restore_latest_valid()
        assert snap is not None
        killed_at = est_r.global_step
        assert 0 < killed_at < 40
        fs_r = cc.build_stream(child_dir)
        est_r.train_online(fs_r, batch_size=8, max_steps=40,
                           snapshot_interval_s=3600)
        assert est_r.global_step == 40
        fs_r.close()

        # uninterrupted reference over the identical click sequence
        qr = make_queue(f"dir://{ref_dir}/q")
        qr.enqueue_many(total_clicks)
        est_ref = cc.build_estimator()
        fs_ref = cc.build_stream(ref_dir)
        est_ref.train_online(fs_ref, batch_size=8, max_steps=40,
                             snapshot_interval_s=3600)
        fs_ref.close()

        _params_equal(est_ref.params, est_r.params)
