"""Async checkpointing: triggered snapshots copy device→host synchronously
but serialize+write on a background thread behind a fence (reference writes
everything inline in the train loop — the TPU redesign must not stall the
step pipeline on storage)."""
import os
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common.triggers import SeveralIteration
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Dense


def _make(tmp_path, ckpt_trigger=None):
    model = Sequential([Dense(8, activation="tanh"), Dense(1)])
    est = Estimator(model=model, loss_fn=objectives.get("mse"),
                    optimizer=optimizers.Adam(1e-2))
    est.set_checkpoint(str(tmp_path / "ckpts"),
                       ckpt_trigger or SeveralIteration(2))
    return est


def _data(n=256):
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype(np.float32)
    y = x.sum(1, keepdims=True).astype(np.float32)
    return FeatureSet.from_ndarrays(x, y, shuffle=False)


class TestAsyncSnapshot:
    def test_writes_happen_in_background_with_fence(self, ctx, tmp_path,
                                                    monkeypatch):
        """_save_snapshot must return while the (slowed) write is still in
        flight; the next snapshot fences the previous; train() never
        returns with a write outstanding."""
        est = _make(tmp_path)
        fs = _data()
        est.train(fs, batch_size=64, epochs=1)  # init + first snapshots
        est._ckpt_writer.wait()

        writes = []
        real_write = est._write_snapshot

        def slow_write(path, tree):
            time.sleep(0.4)
            writes.append(path)
            real_write(path, tree)

        monkeypatch.setattr(est, "_write_snapshot", slow_write)
        t0 = time.perf_counter()
        est._save_snapshot()
        stall = time.perf_counter() - t0
        assert est._ckpt_writer.in_flight
        # the trigger-time cost is the host copy only, NOT the 0.4s write
        assert stall < 0.2, f"snapshot stalled the loop {stall:.3f}s"
        # fence: submitting the next one waits for the first
        est.global_step += 1
        est._save_snapshot()
        assert len(writes) == 1  # first write completed before second began
        est._ckpt_writer.wait()
        assert len(writes) == 2

    def test_snapshot_stall_under_10pct_of_step(self, ctx, tmp_path,
                                                monkeypatch):
        """The in-loop stall of a triggered snapshot is bounded by the
        device→host copy — with a slowed writer it must stay well under
        one (artificially slow) step time."""
        est = _make(tmp_path, SeveralIteration(2))
        fs = _data()
        est.train(fs, batch_size=64, epochs=1)
        est._ckpt_writer.wait()
        real_write = est._write_snapshot
        monkeypatch.setattr(
            est, "_write_snapshot",
            lambda p, t: (time.sleep(0.5), real_write(p, t)) and None)
        step_time = 0.5  # pretend step time == write time
        t0 = time.perf_counter()
        est._save_snapshot()
        stall = time.perf_counter() - t0
        est._ckpt_writer.wait()
        assert stall < 0.1 * step_time, \
            f"stall {stall*1e3:.1f}ms ≥ 10% of {step_time*1e3:.0f}ms step"

    def test_crash_between_copy_and_write_keeps_previous(self, ctx,
                                                         tmp_path,
                                                         monkeypatch):
        """A writer killed mid-write (simulated: staging dir written, rename
        never happens) must leave the previous snapshot as the newest
        restorable one."""
        est = _make(tmp_path)
        fs = _data()
        est.train(fs, batch_size=64, epochs=1)
        est._ckpt_writer.wait()
        good = est._latest_snapshot()
        assert good is not None

        import orbax.checkpoint as ocp

        def dying_write(path, tree):
            # simulate the process dying after staging, before publish:
            # write the staging dir, then abort without the rename
            staging = os.path.abspath(path) + ".writing"
            ocp.PyTreeCheckpointer().save(staging, tree, force=True)
            raise SystemExit("killed mid-write")

        monkeypatch.setattr(est, "_write_snapshot", dying_write)
        est.global_step += 1
        est._save_snapshot()
        with pytest.raises(RuntimeError, match="background checkpoint"):
            est._ckpt_writer.wait()
        # the half-written snapshot is invisible; the previous one intact
        assert est._latest_snapshot() == good
        est.load_checkpoint(est._latest_snapshot())  # restores cleanly

    def test_failed_write_surfaces_at_train_end(self, ctx, tmp_path,
                                                monkeypatch):
        est = _make(tmp_path, SeveralIteration(2))
        fs = _data()
        est.train(fs, batch_size=64, epochs=1)
        monkeypatch.setattr(
            est, "_write_snapshot",
            lambda p, t: (_ for _ in ()).throw(OSError("disk full")))
        with pytest.raises(Exception):
            est.train(fs, batch_size=64, epochs=2)
