"""Expert parallelism (MoE) and pipeline parallelism — the new mesh axes
completing dp/tp/sp/ep/pp (the reference has neither; SURVEY §5)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.moe import MoE, moe_sharding_rule
from analytics_zoo_tpu.parallel.pipeline import (
    gpipe, pipeline_apply, stack_stage_params)

RNG = jax.random.PRNGKey(0)


class TestMoE:
    def _layer_and_params(self, e=4, d=8, h=16, cap=8.0):
        layer = MoE(num_experts=e, hidden_dim=h, capacity_factor=cap,
                    aux_loss_weight=0.0, name="moe")
        params, state = layer.build(RNG, (None, 6, d))
        return layer, params, state

    def test_matches_manual_dense_routing(self):
        """With ample capacity, output == gate * expert_ffn(token)."""
        layer, params, state = self._layer_and_params()
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 8))
        y, _ = layer.call(params, state, x)
        flat = np.asarray(x).reshape(-1, 8)
        gate_logits = flat @ np.asarray(params["gate"])
        probs = np.exp(gate_logits - gate_logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        eidx = probs.argmax(-1)
        expected = np.empty_like(flat)
        for t in range(flat.shape[0]):
            e = eidx[t]
            hlay = np.maximum(
                flat[t] @ np.asarray(params["w_in"])[e]
                + np.asarray(params["b_in"])[e], 0)
            out = hlay @ np.asarray(params["w_out"])[e] \
                + np.asarray(params["b_out"])[e]
            expected[t] = out * probs[t, e]
        np.testing.assert_allclose(np.asarray(y).reshape(-1, 8), expected,
                                   rtol=1e-4, atol=1e-4)

    def test_capacity_overflow_rides_residual(self):
        """capacity_factor→0 forces every token over capacity: identity."""
        layer = MoE(num_experts=2, hidden_dim=4, capacity_factor=1e-9,
                    aux_loss_weight=0.0)
        params, state = layer.build(RNG, (None, 4, 4))
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 4, 4))
        y, _ = layer.call(params, state, x)
        cap = 1  # max(1, int(...)) floor
        # at most `experts*cap` tokens transformed; the rest are identity
        same = np.isclose(np.asarray(y).reshape(-1, 4),
                          np.asarray(x).reshape(-1, 4)).all(axis=1)
        assert same.sum() >= 4 - 2 * cap

    def test_trains_sharded_over_expert_axis(self, ):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense

        devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devices, ("data", "expert"))
        model = Sequential([Dense(8, name="proj"),
                            MoE(num_experts=4, hidden_dim=16, name="moe"),
                            Dense(2, activation="softmax", name="head")])
        est = Estimator(
            model=model,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(1e-2), mesh=mesh,
            param_sharding_rules=[moe_sharding_rule])
        rs = np.random.RandomState(0)
        x = rs.randn(64, 6, 8).astype(np.float32)
        y = rs.randint(0, 2, (64, 6)).astype(np.float32)
        fs = FeatureSet.from_ndarrays(x, y)
        with mesh:
            result = est.train(fs, batch_size=16, epochs=2)
        assert result["iterations"] == 8
        assert np.isfinite(result["loss_history"]).all()
        # expert-major params really sharded over the expert axis
        w_in = est.params["moe"]["w_in"]
        assert w_in.sharding.spec[0] == "expert"

    def test_aux_loss_flows_through_state_contract(self):
        """The balance penalty travels via the `__aux_loss__` state leaf
        (added to the objective by the Estimator) with a FIXED weight — not
        scaled by downstream cotangents."""
        layer, params, state = self._layer_and_params()
        layer.aux_loss_weight = 0.1

        def loss(p, x):
            y, st = layer.call(p, state, x)
            return jnp.sum(y ** 2) * 0.0 + st["__aux_loss__"]

        x = jax.random.normal(jax.random.PRNGKey(3), (2, 6, 8))
        g = jax.grad(loss)(params, x)
        # even with ZERO downstream gradient the router is still pushed
        # toward balance — the straight-through formulation failed this
        assert float(jnp.abs(g["gate"]).max()) > 0

    def test_grouped_routing_matches_flat_small(self):
        """group_size smaller than the token count must not change results
        when capacity is ample (routing is per group but experts see the
        same tokens)."""
        d = 8
        big = MoE(num_experts=2, hidden_dim=4, capacity_factor=64.0,
                  group_size=4096, name="m1")
        params, state = big.build(RNG, (None, 6, d))
        small = MoE(num_experts=2, hidden_dim=4, capacity_factor=64.0,
                    group_size=4, name="m2")
        x = jax.random.normal(jax.random.PRNGKey(9), (2, 6, d))
        y1, _ = big.call(params, state, x)
        y2, _ = small.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-4, atol=1e-5)


class TestPipeline:
    def test_stage_count_mismatch_rejected(self):
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
        stages = [{"w": jnp.eye(4), "b": jnp.zeros(4)}] * 8
        with pytest.raises(ValueError, match="stages"):
            gpipe(mesh, lambda p, x: x, stages)

    def _stages(self, p=4, d=8):
        rngs = jax.random.split(jax.random.PRNGKey(4), p)
        return [{"w": jax.random.normal(r, (d, d)) * 0.3,
                 "b": jnp.zeros(d)} for r in rngs]

    @staticmethod
    def _stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def test_pipeline_matches_sequential(self):
        p, d, batch = 4, 8, 16
        stages = self._stages(p, d)
        mesh = Mesh(np.asarray(jax.devices()[:p]), ("pipe",))
        stacked, fn = gpipe(mesh, self._stage_fn, stages, n_microbatches=4)
        x = jax.random.normal(jax.random.PRNGKey(5), (batch, d))
        y = fn(stacked, x)
        ref = x
        for sp in stages:
            ref = self._stage_fn(sp, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_pipeline_gradients_match(self):
        p, d, batch = 4, 8, 8
        stages = self._stages(p, d)
        mesh = Mesh(np.asarray(jax.devices()[:p]), ("pipe",))
        stacked, fn = gpipe(mesh, self._stage_fn, stages, n_microbatches=2)
        x = jax.random.normal(jax.random.PRNGKey(6), (batch, d))

        g_pipe = jax.grad(lambda sp: jnp.sum(fn(sp, x) ** 2))(stacked)

        def seq_loss(stage_list):
            h = x
            for spar in stage_list:
                h = self._stage_fn(spar, h)
            return jnp.sum(h ** 2)

        g_seq = jax.grad(seq_loss)(stages)
        for i in range(p):
            np.testing.assert_allclose(np.asarray(g_pipe["w"][i]),
                                       np.asarray(g_seq[i]["w"]),
                                       rtol=1e-3, atol=1e-4)

    def test_batch_must_divide_microbatches(self):
        p, d = 4, 8
        mesh = Mesh(np.asarray(jax.devices()[:p]), ("pipe",))
        stacked, fn = gpipe(mesh, self._stage_fn, self._stages(p, d),
                            n_microbatches=3)
        x = jnp.zeros((8, d))  # 8 % 3 != 0
        with pytest.raises(Exception):
            jax.block_until_ready(fn(stacked, x))


class TestTensorParallel:
    """Megatron-style layer sharding rules over the model axis: training
    must produce the SAME result as unsharded DP, with kernels actually
    laid out over the mesh."""

    def test_mlp_tp_matches_replicated(self, ctx):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        from analytics_zoo_tpu.parallel import megatron_mlp_rules

        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("data", "model"))
        rs = np.random.RandomState(0)
        x = rs.rand(64, 8).astype(np.float32)
        y = rs.rand(64, 1).astype(np.float32)

        def make(rules):
            model = Sequential([Dense(16, name="fc1"), Activation("relu"),
                                Dense(1, name="fc2")])
            return Estimator(model=model, loss_fn=objectives.get("mse"),
                             optimizer=optimizers.SGD(0.05), mesh=mesh,
                             param_sharding_rules=rules)

        rules = megatron_mlp_rules(up=("fc1",), down=("fc2",))
        est_tp = make(rules)
        est_dp = make(None)
        fs = lambda: FeatureSet.from_ndarrays(x, y, shuffle=False)
        r_tp = est_tp.train(fs(), batch_size=16, epochs=3)
        r_dp = est_dp.train(fs(), batch_size=16, epochs=3)
        np.testing.assert_allclose(r_tp["loss_history"],
                                   r_dp["loss_history"], rtol=1e-4)

        # the up-projection kernel is genuinely sharded over the model axis
        k1 = est_tp.params["fc1"]["kernel"]
        spec = k1.sharding.spec
        assert tuple(spec) == (None, "model"), spec
        k2 = est_tp.params["fc2"]["kernel"]
        assert tuple(k2.sharding.spec)[:1] == ("model",)  # trailing None
        # dims are normalized away by PartitionSpec

        p_tp = np.asarray(est_tp.predict(x[:16]))
        p_dp = np.asarray(est_dp.predict(x[:16]))
        np.testing.assert_allclose(p_tp, p_dp, atol=1e-5)


class TestMoETopK:
    def _x(self, n=16, d=8, seed=0):
        rs = np.random.RandomState(seed)
        return rs.randn(n, d).astype(np.float32)

    def test_top2_is_weighted_expert_mix(self, ctx):
        """With ample capacity, top-2 output must equal the gate-weighted
        sum of the two chosen experts' FFN outputs, gates renormalized."""
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.parallel.moe import MoE
        d, e = 8, 4
        moe = MoE(num_experts=e, hidden_dim=16, k=2, capacity_factor=8.0)
        rng = jax.random.PRNGKey(0)
        params, state = moe.build(rng, (None, d))
        x = jnp.asarray(self._x())
        y, _ = moe.call(params, state, x)

        # manual reference
        logits = x @ params["gate"]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top2 = jnp.argsort(-probs, axis=-1)[:, :2]
        ref = []
        for i in range(x.shape[0]):
            total = 0.0
            g2 = probs[i, top2[i]]
            g2 = g2 / g2.sum()
            for j, ei in enumerate(np.asarray(top2[i])):
                h = jax.nn.relu(x[i] @ params["w_in"][ei]
                                + params["b_in"][ei])
                total = total + g2[j] * (h @ params["w_out"][ei]
                                         + params["b_out"][ei])
            ref.append(total)
        np.testing.assert_allclose(np.asarray(y), np.asarray(jnp.stack(ref)),
                                   atol=1e-4)

    def test_top1_unchanged_default(self, ctx):
        from analytics_zoo_tpu.parallel.moe import MoE
        assert MoE(num_experts=4, hidden_dim=8).k == 1

    def test_invalid_k_raises(self, ctx):
        from analytics_zoo_tpu.parallel.moe import MoE
        with pytest.raises(ValueError, match="k=5"):
            MoE(num_experts=4, hidden_dim=8, k=5)
