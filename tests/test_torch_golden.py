"""Golden-validated pretrained import (VERDICT r2 item 4).

A torchvision-architecture ResNet-18 built in torch (the golden reference —
torch computes the expected activations at test time, which is strictly
stronger than frozen golden files: ANY layer-mapping error shows up as a
logit mismatch) is imported via ``net.load_torch_state_dict`` into the
native ``resnet(18, padding_mode="torch")`` graph. The probabilities must
match torch within 1e-4, BN statistics must transfer, and a freeze-backbone
fine-tune must leave imported backbone weights untouched.

Reference parity: ``models/image/imageclassification/ImageClassifier.scala:37``
loads published pretrained artifacts; the import path here is the TPU-native
equivalent.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
nn = torch.nn


@pytest.fixture(scope="module")
def imported():
    from analytics_zoo_tpu.net.torch_import import torchvision_resnet18
    torch.manual_seed(0)
    tm = torchvision_resnet18(num_classes=10)
    # a couple of train-mode passes give the BN running stats non-trivial
    # values, so a stats-transfer bug can't hide behind zeros/ones
    tm.train()
    with torch.no_grad():
        for i in range(2):
            tm(torch.randn(4, 3, 64, 64,
                           generator=torch.Generator().manual_seed(i)))
    tm.eval()

    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.net import load_torch_state_dict
    model = resnet(18, num_classes=10, input_shape=(64, 64, 3),
                   padding_mode="torch")
    params, state = load_torch_state_dict(model, tm.state_dict())
    return tm, model, params, state


class TestGoldenResnet18Import:
    def test_probabilities_match_torch_1e4(self, ctx, imported):
        tm, model, params, state = imported
        rs = np.random.RandomState(7)
        x = rs.randn(3, 64, 64, 3).astype(np.float32)
        with torch.no_grad():
            logits = tm(torch.from_numpy(np.transpose(x, (0, 3, 1, 2))))
            want = torch.softmax(logits, dim=-1).numpy()
        y, _ = model.call(params, state, x, training=False)
        got = np.asarray(y, np.float32)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
        # log-domain comparison ≈ logit deltas (up to the softmax constant)
        np.testing.assert_allclose(np.log(got + 1e-12),
                                   np.log(want + 1e-12), atol=1e-3)

    def test_bn_stats_transferred(self, imported):
        tm, model, params, state = imported
        want = tm.bn1.running_mean.numpy()
        got = np.asarray(state["stem_bn"]["moving_mean"])
        np.testing.assert_allclose(got, want, atol=1e-6)
        assert float(np.abs(want).max()) > 1e-4, \
            "BN stats trivially zero — the fixture failed to train them"

    def test_wrong_mapping_fails(self, ctx, imported):
        # the golden check has teeth: corrupt ONE imported kernel and the
        # probabilities must diverge far beyond tolerance
        tm, model, params, state = imported
        import jax
        bad = jax.tree_util.tree_map(lambda x: x, params)
        k = np.asarray(bad["stage2_block1_sc_conv"]["kernel"]).copy()
        bad["stage2_block1_sc_conv"]["kernel"] = k[..., ::-1]
        rs = np.random.RandomState(7)
        x = rs.randn(2, 64, 64, 3).astype(np.float32)
        with torch.no_grad():
            want = torch.softmax(
                tm(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))),
                dim=-1).numpy()
        y, _ = model.call(bad, state, x, training=False)
        assert np.max(np.abs(np.asarray(y) - want)) > 1e-3

    def test_classifier_pretrained_with_label_map(self, ctx, imported,
                                                  tmp_path):
        # end-to-end zoo path: ImageClassifier.load_pretrained_torch +
        # a label map file feeding predict_image_set's labeled top-k
        tm, *_ = imported
        import json

        from analytics_zoo_tpu.feature.image import LocalImageSet
        from analytics_zoo_tpu.models import ImageClassifier
        labels = [f"class_{i}" for i in range(10)]
        (tmp_path / "labels.json").write_text(json.dumps(labels))
        clf = ImageClassifier("resnet18", num_classes=10,
                              input_shape=(64, 64, 3))
        clf.load_pretrained_torch(tm).with_label_map(
            str(tmp_path / "labels.json"))
        rs = np.random.RandomState(11)
        imgs = [rs.randint(0, 255, (64, 64, 3)).astype(np.uint8)
                for _ in range(3)]
        out = clf.predict_image_set(LocalImageSet(imgs), top_k=3)
        assert len(out) == 3 and all(len(r) == 3 for r in out)
        assert all(lbl in labels for r in out for lbl, _ in r)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_pretrained_save_load_keeps_geometry(self, ctx, imported,
                                                 tmp_path):
        # the padding geometry must survive save_model/load_model — a
        # reloaded torch-import would otherwise silently pad differently
        tm, *_ = imported
        from analytics_zoo_tpu.models import ImageClassifier
        clf = ImageClassifier("resnet18", num_classes=10,
                              input_shape=(64, 64, 3))
        clf.load_pretrained_torch(tm)
        rs = np.random.RandomState(13)
        x = rs.randn(2, 64, 64, 3).astype(np.float32)
        want = np.asarray(clf.predict(x))
        clf.save_model(str(tmp_path / "m"))
        clf2 = ImageClassifier.load_model(str(tmp_path / "m"))
        assert clf2.padding_mode == "torch"
        np.testing.assert_allclose(np.asarray(clf2.predict(x)), want,
                                   atol=1e-5)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_golden_import_bundles_to_remote(self, ctx, imported, tmp_path):
        # the golden torch import, shipped as ONE pretrained bundle over a
        # fake-remote scheme, reloads with labels + torch padding geometry
        # and reproduces the golden-validated predictions exactly
        tm, *_ = imported
        from fsspec.implementations.memory import MemoryFileSystem

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.models import ImageClassifier, ZooModel
        clf = ImageClassifier("resnet18", num_classes=10,
                              input_shape=(64, 64, 3),
                              labels=[f"class_{i}" for i in range(10)])
        clf.load_pretrained_torch(tm)
        rs = np.random.RandomState(17)
        x = rs.randn(2, 64, 64, 3).astype(np.float32)
        want = np.asarray(clf.predict(x))
        file_io.register_filesystem("goldfs", MemoryFileSystem())
        try:
            uri = "goldfs://zoo/resnet18-golden"
            clf.save_pretrained(uri)
            loaded = ZooModel.load_pretrained(uri)
            assert loaded.padding_mode == "torch"
            assert loaded.labels == [f"class_{i}" for i in range(10)]
            np.testing.assert_allclose(np.asarray(loaded.predict(x)), want,
                                       atol=1e-5)
        finally:
            file_io.unregister_filesystem("goldfs")

    def test_label_map_formats(self, tmp_path):
        import json

        from analytics_zoo_tpu.models import ImageClassifier
        (tmp_path / "zero.json").write_text(json.dumps(
            {"0": "a", "1": "b", "2": "c"}))
        (tmp_path / "one.json").write_text(json.dumps(
            {"1": "a", "2": "b", "3": "c"}))
        (tmp_path / "lines.txt").write_text("a\nb\nc\n")
        for f in ("zero.json", "one.json", "lines.txt"):
            assert ImageClassifier.load_label_map(
                str(tmp_path / f)) == ["a", "b", "c"], f
        (tmp_path / "gap.json").write_text(json.dumps({"0": "a", "5": "b"}))
        with pytest.raises(ValueError):
            ImageClassifier.load_label_map(str(tmp_path / "gap.json"))

    def test_freeze_backbone_finetune(self, ctx, imported):
        tm, model, params, state = imported
        from analytics_zoo_tpu.feature import FeatureSet
        model.compile(optimizer="sgd",
                      loss="sparse_categorical_crossentropy")
        est = model.get_estimator()
        est.set_params(params)
        est.set_model_state(state)
        model.freeze([n for n in params if n != "logits"])
        rs = np.random.RandomState(3)
        x = rs.randn(8, 64, 64, 3).astype(np.float32)
        y = rs.randint(0, 10, 8).astype(np.float32)
        before = {"stem": np.asarray(params["stem_conv"]["kernel"]).copy(),
                  "logits": np.asarray(params["logits"]["kernel"]).copy()}
        model.fit(FeatureSet.from_ndarrays(x, y), batch_size=8, nb_epoch=1)
        after = est.get_params()
        np.testing.assert_allclose(np.asarray(after["stem_conv"]["kernel"]),
                                   np.asarray(before["stem"]),
                                   err_msg="frozen backbone moved")
        assert np.max(np.abs(np.asarray(after["logits"]["kernel"])
                             - np.asarray(before["logits"]))) > 0, \
            "head did not train"
