"""Serving tests (reference strategy: config parsing + pre/post processing
unit tests + an in-process end-to-end loop, SURVEY.md §4 'serving unit
tests')."""
import os
import time

import numpy as np
import pytest


class TestQueues:
    def test_file_queue_roundtrip(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue
        q = FileQueue(str(tmp_path))
        q.enqueue("a", {"tensor": [1, 2]})
        q.enqueue("b", {"tensor": [3, 4]})
        assert q.pending_count() == 2
        batch = q.claim_batch(10)
        assert [u for u, _ in batch] == ["a", "b"]
        assert q.pending_count() == 0
        q.put_result("a", {"value": [0.5]})
        assert q.get_result("a")["value"] == [0.5]
        assert q.get_result("missing") is None

    def test_trim_backpressure(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue
        q = FileQueue(str(tmp_path))
        for i in range(10):
            q.enqueue(f"u{i}", {"tensor": [i]})
        dropped = q.trim(4)
        assert dropped == 6
        assert q.pending_count() == 4
        # oldest were dropped; newest survive
        uris = [u for u, _ in q.claim_batch(10)]
        assert uris == ["u6", "u7", "u8", "u9"]

    def test_make_queue_dispatch(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue, make_queue
        assert isinstance(make_queue(f"dir://{tmp_path}"), FileQueue)
        assert isinstance(make_queue(str(tmp_path)), FileQueue)

    def test_image_codec(self):
        from analytics_zoo_tpu.serving.queues import decode_image, encode_image
        rs = np.random.RandomState(0)
        img = rs.randint(0, 255, (16, 16, 3)).astype(np.uint8)
        out = decode_image(encode_image(img))
        assert out.shape == (16, 16, 3)  # jpg is lossy; shape must hold


class TestConfig:
    def test_from_yaml(self, tmp_path):
        from analytics_zoo_tpu.serving import ServingConfig
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(
            "model:\n  path: /m\n  type: zoo\n"
            "data:\n  src: dir:///q\n  image_shape: 8,8,3\n"
            "  filter: topN(3)\n"
            "params:\n  batch_size: 16\n  max_pending: 100\n")
        cfg = ServingConfig.from_yaml(str(cfg_file))
        assert cfg.model_path == "/m"
        assert cfg.image_shape == (8, 8, 3)
        assert cfg.filter_top_n == 3
        assert cfg.batch_size == 16
        assert cfg.max_pending == 100


class TestPostProcessing:
    def test_top_n(self):
        from analytics_zoo_tpu.serving.server import top_n
        probs = np.array([0.1, 0.6, 0.3])
        out = top_n(probs, 2)
        assert out[0] == {"class": 1, "prob": pytest.approx(0.6)}
        assert out[1]["class"] == 2


class TestCompileWarmth:
    def test_prewarm_compiles_once_per_bucket(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_jax(
            lambda p, x: x @ p["w"], {"w": np.eye(4, 3, dtype=np.float32)})
        im.prewarm(np.zeros((3, 4), np.float32))  # batch 3 → bucket 4
        assert im.compile_counts == {4: 1}
        assert im.compile_seconds[4] > 0
        out = im.predict(np.ones((3, 4), np.float32))
        assert out.shape == (3, 3)
        # first request hit the prewarmed executable: NO new compile
        assert im.compile_counts == {4: 1}
        im.predict(np.ones((5, 4), np.float32))  # bucket 8: cold, compiles
        assert im.compile_counts == {4: 1, 8: 1}
        im.predict(np.ones((7, 4), np.float32))  # bucket 8 again: warm
        assert im.compile_counts == {4: 1, 8: 1}

    def test_prewarm_multiple_buckets(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_jax(lambda p, x: x * 2.0, {})
        im.prewarm(np.zeros((1, 2), np.float32), buckets=(1, 4, 30))
        assert im.compile_counts == {1: 1, 4: 1, 32: 1}

    def test_cluster_serving_startup_prewarm(self, ctx, tmp_path):
        """The server compiles its configured batch bucket at construction;
        the first claimed full batch runs with zero new compiles."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=4, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im)
        assert serving.prewarmed
        assert im.compile_counts == {4: 1}
        inq = InputQueue(src)
        rs = np.random.RandomState(0)
        for i in range(4):
            inq.enqueue_image(
                f"w{i}", rs.randint(0, 255, (4, 4, 3)).astype(np.uint8))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        assert served >= 4
        assert OutputQueue(src).query("w3", timeout_s=5.0) is not None
        assert im.compile_counts == {4: 1}  # first traffic: still warm

    def test_compile_cache_dir_wiring(self, ctx, tmp_path):
        import jax
        from analytics_zoo_tpu.common import context as ctx_mod
        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.inference import InferenceModel
        cfg = global_config()
        cfg.set("compile.cache_dir", str(tmp_path / "xla-cache"))
        try:
            InferenceModel()  # construction wires the persistent cache
            assert jax.config.jax_compilation_cache_dir == \
                str(tmp_path / "xla-cache")
        finally:
            cfg.unset("compile.cache_dir")
            ctx_mod._cache_wired = False
            jax.config.update("jax_compilation_cache_dir", None)


class TestEndToEnd:
    def test_serve_loop_tensor_records(self, ctx, tmp_path):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        w = np.eye(4, 3).astype(np.float32)
        im = InferenceModel().load_jax(
            lambda p, x: jax.nn.softmax(x @ p["w"], axis=-1),
            {"w": jnp.asarray(w)})
        import jax
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), filter_top_n=2,
                            batch_size=4, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im)

        inq = InputQueue(src)
        for i in range(6):
            inq.enqueue_tensor(f"rec{i}", np.eye(4)[i % 4] * (i + 1))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 6:
                break
        assert served >= 6
        outq = OutputQueue(src)
        res = outq.query("rec0", timeout_s=1.0)
        assert res is not None and len(res["topN"]) == 2
        assert res["topN"][0]["class"] == 0
        all_res = outq.dequeue()
        assert len(all_res) == 6

    def test_serve_loop_images_threaded(self, ctx, tmp_path):
        import cv2
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        rs = np.random.RandomState(0)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(8, 8, 3),
                            batch_size=2, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im).start()
        try:
            inq = InputQueue(src)
            for i in range(4):
                inq.enqueue_image(
                    f"img{i}", rs.randint(0, 255, (10, 12, 3)).astype(np.uint8))
            outq = OutputQueue(src)
            res = outq.query("img3", timeout_s=10.0)
            assert res is not None and "value" in res
        finally:
            serving.stop()

    def test_pipelined_run_many_batches(self, ctx, tmp_path):
        # the run() pipeline (decode thread / dispatch / writeback thread)
        # must serve every record across many micro-batches and account
        # device time
        import jax
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=4, batch_wait_ms=5, decode_threads=2)
        serving = ClusterServing(cfg, model=im).start()
        try:
            inq, outq = InputQueue(src), OutputQueue(src)
            rs = np.random.RandomState(1)
            for i in range(17):  # several batches + a ragged tail
                inq.enqueue_image(
                    f"p{i}", rs.randint(0, 255, (4, 4, 3)).astype(np.uint8))
            for i in range(17):
                assert outq.query(f"p{i}", timeout_s=20.0) is not None
        finally:
            serving.stop()
        assert serving.records_served >= 17
        assert serving.device_seconds > 0

    def test_bad_record_gets_error_result(self, ctx, tmp_path):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, FileQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(lambda p, x: x, {})
        src = f"dir://{tmp_path}"
        q = FileQueue(str(tmp_path))
        q.enqueue("bad", {"image": "not-base64-image!!"})
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=1, batch_wait_ms=1)
        serving = ClusterServing(cfg, model=im)
        serving.serve_once()
        res = OutputQueue(src).query("bad")
        assert res is not None and "error" in res
