"""Serving tests (reference strategy: config parsing + pre/post processing
unit tests + an in-process end-to-end loop, SURVEY.md §4 'serving unit
tests')."""
import os
import time

import numpy as np
import pytest


class TestQueues:
    def test_file_queue_roundtrip(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue
        q = FileQueue(str(tmp_path))
        q.enqueue("a", {"tensor": [1, 2]})
        q.enqueue("b", {"tensor": [3, 4]})
        assert q.pending_count() == 2
        batch = q.claim_batch(10)
        assert [u for u, _ in batch] == ["a", "b"]
        assert q.pending_count() == 0
        q.put_result("a", {"value": [0.5]})
        assert q.get_result("a")["value"] == [0.5]
        assert q.get_result("missing") is None

    def test_trim_backpressure(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue
        q = FileQueue(str(tmp_path))
        for i in range(10):
            q.enqueue(f"u{i}", {"tensor": [i]})
        dropped = q.trim(4)
        assert dropped == 6
        assert q.pending_count() == 4
        # oldest were dropped; newest survive
        uris = [u for u, _ in q.claim_batch(10)]
        assert uris == ["u6", "u7", "u8", "u9"]

    def test_enqueue_many_parity_with_singles(self, tmp_path):
        """A batch enqueue must be observationally identical to the same
        records enqueued one by one: same claim order, same payloads."""
        from analytics_zoo_tpu.serving import FileQueue
        recs = [(f"u{i}", {"tensor": [i, i + 1]}) for i in range(6)]
        single = FileQueue(str(tmp_path / "single"))
        for uri, payload in recs:
            single.enqueue(uri, payload)
        batched = FileQueue(str(tmp_path / "batched"))
        batched.enqueue_many(recs[:4])   # one rename publishes all four
        batched.enqueue_many(recs[4:])
        assert batched.claim_batch(10) == single.claim_batch(10)

    def test_enqueue_many_depth_and_trim_accounting(self, tmp_path):
        """pending_count / trim / shed see through batch files: depth is
        records, not files, and trimming drops oldest records first."""
        from analytics_zoo_tpu.serving import FileQueue
        q = FileQueue(str(tmp_path))
        q.enqueue_many([(f"b{i}", {"tensor": [i]}) for i in range(5)])
        q.enqueue("tail", {"tensor": [99]})
        assert q.pending_count() == 6
        dropped = q.trim(3)
        assert dropped == 3
        assert q.pending_count() == 3
        assert [u for u, _ in q.claim_batch(10)] == ["b3", "b4", "tail"]

    def test_make_queue_dispatch(self, tmp_path):
        from analytics_zoo_tpu.serving import FileQueue, make_queue
        assert isinstance(make_queue(f"dir://{tmp_path}"), FileQueue)
        assert isinstance(make_queue(str(tmp_path)), FileQueue)

    def test_image_codec(self):
        from analytics_zoo_tpu.serving.queues import decode_image, encode_image
        rs = np.random.RandomState(0)
        img = rs.randint(0, 255, (16, 16, 3)).astype(np.uint8)
        out = decode_image(encode_image(img))
        assert out.shape == (16, 16, 3)  # jpg is lossy; shape must hold


class TestConfig:
    def test_from_yaml(self, tmp_path):
        from analytics_zoo_tpu.serving import ServingConfig
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(
            "model:\n  path: /m\n  type: zoo\n"
            "data:\n  src: dir:///q\n  image_shape: 8,8,3\n"
            "  filter: topN(3)\n"
            "params:\n  batch_size: 16\n  max_pending: 100\n")
        cfg = ServingConfig.from_yaml(str(cfg_file))
        assert cfg.model_path == "/m"
        assert cfg.image_shape == (8, 8, 3)
        assert cfg.filter_top_n == 3
        assert cfg.batch_size == 16
        assert cfg.max_pending == 100


class TestPostProcessing:
    def test_top_n(self):
        from analytics_zoo_tpu.serving.server import top_n
        probs = np.array([0.1, 0.6, 0.3])
        out = top_n(probs, 2)
        assert out[0] == {"class": 1, "prob": pytest.approx(0.6)}
        assert out[1]["class"] == 2


class TestCompileWarmth:
    def test_prewarm_compiles_once_per_bucket(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_jax(
            lambda p, x: x @ p["w"], {"w": np.eye(4, 3, dtype=np.float32)})
        im.prewarm(np.zeros((3, 4), np.float32))  # batch 3 → bucket 4
        assert im.compile_counts == {4: 1}
        assert im.compile_seconds[4] > 0
        out = im.predict(np.ones((3, 4), np.float32))
        assert out.shape == (3, 3)
        # first request hit the prewarmed executable: NO new compile
        assert im.compile_counts == {4: 1}
        im.predict(np.ones((5, 4), np.float32))  # bucket 8: cold, compiles
        assert im.compile_counts == {4: 1, 8: 1}
        im.predict(np.ones((7, 4), np.float32))  # bucket 8 again: warm
        assert im.compile_counts == {4: 1, 8: 1}

    def test_prewarm_multiple_buckets(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_jax(lambda p, x: x * 2.0, {})
        im.prewarm(np.zeros((1, 2), np.float32), buckets=(1, 4, 30))
        assert im.compile_counts == {1: 1, 4: 1, 32: 1}

    def test_cluster_serving_startup_prewarm(self, ctx, tmp_path):
        """The server compiles its configured batch bucket at construction;
        the first claimed full batch runs with zero new compiles."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=4, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im)
        assert serving.prewarmed
        assert im.compile_counts == {4: 1}
        inq = InputQueue(src)
        rs = np.random.RandomState(0)
        for i in range(4):
            inq.enqueue_image(
                f"w{i}", rs.randint(0, 255, (4, 4, 3)).astype(np.uint8))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        assert served >= 4
        assert OutputQueue(src).query("w3", timeout_s=5.0) is not None
        assert im.compile_counts == {4: 1}  # first traffic: still warm

    def test_compile_cache_dir_wiring(self, ctx, tmp_path):
        import jax
        from analytics_zoo_tpu.common import context as ctx_mod
        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.inference import InferenceModel
        cfg = global_config()
        cfg.set("compile.cache_dir", str(tmp_path / "xla-cache"))
        try:
            InferenceModel()  # construction wires the persistent cache
            assert jax.config.jax_compilation_cache_dir == \
                str(tmp_path / "xla-cache")
        finally:
            cfg.unset("compile.cache_dir")
            ctx_mod._cache_wired = False
            jax.config.update("jax_compilation_cache_dir", None)


def _mean_model():
    from analytics_zoo_tpu.inference import InferenceModel
    return InferenceModel().load_jax(
        lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})


def _sum_model():
    from analytics_zoo_tpu.inference import InferenceModel
    return InferenceModel().load_jax(
        lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True), {})


class TestDeadlines:
    def _serving(self, tmp_path, **cfg_kw):
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=4,
                            batch_wait_ms=5, **cfg_kw)
        return ClusterServing(cfg, model=_sum_model()), src

    def test_expired_at_claim_gets_deadline_error_not_device_time(
            self, ctx, tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        inq = InputQueue(src)
        for i in range(3):
            inq.enqueue_tensor(f"d{i}", np.full(4, 1.0), deadline_ms=1)
        inq.enqueue_tensor("live", np.full(4, 1.0))  # no deadline
        time.sleep(0.05)  # the 1ms budgets are long gone
        served = serving.serve_once()
        assert served == 4  # all four answered
        outq = OutputQueue(src)
        for i in range(3):
            res = outq.query(f"d{i}")
            assert res is not None and res["error"] == "deadline exceeded"
        assert "value" in outq.query("live")
        assert serving.counters["expired"] == 3
        assert serving.records_served == 1  # dead requests never dispatched

    def test_server_side_default_deadline(self, ctx, tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path, default_deadline_ms=1)
        inq = InputQueue(src)
        inq.enqueue_tensor("r0", np.full(4, 1.0))  # client stamped no budget
        time.sleep(0.05)
        serving.serve_once()
        res = OutputQueue(src).query("r0")
        assert res is not None and res["error"] == "deadline exceeded"

    def test_expiry_before_dispatch_filters_rows(self, ctx, tmp_path):
        """The last deadline check masks expired rows out of an already-
        stacked batch without disturbing the live ones."""
        serving, src = self._serving(tmp_path)
        uris = ["a", "b", "c"]
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        expiries = [None, time.time() - 1.0, time.time() + 60.0]
        kept_uris, kept_x = serving._expire_before_dispatch(uris, x, expiries)
        assert kept_uris == ["a", "c"]
        np.testing.assert_array_equal(kept_x, x[[0, 2]])
        assert serving.counters["expired"] == 1
        from analytics_zoo_tpu.serving import OutputQueue
        assert OutputQueue(src).query("b")["error"] == "deadline exceeded"


class TestLoadShed:
    def test_shed_posts_error_for_every_dropped_uri(self, ctx, tmp_path):
        """Overload answers the oldest requests with explicit shed errors
        (the silent trim is gone); the newest still serve."""
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5, max_pending=4)
        serving = ClusterServing(cfg, model=_sum_model())
        inq = InputQueue(src)
        for i in range(10):
            inq.enqueue_tensor(f"u{i}", np.full(4, float(i)))
        served = 0
        for _ in range(20):
            served += serving.serve_once()
            if served >= 4:
                break
        outq = OutputQueue(src)
        results = {u: outq.query(u, timeout_s=5.0) for u in
                   (f"u{i}" for i in range(10))}
        assert all(r is not None for r in results.values())  # none hang
        shed = [u for u, r in results.items() if "error" in r
                and "overloaded" in r["error"]]
        ok = [u for u, r in results.items() if "value" in r]
        assert sorted(shed) == [f"u{i}" for i in range(6)]  # oldest shed
        assert sorted(ok) == [f"u{i}" for i in range(6, 10)]
        assert serving.counters["shed"] == 6

    def test_estimated_wait_shed_knob(self, ctx, tmp_path):
        """With shed_wait_ms set, the allowed depth follows the measured
        service rate: a slow model sheds down to what it can answer in
        time, not to the static max_pending."""
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5, max_pending=1000,
                            shed_wait_ms=100)
        serving = ClusterServing(cfg, model=_sum_model())
        serving._ewma_record_s = 0.05  # measured: 50ms/record → depth 2
        inq = InputQueue(src)
        for i in range(8):
            inq.enqueue_tensor(f"u{i}", np.full(4, float(i)))
        serving.serve_once()
        outq = OutputQueue(src)
        results = {u: outq.query(u, timeout_s=5.0) for u in
                   (f"u{i}" for i in range(8))}
        shed = [u for u, r in results.items()
                if r and "error" in r and "overloaded" in r["error"]]
        assert sorted(shed) == [f"u{i}" for i in range(6)]
        assert serving.counters["shed"] == 6


class TestDrain:
    def test_drain_finishes_inflight_and_leaves_no_threads(self, ctx,
                                                           tmp_path):
        import threading

        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        # snapshot BEFORE this server exists: stray decode-pool threads
        # from earlier serve_once-only tests die on GC, asynchronously —
        # only THIS server's threads are this test's drain contract
        pre = set(threading.enumerate())
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=4,
                            batch_wait_ms=5,
                            health_path=str(tmp_path / "health.json"),
                            health_interval_s=0.0)
        serving = ClusterServing(cfg, model=_sum_model()).start()
        inq, outq = InputQueue(src), OutputQueue(src)
        for i in range(8):
            inq.enqueue_tensor(f"r{i}", np.full(4, float(i)))
        for i in range(8):
            assert outq.query(f"r{i}", timeout_s=20.0) is not None
        serving.drain(timeout_s=20.0)
        # drained = every claimed request answered with a VALUE (a drain
        # never errors in-flight work) and the loop machinery is gone
        results = outq.dequeue()
        assert len(results) == 8
        assert all("value" in r for r in results.values())
        assert serving.health_snapshot()["state"] == "drained"
        assert serving._in_flight == 0
        leaked = [t.name for t in threading.enumerate()
                  if t not in pre and t.name.startswith("zoo-serving")]
        assert not leaked
        # terminal health state landed on disk for the supervisor
        import json
        health = json.loads((tmp_path / "health.json").read_text())
        assert health["state"] == "drained"
        assert health["records_served"] == 8
        assert health["counters"]["shed"] == 0

    def test_drain_is_restartable(self, ctx, tmp_path):
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5)
        serving = ClusterServing(cfg, model=_sum_model()).start()
        serving.drain(timeout_s=20.0)
        serving.start()  # a drained server can serve again
        try:
            inq = InputQueue(src)
            inq.enqueue_tensor("after", np.full(4, 1.0))
            assert OutputQueue(src).query("after", timeout_s=20.0) is not None
        finally:
            serving.stop()


class TestHotReload:
    def _serving(self, tmp_path, **kw):
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5, **kw)
        return ClusterServing(cfg, model=_sum_model()), src

    def test_reload_swaps_model_with_zero_lost_requests(self, ctx, tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        serving.start()
        try:
            inq, outq = InputQueue(src), OutputQueue(src)
            inq.enqueue_tensor("pre", np.full(4, 1.0))
            pre = outq.query("pre", timeout_s=20.0)
            assert pre["value"] == [pytest.approx(4.0)]  # sum model
            assert serving.model_version == "inline-0"  # stamped at load
            serving.reload_model(model=_mean_model())
            inq.enqueue_tensor("post", np.full(4, 1.0))
            post = outq.query("post", timeout_s=20.0)
            assert post["value"] == [pytest.approx(1.0)]  # mean model
            assert serving.counters["reloads"] == 1
            # version advanced with the swap and health reports it
            assert serving.model_version == "inline-1"
            assert serving.health_snapshot()["model_version"] == "inline-1"
            serving.check_health()
        finally:
            serving.stop()
        assert len(outq.dequeue()) == 2  # nothing dropped across the swap

    def test_reload_canary_failure_rolls_back(self, ctx, tmp_path):
        """A candidate whose canary predict fails must never reach the
        serve path: the old model keeps serving."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (InputQueue, ModelReloadError,
                                               OutputQueue)
        serving, src = self._serving(tmp_path)
        old = serving.model

        def bad_forward(p, x):
            raise ValueError("incompatible input shape")

        bad = InferenceModel().load_jax(bad_forward, {})
        with pytest.raises(ModelReloadError, match="previous model"):
            serving.reload_model(model=bad)
        assert serving.model is old
        assert serving.counters["reload_failures"] == 1
        # a failed reload must NOT advance the advertised version
        assert serving.model_version == "inline-0"
        assert serving.health_snapshot()["model_version"] == "inline-0"
        # ...and the old model still answers traffic
        InputQueue(src).enqueue_tensor("r0", np.full(4, 1.0))
        serving.serve_once()
        assert OutputQueue(src).query("r0")["value"] == [pytest.approx(4.0)]

    def test_reload_wrong_batch_dim_rolls_back(self, ctx, tmp_path):
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import ModelReloadError
        serving, _ = self._serving(tmp_path)
        old = serving.model
        # collapses the batch dim: the canary's leading-dim gate must trip
        squash = InferenceModel().load_jax(
            lambda p, x: x.reshape(-1).sum(keepdims=True)[None], {})
        with pytest.raises(ModelReloadError):
            serving.reload_model(model=squash)
        assert serving.model is old


class TestDeepHealth:
    def test_snapshot_fields_and_periodic_file(self, ctx, tmp_path):
        import json

        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, ServingConfig)
        src = f"dir://{tmp_path / 'spool'}"
        health = tmp_path / "health.json"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5, health_path=str(health),
                            health_interval_s=0.0)
        serving = ClusterServing(cfg, model=_sum_model())
        inq = InputQueue(src)
        for i in range(4):
            inq.enqueue_tensor(f"r{i}", np.full(4, float(i)))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        snap = serving.health_snapshot()
        assert snap["state"] == "idle"
        assert snap["queue_pending"] == 0
        assert snap["in_flight"] == 0
        assert snap["records_served"] == 4
        assert snap["last_claim_age_s"] is not None
        assert snap["latency_ms"]["window"] == 4
        assert snap["latency_ms"]["p50"] <= snap["latency_ms"]["p99"]
        assert snap["counters"]["shed"] == 0
        assert snap["counters"]["expired"] == 0
        assert snap["model_version"] == "inline-0"
        # the same snapshot streams to the health file on the serve path
        on_disk = json.loads(health.read_text())
        assert on_disk["records_served"] >= 2
        assert on_disk["model_version"] == "inline-0"
        serving.stop()
        assert json.loads(health.read_text())["state"] == "stopped"


class TestShutdownErrorPaths:
    def test_force_sentinel_errors_displaced_inflight_item(self, ctx,
                                                           tmp_path):
        """Satellite: a full pipeline queue at shutdown displaces a REAL
        in-flight item to land the sentinel — its requests must get
        explicit shutdown error results, never vanish."""
        import queue as pyqueue

        from analytics_zoo_tpu.serving import (
            ClusterServing, OutputQueue, ServingConfig)
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=2,
                            batch_wait_ms=5)
        serving = ClusterServing(cfg, model=_sum_model())
        serving._in_flight = 2
        q = pyqueue.Queue(maxsize=1)
        q.put((["lost-a", "lost-b"], object()))  # stuck in-flight batch
        serving._force_sentinel(q)
        outq = OutputQueue(src)
        for uri in ("lost-a", "lost-b"):
            res = outq.query(uri)
            assert res is not None
            assert res["error"].startswith("serving shut down")
        assert q.get_nowait() is None  # the sentinel landed
        assert serving._in_flight == 0
        assert serving.counters["errors"] == 2

    def test_malformed_request_file_under_slo_flow(self, ctx, tmp_path):
        """Satellite: junk in the spool (partial write, foreign producer)
        is dropped without wedging the loop, and the well-formed requests
        around it still get exactly one terminal result each."""
        from analytics_zoo_tpu.serving import (
            ClusterServing, FileQueue, InputQueue, OutputQueue,
            ServingConfig)
        src = f"dir://{tmp_path}"
        q = FileQueue(str(tmp_path))
        (tmp_path / "requests" / "00000000000000000000-junk.json"
         ).write_text("{not json")
        inq = InputQueue(src)
        inq.enqueue_tensor("good0", np.full(4, 1.0))
        inq.enqueue_tensor("good1", np.full(4, 2.0), deadline_ms=60_000)
        cfg = ServingConfig(data_src=src, image_shape=(4,), batch_size=4,
                            batch_wait_ms=5, max_pending=2)
        serving = ClusterServing(cfg, model=_sum_model())
        # max_pending=2 with 3 spool files: the shed pass hits the
        # malformed file FIRST (it sorts oldest) and must drop it without
        # posting a bogus result or crashing
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 2:
                break
        outq = OutputQueue(src)
        assert outq.query("good0", timeout_s=5.0)["value"] == \
            [pytest.approx(4.0)]
        assert outq.query("good1", timeout_s=5.0)["value"] == \
            [pytest.approx(8.0)]
        assert q.pending_count() == 0  # junk removed from the spool
        assert len(outq.dequeue()) == 2  # and no phantom result for it

    def test_query_backs_off_exponentially(self, tmp_path, monkeypatch):
        """Satellite: the result poll must not hammer the store at a fixed
        10ms — sleeps grow geometrically (monotonic-deadline bounded)."""
        import time as time_mod

        from analytics_zoo_tpu.serving.client import OutputQueue
        sleeps = []
        monkeypatch.setattr(time_mod, "sleep",
                            lambda s: sleeps.append(s))
        outq = OutputQueue(f"dir://{tmp_path}")
        assert outq.query("missing", timeout_s=0.05) is None
        assert sleeps, "poll loop never slept"
        assert sleeps[0] <= 0.005
        doubling = [b for a, b in zip(sleeps, sleeps[1:]) if b >= a]
        assert len(doubling) >= min(3, len(sleeps) - 1)


class TestEndToEnd:
    def test_serve_loop_tensor_records(self, ctx, tmp_path):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        w = np.eye(4, 3).astype(np.float32)
        im = InferenceModel().load_jax(
            lambda p, x: jax.nn.softmax(x @ p["w"], axis=-1),
            {"w": jnp.asarray(w)})
        import jax
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,), filter_top_n=2,
                            batch_size=4, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im)

        inq = InputQueue(src)
        for i in range(6):
            inq.enqueue_tensor(f"rec{i}", np.eye(4)[i % 4] * (i + 1))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 6:
                break
        assert served >= 6
        outq = OutputQueue(src)
        res = outq.query("rec0", timeout_s=1.0)
        assert res is not None and len(res["topN"]) == 2
        assert res["topN"][0]["class"] == 0
        all_res = outq.dequeue()
        assert len(all_res) == 6

    def test_serve_loop_images_threaded(self, ctx, tmp_path):
        import cv2
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        rs = np.random.RandomState(0)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(8, 8, 3),
                            batch_size=2, batch_wait_ms=5)
        serving = ClusterServing(cfg, model=im).start()
        try:
            inq = InputQueue(src)
            for i in range(4):
                inq.enqueue_image(
                    f"img{i}", rs.randint(0, 255, (10, 12, 3)).astype(np.uint8))
            outq = OutputQueue(src)
            res = outq.query("img3", timeout_s=10.0)
            assert res is not None and "value" in res
        finally:
            serving.stop()

    def test_pipelined_run_many_batches(self, ctx, tmp_path):
        # the run() pipeline (decode thread / dispatch / writeback thread)
        # must serve every record across many micro-batches and account
        # device time
        import jax
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, InputQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=4, batch_wait_ms=5, decode_threads=2)
        serving = ClusterServing(cfg, model=im).start()
        try:
            inq, outq = InputQueue(src), OutputQueue(src)
            rs = np.random.RandomState(1)
            for i in range(17):  # several batches + a ragged tail
                inq.enqueue_image(
                    f"p{i}", rs.randint(0, 255, (4, 4, 3)).astype(np.uint8))
            for i in range(17):
                assert outq.query(f"p{i}", timeout_s=20.0) is not None
        finally:
            serving.stop()
        assert serving.records_served >= 17
        assert serving.device_seconds > 0

    def test_bad_record_gets_error_result(self, ctx, tmp_path):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (
            ClusterServing, FileQueue, OutputQueue, ServingConfig)
        im = InferenceModel().load_jax(lambda p, x: x, {})
        src = f"dir://{tmp_path}"
        q = FileQueue(str(tmp_path))
        q.enqueue("bad", {"image": "not-base64-image!!"})
        cfg = ServingConfig(data_src=src, image_shape=(4, 4, 3),
                            batch_size=1, batch_wait_ms=1)
        serving = ClusterServing(cfg, model=im)
        serving.serve_once()
        res = OutputQueue(src).query("bad")
        assert res is not None and "error" in res
