"""Tests for the extended layer library (conv_extended, advanced,
sparse embedding/dense, ConvLSTM2D) — forward shapes + golden values,
mirroring the reference's per-layer spec strategy (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.keras.layers import (
    AddConstant, AtrousConvolution1D, AtrousConvolution2D, AveragePooling1D,
    AveragePooling3D, BinaryThreshold, CAdd, CMul, Convolution3D, ConvLSTM2D,
    Cropping1D, Cropping2D, Cropping3D, Deconvolution2D, ELU, Exp, ExpandDim,
    GaussianDropout, GaussianNoise, GaussianSampler, GlobalAveragePooling3D,
    GlobalMaxPooling3D, HardShrink, HardTanh, Highway, Identity, LeakyReLU,
    LocallyConnected1D, LocallyConnected2D, Log, LRN2D, Masking, Max,
    MaxoutDense, MaxPooling3D, Mul, MulConstant, Narrow, Negative, Power,
    PReLU, ResizeBilinear, RReLU, Scale, SelectTable, SeparableConvolution2D,
    Softmax, SoftShrink, SparseDense, SparseEmbedding, SpatialDropout1D,
    SpatialDropout2D, SplitTensor, Sqrt, Square, SReLU, Threshold,
    ThresholdedReLU, TimeDistributed, UpSampling1D, UpSampling2D,
    UpSampling3D, WithinChannelLRN2D, ZeroPadding1D, ZeroPadding3D, Dense)

RNG = jax.random.PRNGKey(0)


def run_layer(layer, x, training=False, rng=None):
    shape = ([(None,) + np.asarray(a).shape[1:] for a in x]
             if isinstance(x, list) else (None,) + np.asarray(x).shape[1:])
    params, state = layer.build(RNG, shape)
    xs = [jnp.asarray(a) for a in x] if isinstance(x, list) else jnp.asarray(x)
    y, new_state = layer.call(params, state, xs, training=training, rng=rng)
    return y, params, new_state


class TestConvExtended:
    def test_conv3d(self):
        x = np.zeros((2, 6, 8, 8, 3), np.float32)
        layer = Convolution3D(4, 3, 3, 3)
        y, _, _ = run_layer(layer, x)
        assert y.shape == (2, 4, 6, 6, 4)
        assert layer.compute_output_shape((None, 6, 8, 8, 3)) == (None, 4, 6, 6, 4)

    def test_conv3d_known_value(self):
        x = np.ones((1, 2, 2, 2, 1), np.float32)
        layer = Convolution3D(1, 2, 2, 2, init="ones", bias=False)
        y, _, _ = run_layer(layer, x)
        np.testing.assert_allclose(y, 8 * np.ones((1, 1, 1, 1, 1)), rtol=1e-6)

    def test_deconv2d(self):
        x = np.ones((1, 4, 4, 2), np.float32)
        layer = Deconvolution2D(3, 3, 3, subsample=(2, 2), border_mode="same")
        y, _, _ = run_layer(layer, x)
        assert y.shape == (1, 8, 8, 3)
        assert layer.compute_output_shape((None, 4, 4, 2)) == (None, 8, 8, 3)

    def test_separable_conv(self):
        x = np.random.RandomState(0).randn(2, 8, 8, 3).astype(np.float32)
        layer = SeparableConvolution2D(6, 3, 3, depth_multiplier=2)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 6, 6, 6)
        assert params["depthwise"].shape == (3, 3, 1, 6)
        assert params["pointwise"].shape == (1, 1, 6, 6)

    def test_atrous_conv2d(self):
        x = np.zeros((1, 10, 10, 2), np.float32)
        layer = AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2))
        y, _, _ = run_layer(layer, x)
        assert y.shape == (1, 6, 6, 4)  # effective kernel 5
        assert layer.compute_output_shape((None, 10, 10, 2)) == (None, 6, 6, 4)

    def test_atrous_conv1d(self):
        x = np.zeros((1, 10, 2), np.float32)
        y, _, _ = run_layer(AtrousConvolution1D(4, 3, atrous_rate=2), x)
        assert y.shape == (1, 6, 4)

    def test_locally_connected1d(self):
        x = np.ones((2, 6, 3), np.float32)
        layer = LocallyConnected1D(5, 3)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 4, 5)
        assert params["kernel"].shape == (4, 9, 5)

    def test_locally_connected2d_matches_conv_when_shared(self):
        # with a constant kernel, locally-connected == conv
        x = np.random.RandomState(0).randn(1, 5, 5, 2).astype(np.float32)
        lc = LocallyConnected2D(3, 2, 2, bias=False)
        params, _ = lc.build(RNG, (None, 5, 5, 2))
        k = np.asarray(params["kernel"][0])  # [K*K*C, F]
        params = {"kernel": jnp.broadcast_to(jnp.asarray(k), params["kernel"].shape)}
        y, _ = lc.call(params, {}, jnp.asarray(x))
        from jax import lax
        kern = k.reshape(2, 2, 2, 3)
        want = lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(kern), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-5)

    def test_pool3d(self):
        x = np.arange(64, dtype=np.float32).reshape(1, 4, 4, 4, 1)
        y, _, _ = run_layer(MaxPooling3D((2, 2, 2)), x)
        assert y.shape == (1, 2, 2, 2, 1)
        y2, _, _ = run_layer(AveragePooling3D((2, 2, 2)), x)
        np.testing.assert_allclose(float(y2[0, 0, 0, 0, 0]),
                                   np.mean([0, 1, 4, 5, 16, 17, 20, 21]))
        assert run_layer(GlobalMaxPooling3D(), x)[0].shape == (1, 1)
        assert run_layer(GlobalAveragePooling3D(), x)[0].shape == (1, 1)

    def test_avg_pool1d(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 8, 1)
        y, _, _ = run_layer(AveragePooling1D(2), x)
        np.testing.assert_allclose(y[0, :, 0], [0.5, 2.5, 4.5, 6.5])

    def test_crops(self):
        x = np.zeros((1, 8, 8, 2), np.float32)
        assert run_layer(Cropping2D(((1, 2), (2, 1))), x)[0].shape == (1, 5, 5, 2)
        x1 = np.zeros((1, 8, 2), np.float32)
        assert run_layer(Cropping1D((1, 1)), x1)[0].shape == (1, 6, 2)
        x3 = np.zeros((1, 6, 6, 6, 2), np.float32)
        assert run_layer(Cropping3D(), x3)[0].shape == (1, 4, 4, 4, 2)

    def test_upsampling_padding(self):
        x = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
        y, _, _ = run_layer(UpSampling2D((2, 2)), x)
        assert y.shape == (1, 4, 4, 1)
        np.testing.assert_allclose(
            y[0, :, :, 0],
            [[0, 0, 1, 1], [0, 0, 1, 1], [2, 2, 3, 3], [2, 2, 3, 3]])
        x1 = np.zeros((1, 3, 2), np.float32)
        assert run_layer(UpSampling1D(3), x1)[0].shape == (1, 9, 2)
        x3 = np.zeros((1, 2, 2, 2, 1), np.float32)
        assert run_layer(UpSampling3D(), x3)[0].shape == (1, 4, 4, 4, 1)
        assert run_layer(ZeroPadding1D(2), x1)[0].shape == (1, 7, 2)
        assert run_layer(ZeroPadding3D(1), x3)[0].shape == (1, 4, 4, 4, 1)

    def test_resize_bilinear(self):
        x = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
        y, _, _ = run_layer(ResizeBilinear(4, 4), x)
        assert y.shape == (1, 4, 4, 1)

    def test_lrn(self):
        x = np.ones((1, 4, 4, 8), np.float32)
        y, _, _ = run_layer(LRN2D(), x)
        assert y.shape == (1, 4, 4, 8)
        assert float(y[0, 0, 0, 4]) < 1.0  # normalized down
        y2, _, _ = run_layer(WithinChannelLRN2D(), x)
        assert y2.shape == (1, 4, 4, 8)


class TestAdvancedActivations:
    def test_unary_golden(self):
        x = np.array([[-2.0, -0.3, 0.0, 0.5, 2.0]], np.float32)
        cases = [
            (ELU(1.0), np.where(x > 0, x, np.expm1(x))),
            (LeakyReLU(0.1), np.where(x > 0, x, 0.1 * x)),
            (ThresholdedReLU(0.4), np.where(x > 0.4, x, 0)),
            (Threshold(0.0, -1.0), np.where(x > 0, x, -1.0)),
            (BinaryThreshold(0.0), (x > 0).astype(np.float32)),
            (HardTanh(), np.clip(x, -1, 1)),
            (HardShrink(0.5), np.where(np.abs(x) > 0.5, x, 0)),
            (SoftShrink(0.5), np.sign(x) * np.maximum(np.abs(x) - 0.5, 0)),
            (Negative(), -x),
            (Square(), x * x),
            (AddConstant(3.0), x + 3),
            (MulConstant(2.0), x * 2),
            (Identity(), x),
        ]
        for layer, want in cases:
            y, _, _ = run_layer(layer, x)
            np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6,
                                       err_msg=type(layer).__name__)

    def test_exp_log_sqrt_power(self):
        x = np.array([[0.5, 1.0, 4.0]], np.float32)
        np.testing.assert_allclose(run_layer(Exp(), x)[0], np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(run_layer(Log(), x)[0], np.log(x), rtol=1e-5)
        np.testing.assert_allclose(run_layer(Sqrt(), x)[0], np.sqrt(x), rtol=1e-5)
        np.testing.assert_allclose(run_layer(Power(2.0, 2.0, 1.0), x)[0],
                                   (1 + 2 * x) ** 2, rtol=1e-5)

    def test_softmax(self):
        x = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        y, _, _ = run_layer(Softmax(), x)
        np.testing.assert_allclose(np.sum(y, -1), 1.0, rtol=1e-5)

    def test_prelu_srelu(self):
        x = np.array([[-1.0, 2.0]], np.float32)
        y, params, _ = run_layer(PReLU(), x)
        np.testing.assert_allclose(y, [[-0.25, 2.0]], rtol=1e-6)
        y2, _, _ = run_layer(SReLU(), x)
        assert y2.shape == x.shape

    def test_rrelu(self):
        x = np.array([[-4.0, 4.0]], np.float32)
        y, _, _ = run_layer(RReLU(), x)  # inference: mean leak
        np.testing.assert_allclose(y, [[-4 * (1 / 8 + 1 / 3) / 2, 4.0]], rtol=1e-5)
        y_tr, _, _ = run_layer(RReLU(), x, training=True,
                               rng=jax.random.PRNGKey(3))
        assert -4 * (1 / 3) <= float(y_tr[0, 0]) <= -4 * (1 / 8)


class TestStochastic:
    def test_gaussian_dropout_noise(self):
        x = np.ones((512, 8), np.float32)
        y, _, _ = run_layer(GaussianDropout(0.3), x, training=True,
                            rng=jax.random.PRNGKey(0))
        assert abs(float(jnp.mean(y)) - 1.0) < 0.05
        assert float(jnp.std(y)) > 0.1
        y_inf, _, _ = run_layer(GaussianDropout(0.3), x)
        np.testing.assert_array_equal(y_inf, x)
        y2, _, _ = run_layer(GaussianNoise(0.5), x, training=True,
                             rng=jax.random.PRNGKey(1))
        assert abs(float(jnp.std(y2)) - 0.5) < 0.05

    def test_gaussian_sampler(self):
        mean = np.zeros((1000, 2), np.float32)
        log_var = np.zeros((1000, 2), np.float32)
        layer = GaussianSampler()
        y, _ = layer.call({}, {}, [jnp.asarray(mean), jnp.asarray(log_var)],
                          rng=jax.random.PRNGKey(0))
        assert abs(float(jnp.std(y)) - 1.0) < 0.1

    def test_spatial_dropout(self):
        x = np.ones((4, 10, 8), np.float32)
        y, _, _ = run_layer(SpatialDropout1D(0.5), x, training=True,
                            rng=jax.random.PRNGKey(0))
        # whole channels dropped: each [b, :, c] slice all-zero or all-scaled
        arr = np.asarray(y)
        for b in range(4):
            for c in range(8):
                col = arr[b, :, c]
                assert np.all(col == 0) or np.all(col == 2.0)
        x2 = np.ones((2, 5, 5, 3), np.float32)
        y2, _, _ = run_layer(SpatialDropout2D(0.5), x2, training=True,
                             rng=jax.random.PRNGKey(1))
        assert y2.shape == x2.shape


class TestStructural:
    def test_masking(self):
        x = np.array([[[0.0, 0.0], [1.0, 2.0]]], np.float32)
        y, _, _ = run_layer(Masking(0.0), x)
        np.testing.assert_allclose(y[0, 0], [0, 0])
        np.testing.assert_allclose(y[0, 1], [1, 2])

    def test_highway(self):
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        y, _, _ = run_layer(Highway(), x)
        assert y.shape == (4, 6)

    def test_maxout(self):
        x = np.ones((3, 5), np.float32)
        layer = MaxoutDense(4, nb_feature=3)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (3, 4)
        assert params["kernel"].shape == (5, 12)
        assert layer.compute_output_shape((None, 5)) == (None, 4)

    def test_time_distributed(self):
        x = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
        layer = TimeDistributed(Dense(6))
        y, _, _ = run_layer(layer, x)
        assert y.shape == (2, 4, 6)
        assert layer.compute_output_shape((None, 4, 3)) == (None, 4, 6)

    def test_select_split_narrow(self):
        a, b = jnp.ones((2, 3)), 2 * jnp.ones((2, 3))
        y, _ = SelectTable(1).call({}, {}, [a, b])
        np.testing.assert_allclose(y, b)
        parts, _ = SplitTensor(1, 3).call({}, {}, jnp.arange(6.0).reshape(1, 6))
        assert len(parts) == 3 and parts[0].shape == (1, 2)
        y2, _ = Narrow(1, 2, 3).call({}, {}, jnp.arange(8.0).reshape(1, 8))
        np.testing.assert_allclose(y2, [[2, 3, 4]])

    def test_expand_dims_max(self):
        x = jnp.ones((2, 3))
        y, _ = ExpandDim(1).call({}, {}, x)
        assert y.shape == (2, 1, 3)
        y2, _ = Max(1).call({}, {}, x)
        assert y2.shape == (2,)

    def test_cadd_cmul_mul_scale(self):
        x = np.ones((2, 3), np.float32)
        y, params, _ = run_layer(CAdd((3,)), x)
        np.testing.assert_allclose(y, x)  # bias starts 0
        y2, _, _ = run_layer(CMul((3,)), x)
        np.testing.assert_allclose(y2, x)  # weight starts 1
        y3, _, _ = run_layer(Mul(), x)
        np.testing.assert_allclose(y3, x)
        y4, _, _ = run_layer(Scale((3,)), x)
        np.testing.assert_allclose(y4, x)


class TestSparse:
    def test_sparse_embedding_combiners(self):
        table = np.arange(20, dtype=np.float32).reshape(5, 4)
        idx = np.array([[0, 2, -1]], np.int32)  # -1 = padding
        for combiner, want in [
            ("sum", table[0] + table[2]),
            ("mean", (table[0] + table[2]) / 2),
            ("sqrtn", (table[0] + table[2]) / np.sqrt(2)),
        ]:
            layer = SparseEmbedding(5, 4, combiner=combiner, weights=table)
            y, _, _ = run_layer(layer, idx)
            np.testing.assert_allclose(y[0], want, rtol=1e-5,
                                       err_msg=combiner)

    def test_sparse_embedding_grad_is_sparse_shape(self):
        layer = SparseEmbedding(100, 8)
        params, _ = layer.build(RNG, (None, 3))
        idx = jnp.array([[1, 5, 7]], jnp.int32)
        g = jax.grad(lambda p: layer.call(p, {}, idx)[0].sum())(params)
        assert g["embeddings"].shape == (100, 8)
        # only touched rows have gradient
        nz = np.nonzero(np.any(np.asarray(g["embeddings"]) != 0, axis=1))[0]
        np.testing.assert_array_equal(nz, [1, 5, 7])

    def test_sparse_dense(self):
        layer = SparseDense(3, input_dim=10, bias=False)
        shape = [(None, 2), (None, 2)]
        params, _ = layer.build(RNG, shape)
        idx = jnp.array([[0, 4]], jnp.int32)
        vals = jnp.array([[2.0, 1.0]], jnp.float32)
        y, _ = layer.call(params, {}, [idx, vals])
        k = np.asarray(params["kernel"])
        np.testing.assert_allclose(y[0], 2 * k[0] + k[4], rtol=1e-5)


class TestConvLSTM:
    def test_conv_lstm_shapes(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8, 4).astype(np.float32)
        layer = ConvLSTM2D(6, 3)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 8, 8, 6)
        assert params["kernel"].shape == (3, 3, 10, 24)
        y2, _, _ = run_layer(ConvLSTM2D(6, 3, return_sequences=True), x)
        assert y2.shape == (2, 3, 8, 8, 6)

    def test_conv_lstm3d_shapes_and_grad(self):
        from analytics_zoo_tpu.keras.layers import ConvLSTM3D
        x = np.random.RandomState(0).randn(2, 2, 4, 4, 4, 3).astype(np.float32)
        layer = ConvLSTM3D(5, 3)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 4, 4, 4, 5)
        assert params["kernel"].shape == (3, 3, 3, 8, 20)
        y2, _, _ = run_layer(ConvLSTM3D(5, 3, return_sequences=True), x)
        assert y2.shape == (2, 2, 4, 4, 4, 5)
        g = jax.grad(lambda p: layer.call(p, {}, jnp.asarray(x))[0].sum())(
            params)
        assert g["kernel"].shape == params["kernel"].shape

    def test_get_shape(self):
        from analytics_zoo_tpu.keras.layers import GetShape
        y, _, _ = run_layer(GetShape(), np.zeros((2, 3, 5), np.float32))
        np.testing.assert_array_equal(np.asarray(y), [2, 3, 5])

    def test_conv_lstm_grad(self):
        x = jnp.ones((1, 2, 4, 4, 2))
        layer = ConvLSTM2D(3, 3)
        params, _ = layer.build(RNG, (None, 2, 4, 4, 2))
        g = jax.grad(lambda p: layer.call(p, {}, x)[0].sum())(params)
        assert g["kernel"].shape == params["kernel"].shape


class TestReviewRegressions:
    def test_lrn_even_window(self):
        x = np.ones((1, 4, 4, 8), np.float32)
        y, _, _ = run_layer(LRN2D(n=4), x)
        assert y.shape == x.shape
        y2, _, _ = run_layer(WithinChannelLRN2D(size=4), x)
        assert y2.shape == x.shape

    def test_gaussian_sampler_requires_rng(self):
        layer = GaussianSampler()
        with pytest.raises(ValueError, match="rng"):
            layer.call({}, {}, [jnp.zeros((2, 2)), jnp.zeros((2, 2))])

    def test_grouped_ranking_metric_multiclass(self):
        from analytics_zoo_tpu.keras.metrics import NDCG
        m = NDCG(k=1)
        st = m.init_state()
        y_true = jnp.asarray([[1.0, 0.0]])
        # [Q, L, C] softmax output: positive-class prob ranks list correctly
        y_pred = jnp.asarray([[[0.1, 0.9], [0.8, 0.2]]])
        st = m.update(st, y_true, y_pred, jnp.ones(1))
        assert abs(m.compute(st) - 1.0) < 1e-6

    def test_grouped_ranking_metric_bad_shape(self):
        from analytics_zoo_tpu.keras.metrics import NDCG
        m = NDCG(k=1)
        with pytest.raises(ValueError, match="ranking metric"):
            m.update(m.init_state(), jnp.ones((2, 3)), jnp.ones((2, 4)),
                     jnp.ones(2))
