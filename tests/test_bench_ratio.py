"""Tests for bench.py's outage-proof harness pieces: the CPU-parity ratio
mode (every workload must land a schema-valid record with no accelerator),
resumable sharding (BENCH_STATE.json round-trip, --shard selection),
baseline diffing, record validation, partial-record stashing, and the
argument parser. bench.py is a script, not a package module — loaded here
by file path."""
import importlib.util
import os

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("zoo_bench", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


class TestRatioMode:
    def test_plan_covers_every_workload(self):
        assert set(bench._RATIO_PLAN) == set(bench._WORKLOADS)
        for impl_key, _value_key in bench._RATIO_PLAN.values():
            assert impl_key in bench._RATIO_IMPLS

    @pytest.mark.parametrize("name", [
        pytest.param(n, marks=pytest.mark.slow) if n == "generate" else n
        for n in sorted(bench._RATIO_PLAN)])
    def test_every_workload_lands_a_valid_record(self, name, ctx):
        """The outage contract: with no accelerator at all, each workload
        still produces one schema-valid ratio record. Impl results are
        memoized, so the parametrizations run one actual probe per impl
        key. The ``generate`` probe decodes 32 serial reference streams
        (minutes of wall time) and runs in the slow tier."""
        rec = bench._run_ratio(name)
        assert bench._validate_record(rec) == []
        assert rec["metric"] == f"{name}_cpu_ratio"
        assert rec["unit"] == "ratio"
        d = rec["detail"]
        assert d["mode"] == "cpu_ratio"
        assert d["proxy_for"] == name
        if rec["value"] is not None:  # mp ratio is None where fork isn't
            assert rec["value"] > 0

    def test_obs_ratio_honors_disabled_contract(self):
        detail = bench._ratio_memo.get("obs") or bench._ratio_obs()
        assert detail["disabled_under_1us"] is True


class TestShardAndState:
    def test_shards_partition_the_run_order(self):
        names = list(bench._WORKLOADS)
        shards = [bench._select_shard(names, (i, 3)) for i in range(3)]
        flat = [n for s in shards for n in s]
        assert sorted(flat) == sorted(names)      # disjoint and complete
        assert len(flat) == len(set(flat))
        # round-robin: the expensive head rows spread across shards
        assert names[0] in shards[0] and names[1] in shards[1]
        assert bench._select_shard(names, None) == names

    def test_state_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_STATE_PATH",
                            str(tmp_path / "BENCH_STATE.json"))
        assert bench._load_state() == {}
        results = {"resnet50": bench._BenchResult(
            metric="resnet50_cpu_ratio", value=2.5, unit="ratio",
            mfu=None, detail={"mode": "cpu_ratio"})}
        bench._save_state(results)
        loaded = bench._load_state()
        assert set(loaded) == {"resnet50"}
        assert loaded["resnet50"]["value"] == 2.5
        assert isinstance(loaded["resnet50"], bench._BenchResult)
        bench._clear_state()
        assert bench._load_state() == {}
        bench._clear_state()  # idempotent

    def test_corrupt_state_is_ignored(self, tmp_path, monkeypatch):
        path = tmp_path / "BENCH_STATE.json"
        path.write_text("{not json")
        monkeypatch.setattr(bench, "_STATE_PATH", str(path))
        assert bench._load_state() == {}


class TestBaseline:
    def test_diff_math_and_filters(self):
        baseline = {"workloads": {
            "a": {"value": 100.0, "unit": "images/s"},
            "b": {"value": 10.0, "unit": "ratio"},
            "c": {"value": 50.0, "unit": "images/s"},
            "z": {"value": 0.0, "unit": "x"},
        }}
        results = {
            "a": bench._BenchResult(metric="a", value=110.0,
                                    unit="images/s", detail={}),
            "b": bench._BenchResult(metric="b", value=10.0,
                                    unit="records/s", detail={}),  # unit drift
            "c": bench._BenchResult(metric="c", value=None,
                                    unit="images/s", detail={}),   # no value
            "z": bench._BenchResult(metric="z", value=3.0,
                                    unit="x", detail={}),          # zero base
            "d": bench._BenchResult(metric="d", value=1.0,
                                    unit="x", detail={}),          # no base
        }
        assert bench._baseline_diff(results, baseline) == {"a": 10.0}

    def test_diff_is_null_without_reference_numbers(self):
        results = {"a": bench._BenchResult(metric="a", value=1.0,
                                           unit="x", detail={})}
        assert bench._baseline_diff(results, {}) is None
        assert bench._baseline_diff(results, {"published": {}}) is None

    def test_write_then_diff_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BENCH_BASELINE",
                           str(tmp_path / "BASELINE.json"))
        results = {"a": bench._BenchResult(metric="a", value=200.0,
                                           unit="x", detail={})}
        doc = {"workloads": {"a": {"value": 160.0, "unit": "x"}}}
        (tmp_path / "BASELINE.json").write_text(__import__("json").dumps(doc))
        assert bench._baseline_diff(results) == {"a": 25.0}


class TestRecordSchema:
    def test_valid_record_is_clean(self):
        rec = bench._BenchResult(metric="x_cpu_ratio", value=1.5,
                                 unit="ratio", mfu=None, detail={})
        assert bench._validate_record(rec) == []
        rec["value"] = None  # null value is legal (failed sub-probe)
        assert bench._validate_record(rec) == []

    def test_junk_records_are_named(self):
        assert bench._validate_record("nope") == ["record must be a dict"]
        problems = bench._validate_record({"metric": "", "unit": 3,
                                           "value": "fast", "detail": []})
        assert len(problems) == 4

    def test_note_partial_stashes_best_so_far(self):
        saved = dict(bench._PARTIAL), dict(bench._PARTIAL["detail"])
        try:
            bench._PARTIAL.clear()
            bench._PARTIAL["detail"] = {}
            bench._note_partial(warmup_done=True)
            assert "metric" not in bench._PARTIAL
            bench._note_partial(metric="m", value=7.0, unit="u", rate=7.0)
            assert bench._PARTIAL["metric"] == "m"
            assert bench._PARTIAL["value"] == 7.0
            assert bench._PARTIAL["detail"] == {"warmup_done": True,
                                                "rate": 7.0}
        finally:
            bench._PARTIAL.clear()
            bench._PARTIAL.update(saved[0])
            bench._PARTIAL["detail"] = saved[1]


class TestArgs:
    def test_defaults(self):
        args = bench._parse_args([])
        assert args["which"] == "all" and args["one"] is None
        assert not args["ratio"] and not args["resume"]
        assert args["shard"] is None and args["budget"] is None

    def test_flags_and_aliases(self):
        args = bench._parse_args(["--one", "input_pipeline",
                                  "--budget", "120.5"])
        assert args["one"] == "pipeline"  # alias resolved
        assert args["budget"] == 120.5
        args = bench._parse_args(["--ratio", "--resume", "--full",
                                  "--write-baseline", "--shard", "1/4",
                                  "eval"])
        assert args["ratio"] and args["resume"] and args["full"]
        assert args["write_baseline"]
        assert args["shard"] == (1, 4)
        assert args["which"] == "eval"

    def test_bad_input_rejected(self):
        with pytest.raises(SystemExit):
            bench._parse_args(["--wat"])
        with pytest.raises(SystemExit):
            bench._parse_args(["--shard", "4/4"])
