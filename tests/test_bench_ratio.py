"""Tests for bench.py's outage-proof harness pieces: the CPU-parity ratio
mode (every workload must land a schema-valid record with no accelerator),
resumable sharding (BENCH_STATE.json round-trip, --shard selection),
baseline diffing, record validation, partial-record stashing, and the
argument parser. bench.py is a script, not a package module — loaded here
by file path."""
import importlib.util
import os

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("zoo_bench", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


class TestRatioMode:
    def test_plan_covers_every_workload(self):
        assert set(bench._RATIO_PLAN) == set(bench._WORKLOADS)
        for impl_key, _value_key in bench._RATIO_PLAN.values():
            assert impl_key in bench._RATIO_IMPLS

    @pytest.mark.parametrize("name", [
        pytest.param(n, marks=pytest.mark.slow)
        if n in ("generate", "tp_decode") else n
        for n in sorted(bench._RATIO_PLAN)])
    def test_every_workload_lands_a_valid_record(self, name, ctx):
        """The outage contract: with no accelerator at all, each workload
        still produces one schema-valid ratio record. Impl results are
        memoized, so the parametrizations run one actual probe per impl
        key. The ``generate`` probe decodes 32 serial reference streams
        (minutes of wall time) and runs in the slow tier."""
        rec = bench._run_ratio(name)
        assert bench._validate_record(rec) == []
        assert rec["metric"] == f"{name}_cpu_ratio"
        assert rec["unit"] == "ratio"
        d = rec["detail"]
        assert d["mode"] == "cpu_ratio"
        assert d["proxy_for"] == name
        if rec["value"] is not None:  # mp ratio is None where fork isn't
            assert rec["value"] > 0

    def test_obs_ratio_honors_disabled_contract(self):
        detail = bench._ratio_memo.get("obs") or bench._ratio_obs()
        assert detail["disabled_under_1us"] is True


class TestShardAndState:
    def test_shards_partition_the_run_order(self):
        names = list(bench._WORKLOADS)
        shards = [bench._select_shard(names, (i, 3)) for i in range(3)]
        flat = [n for s in shards for n in s]
        assert sorted(flat) == sorted(names)      # disjoint and complete
        assert len(flat) == len(set(flat))
        # round-robin: the expensive head rows spread across shards
        assert names[0] in shards[0] and names[1] in shards[1]
        assert bench._select_shard(names, None) == names

    def test_state_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_STATE_PATH",
                            str(tmp_path / "BENCH_STATE.json"))
        assert bench._load_state() == {}
        results = {"resnet50": bench._BenchResult(
            metric="resnet50_cpu_ratio", value=2.5, unit="ratio",
            mfu=None, detail={"mode": "cpu_ratio"})}
        bench._save_state(results)
        loaded = bench._load_state()
        assert set(loaded) == {"resnet50"}
        assert loaded["resnet50"]["value"] == 2.5
        assert isinstance(loaded["resnet50"], bench._BenchResult)
        bench._clear_state()
        assert bench._load_state() == {}
        bench._clear_state()  # idempotent

    def test_corrupt_state_is_ignored(self, tmp_path, monkeypatch):
        path = tmp_path / "BENCH_STATE.json"
        path.write_text("{not json")
        monkeypatch.setattr(bench, "_STATE_PATH", str(path))
        assert bench._load_state() == {}


class TestBaseline:
    def test_diff_math_and_filters(self):
        baseline = {"workloads": {
            "a": {"value": 100.0, "unit": "images/s"},
            "b": {"value": 10.0, "unit": "ratio"},
            "c": {"value": 50.0, "unit": "images/s"},
            "z": {"value": 0.0, "unit": "x"},
        }}
        results = {
            "a": bench._BenchResult(metric="a", value=110.0,
                                    unit="images/s", detail={}),
            "b": bench._BenchResult(metric="b", value=10.0,
                                    unit="records/s", detail={}),  # unit drift
            "c": bench._BenchResult(metric="c", value=None,
                                    unit="images/s", detail={}),   # no value
            "z": bench._BenchResult(metric="z", value=3.0,
                                    unit="x", detail={}),          # zero base
            "d": bench._BenchResult(metric="d", value=1.0,
                                    unit="x", detail={}),          # no base
        }
        assert bench._baseline_diff(results, baseline) == {"a": 10.0}

    def test_diff_is_null_without_reference_numbers(self):
        results = {"a": bench._BenchResult(metric="a", value=1.0,
                                           unit="x", detail={})}
        assert bench._baseline_diff(results, {}) is None
        assert bench._baseline_diff(results, {"published": {}}) is None

    def test_write_then_diff_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BENCH_BASELINE",
                           str(tmp_path / "BASELINE.json"))
        results = {"a": bench._BenchResult(metric="a", value=200.0,
                                           unit="x", detail={})}
        doc = {"workloads": {"a": {"value": 160.0, "unit": "x"}}}
        (tmp_path / "BASELINE.json").write_text(__import__("json").dumps(doc))
        assert bench._baseline_diff(results) == {"a": 25.0}


class TestRecordSchema:
    def test_valid_record_is_clean(self):
        rec = bench._BenchResult(metric="x_cpu_ratio", value=1.5,
                                 unit="ratio", mfu=None, detail={})
        assert bench._validate_record(rec) == []
        rec["value"] = None  # null value is legal (failed sub-probe)
        assert bench._validate_record(rec) == []

    def test_junk_records_are_named(self):
        assert bench._validate_record("nope") == ["record must be a dict"]
        problems = bench._validate_record({"metric": "", "unit": 3,
                                           "value": "fast", "detail": []})
        assert len(problems) == 4

    def test_note_partial_stashes_best_so_far(self):
        saved = dict(bench._PARTIAL), dict(bench._PARTIAL["detail"])
        try:
            bench._PARTIAL.clear()
            bench._PARTIAL["detail"] = {}
            bench._note_partial(warmup_done=True)
            assert "metric" not in bench._PARTIAL
            bench._note_partial(metric="m", value=7.0, unit="u", rate=7.0)
            assert bench._PARTIAL["metric"] == "m"
            assert bench._PARTIAL["value"] == 7.0
            assert bench._PARTIAL["detail"] == {"warmup_done": True,
                                                "rate": 7.0}
        finally:
            bench._PARTIAL.clear()
            bench._PARTIAL.update(saved[0])
            bench._PARTIAL["detail"] = saved[1]


def _gate_baseline():
    return {"workloads": {
        "ncf": {"value": 9.4e6, "unit": "samples/s", "mfu": 0.0075,
                "detail": {"hbm_roofline_fraction": 0.5,
                           "embedding_fused_speedup": 1.3}},
        "widedeep": {"value": 2.9e6, "unit": "samples/s", "mfu": 0.0001,
                     "detail": {"hbm_roofline_fraction": 0.4}},
    }}


def _real_record(name, mfu, frac):
    return bench._BenchResult(
        metric=f"{name}_train_samples_per_sec", value=1e6,
        unit="samples/s", mfu=mfu,
        detail={"hbm_roofline_fraction": frac})


class TestRooflineGate:
    def test_healthy_round_passes(self):
        results = {"ncf": _real_record("ncf", 0.0074, 0.49),
                   "widedeep": _real_record("widedeep", 0.0001, 0.41)}
        assert bench._gate_check(results, _gate_baseline()) == []
        assert bench._apply_gate(results, baseline=_gate_baseline()) == []
        assert results["ncf"]["detail"]["roofline_gate_ok"] is True

    def test_synthetic_regression_fails_with_explicit_fields(self):
        """A regressed round — roofline fraction halves while samples/s
        holds — must fail the gate AND stamp the failure into the record,
        not just the exit code."""
        results = {"ncf": _real_record("ncf", 0.003, 0.2),
                   "widedeep": _real_record("widedeep", 0.00005, 0.1)}
        failures = bench._apply_gate(results, baseline=_gate_baseline())
        kinds = {f.split(":")[0] for f in failures}
        # widedeep.mfu is exempt: its 0.0001 baseline is below the noise
        # floor (gather-bound steps are judged by the hbm fraction)
        assert kinds == {"ncf.hbm_roofline_fraction", "ncf.mfu",
                         "widedeep.hbm_roofline_fraction"}
        assert results["ncf"]["detail"]["roofline_gate_ok"] is False
        assert results["ncf"]["detail"]["roofline_gate_failures"]
        assert results["widedeep"]["detail"]["roofline_gate_ok"] is False

    def test_tolerance_is_relative(self):
        results = {"ncf": _real_record("ncf", 0.0075, 0.46)}  # -8% ok
        assert bench._gate_check(results, _gate_baseline()) == []
        results = {"ncf": _real_record("ncf", 0.0075, 0.44)}  # -12% not
        assert len(bench._gate_check(results, _gate_baseline())) == 1

    def test_ratio_failed_and_unbaselined_records_are_exempt(self):
        ratio = bench._BenchResult(metric="ncf_cpu_ratio", value=2.5,
                                   unit="ratio", mfu=None,
                                   detail={"mode": "cpu_ratio"})
        failed = bench._BenchResult(metric="widedeep_failed", value=None,
                                    unit="", mfu=None,
                                    detail={"error": "boom"})
        fresh = _real_record("widedeep_sharded", 0.001, 0.01)  # no base
        results = {"ncf": ratio, "widedeep": failed,
                   "widedeep_sharded": fresh}
        assert bench._gate_check(results, _gate_baseline()) == []
        bench._apply_gate(results, baseline=_gate_baseline())
        assert "roofline_gate_ok" not in ratio["detail"]

    def test_no_gate_skips_and_stamps(self):
        results = {"ncf": _real_record("ncf", 0.001, 0.01)}  # regressed
        assert bench._apply_gate(results, no_gate=True,
                                 baseline=_gate_baseline()) == []
        assert results["ncf"]["detail"]["roofline_gate"] == "skipped"
        assert "roofline_gate_ok" not in results["ncf"]["detail"]

    def test_write_baseline_records_mfu_and_fused_speedup(
            self, tmp_path, monkeypatch):
        """--write-baseline must persist everything the gate and the
        fused-A/B diff later compare: mfu at the top level, the roofline
        fraction and embedding_fused_speedup in the tracked detail."""
        monkeypatch.setattr(bench, "__file__",
                            str(tmp_path / "bench.py"))
        results = {"ncf": bench._BenchResult(
            metric="ncf_train_samples_per_sec", value=9.4e6,
            unit="samples/s", mfu=0.0075,
            detail={"hbm_roofline_fraction": 0.5,
                    "embedding_fused_speedup": 1.3})}
        bench._write_baseline(results)
        doc = __import__("json").loads(
            (tmp_path / "BASELINE.json").read_text())
        entry = doc["workloads"]["ncf"]
        assert entry["mfu"] == 0.0075
        assert entry["detail"]["hbm_roofline_fraction"] == 0.5
        assert entry["detail"]["embedding_fused_speedup"] == 1.3
        # and the round that just wrote it gates green against it
        assert bench._gate_check(results, doc) == []

    def test_regressed_resumed_round_exits_nonzero(self, tmp_path):
        """End-to-end: a real bench.py invocation whose (resumed) round
        regressed vs BASELINE.json must exit nonzero with the gate
        verdict in the compact line; --no-gate is the escape hatch."""
        import json as _json
        import subprocess
        import sys as _sys
        baseline = tmp_path / "BASELINE.json"
        baseline.write_text(_json.dumps(_gate_baseline()))
        state = {"results": {"ncf": dict(_real_record("ncf", 0.003, 0.2))}}
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_BASELINE=str(baseline))
        saved = None
        if os.path.exists(bench._STATE_PATH):
            saved = open(bench._STATE_PATH).read()
        # the subprocess rewrites the repo's BENCH_DETAIL.json — a tracked
        # bench artifact — so park the original for the finally block
        detail_path = os.path.join(os.path.dirname(_BENCH_PATH),
                                   "BENCH_DETAIL.json")
        saved_detail = None
        if os.path.exists(detail_path):
            saved_detail = open(detail_path).read()
        try:
            with open(bench._STATE_PATH, "w") as f:
                _json.dump(state, f)
            proc = subprocess.run(
                [_sys.executable, _BENCH_PATH, "ncf", "--resume"],
                capture_output=True, text=True, timeout=240, env=env)
            assert proc.returncode == 3, proc.stdout + proc.stderr
            final = _json.loads(proc.stdout.strip().splitlines()[-1])
            row = final["detail"]["workloads"]["ncf"]
            assert row["roofline_gate_ok"] is False

            with open(bench._STATE_PATH, "w") as f:
                _json.dump(state, f)
            proc = subprocess.run(
                [_sys.executable, _BENCH_PATH, "ncf", "--resume",
                 "--no-gate"],
                capture_output=True, text=True, timeout=240, env=env)
            assert proc.returncode == 0, proc.stdout + proc.stderr
        finally:
            if saved is not None:
                open(bench._STATE_PATH, "w").write(saved)
            else:
                bench._clear_state()
            if saved_detail is not None:
                open(detail_path, "w").write(saved_detail)
            elif os.path.exists(detail_path):
                os.remove(detail_path)


class TestArgs:
    def test_defaults(self):
        args = bench._parse_args([])
        assert args["which"] == "all" and args["one"] is None
        assert not args["ratio"] and not args["resume"]
        assert args["shard"] is None and args["budget"] is None
        assert not args["no_gate"]

    def test_flags_and_aliases(self):
        args = bench._parse_args(["--one", "input_pipeline",
                                  "--budget", "120.5"])
        assert args["one"] == "pipeline"  # alias resolved
        assert args["budget"] == 120.5
        args = bench._parse_args(["--ratio", "--resume", "--full",
                                  "--write-baseline", "--shard", "1/4",
                                  "--no-gate", "eval"])
        assert args["ratio"] and args["resume"] and args["full"]
        assert args["write_baseline"]
        assert args["no_gate"]
        assert args["shard"] == (1, 4)
        assert args["which"] == "eval"

    def test_bad_input_rejected(self):
        with pytest.raises(SystemExit):
            bench._parse_args(["--wat"])
        with pytest.raises(SystemExit):
            bench._parse_args(["--shard", "4/4"])
