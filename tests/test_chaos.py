"""Chaos layer: fault injection exercising every recovery path the
platform claims — elastic step retry, checksum-manifest fallback past a
torn newest snapshot, snapshot retention, SIGTERM preemption with a
resumable marker, transient remote-IO retries, worker-pool self-healing,
producer-thread failures, and serving decode/writeback faults.

The capstone is the soak: a training run with faults armed at EVERY
registered training site must finish and produce final params
BIT-IDENTICAL to the fault-free run — recovery that changes the math is
not recovery."""
import json
import os
import signal
import uuid

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults, file_io
from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.common.triggers import SeveralIteration
from analytics_zoo_tpu.estimator import (CheckpointCorruptError, Estimator,
                                         PreemptedError)
from analytics_zoo_tpu.feature import FeatureSet, Lambda
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Dense


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()
    for key in ("faults.plan", "data.task_retries", "data.worker_respawns",
                "failure.io_backoff_s", "checkpoint.keep"):
        global_config().unset(key)


def _estimator(lr=0.05):
    model = Sequential([Dense(16, name="d1"), Dense(2, name="d2")])
    return Estimator(
        model=model,
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.SGD(lr))


def _data(n=256, d=6, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(n, d).astype(np.float32),
            rs.randint(0, 2, n).astype(np.float32))


def _fs(n=256, shuffle=True):
    x, y = _data(n)
    return FeatureSet.from_ndarrays(x, y, shuffle=shuffle, seed=7)


def _params_equal(pa, pb):
    import jax
    la, lb = jax.tree_util.tree_leaves(pa), jax.tree_util.tree_leaves(pb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestSnapshotCandidates:
    """Satellite: `_latest_snapshot` filtering is a real suffix check and
    tolerates foreign dirs."""

    def test_skips_writing_staging_and_non_integer_suffixes(self, ctx,
                                                            tmp_path):
        for name in ("snapshot-2", "snapshot-10", "snapshot-7.writing",
                     "snapshot-abc", "snapshot-", "notes", "snapshot-3x"):
            (tmp_path / name).mkdir()
        est = _estimator()
        est.set_checkpoint(str(tmp_path))
        cands = est._snapshot_candidates()
        assert [s for s, _ in cands] == [2, 10]
        assert est._latest_snapshot().endswith("snapshot-10")

    def test_substring_writing_name_not_hidden(self, ctx, tmp_path):
        # the old `".writing" not in d` substring test would hide this
        # perfectly valid published snapshot
        weird = tmp_path / "ck.writing.dir"
        (weird / "snapshot-4").mkdir(parents=True)
        est = _estimator()
        est.set_checkpoint(str(weird))
        assert est._latest_snapshot().endswith("snapshot-4")

    def test_empty_or_missing_dir(self, ctx, tmp_path):
        est = _estimator()
        est.set_checkpoint(str(tmp_path / "nope"))
        assert est._latest_snapshot() is None


class TestChecksumIntegrity:
    def _trained(self, tmp_path, epochs=2):
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(1))
        est.train(_fs(), batch_size=64, epochs=epochs)
        est._ckpt_writer.wait()
        return est

    def test_manifest_written_and_verified(self, ctx, tmp_path):
        est = self._trained(tmp_path)
        snap = est._latest_snapshot()
        manifest = os.path.join(snap, "zoo_manifest.json")
        assert os.path.exists(manifest)
        files = json.load(open(manifest))["files"]
        assert files  # every data file checksummed
        est2 = _estimator()
        est2.load_checkpoint(snap)  # verifies clean
        assert est2.global_step == est.global_step

    def test_torn_snapshot_rejected_and_fallen_past(self, ctx, tmp_path):
        est = self._trained(tmp_path)
        newest = est._latest_snapshot()
        faults.tear_snapshot(newest)
        est2 = _estimator()
        with pytest.raises(CheckpointCorruptError, match="checksum"):
            est2.load_checkpoint(newest)
        # transparent fallback: restore lands on the next-older snapshot
        est3 = _estimator()
        est3.set_checkpoint(str(tmp_path))
        restored = est3._restore_latest_valid()
        assert restored is not None and restored != newest
        assert est3.global_step == est.global_step - 1

    def test_elastic_retry_falls_back_past_torn_newest(self, ctx, tmp_path):
        """ckpt.corrupt tears the newest published snapshot, the next step
        fails — training must fall back one snapshot and still finish."""
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(1))
        est.train(_fs(), batch_size=64, epochs=1)  # 4 steps, snapshots 1-4
        faults.arm("ckpt.corrupt", at=1, budget=1)   # tears snapshot-5
        faults.arm("train.step", at=2, budget=1)     # fails step 6 dispatch
        est.train(_fs(), batch_size=64, epochs=3)
        assert faults.fire_count("ckpt.corrupt") == 1
        assert faults.fire_count("train.step") == 1
        assert est.epoch == 4 and est.global_step == 12

    def test_retention_keeps_newest_k(self, ctx, tmp_path):
        global_config().set("checkpoint.keep", 2)
        est = self._trained(tmp_path, epochs=2)  # 8 snapshot writes
        names = sorted(os.listdir(tmp_path))
        assert names == ["snapshot-7", "snapshot-8"]

    def test_verify_can_be_disabled(self, ctx, tmp_path):
        est = self._trained(tmp_path)
        snap = est._latest_snapshot()
        manifest = os.path.join(snap, "zoo_manifest.json")
        data = json.load(open(manifest))
        next(iter(data["files"].values()))[1] ^= 1  # poison a checksum
        json.dump(data, open(manifest, "w"))
        global_config().set("checkpoint.verify", False)
        try:
            _estimator().load_checkpoint(snap)  # tolerated when disabled
        finally:
            global_config().unset("checkpoint.verify")
        with pytest.raises(CheckpointCorruptError):
            _estimator().load_checkpoint(snap)


class TestPreemption:
    def test_preempt_site_writes_snapshot_and_marker(self, ctx, tmp_path):
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(100))  # no
        # triggered snapshots: the final one must come from preemption
        faults.arm("train.preempt", at=5)
        with pytest.raises(PreemptedError) as ei:
            est.train(_fs(), batch_size=64, epochs=3)
        assert ei.value.snapshot.endswith("snapshot-5")
        marker = Estimator.preemption_marker(str(tmp_path))
        assert marker == {"global_step": 5, "epoch": 2,
                          "snapshot": "snapshot-5", "resumable": True}

    def test_resume_after_preemption_bit_identical(self, ctx, tmp_path):
        est_a = _estimator()
        est_a.train(_fs(), batch_size=64, epochs=3)

        est_b = _estimator()
        est_b.set_checkpoint(str(tmp_path), SeveralIteration(100))
        faults.arm("train.preempt", at=5)
        with pytest.raises(PreemptedError):
            est_b.train(_fs(), batch_size=64, epochs=3)
        faults.reset()

        est_c = _estimator()
        est_c.set_checkpoint(str(tmp_path))
        est_c.load_checkpoint(est_c._latest_snapshot())
        assert est_c.global_step == 5
        est_c.train(_fs(), batch_size=64, epochs=3)
        # marker consumed by the resumed run
        assert Estimator.preemption_marker(str(tmp_path)) is None
        _params_equal(est_a.get_params(), est_c.get_params())

    def test_real_sigterm_is_a_preemption(self, ctx, tmp_path):
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(100))
        est.train(_fs(), batch_size=64, epochs=1)  # build the step
        real_step = est._train_step
        seen = {"n": 0}

        def step_then_sigterm(*args):
            seen["n"] += 1
            if seen["n"] == 2:
                os.kill(os.getpid(), signal.SIGTERM)
            return real_step(*args)

        est._train_step = step_then_sigterm
        with pytest.raises(PreemptedError, match="preempted"):
            est.train(_fs(), batch_size=64, epochs=3)
        assert Estimator.preemption_marker(str(tmp_path)) is not None
        # the handler was restored: SIGTERM is no longer swallowed
        assert signal.getsignal(signal.SIGTERM) != est._on_sigterm


class TestElasticityExhaustion:
    """Satellite: after `failure.retry_times` consecutive failing steps the
    estimator restores the newest valid checkpoint, THEN re-raises — the
    params stay a usable, known-good state."""

    def test_exhaustion_restores_then_reraises(self, ctx, tmp_path):
        x, y = _data(128)
        fs = FeatureSet.from_ndarrays(x, y)
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(1))
        est.train(fs, batch_size=64, epochs=1)  # 2 steps, snapshots 1-2
        est._ckpt_writer.wait()
        snap_step = est.global_step

        calls = {"n": 0}

        def always_fails(*args):
            calls["n"] += 1
            raise RuntimeError("permanent failure")

        est._train_step = always_fails
        budget = int(global_config().get("failure.retry_times"))
        with pytest.raises(RuntimeError, match="permanent failure"):
            est.train(fs, batch_size=64, epochs=2)
        assert calls["n"] == budget + 1
        # restored to the newest valid snapshot, not left mid-failure
        assert est.global_step == snap_step
        # ...and usable: a fresh compiled step evaluates finitely
        est._train_step = None
        scores = est.evaluate(fs, batch_size=64)
        assert np.isfinite(list(scores.values())).all()

    def test_exhaustion_skips_torn_newest_on_final_restore(self, ctx,
                                                           tmp_path):
        x, y = _data(128)
        fs = FeatureSet.from_ndarrays(x, y)
        est = _estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(1))
        est.train(fs, batch_size=64, epochs=1)
        est._ckpt_writer.wait()
        faults.tear_snapshot(est._latest_snapshot())
        est._train_step = lambda *a: (_ for _ in ()).throw(
            RuntimeError("permanent failure"))
        with pytest.raises(RuntimeError, match="permanent failure"):
            est.train(fs, batch_size=64, epochs=2)
        assert est.global_step == 1  # fell back past torn snapshot-2


class TestRemoteIORetries:
    def _uri(self):
        return f"memory://zoo-chaos-{uuid.uuid4().hex[:10]}"

    def test_transient_failures_absorbed(self, ctx):
        global_config().set("failure.io_backoff_s", 0.001)
        root = self._uri()
        file_io.makedirs(root)
        p = file_io.join(root, "f.txt")
        with file_io.fopen(p, "w") as f:
            f.write("payload")
        # two consecutive injected faults < failure.io_retries (3)
        faults.arm("io.remote", p=1.0, budget=2)
        with file_io.fopen(p) as f:
            assert f.read() == "payload"
        assert faults.fire_count("io.remote") == 2

    def test_retry_budget_exhausts_to_caller(self, ctx):
        global_config().set("failure.io_backoff_s", 0.001)
        root = self._uri()
        file_io.makedirs(root)
        faults.arm("io.remote", p=1.0, budget=50)
        with pytest.raises(faults.FaultInjected):
            file_io.listdir(root)
        # 1 attempt + failure.io_retries retries
        retries = int(global_config().get("failure.io_retries"))
        assert faults.fire_count("io.remote") == retries + 1

    def test_deterministic_errors_not_retried(self, ctx):
        from analytics_zoo_tpu.common.file_io import _retryable
        assert not _retryable(FileNotFoundError("x"))
        assert not _retryable(FileExistsError("x"))
        assert not _retryable(PermissionError("x"))
        assert _retryable(ConnectionError("x"))
        assert _retryable(TimeoutError("x"))
        assert _retryable(faults.FaultInjected("io.remote", 1))
        assert not _retryable(ValueError("x"))

    def test_local_paths_bypass_injection(self, ctx, tmp_path):
        faults.arm("io.remote", p=1.0, budget=100)
        p = tmp_path / "local.txt"
        p.write_text("ok")
        with file_io.fopen(str(p)) as f:  # local: no remote site in path
            assert f.read() == "ok"
        assert faults.fire_count("io.remote") == 0


class TestFeedProduceFault:
    def test_producer_fault_surfaces_on_consumer(self, ctx):
        from analytics_zoo_tpu.feature.device_feed import DeviceFeed
        faults.arm("feed.produce", at=3)
        batches = (np.full((8, 2), i, np.float32) for i in range(6))
        got = []
        with pytest.raises(faults.FaultInjected, match="feed.produce"):
            with DeviceFeed(batches, ctx.mesh) as feed:
                for b in feed:
                    got.append(np.asarray(b))
        assert len(got) == 2  # batches before the fault arrived intact

    def test_estimator_recovers_from_producer_fault(self, ctx, tmp_path):
        est_a = _estimator()
        est_a.train(_fs(), batch_size=64, epochs=2)

        est_b = _estimator()
        est_b.set_checkpoint(str(tmp_path), SeveralIteration(1))
        faults.arm("feed.produce", at=6, budget=1)
        est_b.train(_fs(), batch_size=64, epochs=2)
        assert faults.fire_count("feed.produce") == 1
        assert est_b.epoch == 3 and est_b.global_step == 8
        _params_equal(est_a.get_params(), est_b.get_params())


class TestServingChaos:
    def _serving(self, tmp_path, batch_size=4):
        import jax
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True), {})
        src = f"dir://{tmp_path}"
        cfg = ServingConfig(data_src=src, image_shape=(4,),
                            batch_size=batch_size, batch_wait_ms=5)
        return ClusterServing(cfg, model=im), src

    def test_decode_fault_errors_one_record_not_the_loop(self, ctx,
                                                         tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        faults.arm("serving.decode", at=2, budget=1)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i in range(4):
            inq.enqueue_tensor(f"r{i}", np.full(4, float(i)))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        results = [outq.query(f"r{i}", timeout_s=5.0) for i in range(4)]
        assert all(r is not None for r in results)
        errors = [r for r in results if "error" in r]
        values = [r for r in results if "value" in r]
        assert len(errors) == 1 and len(values) == 3
        assert "injected fault" in errors[0]["error"]

    def test_writeback_fault_errors_batch_keeps_draining(self, ctx,
                                                         tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        faults.arm("serving.writeback", at=1, budget=1)
        serving.start()
        try:
            inq, outq = InputQueue(src), OutputQueue(src)
            for i in range(4):
                inq.enqueue_tensor(f"a{i}", np.full(4, float(i)))
            first = [outq.query(f"a{i}", timeout_s=10.0) for i in range(4)]
            # the faulted batch's records got ERROR results (not dropped:
            # a client would otherwise poll to its timeout)
            assert all(r is not None and "error" in r for r in first)
            # ...and the loop kept going: the next batch serves normally
            for i in range(4):
                inq.enqueue_tensor(f"b{i}", np.full(4, float(i)))
            second = [outq.query(f"b{i}", timeout_s=10.0) for i in range(4)]
            assert all(r is not None and "value" in r for r in second)
            serving.check_health()
        finally:
            serving.stop()
        assert faults.fire_count("serving.writeback") == 1

    def test_claim_fault_absorbed_and_retried(self, ctx, tmp_path):
        """A transient claim failure (flaky backend) is retried inside the
        loop — no request lost, no loop death."""
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        faults.arm("serving.claim", at=1, budget=1)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i in range(4):
            inq.enqueue_tensor(f"r{i}", np.full(4, float(i)))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        assert served >= 4
        assert all(outq.query(f"r{i}", timeout_s=5.0) is not None
                   for i in range(4))
        assert faults.fire_count("serving.claim") == 1
        assert serving.counters["claim_faults"] == 1

    def test_claim_fault_streak_surfaces_dead_backend(self, ctx, tmp_path):
        """claim_retries consecutive failures = the backend is dead, not
        flaky — the loop must surface it, not spin silently forever."""
        serving, src = self._serving(tmp_path)
        serving.config.claim_retries = 3
        faults.arm("serving.claim", p=1.0, budget=100)
        # the failure STREAK survives across claim windows: however the
        # batch-wait slices the retries, the 4th consecutive one surfaces
        with pytest.raises(faults.FaultInjected):
            for _ in range(10):
                serving.serve_once()
        assert serving.counters["claim_faults"] == 4  # retries + surface

    def test_predict_fault_errors_batch_keeps_serving(self, ctx, tmp_path):
        from analytics_zoo_tpu.serving import InputQueue, OutputQueue
        serving, src = self._serving(tmp_path)
        faults.arm("serving.predict", at=1, budget=1)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i in range(4):
            inq.enqueue_tensor(f"a{i}", np.full(4, float(i)))
        serving.serve_once()
        first = [outq.query(f"a{i}", timeout_s=5.0) for i in range(4)]
        assert all(r is not None and "injected fault" in r["error"]
                   for r in first)
        for i in range(4):
            inq.enqueue_tensor(f"b{i}", np.full(4, float(i)))
        served = 0
        for _ in range(10):
            served += serving.serve_once()
            if served >= 4:
                break
        second = [outq.query(f"b{i}", timeout_s=5.0) for i in range(4)]
        assert all(r is not None and "value" in r for r in second)
        assert faults.fire_count("serving.predict") == 1

    def test_reload_fault_rolls_back_and_serving_continues(self, ctx,
                                                           tmp_path):
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import (InputQueue, ModelReloadError,
                                               OutputQueue)
        serving, src = self._serving(tmp_path)
        old = serving.model
        replacement = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
        faults.arm("serving.reload", at=1, budget=1)
        with pytest.raises(ModelReloadError):
            serving.reload_model(model=replacement)
        assert serving.model is old  # rolled back
        assert serving.counters["reload_failures"] == 1
        assert faults.fire_count("serving.reload") == 1
        # the fault budget is spent: the SAME reload now goes through,
        # and traffic flows across the whole episode
        assert serving.reload_model(model=replacement) is replacement
        InputQueue(src).enqueue_tensor("r0", np.full(4, 2.0))
        serving.serve_once()
        res = OutputQueue(src).query("r0", timeout_s=5.0)
        assert res["value"] == [pytest.approx(2.0)]  # the NEW (mean) model


def _soak_record(r):
    # deterministic shape-changing transform, applied in forked workers
    return np.concatenate([r * 1.5, r[:1] + 0.25]).astype(np.float32)


class TestChaosSoak:
    """The capstone: every registered training site armed, one run."""

    N, BATCH, EPOCHS = 512, 64, 3  # 8 steps/epoch, 24 total

    def _run(self, ckpt_root, chaos: bool):
        faults.reset()
        cfg = global_config()
        cfg.set("data.task_retries", 1)       # absorbs worker.task
        cfg.set("failure.io_backoff_s", 0.001)
        if chaos:
            faults.arm("worker.kill", at=2, budget=1)   # one child SIGKILL
            faults.arm("worker.task", at=3, budget=1)   # one task fault
            faults.arm("ckpt.write", at=3, budget=1)    # background write
            # dies before publish (previous snapshot stays newest intact)
            faults.arm("ckpt.corrupt", at=5, budget=1)  # tear a published
            # snapshot (restore falls back past it if it is newest)
            faults.arm("train.step", at=6, budget=1)    # chip/tunnel step
            # failure — the elastic retry loop's bread and butter
            faults.arm("io.remote", p=0.05, budget=3, seed=13)  # flaky store
            faults.arm("feed.produce", at=18, budget=1)  # data plane dies
            faults.arm("train.preempt", at=16, budget=1)  # SIGTERM notice
        x, y = _data(self.N)
        base = FeatureSet.from_ndarrays(x, y, shuffle=True, seed=7)
        fs = base.transform(Lambda(_soak_record), num_workers=2, mode="mp")
        est = _estimator()
        est.set_checkpoint(ckpt_root, SeveralIteration(1))
        try:
            est.train(fs, batch_size=self.BATCH, epochs=self.EPOCHS)
        except PreemptedError:
            assert Estimator.preemption_marker(ckpt_root) is not None
            est.load_checkpoint(est._latest_snapshot())
            est.train(fs, batch_size=self.BATCH, epochs=self.EPOCHS)
        est._ckpt_writer.wait()
        return est

    def test_soak_bit_identical_to_fault_free(self, ctx, tmp_path):
        clean = self._run(str(tmp_path / "clean"), chaos=False)
        # chaos checkpoints live on a (fake) OBJECT STORE: remote staging
        # uploads, no atomic rename, flaky ops — the production worst case
        remote_root = f"memory://zoo-soak-{uuid.uuid4().hex[:10]}/ck"
        chaotic = self._run(remote_root, chaos=True)

        # every armed site actually fired — a soak that injected nothing
        # proves nothing
        for site in ("worker.kill", "worker.task", "ckpt.write",
                     "ckpt.corrupt", "train.step", "train.preempt"):
            assert faults.fire_count(site) >= 1, f"{site} never fired"
        assert chaotic.epoch == self.EPOCHS + 1
        assert chaotic.global_step == clean.global_step

        _params_equal(clean.get_params(), chaotic.get_params())


class TestServingOverloadSoak:
    """Serving capstone: overload + chaos on every serving fault site
    across two servers sharing one spool. The invariant under test is the
    SLO layer's contract — **every enqueued request receives exactly one
    terminal result (value or error); none hang to client timeout** — and
    the drain/reload paths leave no orphan threads, claim state, or
    unanswered uris behind."""

    N = 96

    def _model(self):
        from analytics_zoo_tpu.inference import InferenceModel
        return InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).sum(1, keepdims=True), {})

    def _spy_terminal_posts(self, servers):
        """Wrap each server's queue.put_result to record every terminal
        post (server results AND queue-level shed errors ride through the
        same method)."""
        import threading as _threading
        posts = []
        lock = _threading.Lock()
        for s in servers:
            orig = s.queue.put_result

            def wrapped(uri, value, _orig=orig):
                with lock:
                    posts.append(uri)
                return _orig(uri, value)

            s.queue.put_result = wrapped
        return posts

    def _arm_all_serving_sites(self):
        faults.arm("serving.claim", p=0.1, budget=4, seed=3)
        faults.arm("serving.decode", at=7, budget=1)
        faults.arm("serving.predict", at=3, budget=1)
        faults.arm("serving.writeback", at=5, budget=1)

    def _enqueue_overload(self, inq):
        # pre-loaded burst BEYOND max_pending → the first claims must shed
        # the oldest with explicit error results; every 10th request is
        # born with a 1ms budget → guaranteed deadline errors for the
        # survivors of the shed
        rs = np.random.RandomState(0)
        for i in range(self.N):
            inq.enqueue_tensor(f"r{i}", rs.rand(4).astype(np.float32),
                               deadline_ms=1 if i % 10 == 0 else None)

    def _assert_soak_invariants(self, results, posts, servers):
        expect = {f"r{i}" for i in range(self.N)}
        unanswered = expect - set(results)
        assert not unanswered, f"requests hung to timeout: {unanswered}"
        # exactly one terminal post per uri across both servers + sheds
        assert len(posts) == len(set(posts)), "a uri got TWO terminal posts"
        assert set(posts) == expect
        # the soak actually exercised overload + deadlines + chaos
        shed = sum(s.counters["shed"] for s in servers)
        expired = sum(s.counters["expired"] for s in servers)
        assert shed >= 1, "overload never shed"
        assert expired >= 1, "no deadline ever expired"
        for site in ("serving.claim", "serving.decode", "serving.predict",
                     "serving.writeback"):
            assert faults.fire_count(site) >= 1, f"{site} never fired"
        values = sum(1 for r in results.values() if "value" in r)
        errors = sum(1 for r in results.values() if "error" in r)
        assert values + errors == self.N
        assert values >= 1  # the chaos did not take ALL traffic down

    def test_file_queue_multiserver_soak(self, ctx, tmp_path):
        import threading as _threading
        import time as _time

        from analytics_zoo_tpu.common import file_io
        from analytics_zoo_tpu.serving import (ClusterServing, FileQueue,
                                               InputQueue, OutputQueue,
                                               ServingConfig)
        root = str(tmp_path / "spool")
        FileQueue(root)  # create the spool dirs
        src = f"dir://{root}"
        # only THESE servers' threads are the drain contract (earlier
        # tests' decode pools die on GC, asynchronously)
        pre = set(_threading.enumerate())
        servers = []
        for tag in ("a", "b"):
            cfg = ServingConfig(
                data_src=src, image_shape=(4,), batch_size=4,
                batch_wait_ms=5, max_pending=40,
                health_path=str(tmp_path / f"health_{tag}.json"),
                health_interval_s=0.05)
            servers.append(ClusterServing(cfg, model=self._model()))
        posts = self._spy_terminal_posts(servers)
        self._arm_all_serving_sites()
        self._enqueue_overload(InputQueue(src))
        for s in servers:
            s.start()
        outq = OutputQueue(src)
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            if len(outq.dequeue()) >= self.N:
                break
            _time.sleep(0.05)
        # mid-soak reload on server A exercises the swap under live chaos
        from analytics_zoo_tpu.inference import InferenceModel
        servers[0].reload_model(model=InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True),
            {}))
        for s in servers:
            s.drain(timeout_s=30.0)
        self._assert_soak_invariants(outq.dequeue(), posts, servers)
        # drain left nothing behind: no pending spool entries, no claim
        # state, no serve-loop or decode-pool threads, terminal health
        assert servers[0].queue.pending_count() == 0
        assert file_io.listdir(file_io.join(root, "claimed")) == []
        leaked = [t.name for t in _threading.enumerate()
                  if t not in pre and t.name.startswith("zoo-serving")]
        assert not leaked
        for tag, s in zip(("a", "b"), servers):
            assert s._in_flight == 0
            health = json.loads(
                (tmp_path / f"health_{tag}.json").read_text())
            assert health["state"] == "drained"
        assert sum(s.counters["reloads"] for s in servers) == 1

    def test_redis_stub_multiserver_soak(self, ctx, tmp_path, monkeypatch):
        import sys as _sys
        import threading as _threading
        import time as _time
        import types as _types

        from tests.test_redis_serving import FakeRedis

        # the real broker pops/acks atomically across connections; the
        # in-memory fake needs a lock to model that under two serve loops
        lock = _threading.Lock()
        for meth in ("xreadgroup", "xack", "xautoclaim"):
            orig = getattr(FakeRedis, meth)

            def locked(self, *a, _orig=orig, **k):
                with lock:
                    return _orig(self, *a, **k)

            monkeypatch.setattr(FakeRedis, meth, locked)
        fake_mod = _types.ModuleType("redis")
        fake_mod.StrictRedis = FakeRedis
        monkeypatch.setitem(_sys.modules, "redis", fake_mod)
        FakeRedis.instances.clear()

        from analytics_zoo_tpu.serving import (ClusterServing, InputQueue,
                                               OutputQueue, ServingConfig)
        src = "soakredis:6379"
        pre = set(_threading.enumerate())
        servers = []
        for tag in ("a", "b"):
            cfg = ServingConfig(data_src=src, image_shape=(4,),
                                batch_size=4, batch_wait_ms=5,
                                max_pending=40)
            servers.append(ClusterServing(cfg, model=self._model()))
        posts = self._spy_terminal_posts(servers)
        self._arm_all_serving_sites()
        self._enqueue_overload(InputQueue(src))
        for s in servers:
            s.start()
        outq = OutputQueue(src)
        results = {}
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline and len(results) < self.N:
            for i in range(self.N):
                uri = f"r{i}"
                if uri not in results:
                    res = outq.query(uri)
                    if res is not None:
                        results[uri] = res
            _time.sleep(0.05)
        for s in servers:
            s.drain(timeout_s=30.0)
        self._assert_soak_invariants(results, posts, servers)
        assert servers[0].queue.pending_count() == 0
        # ack bookkeeping is complete: nothing stranded in the PEL
        broker = FakeRedis.instances[("soakredis", 6379, 0)]
        assert broker.groups[("image_stream", "serving")]["pel"] == {}
        leaked = [t.name for t in _threading.enumerate()
                  if t not in pre and t.name.startswith("zoo-serving")]
        assert not leaked
