"""Tier-1 collection-time guard: the estimator eval/predict dispatch loops
AND the data-plane hot paths (``FeatureSet._gather``, the lazy-transform
iterator cores, ``masked_eval_batches``, the DeviceFeed producer) must stay
free of per-batch host↔device syncs, per-record Python, and per-batch mask
re-allocation (``scripts/check_hot_path_syncs.py``).

The lint runs at IMPORT (= pytest collection) so a reintroduced
``float(...)``/``np.asarray(...)`` inside a dispatch loop — or a
``np.arange`` rebuilt per eval batch, or a per-record loop inside the
batch gather — fails the suite even if no behavioral test notices the
restored stall."""
import importlib.util
import os

_script = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "check_hot_path_syncs.py")
_spec = importlib.util.spec_from_file_location("check_hot_path_syncs",
                                               _script)
_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_lint)

_violations = _lint.check()
if _violations:  # collection-time failure, with the offending lines
    raise AssertionError(
        "hot-path regression reintroduced: "
        + "; ".join(f"{os.path.basename(f)}:{fn}:{line} {what}"
                    for f, fn, line, what in _violations))


def test_hot_paths_have_no_per_batch_syncs():
    assert _lint.check() == []


def test_lint_covers_data_plane_files():
    """The policy table must keep policing the data-plane files — a
    refactor that drops them would silently shrink coverage."""
    files = {os.path.basename(row[0]) for row in _lint._CHECKS}
    assert {"estimator.py", "featureset.py", "device_feed.py",
            "embedding.py"} <= files
    funcs = {fn for row in _lint._CHECKS for fn in row[2]}
    assert {"_gather", "masked_eval_batches", "_produce",
            "evaluate", "predict", "_routing", "_lookup_body",
            "_lookup_bwd_body", "_update_body"} <= funcs


def test_lint_covers_etl_engine_bodies():
    """The XShard shuffle kernels and exchange/gather/combine task bodies
    must stay under the hot-path policy."""
    files = {os.path.basename(row[0]) for row in _lint._CHECKS}
    assert "engine.py" in files
    funcs = {fn for row in _lint._CHECKS for fn in row[2]}
    assert {"_mix64", "_bucket_order", "_join_match", "_stack_into",
            "_exchange_task", "_gather_dest", "_groupby_task",
            "_join_task", "_handoff_task", "_take_cols_into"} <= funcs


def test_lint_catches_a_seeded_sync(tmp_path):
    """The checker itself must detect a seeded violation (guards against
    the lint rotting into a silent always-pass)."""
    bad = tmp_path / "estimator.py"
    bad.write_text(
        "class Estimator:\n"
        "    def predict(self, x):\n"
        "        for b in x:\n"
        "            v = float(self._step(b))\n"
        "            a = np.asarray(v)\n"
        "        return a\n")
    found = _lint.check(str(bad))
    assert {w for _, _, _, w in found} == {"float()", "np.asarray()"}


def test_lint_catches_seeded_data_plane_regressions(tmp_path):
    """Seeded _gather per-record loop + per-batch arange must trip the new
    data-plane rules."""
    bad_fs = tmp_path / "featureset.py"
    bad_fs.write_text(
        "class FeatureSet:\n"
        "    def _gather(self, idx):\n"
        "        x = np.asarray(self.features[idx])\n"
        "        rows = [self.features[i] for i in idx]\n"
        "        return x, rows\n")
    found = _lint._check_file(str(bad_fs), "FeatureSet", ("_gather",),
                              ("asarray",), True, "body")
    whats = {w for _, _, w in found}
    assert "np.asarray()" in whats
    assert "per-record Python loop" in whats

    bad_df = tmp_path / "device_feed.py"
    bad_df.write_text(
        "def masked_eval_batches(it, batch_size):\n"
        "    for x, y, valid in it:\n"
        "        mask = (np.arange(batch_size) < valid)\n"
        "        yield (x, y, mask), valid\n")
    found = _lint._check_file(str(bad_df), None, ("masked_eval_batches",),
                              ("arange",), False, "loops")
    assert {w for _, _, w in found} == {"np.arange()"}


def test_lint_catches_seeded_embedding_regressions(tmp_path):
    """A one-hot densified gradient, a per-row Python loop, or a host sync
    inside the sharded lookup/grad bodies must trip the embedding rules."""
    bad = tmp_path / "embedding.py"
    bad.write_text(
        "def _lookup_bwd_body(ct, ids, table):\n"
        "    onehot = jax.nn.one_hot(ids, table.shape[0])\n"
        "    grads = [ct[i] for i in range(ct.shape[0])]\n"
        "    n = float(ct.sum())\n"
        "    return onehot.T @ ct, grads, n\n")
    found = _lint._check_file(str(bad), None, _lint.EMBED_BODIES, (),
                              True, "body")
    whats = {w for _, _, w in found}
    assert {"one_hot()", "per-record Python loop", "float()"} <= whats


def test_lint_catches_seeded_etl_regressions(tmp_path):
    """A per-row Python loop in a shuffle kernel, or a full-frame
    ``pd.concat`` / host sync in an exchange/gather body, must trip the
    ETL rules (the seed-era gather-everything antipattern)."""
    bad = tmp_path / "engine.py"
    bad.write_text(
        "def _bucket_order(dest, nparts):\n"
        "    order = [i for i in range(len(dest)) if dest[i] == 0]\n"
        "    return np.asarray(order)\n")
    found = _lint._check_file(str(bad), None, _lint.ETL_KERNELS, (),
                              True, "body")
    whats = {w for _, _, w in found}
    assert {"per-record Python loop", "np.asarray()"} <= whats

    bad2 = tmp_path / "engine2.py"
    bad2.write_text(
        "def _gather_dest(refs, j):\n"
        "    frames = load_all(refs)\n"
        "    whole = pd.concat(frames, ignore_index=True)\n"
        "    n = float(whole.size)\n"
        "    return whole, n\n")
    found = _lint._check_file(str(bad2), None, _lint.ETL_TASKS, (),
                              False, "body")
    assert {w for _, _, w in found} == {"pd.concat()", "float()"}


def test_lint_covers_fleet_router_scoring():
    """The fleet router's placement scoring must stay under the hot-path
    policy — it runs once per routed request over the instance-gauge
    arrays and must stay a single vectorized pass."""
    files = {os.path.basename(row[0]) for row in _lint._CHECKS}
    assert "fleet.py" in files
    funcs = {fn for row in _lint._CHECKS for fn in row[2]}
    assert "_score_instances" in funcs


def test_lint_catches_seeded_router_scoring_regressions(tmp_path):
    """A per-instance Python loop or host sync seeded into the router
    scoring body must trip the fleet rule."""
    bad = tmp_path / "fleet.py"
    bad.write_text(
        "def _score_instances(alive, depth, in_flight, slots_free,\n"
        "                     pages_free, service_s, token_s,\n"
        "                     need_tokens, need_pages):\n"
        "    est = [float(depth[i]) * service_s[i]\n"
        "           for i in range(len(depth))]\n"
        "    return np.asarray(est)\n")
    found = _lint._check_file(str(bad), None, ("_score_instances",), (),
                              True, "body")
    whats = {w for _, _, w in found}
    assert {"per-record Python loop", "float()", "np.asarray()"} <= whats


def test_fleet_scoring_is_policed_clean():
    """The real router scoring body must currently satisfy its own policy
    — direct check, independent of _CHECKS."""
    assert _lint._check_file(_lint.FLEET_PY, None, ("_score_instances",),
                             (), True, "body") == []


def test_etl_bodies_are_policed_clean():
    """The real ETL kernels/tasks must currently satisfy their own policy
    — direct check, independent of _CHECKS."""
    assert _lint._check_file(_lint.ENGINE_PY, None, _lint.ETL_KERNELS,
                             (), True, "body") == []
    assert _lint._check_file(_lint.ENGINE_PY, None, _lint.ETL_TASKS,
                             (), False, "body") == []


def test_embedding_bodies_are_policed_clean():
    """The real engine bodies must currently satisfy their own policy (no
    loops, no syncs, no one_hot) — direct check, independent of _CHECKS."""
    found = _lint._check_file(_lint.EMBEDDING_PY, None, _lint.EMBED_BODIES,
                              (), True, "body")
    assert found == []


def test_lint_covers_fused_embedding_kernels():
    """The fused embedding kernel bodies (ops/embedding_kernels.py) and
    their multi-table/quantize wrappers must stay under the hot-path
    policy — they ARE the recsys per-step hot path when
    kernels.fused_embedding is on."""
    files = {os.path.basename(row[0]) for row in _lint._CHECKS}
    assert "embedding_kernels.py" in files
    funcs = {fn for row in _lint._CHECKS for fn in row[2]}
    assert {"gather_rows", "gather_rows_clip", "segment_grads",
            "scatter_rows", "gather_pool", "gather_pool_int8",
            "_gather_kernel", "_gather_pool_kernel",
            "_scatter_add_kernel", "multi_table_lookup",
            "quantize_table"} <= funcs


def test_lint_catches_seeded_fused_kernel_regressions(tmp_path):
    """A one-hot densified gather, a per-row Python loop, or a host sync
    seeded into a fused kernel body must trip the kernel rules (guards
    the new rows against rotting into a silent always-pass)."""
    bad = tmp_path / "embedding_kernels.py"
    bad.write_text(
        "def gather_pool(table, idx, combiner=None, mask_negative=True):\n"
        "    hot = jax.nn.one_hot(idx, table.shape[0])\n"
        "    rows = [table[i] for i in idx]\n"
        "    total = float(hot.sum())\n"
        "    return hot @ table, rows, total\n")
    found = _lint._check_file(str(bad), None, _lint.EMBED_KERNEL_BODIES,
                              (), True, "body")
    whats = {w for _, _, w in found}
    assert {"one_hot()", "per-record Python loop", "float()"} <= whats


def test_fused_kernel_bodies_are_policed_clean():
    """The real fused kernel bodies and wrappers must currently satisfy
    their own policy — direct check, independent of _CHECKS."""
    assert _lint._check_file(_lint.EMBED_KERNELS_PY, None,
                             _lint.EMBED_KERNEL_BODIES, (), True,
                             "body") == []
    assert _lint._check_file(_lint.EMBED_KERNELS_PY, None,
                             _lint.EMBED_KERNEL_WRAPPERS, (), False,
                             "body") == []


def test_lint_covers_model_parallel_bodies():
    """The pipeline scan bodies, the ring-attention hop bodies, and the
    MoE expert exchange must stay under the hot-path policy — they run
    once per tick/hop/step inside shard_map'd device code where a host
    sync stalls every device on the mesh."""
    files = {os.path.basename(row[0]) for row in _lint._CHECKS}
    assert {"pipeline.py", "ring_attention.py", "moe.py"} <= files
    funcs = {fn for row in _lint._CHECKS for fn in row[2]}
    assert {"pipeline_apply", "_pipe_fwd_body", "_pipe_1f1b_body",
            "ring_attention", "ring_masked_context",
            "_expert_exchange"} <= funcs


def test_lint_catches_seeded_model_parallel_regressions(tmp_path):
    """A per-tick host fetch, a per-microbatch Python loop, or a one-hot
    densified dispatch seeded into the new traced bodies must trip the
    model-parallel rules (guards the rows against rotting into a silent
    always-pass)."""
    bad_pipe = tmp_path / "pipeline.py"
    bad_pipe.write_text(
        "def _pipe_1f1b_body(stage_fn, head_loss_fn, n, axis_name):\n"
        "    def body(carry, tick):\n"
        "        outs = [stage_fn(p, x) for p, x in carry]\n"
        "        n_done = float(tick)\n"
        "        return carry, np.asarray(outs)\n"
        "    return body\n")
    found = _lint._check_file(str(bad_pipe), None, _lint.PIPELINE_BODIES,
                              (), True, "body")
    whats = {w for _, _, w in found}
    assert {"per-record Python loop", "float()", "np.asarray()"} <= whats

    bad_ring = tmp_path / "ring_attention.py"
    bad_ring.write_text(
        "def ring_masked_context(q, k_blk, v_blk, visible, scale,\n"
        "                        axis_name='seq'):\n"
        "    hops = [jax.device_get(k_blk) for _ in range(8)]\n"
        "    return hops\n")
    found = _lint._check_file(str(bad_ring), None, _lint.RING_BODIES,
                              (), True, "body")
    whats = {w for _, _, w in found}
    assert {"per-record Python loop", "jax.device_get()"} <= whats

    bad_moe = tmp_path / "moe.py"
    bad_moe.write_text(
        "def _expert_exchange(xin, w_in, b_in, w_out, b_out, act,\n"
        "                     axis_name):\n"
        "    hot = jax.nn.one_hot(xin, w_in.shape[0])\n"
        "    hot.block_until_ready()\n"
        "    return hot\n")
    found = _lint._check_file(str(bad_moe), None, _lint.MOE_BODIES,
                              (), True, "body")
    whats = {w for _, _, w in found}
    assert {"one_hot()", ".block_until_ready()"} <= whats


def test_model_parallel_bodies_are_policed_clean():
    """The real pipeline/ring/MoE traced bodies must currently satisfy
    their own policy — direct check, independent of _CHECKS."""
    assert _lint._check_file(_lint.PIPELINE_PY, None,
                             _lint.PIPELINE_BODIES, (), True, "body") == []
    assert _lint._check_file(_lint.RING_PY, None, _lint.RING_BODIES,
                             (), True, "body") == []
    assert _lint._check_file(_lint.MOE_PY, None, _lint.MOE_BODIES,
                             (), True, "body") == []
