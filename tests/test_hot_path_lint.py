"""Tier-1 collection-time guard: the eval/predict hot paths must stay free
of per-batch host↔device syncs (``scripts/check_hot_path_syncs.py``).

The lint runs at IMPORT (= pytest collection) so a reintroduced
``float(...)``/``np.asarray(...)`` inside an ``evaluate*``/``predict``
dispatch loop fails the suite even if no behavioral test notices the
restored stall."""
import importlib.util
import os

_script = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "check_hot_path_syncs.py")
_spec = importlib.util.spec_from_file_location("check_hot_path_syncs",
                                               _script)
_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_lint)

_violations = _lint.check()
if _violations:  # collection-time failure, with the offending lines
    raise AssertionError(
        "per-batch host sync reintroduced in estimator hot paths: "
        + "; ".join(f"{fn}:{line} {what}" for fn, line, what in _violations))


def test_hot_paths_have_no_per_batch_syncs():
    assert _lint.check() == []


def test_lint_catches_a_seeded_sync(tmp_path):
    """The checker itself must detect a seeded violation (guards against
    the lint rotting into a silent always-pass)."""
    bad = tmp_path / "estimator.py"
    bad.write_text(
        "class Estimator:\n"
        "    def predict(self, x):\n"
        "        for b in x:\n"
        "            v = float(self._step(b))\n"
        "            a = np.asarray(v)\n"
        "        return a\n")
    found = _lint.check(str(bad))
    assert {w for _, _, w in found} == {"float()", "np.asarray()"}
