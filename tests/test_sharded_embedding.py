"""Engine tests for the sharded sparse-embedding plane
(``parallel/embedding.py``): all-to-all lookup vs the dense gather,
segment-sum gradients, row-subset optimizer updates, the
``data.validate_ids`` policy, and the host-DRAM cold tier.

Estimator-level N-step training parity lives in
``tests/test_embedding_parity.py``; this file stays at the engine API.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.parallel import embedding as embed


def _spec(ctx, vocab=96, dim=8):
    spec = embed.make_shard_spec(vocab, dim, mesh=ctx.mesh)
    assert spec is not None and spec.shards == 8
    return spec


def _table(spec, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(spec.padded, spec.dim).astype(np.float32))


class TestShardedLookup:
    def test_forward_matches_dense_gather(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = jnp.asarray(np.random.RandomState(1).randint(
            0, spec.vocab, 64).astype(np.int32))
        assert embed.can_run(spec, ids.shape[0])
        rows, blob = jax.jit(embed.sharded_lookup,
                             static_argnums=(2,))(table, ids, spec)
        dense = jax.jit(lambda t: jnp.take(t, ids, axis=0))(table)
        np.testing.assert_array_equal(np.asarray(rows), np.asarray(dense))
        assert blob.shape[0] == ids.shape[0]  # blob rides the id axis

    def test_sentinel_ids_read_zero_rows(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = np.random.RandomState(2).randint(
            0, spec.vocab, 64).astype(np.int32)
        ids[::4] = spec.padded  # SENTINEL
        rows, _ = jax.jit(embed.sharded_lookup, static_argnums=(2,))(
            table, jnp.asarray(ids), spec)
        out = np.asarray(rows)
        np.testing.assert_array_equal(out[::4], 0.0)
        np.testing.assert_array_equal(
            out[1::4], np.asarray(table)[ids[1::4]])

    def test_grad_matches_dense_segment_sum(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        # repeated ids so the segment-sum accumulation is exercised
        ids = jnp.asarray((np.arange(64) % 13).astype(np.int32))
        w = jnp.asarray(np.random.RandomState(3).randn(
            64, spec.dim).astype(np.float32))

        @jax.jit
        def sharded_grad(t):
            def loss(tt):
                rows, _ = embed.sharded_lookup(tt, ids, spec)
                return jnp.sum(rows * w)
            return jax.grad(loss)(t)

        @jax.jit
        def dense_grad(t):
            return jax.grad(
                lambda tt: jnp.sum(jnp.take(tt, ids, axis=0) * w))(t)

        g_sh, g_d = np.asarray(sharded_grad(table)), np.asarray(
            dense_grad(table))
        np.testing.assert_array_equal(g_sh, g_d)
        assert np.all(g_sh[13:] == 0.0)  # untouched rows: exactly zero

    def test_can_run_requires_divisible_ids(self, ctx):
        spec = _spec(ctx)
        assert embed.can_run(spec, 64)
        assert not embed.can_run(spec, 63)   # not divisible by 8
        assert not embed.can_run(spec, 4)    # fewer ids than shards
        assert not embed.can_run(None, 64)

    def test_no_spec_without_multi_device_axis(self, ctx):
        from jax.sharding import Mesh
        one = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        assert embed.make_shard_spec(96, 8, mesh=one) is None


class TestRowUpdates:
    def test_sgd_touches_only_looked_up_rows(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = jnp.asarray((np.arange(64) % 13).astype(np.int32))

        @jax.jit
        def step(t):
            def loss(tt):
                rows, blob = embed.sharded_lookup(tt, ids, spec)
                return jnp.sum(rows ** 2), blob
            (_l, blob), g = jax.value_and_grad(loss, has_aux=True)(t)
            new_t, _ = embed.apply_row_update(
                "sgd", {"lr": 0.1}, spec, t, g, blob, {})
            return new_t, g

        new_t, g = step(table)
        old, new = np.asarray(table), np.asarray(new_t)
        np.testing.assert_array_equal(new[13:], old[13:])  # untouched
        assert not np.array_equal(new[:13], old[:13])
        # same arithmetic as the dense elementwise mirror, bitwise
        dense_new, _ = jax.jit(lambda t, gg: embed.apply_dense_update(
            "sgd", {"lr": 0.1}, t, gg, {}))(table, g)
        np.testing.assert_array_equal(new, np.asarray(dense_new))

    def test_adagrad_row_state_only_accumulates_touched(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = jnp.asarray((np.arange(64) % 13).astype(np.int32))
        state = embed.init_row_state("adagrad", table)
        np.testing.assert_array_equal(np.asarray(state["acc"]),
                                      np.float32(0.1))

        @jax.jit
        def step(t, st):
            def loss(tt):
                rows, blob = embed.sharded_lookup(tt, ids, spec)
                return jnp.sum(rows ** 2), blob
            (_l, blob), g = jax.value_and_grad(loss, has_aux=True)(t)
            return embed.apply_row_update(
                "adagrad", {"lr": 0.1, "eps": 1e-7}, spec, t, g, blob, st)

        new_t, new_st = step(table, state)
        acc = np.asarray(new_st["acc"])
        np.testing.assert_array_equal(acc[13:], np.float32(0.1))
        assert np.all(acc[:13] > np.float32(0.1))
        np.testing.assert_array_equal(
            np.asarray(new_t)[13:], np.asarray(table)[13:])

    def test_adam_counts_steps_and_updates_moments(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = jnp.asarray((np.arange(64) % 13).astype(np.int32))
        state = embed.init_row_state("adam", table)

        @jax.jit
        def step(t, st):
            def loss(tt):
                rows, blob = embed.sharded_lookup(tt, ids, spec)
                return jnp.sum(rows ** 2), blob
            (_l, blob), g = jax.value_and_grad(loss, has_aux=True)(t)
            return embed.apply_row_update(
                "adam", {"lr": 1e-2, "b1": 0.9, "b2": 0.999, "eps": 1e-8},
                spec, t, g, blob, st)

        t1, s1 = step(table, state)
        t2, s2 = step(t1, s1)
        assert int(s2["count"]) == 2
        mu = np.asarray(s2["mu"])
        assert np.all(mu[13:] == 0.0)  # lazy: untouched moments never move
        assert np.isfinite(np.asarray(t2)).all()

    def test_apply_dense_update_mirrors_optax(self):
        import optax
        rs = np.random.RandomState(7)
        t = jnp.asarray(rs.randn(10, 4).astype(np.float32))
        g = jnp.asarray(rs.randn(10, 4).astype(np.float32))

        tx = optax.sgd(0.1)
        upd, _ = tx.update({"t": g}, tx.init({"t": t}), {"t": t})
        ref = optax.apply_updates({"t": t}, upd)["t"]
        got, _ = embed.apply_dense_update("sgd", {"lr": 0.1}, t, g, {})
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-7, atol=0)

        tx = optax.adam(1e-2)
        st = tx.init({"t": t})
        upd, _ = tx.update({"t": g}, st, {"t": t})
        ref = optax.apply_updates({"t": t}, upd)["t"]
        got, new_st = embed.apply_dense_update(
            "adam", {"lr": 1e-2, "b1": 0.9, "b2": 0.999, "eps": 1e-8},
            t, g, embed.init_row_state("adam", t))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-6, atol=1e-7)
        assert int(new_st["count"]) == 1

    def test_unknown_kind_raises(self):
        t = jnp.zeros((4, 2))
        with pytest.raises(ValueError, match="sparse row update"):
            embed.init_row_state("rmsprop", t)
        with pytest.raises(ValueError, match="sparse row update"):
            embed.apply_dense_update("rmsprop", {"lr": 0.1}, t, t, {})


class TestValidateIds:
    @pytest.fixture(autouse=True)
    def _restore_mode(self):
        yield
        global_config().unset("data.validate_ids")

    def test_raise_mode_raises_on_eager_oob(self):
        global_config().set("data.validate_ids", "raise")
        with pytest.raises(ValueError, match="out of range"):
            embed.validate_ids(jnp.asarray([0, 5, 99]), 10)
        # in-range ids pass through
        out = embed.validate_ids(jnp.asarray([0, 5, 9]), 10)
        np.testing.assert_array_equal(np.asarray(out), [0, 5, 9])

    def test_count_mode_clamps_and_counts(self):
        global_config().set("data.validate_ids", "count")
        before = embed._M_OOB.value()
        out = embed.validate_ids(jnp.asarray([-1, 5, 99]), 10)
        jax.effects_barrier()
        np.testing.assert_array_equal(np.asarray(out), [0, 5, 9])
        assert embed._M_OOB.value() == before + 2

    def test_clamp_mode_stays_silent(self):
        global_config().set("data.validate_ids", "clamp")
        before = embed._M_OOB.value()
        out = embed.validate_ids(jnp.asarray([-1, 99]), 10)
        jax.effects_barrier()
        np.testing.assert_array_equal(np.asarray(out), [0, 9])
        assert embed._M_OOB.value() == before

    def test_allow_negative_keeps_padding_ids(self):
        global_config().set("data.validate_ids", "raise")
        out = embed.validate_ids(jnp.asarray([-1, 3, 9]), 10,
                                 allow_negative=True)
        np.testing.assert_array_equal(np.asarray(out), [-1, 3, 9])
        with pytest.raises(ValueError, match="out of range"):
            embed.validate_ids(jnp.asarray([-1, 99]), 10,
                               allow_negative=True)

    def test_bad_mode_rejected(self):
        global_config().set("data.validate_ids", "never")
        with pytest.raises(ValueError, match="data.validate_ids"):
            embed.validate_ids(jnp.asarray([1]), 10)


class TestColdTier:
    def test_fetch_roundtrip_and_masking(self):
        tier = embed.HostColdTier(8, 4, name="t_fetch")
        try:
            vals = np.arange(32, dtype=np.float32).reshape(8, 4)
            tier.fill(vals)
            out = tier.fetch(np.asarray([2, -1, 7, 99]))
            np.testing.assert_array_equal(out[0], vals[2])
            np.testing.assert_array_equal(out[1], 0.0)
            np.testing.assert_array_equal(out[2], vals[7])
            np.testing.assert_array_equal(out[3], 0.0)
        finally:
            tier.close()

    def test_cold_hits_counter(self):
        tier = embed.HostColdTier(8, 4, name="t_hits")
        try:
            before = embed._M_COLD_HITS.value()
            tier.fetch(np.asarray([1, 2, -1]))
            assert embed._M_COLD_HITS.value() == before + 2
        finally:
            tier.close()

    def test_backward_trains_the_slab(self):
        tier = embed.HostColdTier(8, 4, name="t_train", lr=0.5)
        try:
            vals = np.ones((8, 4), dtype=np.float32)
            tier.fill(vals)
            rel = jnp.asarray([1, 3, -1], dtype=jnp.int32)
            anchor = jnp.float32(0.0)

            @jax.jit
            def loss(a):
                rows = embed.cold_lookup(tier, rel, a)
                return jnp.sum(rows ** 2)

            jax.grad(loss)(anchor)
            jax.effects_barrier()
            # d/drow sum(row^2) = 2*row = 2 -> row - 0.5*2 = 0
            np.testing.assert_array_equal(tier.view[1], 0.0)
            np.testing.assert_array_equal(tier.view[3], 0.0)
            np.testing.assert_array_equal(tier.view[0], 1.0)  # untouched
        finally:
            tier.close()

    def test_save_load_roundtrip(self, tmp_path):
        tier = embed.HostColdTier(4, 2, name="t_save")
        tier2 = embed.HostColdTier(4, 2, name="t_load")
        try:
            vals = np.random.RandomState(0).randn(4, 2).astype(np.float32)
            tier.fill(vals)
            p = str(tmp_path / "cold.npy")
            tier.save(p)
            tier2.load(p)
            np.testing.assert_array_equal(tier2.view, vals)
        finally:
            tier.close()
            tier2.close()

    def test_close_releases_bytes_and_is_idempotent(self):
        g0 = embed._M_COLD_BYTES.value()
        tier = embed.HostColdTier(8, 4, name="t_close")
        assert embed._M_COLD_BYTES.value() > g0
        tier.close()
        tier.close()
        assert embed._M_COLD_BYTES.value() == g0


class TestPlumbing:
    def test_pop_stashed_rows_strips_and_preserves_structure(self):
        state = {
            "emb": {embed.ROWS_PREFIX + "embeddings": jnp.zeros((2, 3)),
                    "other": jnp.ones(())},
            "emb2": {embed.ROWS_PREFIX + "embeddings": jnp.zeros((2, 3))},
            "bn": {"mean": jnp.zeros((4,))},
            "scalar": jnp.ones(()),
        }
        rows, clean = embed.pop_stashed_rows(state)
        assert set(rows) == {"emb", "emb2"}
        assert set(rows["emb"]) == {"embeddings"}
        assert set(clean) == {"emb", "bn", "scalar"}  # emb2 emptied
        assert set(clean["emb"]) == {"other"}

    def test_trace_bytes_accumulator(self, ctx):
        spec = _spec(ctx)
        table = _table(spec)
        ids = jnp.asarray(np.zeros(64, np.int32))
        embed.reset_trace_bytes()

        @jax.jit
        def step(t):
            rows, blob = embed.sharded_lookup(t, ids, spec)
            return jnp.sum(rows)

        step(table)  # trace happens here
        ex, gr = embed.take_trace_bytes()
        assert ex > 0
        assert embed.take_trace_bytes() == (0, 0)  # drained

    def test_exchange_cost_dwarfed_by_dense_grad(self, ctx):
        spec = embed.make_shard_spec(1 << 16, 64, mesh=ctx.mesh)
        cost = embed.exchange_cost_bytes(spec, 4096)
        assert cost["dense_grad_bytes"] > cost["grad_bytes"]
        assert cost["forward_bytes"] > 0
