"""Estimator-level parity for the sharded sparse-embedding engine: N
training steps through the real ``Estimator.train`` loop on a 4-device CPU
mesh must produce BIT-IDENTICAL parameters to the replicated dense
reference — for NCF and Wide&Deep, for SGD and Adagrad, and across a
snapshot save -> restore -> continue of a sharded table.

Adam is the documented exception (docs/embeddings.md): the row-subset
update is LAZY (untouched rows' moments do not decay), so it is checked
for structure and finiteness, not bit parity.
"""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import objectives
from analytics_zoo_tpu.keras.optimizers import SGD, Adagrad, Adam
from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF
from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
    ColumnFeatureInfo, WideAndDeep)

USERS, ITEMS, B = 40, 36, 16


def _mesh4():
    return Mesh(np.asarray(jax.devices()[:4]), ("data",))


def _loss():
    return objectives.get("sparse_categorical_crossentropy")


def _ncf_fs(n=64):
    rs = np.random.default_rng(0)
    x = np.stack([rs.integers(1, USERS + 1, size=(n,)),
                  rs.integers(1, ITEMS + 1, size=(n,))], 1).astype(np.int32)
    y = rs.integers(0, 2, size=(n,)).astype(np.int32)
    return FeatureSet.from_ndarrays(x, y, shuffle=False)


def _ncf_estimator(shard, opt, mesh):
    model = NeuralCF(USERS, ITEMS, 2, user_embed=8, item_embed=8,
                     hidden_layers=(16, 8), mf_embed=8,
                     shard_embeddings=shard).build_model()
    return Estimator(model=model, loss_fn=_loss(), optimizer=opt,
                     mesh=mesh, seed=7)


def _train_ncf(shard, opt, mesh, epochs=1):
    est = _ncf_estimator(shard, opt, mesh)
    est.train(_ncf_fs(), batch_size=B, epochs=epochs)
    return est


def _wnd_fs(ci, n=64):
    rs = np.random.RandomState(0)
    offsets = np.cumsum([0] + ci.wide_dims)[:-1]
    wide = np.stack([rs.randint(0, d, n) + off
                     for d, off in zip(ci.wide_dims, offsets)],
                    1).astype(np.int32)
    ind = np.stack([rs.randint(0, d, n) for d in ci.indicator_dims],
                   1).astype(np.int32)
    emb = np.stack([rs.randint(0, d, n) for d in ci.embed_in_dims],
                   1).astype(np.int32)
    cont = rs.rand(n, 1).astype(np.float32)
    y = rs.randint(0, 2, n).astype(np.int32)
    return FeatureSet.from_ndarrays([wide, ind, emb, cont], y,
                                    shuffle=False)


def _train_wnd(shard, opt, mesh):
    ci = ColumnFeatureInfo(
        wide_base_cols=["a"], wide_base_dims=[8],
        wide_cross_cols=["ab"], wide_cross_dims=[64],
        indicator_cols=["w"], indicator_dims=[4],
        embed_cols=["a_e"], embed_in_dims=[12], embed_out_dims=[4],
        continuous_cols=["age"])
    wnd = WideAndDeep("wide_n_deep", 2, ci, hidden_layers=(8, 4),
                      shard_embeddings=shard)
    est = Estimator(model=wnd._ensure_built(), loss_fn=_loss(),
                    optimizer=opt, mesh=mesh, seed=7)
    est.train(_wnd_fs(ci), batch_size=B, epochs=1)
    return est


def _assert_params_bitwise(ref, sharded):
    """Compare trees key-by-key; sharded tables carry padding rows, which
    are truncated before the bitwise comparison."""
    pr = jax.tree_util.tree_map(np.asarray, ref.params)
    ps = jax.tree_util.tree_map(np.asarray, sharded.params)
    assert set(pr) == set(ps)
    for lname in sorted(pr):
        assert set(pr[lname]) == set(ps[lname])
        for k in sorted(pr[lname]):
            a, b = pr[lname][k], ps[lname][k]
            if b.ndim == 2 and b.shape[0] > a.shape[0]:
                b = b[:a.shape[0]]
            np.testing.assert_array_equal(
                a, b, err_msg=f"{lname}/{k} diverged")


class TestNCFParity:
    def test_sgd_bitwise(self, ctx):
        mesh = _mesh4()
        ref = _train_ncf(False, SGD(0.1), mesh)
        sh = _train_ncf(True, SGD(0.1), mesh)
        assert sh._embed_plan(), "sharded run did not take the sparse path"
        assert not ref._embed_plan()
        _assert_params_bitwise(ref, sh)

    def test_adagrad_bitwise_with_row_state(self, ctx):
        mesh = _mesh4()
        ref = _train_ncf(False, Adagrad(0.05), mesh)
        sh = _train_ncf(True, Adagrad(0.05), mesh)
        _assert_params_bitwise(ref, sh)
        embed_opt = sh.opt_state["embed"]
        assert sorted(embed_opt) == ["mf_item_table", "mf_user_table",
                                     "mlp_item_table", "mlp_user_table"]
        for sub in embed_opt.values():
            acc = np.asarray(sub["embeddings"]["acc"])
            assert np.any(acc > np.float32(0.1))       # touched rows moved
            assert np.any(acc == np.float32(0.1))      # untouched: pristine

    def test_adam_lazy_trains_and_counts_steps(self, ctx):
        mesh = _mesh4()
        sh = _train_ncf(True, Adam(1e-2), mesh)
        leaves = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, sh.params))
        assert all(np.isfinite(lf).all() for lf in leaves)
        for sub in sh.opt_state["embed"].values():
            assert int(sub["embeddings"]["count"]) == 4  # 64/16 steps


class TestWideAndDeepParity:
    def test_sgd_bitwise(self, ctx):
        mesh = _mesh4()
        ref = _train_wnd(False, SGD(0.1), mesh)
        sh = _train_wnd(True, SGD(0.1), mesh)
        assert sh._embed_plan()
        specs = sh._sharded_table_specs()
        assert ("wide_linear", "table") in specs
        _assert_params_bitwise(ref, sh)


class TestShardedSnapshotResume:
    def test_resume_matches_straight_run(self, ctx, tmp_path):
        mesh = _mesh4()
        straight = _train_ncf(True, SGD(0.1), mesh, epochs=4)

        ck = str(tmp_path / "ck")
        est_b = _ncf_estimator(True, SGD(0.1), mesh)
        est_b.set_checkpoint(ck)
        est_b.train(_ncf_fs(), batch_size=B, epochs=2)

        est_c = _ncf_estimator(True, SGD(0.1), mesh)
        est_c.set_checkpoint(ck)
        est_c.load_checkpoint(est_c._latest_snapshot())
        est_c.train(_ncf_fs(), batch_size=B, epochs=4)

        # the restored sharded tables (padding included) continue exactly
        _assert_params_bitwise(straight, est_c)
        pa = jax.tree_util.tree_map(np.asarray, straight.params)
        pc = jax.tree_util.tree_map(np.asarray, est_c.params)
        np.testing.assert_array_equal(
            pa["mf_user_table"]["embeddings"],
            pc["mf_user_table"]["embeddings"])  # full padded table

    def test_restored_table_keeps_vocab_sharding(self, ctx, tmp_path):
        mesh = _mesh4()
        ck = str(tmp_path / "ck")
        est_a = _ncf_estimator(True, Adagrad(0.05), mesh)
        est_a.set_checkpoint(ck)
        est_a.train(_ncf_fs(), batch_size=B, epochs=1)

        est_b = _ncf_estimator(True, Adagrad(0.05), mesh)
        est_b.set_checkpoint(ck)
        est_b.load_checkpoint(est_b._latest_snapshot())
        # row-subset optimizer state survives the round trip bitwise
        a = np.asarray(
            est_a.opt_state["embed"]["mf_user_table"]["embeddings"]["acc"])
        b = np.asarray(
            est_b.opt_state["embed"]["mf_user_table"]["embeddings"]["acc"])
        np.testing.assert_array_equal(a, b)
        sharding = est_b.params["mf_user_table"]["embeddings"].sharding
        spec = tuple(getattr(sharding, "spec", ()))
        assert spec and spec[0] == "data", (
            f"restored table lost its vocab sharding: {sharding}")
