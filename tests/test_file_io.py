"""Filesystem-layer tests: every save/load path accepts a scheme-prefixed
URI, exercised against a local fake-remote backend (fsspec's ``memory://``
filesystem — object-store semantics, no network), mirroring the reference's
HDFS-aware IO layer (``common/Utils.scala:175`` ``getFileSystem``)."""
import json
import uuid

import numpy as np
import pytest

from analytics_zoo_tpu.common import file_io


def _uri(name=""):
    # fsspec's MemoryFileSystem is a process-global store; unique roots keep
    # tests independent
    return f"memory://zoo-{uuid.uuid4().hex[:10]}" + (f"/{name}" if name else "")


class TestCore:
    def test_scheme_detection(self):
        assert file_io.scheme_of("gs://b/k") == "gs"
        assert file_io.scheme_of("/tmp/x") is None
        assert file_io.scheme_of("relative/path") is None
        assert file_io.is_remote("gs://b/k")
        assert not file_io.is_remote("/tmp/x")
        assert not file_io.is_remote("file:///tmp/x")
        assert file_io.local_path("file:///tmp/x") == "/tmp/x"
        with pytest.raises(ValueError):
            file_io.local_path("gs://b/k")

    def test_join_preserves_scheme(self):
        assert file_io.join("memory://a", "b", "c") == "memory://a/b/c"
        assert file_io.join("/tmp/a", "b") == "/tmp/a/b"

    def test_roundtrip_remote(self):
        root = _uri()
        file_io.makedirs(root)
        p = file_io.join(root, "f.txt")
        with file_io.fopen(p, "w") as f:
            f.write("hello")
        assert file_io.exists(p)
        with file_io.fopen(p) as f:
            assert f.read() == "hello"
        assert "f.txt" in file_io.listdir(root)
        q = file_io.join(root, "g.txt")
        file_io.replace(p, q)
        assert file_io.exists(q) and not file_io.exists(p)
        file_io.remove(q)
        assert not file_io.exists(q)

    def test_binary_roundtrip(self):
        p = _uri("blob.bin")
        payload = bytes(range(256)) * 100
        with file_io.fopen(p, "wb") as f:
            f.write(payload)
        with file_io.fopen(p, "rb") as f:
            assert f.read() == payload

    def test_put_get_tree(self, tmp_path):
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "a.txt").write_text("A")
        (src / "sub" / "b.txt").write_text("B")
        remote = _uri()
        file_io.put_tree(str(src), remote)
        dst = tmp_path / "dst"
        file_io.get_tree(remote, str(dst))
        assert (dst / "a.txt").read_text() == "A"
        assert (dst / "sub" / "b.txt").read_text() == "B"

    def test_localized_read(self, tmp_path):
        p = _uri("loc.txt")
        with file_io.fopen(p, "w") as f:
            f.write("payload")
        with file_io.localized(p) as local:
            assert not file_io.is_remote(local)
            assert open(local).read() == "payload"

    def test_localized_write(self, tmp_path):
        remote = _uri()
        with file_io.localized(remote, "w") as local:
            with open(f"{local}/out.txt", "w") as f:
                f.write("up")
        with file_io.fopen(file_io.join(remote, "out.txt")) as f:
            assert f.read() == "up"

    def test_registered_fake_filesystem_shadows_scheme(self):
        from fsspec.implementations.memory import MemoryFileSystem

        class CountingFS(MemoryFileSystem):
            protocol = "fakefs"
            opens = 0

            def _open(self, *a, **kw):
                CountingFS.opens += 1
                return super()._open(*a, **kw)

        fs = CountingFS()
        file_io.register_filesystem("fakefs", fs)
        try:
            with file_io.fopen("fakefs://x/y.txt", "w") as f:
                f.write("z")
            assert CountingFS.opens >= 1
            assert file_io.exists("fakefs://x/y.txt")
        finally:
            file_io.unregister_filesystem("fakefs")


class TestCheckpointURI:
    def _estimator(self):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        model = Sequential([Dense(8, name="d1"), Activation("relu"),
                            Dense(2, name="d2")])
        return Estimator(
            model=model,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.05))

    def test_checkpoint_to_remote_uri(self):
        rs = np.random.RandomState(0)
        x = rs.randn(16, 6).astype(np.float32)
        y = rs.randint(0, 2, 16).astype(np.float32)
        est = self._estimator()
        est._ensure_initialized(x[:8])
        uri = _uri("ckpt")
        est.save_checkpoint(uri)
        before = est.get_params()

        est2 = self._estimator()
        est2._ensure_initialized(x[:8])
        est2.load_checkpoint(uri)
        after = est2.get_params()
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)):
            np.testing.assert_allclose(a, b)

    def test_train_checkpoints_into_remote_dir(self):
        from analytics_zoo_tpu.common.triggers import EveryEpoch
        from analytics_zoo_tpu.feature import FeatureSet
        rs = np.random.RandomState(0)
        x = rs.randn(16, 6).astype(np.float32)
        y = rs.randint(0, 2, 16).astype(np.float32)
        est = self._estimator()
        root = _uri("ckpts")
        est.set_checkpoint(root, EveryEpoch())
        est.train(FeatureSet.from_ndarrays(x, y), batch_size=8, epochs=2)
        snaps = [d for d in file_io.listdir(root) if d.startswith("snapshot-")]
        assert snaps, "no snapshot written to the remote checkpoint dir"
        assert est._latest_snapshot().startswith(root)


class TestZooModelURI:
    def test_zoo_model_save_load_remote(self):
        from analytics_zoo_tpu.models import NeuralCF
        m = NeuralCF(20, 10, 2, user_embed=4, item_embed=4,
                     hidden_layers=[8], mf_embed=4)
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 21, 8), rs.randint(1, 11, 8)], 1)
        x = x.astype(np.float32)
        ref = np.asarray(m.predict(x))
        uri = _uri("ncf_model")
        m.save_model(uri)
        m2 = NeuralCF.load_model(uri)
        np.testing.assert_allclose(np.asarray(m2.predict(x)), ref, atol=1e-6)


class TestTFRecordURI:
    def test_tfrecord_write_read_remote(self):
        from analytics_zoo_tpu.feature.tfrecord import (
            TFRecordWriter, encode_example, open_tfrecord, parse_example)
        uri = _uri("data.tfrecord")
        w = TFRecordWriter(uri)
        for i in range(5):
            w.write(encode_example({"x": np.arange(3, dtype=np.float32) + i,
                                    "i": i}))
        w.close()
        r = open_tfrecord(uri)
        assert len(r) == 5
        ex = parse_example(r.read(2))
        np.testing.assert_allclose(ex["x"], [2.0, 3.0, 4.0])
        r.close()


class TestTensorboardURI:
    def test_summary_write_read_remote(self):
        from analytics_zoo_tpu.utils.tensorboard import (
            SummaryWriter, read_scalars)
        logdir = _uri("tb")
        with SummaryWriter(logdir, flush_secs=0.1) as w:
            for step in range(3):
                w.add_scalar("Loss", 1.0 / (step + 1), step)
            w.flush()
        scalars = read_scalars(logdir, "Loss")
        assert [s for s, _ in scalars] == [0, 1, 2]


class TestServingQueueURI:
    def test_file_queue_on_remote_root(self):
        from analytics_zoo_tpu.serving.queues import FileQueue
        q = FileQueue(_uri("queue"))
        q.enqueue("u1", {"data": "abc"})
        q.enqueue("u2", {"data": "def"})
        assert q.pending_count() == 2
        got = q.claim_batch(10)
        assert sorted(u for u, _ in got) == ["u1", "u2"]
        q.put_result("u1", {"value": json.dumps([1, 2])})
        assert q.get_result("u1")["value"] == json.dumps([1, 2])
        assert q.get_result("nope") is None


class TestAOTExportURI:
    def test_export_load_compiled_remote(self):
        import jax
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        model = Sequential([Dense(4, name="d")])
        model.compile(optimizer="sgd", loss="mse")
        im = InferenceModel().load_keras(
            model, *model.build(jax.random.PRNGKey(0), (None, 3)))
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        ref = np.asarray(im.predict(x))
        uri = _uri("aot")
        im.export_compiled(uri, x, batch_sizes=(2,), platforms=("cpu",))
        im2 = InferenceModel().load_compiled(uri)
        np.testing.assert_allclose(np.asarray(im2.predict(x)), ref, atol=1e-5)
