"""Tier-1 collection-time guard: the fault-injection registry and the
``faults.inject(...)`` call sites must stay in bijection, with unique
literal site names, and every site exercised by at least one test
(``scripts/check_fault_sites.py``).

Runs at IMPORT (= pytest collection) so a refactor that orphans a
registry row, duplicates a site name, computes a site name dynamically,
or leaves a new site untested fails the suite even though nothing
behavioral notices chaos coverage rotting."""
import importlib.util
import os

_script = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "check_fault_sites.py")
_spec = importlib.util.spec_from_file_location("check_fault_sites", _script)
_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_lint)

_problems = _lint.check()
if _problems:  # collection-time failure, with the drifted sites
    raise AssertionError(
        "fault-site coverage drifted: " + "; ".join(_problems))


def test_fault_sites_clean():
    assert _lint.check() == []


def test_registry_parse_matches_runtime_registry():
    """The lint reads REGISTRY via AST (no jax import); it must agree with
    the imported module — a computed registry would silently blind it."""
    from analytics_zoo_tpu.common import faults
    assert _lint.registry_sites() == set(faults.REGISTRY)


def test_lint_catches_seeded_drift(tmp_path):
    """The checker must detect a seeded unknown/duplicate/unregistered
    site (guards against the lint rotting into a silent always-pass)."""
    bad = tmp_path / "faults.py"
    bad.write_text("REGISTRY = {'a.site': 1, 'b.site': 2}\n")
    assert _lint.registry_sites(str(bad)) == {"a.site", "b.site"}

    calls, non_literal = _lint.inject_sites()
    assert calls  # the codebase really does inject
    # every call the scanner found is a unique literal of a known site
    assert non_literal == []
    known = _lint.registry_sites()
    assert set(calls) <= known


def test_every_site_names_a_test_file():
    for site in sorted(_lint.registry_sites()):
        assert _lint.tests_mentioning(site), site
