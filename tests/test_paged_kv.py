"""Paged KV engine, op + LM level: bit-identity with the contiguous slot
engine, int8 pool error bounds, page-table plumbing, the runtime-checkable
overflow guard, and speculative decoding's token-identity guarantee.

The scheduler-level counterparts (paged GenerativeServing parity, CoW
shared prefixes, page-pool chaos) live in tests/test_paged_serving.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.decode import (
    cached_attention, checked_cached_attention, init_kv_cache,
    init_paged_pool, init_slot_cache, page_copy, page_table_clear,
    page_table_set, paged_attention, paged_gather, paged_insert,
    slot_attention, slot_insert, spec_accept_greedy)

H, D, MAX_LEN, PL = 2, 4, 32, 8        # heads, head_dim, max_len, page_len
WIDTH = MAX_LEN // PL                   # table columns


def _private_tables(slots):
    """One table per slot over disjoint pages 1..slots*WIDTH (page 0 is
    the null page, never handed out)."""
    table = np.zeros((slots, WIDTH), np.int32)
    for s in range(slots):
        table[s] = 1 + s * WIDTH + np.arange(WIDTH)
    return jnp.asarray(table)


class TestPagedBitIdentity:
    def test_paged_attention_matches_slot_attention_bitwise(self, ctx):
        """The tentpole invariant: mixed-length decode over the page pool
        is bit-identical to the contiguous slot rectangles — prefill via
        insert, then several steps with one empty slot joining late."""
        rs = np.random.RandomState(0)
        slots = 4
        slot_c = init_slot_cache(slots, H, MAX_LEN, D)
        paged_c = init_paged_pool(1 + slots * WIDTH, H, PL, D)
        table = _private_tables(slots)
        lens = [5, 1, 11, 0]            # slot 3 starts EMPTY (length 0)
        for s, n in enumerate(lens):
            if n == 0:
                continue
            k = jnp.asarray(rs.randn(H, n, D), jnp.float32)
            v = jnp.asarray(rs.randn(H, n, D), jnp.float32)
            slot_c = slot_insert(slot_c, s, k, v)
            paged_c = paged_insert(paged_c, table[s], k, v)
        lengths = jnp.asarray(lens, jnp.int32)
        for step in range(6):
            q = jnp.asarray(rs.randn(slots, H, 1, D), jnp.float32)
            k = jnp.asarray(rs.randn(slots, H, 1, D), jnp.float32)
            v = jnp.asarray(rs.randn(slots, H, 1, D), jnp.float32)
            ctx_s, slot_c = jax.jit(slot_attention)(q, k, v, slot_c,
                                                    lengths)
            ctx_p, paged_c = jax.jit(
                paged_attention, static_argnames=("max_len",))(
                    q, k, v, paged_c, table, lengths, max_len=MAX_LEN)
            np.testing.assert_array_equal(np.asarray(ctx_s),
                                          np.asarray(ctx_p))
            lengths = lengths + 1
        # the pool holds exactly what the rectangles hold, page-gathered
        k_log, v_log = paged_gather(paged_c, table)
        np.testing.assert_array_equal(np.asarray(k_log),
                                      np.asarray(slot_c["k"]))
        np.testing.assert_array_equal(np.asarray(v_log),
                                      np.asarray(slot_c["v"]))

    def test_paged_insert_roundtrips_through_gather(self, ctx):
        rs = np.random.RandomState(1)
        cache = init_paged_pool(1 + WIDTH, H, PL, D)
        table = _private_tables(1)
        k = jnp.asarray(rs.randn(H, 13, D), jnp.float32)
        v = jnp.asarray(rs.randn(H, 13, D), jnp.float32)
        cache = paged_insert(cache, table[0], k, v)
        k_log, v_log = paged_gather(cache, table)
        np.testing.assert_array_equal(np.asarray(k_log[0, :, :13]),
                                      np.asarray(k))
        np.testing.assert_array_equal(np.asarray(v_log[0, :, :13]),
                                      np.asarray(v))
        # the start offset lands a suffix block at its logical positions
        k2 = jnp.asarray(rs.randn(H, 3, D), jnp.float32)
        cache = paged_insert(cache, table[0], k2, k2, start=13)
        k_log, _ = paged_gather(cache, table)
        np.testing.assert_array_equal(np.asarray(k_log[0, :, 13:16]),
                                      np.asarray(k2))
        # positions 0..12 are untouched by the suffix write
        np.testing.assert_array_equal(np.asarray(k_log[0, :, :13]),
                                      np.asarray(k))

    def test_null_page_absorbs_out_of_allocation_writes(self, ctx):
        """Positions past the table width scatter onto page 0 and never
        corrupt an allocated page — the contiguous engine's 'inactive
        slots write harmlessly' contract, transplanted."""
        rs = np.random.RandomState(2)
        cache = init_paged_pool(1 + WIDTH, H, PL, D)
        table = _private_tables(1)
        k = jnp.asarray(rs.randn(H, MAX_LEN, D), jnp.float32)
        cache = paged_insert(cache, table[0], k, k)
        before = np.asarray(cache["k"][1:])
        q = jnp.asarray(rs.randn(1, H, 1, D), jnp.float32)
        kn = jnp.asarray(rs.randn(1, H, 1, D), jnp.float32)
        # write position MAX_LEN + 3: beyond every table column
        _, cache = paged_attention(q, kn, kn, cache, table,
                                   jnp.asarray([MAX_LEN + 3], jnp.int32),
                                   MAX_LEN)
        np.testing.assert_array_equal(np.asarray(cache["k"][1:]), before)


class TestPageTableOps:
    def test_set_and_clear(self, ctx):
        table = jnp.zeros((3, WIDTH), jnp.int32)
        row = jnp.asarray(np.arange(1, WIDTH + 1, dtype=np.int32))
        table = page_table_set(table, 1, row)
        assert np.asarray(table[1]).tolist() == list(range(1, WIDTH + 1))
        assert np.asarray(table[0]).sum() == 0
        table = page_table_clear(table, jnp.asarray([False, True, False]))
        assert np.asarray(table).sum() == 0

    def test_page_copy_f32_and_int8_scales(self, ctx):
        rs = np.random.RandomState(3)
        for int8 in (False, True):
            cache = init_paged_pool(4, H, PL, D, int8=int8)
            k = jnp.asarray(rs.randn(H, PL, D), jnp.float32)
            row = jnp.asarray([1, 0, 0, 0], jnp.int32)
            cache = paged_insert(cache, row, k, k)
            cache = page_copy(cache, 1, 2)
            np.testing.assert_array_equal(np.asarray(cache["k"][2]),
                                          np.asarray(cache["k"][1]))
            if int8:
                np.testing.assert_array_equal(
                    np.asarray(cache["scale_k"][2]),
                    np.asarray(cache["scale_k"][1]))


class TestInt8PagedPool:
    def test_int8_error_bounded_by_quant_step(self, ctx):
        """int8 pool round-trip error is bounded by half a quantization
        step per position (inline amax on prefill writes)."""
        rs = np.random.RandomState(4)
        cache = init_paged_pool(1 + WIDTH, H, PL, D, int8=True)
        table = _private_tables(1)
        k = rs.randn(H, MAX_LEN, D).astype(np.float32)
        v = rs.randn(H, MAX_LEN, D).astype(np.float32)
        cache = paged_insert(cache, table[0], jnp.asarray(k),
                             jnp.asarray(v))
        k_log, v_log = paged_gather(cache, table)
        # the inline scale is scalar per write (block amax / 127), so the
        # round-trip error is bounded by half a quantization step
        half_k = max(1.0, np.abs(k).max()) / 127.0 / 2.0
        assert np.abs(np.asarray(k_log[0]) - k).max() <= half_k + 1e-7
        half_v = max(1.0, np.abs(v).max()) / 127.0 / 2.0
        assert np.abs(np.asarray(v_log[0]) - v).max() <= half_v + 1e-7

    @pytest.mark.slow
    def test_int8_decode_context_close_to_f32(self, ctx):
        rs = np.random.RandomState(5)
        f32 = init_paged_pool(1 + 2 * WIDTH, H, PL, D)
        i8 = init_paged_pool(1 + 2 * WIDTH, H, PL, D, int8=True)
        table = _private_tables(2)
        lengths = jnp.asarray([6, 2], jnp.int32)
        for s, n in enumerate((6, 2)):
            k = jnp.asarray(rs.randn(H, n, D), jnp.float32)
            v = jnp.asarray(rs.randn(H, n, D), jnp.float32)
            f32 = paged_insert(f32, table[s], k, v)
            i8 = paged_insert(i8, table[s], k, v)
        for _ in range(4):
            q = jnp.asarray(rs.randn(2, H, 1, D), jnp.float32)
            k = jnp.asarray(rs.randn(2, H, 1, D), jnp.float32)
            v = jnp.asarray(rs.randn(2, H, 1, D), jnp.float32)
            ctx_f, f32 = paged_attention(q, k, v, f32, table, lengths,
                                         MAX_LEN)
            ctx_q, i8 = paged_attention(q, k, v, i8, table, lengths,
                                        MAX_LEN)
            np.testing.assert_allclose(np.asarray(ctx_q),
                                       np.asarray(ctx_f), atol=0.08)
            lengths = lengths + 1


class TestCheckedOverflowGuard:
    def test_eager_guard_still_raises(self, ctx):
        cache = init_kv_cache(1, H, 4, D)
        q = jnp.zeros((1, H, 6, D))
        with pytest.raises(ValueError, match="KV cache overflow"):
            cached_attention(q, q, q, cache)

    def test_overflow_caught_under_jit(self, ctx):
        """The documented gap in cached_attention's guard (tracer lengths
        skip it) is closed by checked_cached_attention + checkify: the
        predicate rides THROUGH jit and throws at runtime."""
        from jax.experimental import checkify
        cache = init_kv_cache(1, H, 8, D)
        q = jnp.zeros((1, H, 1, D))

        @jax.jit
        def step(cache, q):
            err, out = checkify.checkify(checked_cached_attention)(
                q, q, q, cache)
            return err, out

        # in-capacity write: no error, bit-identical to the unchecked op
        cache_ok = dict(cache, length=jnp.asarray(4))
        err, (ctx_c, new_c) = step(cache_ok, q)
        err.throw()                      # no-op
        ctx_u, _ = cached_attention(q, q, q, cache_ok)
        np.testing.assert_array_equal(np.asarray(ctx_c), np.asarray(ctx_u))
        # overflowing write: the SILENT-corruption case without checkify
        cache_bad = dict(cache, length=jnp.asarray(8))
        err, _ = step(cache_bad, q)
        with pytest.raises(Exception, match="KV cache overflow"):
            err.throw()


class TestSpeculative:
    def test_spec_accept_greedy_rule(self, ctx):
        v = 8
        drafts = jnp.asarray([[1, 2, 3], [5, 0, 0], [4, 7, 1]], jnp.int32)
        # target argmax rows: [1,2,9?]: build logits whose argmax is given
        g_want = np.asarray([[1, 2, 3, 6],   # all match -> n=4 (bonus)
                             [5, 1, 0, 0],   # first matches only -> n=2
                             [2, 7, 1, 3]])  # first mismatch -> n=1
        logits = np.full((3, 4, v), -5.0, np.float32)
        for s in range(3):
            for j in range(4):
                logits[s, j, g_want[s, j]] = 5.0
        g, n = spec_accept_greedy(drafts, jnp.asarray(logits))
        np.testing.assert_array_equal(np.asarray(g), g_want)
        assert np.asarray(n).tolist() == [4, 2, 1]

    def _lms(self):
        from analytics_zoo_tpu.capture.lm import TransformerLM
        rs = np.random.RandomState(7)
        lm = TransformerLM(vocab_size=16, hidden=16, n_block=2, n_head=2,
                           max_len=32, seed=0)
        lm.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
        draft = TransformerLM(vocab_size=16, hidden=16, n_block=2,
                              n_head=2, max_len=64, seed=1)
        draft.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
        return lm, draft

    @pytest.mark.slow
    def test_generate_speculative_token_identical_to_greedy(self, ctx):
        lm, draft = self._lms()
        rs = np.random.RandomState(8)
        prompts = np.stack([rs.randint(0, 16, (5,)) for _ in range(3)])
        want = lm.generate(prompts, max_new_tokens=10)
        got = lm.generate_speculative(prompts, draft, max_new_tokens=10,
                                      spec_k=3, page_len=8)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow
    def test_generate_speculative_eos_and_one_token_prompt(self, ctx):
        lm, draft = self._lms()
        eos = 1
        prompts = np.asarray([[3], [7]])
        want = lm.generate(prompts, max_new_tokens=12, eos_id=eos)
        got = lm.generate_speculative(prompts, draft, max_new_tokens=12,
                                      spec_k=4, eos_id=eos, page_len=8)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow
    def test_generate_speculative_sampled_is_well_formed(self, ctx):
        """Sampled speculative output follows the accept/resample rule —
        distribution-preserving, not run-identical to serial sampling — so
        the assertion is structural: valid tokens, eos-frozen tails."""
        lm, draft = self._lms()
        eos = 1
        out = lm.generate_speculative(
            np.asarray([[2, 5, 3], [9, 4, 6]]), draft, max_new_tokens=10,
            spec_k=3, eos_id=eos, temperature=0.9, top_k=8, seed=11,
            page_len=8)
        assert out.shape == (2, 10)
        assert out.min() >= 0 and out.max() < 16
        for row in out:
            row = row.tolist()
            if eos in row:   # frozen after the first eos (eos padding)
                assert all(x == eos for x in row[row.index(eos):])
