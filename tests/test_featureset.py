"""Data plane tests: FeatureSet contract, preprocessing, device feed."""
import numpy as np
import pytest

from analytics_zoo_tpu.feature import (
    ArrayToTensor, DeviceFeed, FeatureSet, FeatureLabelPreprocessing, Lambda,
    MemoryType, Preprocessing)


def make_fs(n=100, shuffle=True, **kw):
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    y = np.arange(n, dtype=np.float32)
    return FeatureSet.from_ndarrays(x, y, shuffle=shuffle, **kw)


class TestFeatureSet:
    def test_train_iterator_endless_and_reshuffles(self, ctx):
        fs = make_fs(10, shuffle=True)
        it = fs.train_iterator(batch_size=5)
        epoch1 = [next(it) for _ in range(2)]
        epoch2 = [next(it) for _ in range(2)]  # endless: keeps yielding
        labels1 = np.concatenate([b[1] for b in epoch1])
        labels2 = np.concatenate([b[1] for b in epoch2])
        assert sorted(labels1) == list(range(10))
        assert sorted(labels2) == list(range(10))
        assert not np.array_equal(labels1, labels2)  # reshuffled (w.h.p.)

    def test_train_iterator_drops_remainder(self, ctx):
        fs = make_fs(10, shuffle=False)
        it = fs.train_iterator(batch_size=4)
        for _ in range(4):
            x, y = next(it)
            assert x.shape == (4, 4)  # static shape every step

    def test_eval_iterator_bounded_with_tail(self, ctx):
        fs = make_fs(10, shuffle=False)
        batches = list(fs.eval_iterator(batch_size=4))
        assert [b[2] for b in batches] == [4, 4, 2]
        assert batches[-1][0].shape[0] == 2

    def test_eval_iterator_pad_remainder(self, ctx):
        fs = make_fs(10, shuffle=False)
        batches = list(fs.eval_iterator(batch_size=4, pad_remainder=True))
        assert [b[2] for b in batches] == [4, 4, 2]
        assert all(b[0].shape[0] == 4 for b in batches)  # padded static shape

    def test_disk_tier(self, ctx, tmp_path):
        fs = make_fs(20, memory_type=MemoryType.DISK, cache_dir=str(tmp_path))
        assert isinstance(fs.features, np.memmap)
        x, y = next(fs.train_iterator(batch_size=10))
        assert x.shape == (10, 4)
        assert not isinstance(x, np.memmap)  # gathered to RAM per batch

    def test_slice_boundaries(self, ctx):
        fs = make_fs(100, num_slices=4)
        assert list(fs.slice_boundaries(batch_size=10)) == [2, 4, 6, 10]

    def test_mismatched_leading_axis(self, ctx):
        with pytest.raises(ValueError):
            FeatureSet(np.zeros((5, 2)), np.zeros(4))

    def test_tuple_features(self, ctx):
        fs = FeatureSet.from_ndarrays(
            (np.zeros((8, 2)), np.ones((8, 3))), np.zeros(8))
        x, y = next(fs.train_iterator(4))
        assert x[0].shape == (4, 2) and x[1].shape == (4, 3)

    def test_from_dataframe(self, ctx):
        pd = pytest.importorskip("pandas")
        df = pd.DataFrame({"a": [1.0, 2, 3, 4], "b": [0, 1, 0, 1]})
        fs = FeatureSet.from_dataframe(df, feature_cols=["a"], label_cols=["b"])
        assert fs.size == 4

    def test_from_generator_with_transform(self, ctx):
        def gen():
            for i in range(6):
                yield ([i, i], i % 2)
        tr = FeatureLabelPreprocessing(ArrayToTensor(), ArrayToTensor())
        fs = FeatureSet.from_generator(gen, size_hint=6, transform=tr)
        assert fs.size == 6
        assert fs.features.dtype == np.float32


class TestPreprocessing:
    def test_chain(self):
        p = Lambda(lambda r: r + 1) >> Lambda(lambda r: r * 2)
        assert p.apply(3) == 8
        chained = p >> Lambda(lambda r: r - 1)
        assert len(chained.stages) == 3
        assert chained.apply(3) == 7


class TestDeviceFeed:
    def test_sharded_batches(self, ctx):
        fs = make_fs(64, shuffle=False)
        feed = DeviceFeed(fs.train_iterator(16), ctx.mesh, prefetch=2)
        batch = next(feed)
        x, y = batch
        assert x.shape == (16, 4)
        # batch axis sharded over the 8-device data axis
        assert len(x.sharding.device_set) == 8

    def test_bounded_feed_stops(self, ctx):
        fs = make_fs(16, shuffle=False)
        feed = DeviceFeed((b for b in fs.eval_iterator(8)), ctx.mesh)
        assert len(list(feed)) == 2
        with pytest.raises(StopIteration):
            next(feed)

    def test_indivisible_batch_raises(self, ctx):
        fs = make_fs(8, shuffle=False)
        feed = DeviceFeed(fs.train_iterator(4), ctx.mesh)  # 4 % 8 != 0
        with pytest.raises(ValueError):
            next(feed)
