"""Input-pipeline additions: TFRecord ingest (native C++ reader + Python
fallback), tf.train.Example codec, streaming generator datasets, vectorized
and thread-pooled transforms, string/bytes ingest."""
import os

import numpy as np
import pytest

from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.feature.preprocessing import (
    BatchLambda, Lambda, stack_records)
from analytics_zoo_tpu.feature.tfrecord import (
    TFRecordWriter, _NativeReader, _PythonReader, encode_example,
    iter_tfrecords, open_tfrecord, parse_example, read_examples)


def _write_examples(path, n=10):
    with TFRecordWriter(path) as w:
        for i in range(n):
            w.write_example({
                "x": np.arange(4, dtype=np.float32) + i,
                "y": np.asarray([i % 3], dtype=np.int64),
                "name": f"rec{i}".encode(),
            })


class TestExampleCodec:
    def test_roundtrip(self):
        raw = encode_example({
            "f": np.asarray([1.5, -2.0], dtype=np.float32),
            "i": np.asarray([7, -9, 0], dtype=np.int64),
            "b": [b"ab", b"cde"],
        })
        ex = parse_example(raw)
        np.testing.assert_array_equal(ex["f"], [1.5, -2.0])
        np.testing.assert_array_equal(ex["i"], [7, -9, 0])
        assert ex["b"] == [b"ab", b"cde"]


class TestTFRecordReaders:
    def test_native_and_python_agree(self, tmp_path):
        path = str(tmp_path / "data.tfrecord")
        _write_examples(path, 12)
        py = _PythonReader(path)
        assert len(py) == 12
        if _NativeReader.lib() is not None:
            nat = _NativeReader(path)
            assert len(nat) == 12
            for i in range(12):
                assert nat.read(i) == py.read(i)
            assert nat.read_batch(3, 5) == [py.read(i) for i in range(3, 8)]
            nat.close()
        else:
            pytest.skip("native reader unavailable (no compiler)")

    def test_native_reader_builds(self):
        # the native component is part of the framework contract on this
        # image (g++ is baked in) — fail loudly if the build breaks
        assert _NativeReader.lib() is not None

    def test_corrupt_payload_detected(self, tmp_path):
        path = str(tmp_path / "bad.tfrecord")
        _write_examples(path, 5)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(data))
        with pytest.raises(IOError):
            reader = open_tfrecord(path, verify_crc=True)
            # native reader reports at open; python raises during scan
            reader.close()

    def test_iter_multiple_files(self, tmp_path):
        p1, p2 = str(tmp_path / "a.tfrecord"), str(tmp_path / "b.tfrecord")
        _write_examples(p1, 3)
        _write_examples(p2, 4)
        assert len(list(iter_tfrecords([p1, p2]))) == 7

    def test_read_examples(self, tmp_path):
        path = str(tmp_path / "ex.tfrecord")
        _write_examples(path, 6)
        exs = list(read_examples(path))
        assert len(exs) == 6
        np.testing.assert_array_equal(exs[2]["x"], [2, 3, 4, 5])
        assert exs[2]["name"] == [b"rec2"]


class TestFromTFRecord:
    def test_featureset_from_tfrecord(self, tmp_path):
        path = str(tmp_path / "t.tfrecord")
        _write_examples(path, 16)
        fs = FeatureSet.from_tfrecord(
            path, parser=lambda ex: (ex["x"], ex["y"][0].astype(np.float32)),
            shuffle=False)
        assert fs.size == 16
        x, y = next(fs.train_iterator(8))
        assert x.shape == (8, 4) and y.shape == (8,)
        np.testing.assert_array_equal(x[3], [3, 4, 5, 6])

    def test_streaming_from_tfrecord(self, tmp_path):
        path = str(tmp_path / "s.tfrecord")
        _write_examples(path, 16)
        fs = FeatureSet.from_tfrecord(
            path, parser=lambda ex: (ex["x"], ex["y"][0].astype(np.float32)),
            streaming=True)
        assert fs.size == 16
        it = fs.train_iterator(4)
        seen = [next(it) for _ in range(8)]  # two epochs worth
        assert all(x.shape == (4, 4) for x, _ in seen)
        # epoch 2 replays the same (unshuffled) stream
        np.testing.assert_array_equal(seen[0][0], seen[4][0])


class TestStreaming:
    def _gen(self):
        for i in range(20):
            yield (np.full(3, i, dtype=np.float32),
                   np.float32(i % 2))

    def test_streaming_train(self):
        fs = FeatureSet.from_generator(self._gen, 20, streaming=True)
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense
        est = Estimator(
            model=Sequential([Dense(4, name="a"), Dense(2, name="b")]),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.01))
        out = est.train(fs, batch_size=8, epochs=2)
        assert out["iterations"] == 4  # 2 full batches x 2 epochs

    def test_streaming_eval_iterator_tail(self):
        fs = FeatureSet.from_generator(self._gen, 20, streaming=True)
        batches = list(fs.eval_iterator(8))
        assert [b[2] for b in batches] == [8, 8, 4]

    def test_generator_error_surfaces(self):
        def bad():
            yield (np.zeros(3, np.float32), np.float32(0))
            raise RuntimeError("loader exploded")

        fs = FeatureSet.from_generator(bad, 10, streaming=True)
        it = fs.train_iterator(1)
        next(it)
        with pytest.raises(RuntimeError, match="loader exploded"):
            for _ in range(5):
                next(it)


class TestTransformTiers:
    def test_batch_transform_vectorized(self):
        x = np.arange(12, dtype=np.float32).reshape(6, 2)
        fs = FeatureSet.from_ndarrays(x, np.zeros(6, np.float32),
                                      shuffle=False)
        out = fs.transform(BatchLambda(lambda b: b * 2 + 1))
        np.testing.assert_array_equal(np.asarray(out.features), x * 2 + 1)

    def test_batch_chain_stays_batched(self):
        chain = BatchLambda(lambda b: b * 2) >> BatchLambda(lambda b: b + 1)
        assert chain.batched
        x = np.ones((4, 3), np.float32)
        fs = FeatureSet.from_ndarrays(x, shuffle=False)
        out = fs.transform(chain)
        np.testing.assert_array_equal(np.asarray(out.features),
                                      np.full((4, 3), 3.0))

    def test_mixed_chain_falls_back_per_record(self):
        chain = BatchLambda(lambda b: b * 2) >> Lambda(lambda r: r + 1)
        assert not chain.batched
        x = np.ones((4, 3), np.float32)
        out = FeatureSet.from_ndarrays(x, shuffle=False).transform(chain)
        np.testing.assert_array_equal(np.asarray(out.features),
                                      np.full((4, 3), 3.0))

    def test_threaded_transform_matches_serial(self):
        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        fs = FeatureSet.from_ndarrays(x, shuffle=False)
        serial = fs.transform(Lambda(lambda r: r ** 2))
        threaded = fs.transform(Lambda(lambda r: r ** 2), num_workers=4)
        np.testing.assert_array_equal(np.asarray(serial.features),
                                      np.asarray(threaded.features))


class TestStrings:
    def test_from_strings_with_tokenizer(self):
        texts = ["a b", "b c d", "a"]
        vocab = {"a": 1, "b": 2, "c": 3, "d": 4}

        def tok(s):
            ids = [vocab[w] for w in s.split()][:3]
            return np.pad(np.asarray(ids, np.int32), (0, 3 - len(ids)))

        fs = FeatureSet.from_strings(
            texts, np.zeros(3, np.float32), transform=Lambda(tok),
            shuffle=False)
        np.testing.assert_array_equal(
            np.asarray(fs.features),
            [[1, 2, 0], [2, 3, 4], [1, 0, 0]])


class TestNativeWriter:
    def test_native_writer_roundtrips_with_all_readers(self, tmp_path):
        """Records framed by the C++ writer must read back through the
        native reader, the Python reader, AND tensorboard's parser."""
        path = str(tmp_path / "nw.tfrecord")
        payloads = [b"x" * n for n in (0, 1, 7, 8, 9, 1000)]
        with TFRecordWriter(path) as w:
            for p in payloads:
                w.write(p)
        assert [open_tfrecord(path).read(i)
                for i in range(len(payloads))] == payloads
        assert _PythonReader(path, verify_crc=True).read_batch(
            0, len(payloads)) == payloads
        tb = pytest.importorskip("tensorboard")
        del tb
        from tensorboard.backend.event_processing.event_file_loader import (
            RawEventFileLoader)
        assert list(RawEventFileLoader(path).Load()) == payloads

    def test_writer_used_native_path(self, tmp_path):
        if _NativeReader.lib() is None or not hasattr(
                _NativeReader.lib(), "ztw_open"):
            pytest.skip("native writer unavailable")
        w = TFRecordWriter(str(tmp_path / "n.tfrecord"))
        assert w._handle is not None  # really on the C++ path
        w.close()


class TestStreamingKerasSurface:
    """Streaming sets flow through the Keras fit/evaluate surface directly
    (the reference's PythonLoader sets train endlessly and evaluate in one
    bounded pass)."""

    def _gen(self):
        rs = np.random.RandomState(0)
        for _ in range(64):
            x = rs.rand(4).astype(np.float32)
            yield x, np.float32(x.sum() > 2)

    def test_fit_and_evaluate_streaming_positional(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        fs = FeatureSet.from_generator(self._gen, 64, streaming=True)
        m = Sequential([Dense(8, activation="relu"),
                        Dense(2, activation="softmax")])
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(fs, batch_size=16, nb_epoch=1)
        res = m.evaluate(
            FeatureSet.from_generator(self._gen, 64, streaming=True),
            batch_size=16)
        assert "accuracy" in res and 0.0 <= res["accuracy"] <= 1.0

    def test_fit_with_streaming_validation(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.common.triggers import EveryEpoch
        m = Sequential([Dense(8, activation="relu"),
                        Dense(2, activation="softmax")])
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(FeatureSet.from_generator(self._gen, 64, streaming=True),
              batch_size=16, nb_epoch=2,
              validation_data=FeatureSet.from_generator(
                  self._gen, 64, streaming=True),
              validation_trigger=EveryEpoch())
