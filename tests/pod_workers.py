"""Worker targets for PodLauncher tests — run inside spawned processes
(imported by ``analytics_zoo_tpu.cluster.bootstrap`` AFTER
``jax.distributed.initialize``)."""
import json
import os

import numpy as np


def train_worker(workdir: str) -> int:
    """Drive the full multi-process path: context discovery, per-host
    FeatureSet sharding, global-batch division, a real fit, rank-0
    checkpointing."""
    import jax
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Activation, Dense

    ctx = init_tpu_context()
    assert ctx.process_count == 2, ctx.process_count
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2

    n = 32
    # deterministic dataset, identical on every process; FeatureSet takes
    # this process's interleaved rows
    feats = np.arange(n, dtype=np.float32).reshape(n, 1).repeat(4, axis=1)
    labels = (np.arange(n) % 2).astype(np.float32)
    fs = FeatureSet.from_ndarrays(feats, labels, shuffle=False)
    assert fs.size == n // 2, fs.size  # per-host shard

    model = Sequential([Dense(8, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.05))
    ckpt_dir = os.path.join(workdir, "ckpt")
    est.set_checkpoint(ckpt_dir)
    result = est.train(fs, batch_size=8, epochs=2)
    assert result["iterations"] == 8, result["iterations"]  # 4/epoch x 2

    # every process must see the SAME loss (one logical global batch)
    from jax.experimental import multihost_utils
    losses = multihost_utils.process_allgather(
        np.float32(result["loss_history"][-1]))
    assert np.allclose(losses, losses[0]), losses

    with open(os.path.join(workdir, f"done_{ctx.process_index}.json"), "w") as f:
        json.dump({
            "process_index": ctx.process_index,
            "shard_rows": [float(v) for v in np.asarray(fs.features)[:, 0]],
            "final_loss": float(result["loss_history"][-1]),
            "iterations": result["iterations"],
        }, f)
    return 0


def sleep_worker(_workdir: str) -> int:
    """Parks forever — the parent-death guard test's victim (it must be
    reaped by the ppid watch, never by finishing)."""
    import time
    time.sleep(600)
    return 0


def flaky_worker(workdir: str) -> int:
    """Dies on its first attempt, succeeds on the relaunch — the
    ``PodLauncher(restarts=...)`` per-worker retry path."""
    marker = os.path.join(workdir, "flaky_first_attempt")
    if not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("1")
        raise RuntimeError("flaky worker: first attempt dies")
    return 0


def always_failing_worker(_workdir: str) -> int:
    """Fails on every attempt — exhausts the per-worker retry budget."""
    raise RuntimeError("always failing worker")


def elastic_train_worker(workdir: str, total_epochs: int = 4,
                         chaos: str = "") -> int:
    """The elastic-supervisor capstone target: a 4-process data-parallel
    fit that resumes from the newest sealed snapshot on every generation.
    ``chaos`` selects per-generation failures (conditioned on
    ``ZOO_TPU_GENERATION``, which the supervisor bumps per respawn):

    - ``kill``  (generation 0): train to mid-epoch-2, wait for the
      epoch-1 snapshot to seal, then rank 2 SIGKILLs itself — the
      survivors park so the supervisor's restart barrier must reap them.
    - ``hang``  (generation 1): rank 1 freezes its lease via the
      ``cluster.heartbeat`` chaos site while every rank sleeps — a hung
      host with a live pid, detectable only by monotonic lease age.

    The generation that runs fault-free trains to ``total_epochs`` and
    dumps its final params; the test asserts them bit-identical to a
    fault-free run's."""
    import signal as _signal
    import time as _time

    from analytics_zoo_tpu.common import faults
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.common.triggers import MaxIteration, Never
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Activation, Dense

    ctx = init_tpu_context()
    rank = ctx.process_index
    generation = int(os.environ.get("ZOO_TPU_GENERATION", "0"))

    if "hang" in chaos and generation == 1:
        if rank == 1:
            faults.arm("cluster.heartbeat", at=1)  # next beat freezes
        _time.sleep(30.0)  # parked far past lease expiry; the supervisor
        return 1           # kills the whole generation before this runs

    n = 64
    feats = np.arange(n, dtype=np.float32).reshape(n, 1).repeat(4, axis=1) / n
    labels = (np.arange(n) % 2).astype(np.float32)
    fs = FeatureSet.from_ndarrays(feats, labels, shuffle=False)

    model = Sequential([Dense(8, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.05))
    ckpt_dir = os.path.join(workdir, "ckpt")
    # synchronous epoch-boundary snapshots (trigger=Never, save_checkpoint
    # at the barrier): gloo runs ONE collective at a time, so the async
    # writer's orbax barriers must not interleave with training all-reduces
    est.set_checkpoint(ckpt_dir, trigger=Never())
    if est._snapshot_candidates():
        restored = est._restore_latest_valid()
        assert restored is not None, "no snapshot survived seal checks"

    def snap():
        est.save_checkpoint(
            os.path.join(ckpt_dir, f"snapshot-{est.global_step}"))

    iters_per_epoch = 4  # n=64 / global batch 16

    if "kill" in chaos and generation == 0:
        # epoch 1 + its sealed snapshot, then die mid-epoch-2: the two
        # post-snapshot iterations must be rolled back by the restart
        est.train(fs, batch_size=16, end_trigger=MaxIteration(4))
        snap()
        est.train(fs, batch_size=16, end_trigger=MaxIteration(6))
        if rank == 2:
            os.kill(os.getpid(), _signal.SIGKILL)
        _time.sleep(30.0)  # survivors park; the restart barrier reaps us
        return 1

    for target in range(iters_per_epoch, total_epochs * iters_per_epoch + 1,
                        iters_per_epoch):
        if est.global_step < target:
            est.train(fs, batch_size=16, end_trigger=MaxIteration(target))
            snap()
    flat = {}
    for lname, params in est.get_params().items():
        for key, val in params.items():
            flat[f"{lname}.{key}"] = np.asarray(val)
    np.savez(os.path.join(workdir, f"params_rank{rank}.npz"), **flat)
    return 0


def fleet_predict_factory(root: str, name: str):
    """Fleet-instance factory for FleetSupervisor tests: a one-shot
    ClusterServing on its private spool whose host stall dominates the
    batch, so router demand (and therefore scale-out) is measurable on
    any machine — the bench's ``_fleet_server_proc`` trick."""
    import time as _time

    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.fleet import instance_queue

    def fwd(p, x):
        return x.reshape(x.shape[0], -1).mean(1, keepdims=True)

    im = InferenceModel().load_jax(fwd, {})

    class StallModel:
        def predict(self, x):
            _time.sleep(0.25)
            return im.predict(x)

        def predict_async(self, x):
            f = im.predict_async(x)

            def fetch():
                _time.sleep(0.25)
                return f()
            return fetch

    cfg = ServingConfig(data_src=f"dir://{root}/inst/{name}",
                        batch_size=4, batch_wait_ms=5,
                        input_dtype="float32",
                        health_path=os.path.join(root,
                                                 f"{name}.health.json"),
                        health_interval_s=0.1)
    return ClusterServing(cfg, model=StallModel(),
                          queue=instance_queue(root, name))


def fleet_generative_factory(root: str, name: str):
    """Generative fleet-instance factory: every instance constructs the
    SAME deterministic toy LM (seeded init + seeded fit data), so a
    stream handed off mid-decode must continue token-identically on any
    adopter."""
    from analytics_zoo_tpu.capture.lm import TransformerLM
    from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
    from analytics_zoo_tpu.serving.fleet import instance_queue

    rs = np.random.RandomState(0)
    lm = TransformerLM(vocab_size=16, hidden=16, n_block=2, n_head=2,
                       max_len=32, seed=0)
    lm.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
    cfg = ServingConfig(data_src=root, slots=2, max_new_tokens=10,
                        stream_interval=2,
                        health_path=os.path.join(root,
                                                 f"{name}.health.json"),
                        health_interval_s=0.05)
    return GenerativeServing(cfg, lm, queue=instance_queue(root, name))


def failing_worker(_workdir: str) -> int:
    """Rank 1 dies before the collective; rank 0 would hang in it forever —
    the launcher's failure detection must kill the pod."""
    import jax
    if jax.process_index() == 1:
        raise RuntimeError("injected worker failure")
    import time
    from jax.experimental import multihost_utils
    multihost_utils.process_allgather(np.float32(1.0))  # blocks forever
    time.sleep(600)
    return 0


def exact_eval_worker(workdir: str) -> int:
    """Per-example masked eval across 2 hosts with RAGGED shards (11 vs 5
    rows, neither divisible by the batch) must equal the single-process
    loss over the concatenated data EXACTLY — the property the batch-mean
    weighting could not give (O(pad/batch) bias)."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import optimizers

    ctx = init_tpu_context()
    assert ctx.process_count == 2

    def direct_loss(params, state, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred[:, 0] - y) ** 2), state

    def per_example(params, state, rng, x, y):
        pred = x @ params["w"]
        return (pred[:, 0] - y) ** 2

    n = 11 if ctx.process_index == 0 else 5
    rs = np.random.RandomState(ctx.process_index)
    x = rs.randn(n, 3).astype(np.float32)
    y = rs.randn(n).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
    est = Estimator(model=None, loss_fn=None,
                    optimizer=optimizers.SGD(0.1),
                    direct_loss_fn=direct_loss,
                    direct_eval_per_example_fn=per_example)
    w = np.ones((3, 1), np.float32)
    est.params = jax.device_put({"w": jnp.asarray(w)})
    est.model_state = {}
    est._state_resolved = True
    result = est.evaluate(fs, batch_size=8)  # local_batch 4: padded tails

    # ground truth: plain numpy over BOTH hosts' data (identical on each
    # host because the seeds are the process indices)
    ref_total, ref_n = 0.0, 0
    for pi, nn in ((0, 11), (1, 5)):
        rs_ref = np.random.RandomState(pi)
        xr = rs_ref.randn(nn, 3).astype(np.float32)
        yr = rs_ref.randn(nn).astype(np.float32)
        ref_total += float(np.sum(((xr @ w)[:, 0] - yr) ** 2))
        ref_n += nn
    expect = ref_total / ref_n
    assert abs(result["loss"] - expect) < 1e-5, (result["loss"], expect)
    with open(os.path.join(workdir, f"exact_{ctx.process_index}.json"),
              "w") as f:
        json.dump({"loss": float(result["loss"]), "expect": expect}, f)
    return 0


def direct_eval_tail_worker(workdir: str) -> int:
    """Multi-host direct-loss eval must COUNT tail records (previously
    dropped): 2 hosts x 2 devices, per-host val shard of 11 rows with
    local_batch 4 -> 3 padded steps, global weight 22."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import optimizers

    ctx = init_tpu_context()
    assert ctx.process_count == 2

    def direct_loss(params, state, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred[:, 0] - y) ** 2), state

    # UNEVEN shards (11 vs 5 rows), neither divisible by the local batch:
    # host 0 has more batches than host 1, so host 1 exercises the
    # StopIteration re-feed (valid=0) branch while host 0 still has data
    n = 11 if ctx.process_index == 0 else 5
    rs = np.random.RandomState(ctx.process_index)
    x = rs.randn(n, 3).astype(np.float32)
    y = rs.randn(n).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
    est = Estimator(model=None, loss_fn=None,
                    optimizer=optimizers.SGD(0.1),
                    direct_loss_fn=direct_loss)
    est.params = jax.device_put({"w": jnp.ones((3, 1), jnp.float32)})
    est.model_state = {}
    est._state_resolved = True
    result = est.evaluate(fs, batch_size=8)  # local_batch 4 after division
    assert np.isfinite(result["loss"])
    with open(os.path.join(workdir, f"eval_{ctx.process_index}.json"),
              "w") as f:
        json.dump({"loss": float(result["loss"])}, f)
    return 0
