"""Worker targets for PodLauncher tests — run inside spawned processes
(imported by ``analytics_zoo_tpu.cluster.bootstrap`` AFTER
``jax.distributed.initialize``)."""
import json
import os

import numpy as np


def train_worker(workdir: str) -> int:
    """Drive the full multi-process path: context discovery, per-host
    FeatureSet sharding, global-batch division, a real fit, rank-0
    checkpointing."""
    import jax
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Activation, Dense

    ctx = init_tpu_context()
    assert ctx.process_count == 2, ctx.process_count
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2

    n = 32
    # deterministic dataset, identical on every process; FeatureSet takes
    # this process's interleaved rows
    feats = np.arange(n, dtype=np.float32).reshape(n, 1).repeat(4, axis=1)
    labels = (np.arange(n) % 2).astype(np.float32)
    fs = FeatureSet.from_ndarrays(feats, labels, shuffle=False)
    assert fs.size == n // 2, fs.size  # per-host shard

    model = Sequential([Dense(8, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.05))
    ckpt_dir = os.path.join(workdir, "ckpt")
    est.set_checkpoint(ckpt_dir)
    result = est.train(fs, batch_size=8, epochs=2)
    assert result["iterations"] == 8, result["iterations"]  # 4/epoch x 2

    # every process must see the SAME loss (one logical global batch)
    from jax.experimental import multihost_utils
    losses = multihost_utils.process_allgather(
        np.float32(result["loss_history"][-1]))
    assert np.allclose(losses, losses[0]), losses

    with open(os.path.join(workdir, f"done_{ctx.process_index}.json"), "w") as f:
        json.dump({
            "process_index": ctx.process_index,
            "shard_rows": [float(v) for v in np.asarray(fs.features)[:, 0]],
            "final_loss": float(result["loss_history"][-1]),
            "iterations": result["iterations"],
        }, f)
    return 0


def failing_worker(_workdir: str) -> int:
    """Rank 1 dies before the collective; rank 0 would hang in it forever —
    the launcher's failure detection must kill the pod."""
    import jax
    if jax.process_index() == 1:
        raise RuntimeError("injected worker failure")
    import time
    from jax.experimental import multihost_utils
    multihost_utils.process_allgather(np.float32(1.0))  # blocks forever
    time.sleep(600)
    return 0


def exact_eval_worker(workdir: str) -> int:
    """Per-example masked eval across 2 hosts with RAGGED shards (11 vs 5
    rows, neither divisible by the batch) must equal the single-process
    loss over the concatenated data EXACTLY — the property the batch-mean
    weighting could not give (O(pad/batch) bias)."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import optimizers

    ctx = init_tpu_context()
    assert ctx.process_count == 2

    def direct_loss(params, state, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred[:, 0] - y) ** 2), state

    def per_example(params, state, rng, x, y):
        pred = x @ params["w"]
        return (pred[:, 0] - y) ** 2

    n = 11 if ctx.process_index == 0 else 5
    rs = np.random.RandomState(ctx.process_index)
    x = rs.randn(n, 3).astype(np.float32)
    y = rs.randn(n).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
    est = Estimator(model=None, loss_fn=None,
                    optimizer=optimizers.SGD(0.1),
                    direct_loss_fn=direct_loss,
                    direct_eval_per_example_fn=per_example)
    w = np.ones((3, 1), np.float32)
    est.params = jax.device_put({"w": jnp.asarray(w)})
    est.model_state = {}
    est._state_resolved = True
    result = est.evaluate(fs, batch_size=8)  # local_batch 4: padded tails

    # ground truth: plain numpy over BOTH hosts' data (identical on each
    # host because the seeds are the process indices)
    ref_total, ref_n = 0.0, 0
    for pi, nn in ((0, 11), (1, 5)):
        rs_ref = np.random.RandomState(pi)
        xr = rs_ref.randn(nn, 3).astype(np.float32)
        yr = rs_ref.randn(nn).astype(np.float32)
        ref_total += float(np.sum(((xr @ w)[:, 0] - yr) ** 2))
        ref_n += nn
    expect = ref_total / ref_n
    assert abs(result["loss"] - expect) < 1e-5, (result["loss"], expect)
    with open(os.path.join(workdir, f"exact_{ctx.process_index}.json"),
              "w") as f:
        json.dump({"loss": float(result["loss"]), "expect": expect}, f)
    return 0


def direct_eval_tail_worker(workdir: str) -> int:
    """Multi-host direct-loss eval must COUNT tail records (previously
    dropped): 2 hosts x 2 devices, per-host val shard of 11 rows with
    local_batch 4 -> 3 padded steps, global weight 22."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import optimizers

    ctx = init_tpu_context()
    assert ctx.process_count == 2

    def direct_loss(params, state, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred[:, 0] - y) ** 2), state

    # UNEVEN shards (11 vs 5 rows), neither divisible by the local batch:
    # host 0 has more batches than host 1, so host 1 exercises the
    # StopIteration re-feed (valid=0) branch while host 0 still has data
    n = 11 if ctx.process_index == 0 else 5
    rs = np.random.RandomState(ctx.process_index)
    x = rs.randn(n, 3).astype(np.float32)
    y = rs.randn(n).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
    est = Estimator(model=None, loss_fn=None,
                    optimizer=optimizers.SGD(0.1),
                    direct_loss_fn=direct_loss)
    est.params = jax.device_put({"w": jnp.ones((3, 1), jnp.float32)})
    est.model_state = {}
    est._state_resolved = True
    result = est.evaluate(fs, batch_size=8)  # local_batch 4 after division
    assert np.isfinite(result["loss"])
    with open(os.path.join(workdir, f"eval_{ctx.process_index}.json"),
              "w") as f:
        json.dump({"loss": float(result["loss"])}, f)
    return 0
