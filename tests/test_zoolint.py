"""Consolidated zoolint suite: one session-scoped run of every pass over
the repo (self-clean assertion, including suppression hygiene), seeded
violations per new pass on throwaway project trees, the suppression
machinery end to end, the discovery-vs-legacy acceptance diff for the
jit-boundary pass, and the CLI contract.

The ported passes (hot-path-sync / metric-names / fault-sites) keep their
seeded fixtures in their legacy test files, which now load the shared
``analytics_zoo_tpu.lint`` modules through the ``scripts/check_*.py``
shims — so every entry point in the whole suite shares ONE parsed AST
index per process.
"""
import importlib.util
import os

import pytest

from analytics_zoo_tpu.lint import core, runner
from analytics_zoo_tpu.lint.core import (Finding, Project, run_passes,
                                         UNUSED_SUPPRESSION_ID)
from analytics_zoo_tpu.lint.passes import hot_path, jit_boundary

REPO_ROOT = core.REPO_ROOT

ALL_PASS_IDS = {"config-keys", "event-names", "fault-sites",
                "hot-path-sync", "jit-host-sync", "metric-names",
                "monotonic-clock", "retry-discipline"}


def _seed(tmp_path, files):
    """A throwaway project tree: ``<tmp>/analytics_zoo_tpu/<name>``."""
    pkg = tmp_path / "analytics_zoo_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, text in files.items():
        (pkg / name).write_text(text)
    return Project(root=str(tmp_path))


# -- the repo itself ----------------------------------------------------------

@pytest.fixture(scope="session")
def repo_result():
    """All passes, once per session, over the shared project index."""
    return run_passes(core.get_project())


@pytest.fixture(scope="session")
def discovery():
    """One jit-boundary discovery over the repo, shared by the tests that
    inspect it (the pass itself re-discovers inside repo_result)."""
    return jit_boundary.discover(core.get_project())


def test_repo_is_zoolint_clean(repo_result):
    assert repo_result.clean, "\n" + "\n".join(
        f.text() for f in repo_result.findings)


def test_every_pass_ran(repo_result):
    assert set(repo_result.pass_ids) == ALL_PASS_IDS


def test_live_waivers_actually_engage(repo_result):
    """The repo carries deliberate suppressions (profiling fence, gated
    loss sync, wall_clock, ...); each must have matched a real finding —
    hygiene already fails stale ones, this guards the other direction."""
    assert repo_result.suppressed, (
        "expected live suppressions to waive real findings")
    assert {f.pass_id for f in repo_result.suppressed} <= ALL_PASS_IDS


def test_shared_parse_cache_is_one_per_process():
    p = core.get_project()
    assert core.get_project() is p
    est = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "estimator",
                       "estimator.py")
    assert p.source(est) is p.source(est)


def test_legacy_shims_share_the_lint_modules():
    """scripts/check_hot_path_syncs.py must be a shim over the shared
    pass module — same function objects, same project cache."""
    script = os.path.join(REPO_ROOT, "scripts", "check_hot_path_syncs.py")
    spec = importlib.util.spec_from_file_location("_shim_probe", script)
    shim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(shim)
    assert shim.check is hot_path.check
    assert shim._CHECKS is hot_path._CHECKS


# -- seeded violations: jit-host-sync ----------------------------------------

def test_jit_host_sync_catches_seeded_violations(tmp_path):
    proj = _seed(tmp_path, {"model.py": (
        "import time\n"
        "\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    t = time.time()\n"
        "    v = float(x.sum())\n"
        "    while v > 0:\n"
        "        v -= 1.0\n"
        "    return _inner(x) + t\n"
        "\n"
        "\n"
        "def _inner(x):\n"
        "    total = 0.0\n"
        "    for i in range(x.shape[0]):\n"
        "        total = total + x[i]\n"
        "    return total\n")})
    res = run_passes(proj, ids=["jit-host-sync"])
    by_line = {f.line: f.message for f in res.findings}
    assert "host clock read time.time()" in by_line[9]
    assert "float()" in by_line[10]
    assert "while loop" in by_line[11]
    # _inner is only reachable FROM the jitted root: transitive discovery
    assert "per-element Python loop" in by_line[18]


def test_jit_host_sync_clean_module_stays_clean(tmp_path):
    proj = _seed(tmp_path, {"model.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def step(params, x):\n"
        "    for name, p in sorted(params.items()):\n"
        "        x = x + p\n"
        "    return jnp.exp(x)\n")})
    res = run_passes(proj, ids=["jit-host-sync"])
    assert res.clean, "\n".join(f.text() for f in res.findings)


# -- seeded violations: config-keys ------------------------------------------

def test_config_keys_catches_seeded_drift(tmp_path):
    proj = _seed(tmp_path, {"conf.py": (
        "def global_config():\n"
        "    return None\n"
        "\n"
        "\n"
        "cfg = global_config()\n"
        "cfg.register('orphan.key', 1, 'registered, never read')\n"
        "cfg.register('BadKey', 2, 'breaks the convention')\n"
        "cfg.get('never.registered')\n")})
    res = run_passes(proj, ids=["config-keys"])
    msgs = "\n".join(f.message for f in res.findings)
    assert "'orphan.key' is registered but never read" in msgs
    assert "'BadKey' breaks the dotted 'section.name' convention" in msgs
    assert "'never.registered' read at" in msgs
    assert "no row in docs/configuration.md" in msgs


def test_config_keys_ignores_plain_dict_gets(tmp_path):
    """Receivers are resolved, not guessed: ``d.get("x.y")`` on an
    ordinary dict never counts as a config read."""
    proj = _seed(tmp_path, {"conf.py": (
        "d = {}\n"
        "v = d.get('looks.like_a_key')\n")})
    res = run_passes(proj, ids=["config-keys"])
    assert res.clean, "\n".join(f.text() for f in res.findings)


# -- seeded violations: monotonic-clock --------------------------------------

def test_monotonic_clock_catches_seeded_wall_clock(tmp_path):
    proj = _seed(tmp_path, {"sched.py": (
        "import time\n"
        "\n"
        "\n"
        "def wait():\n"
        "    deadline = time.time() + 5\n"
        "    lease = time.time_ns()\n"
        "    t0 = time.monotonic()\n"
        "    return deadline, lease, t0\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert [f.line for f in res.findings] == [5, 6]
    assert all("wall-clock" in f.message for f in res.findings)


def test_monotonic_clock_catches_mixed_domain_arithmetic(tmp_path):
    """The lease/heartbeat bug class: one expression subtracting (or
    comparing) a monotonic read against a wall_clock() stamp is flagged
    even though each read is legitimate on its own; same-domain
    arithmetic on either clock stays clean."""
    proj = _seed(tmp_path, {"lease.py": (
        "import time\n"
        "from analytics_zoo_tpu.common.utils import wall_clock\n"
        "\n"
        "\n"
        "def age_wrong():\n"
        "    return time.monotonic() - wall_clock()\n"
        "\n"
        "\n"
        "def expired_wrong(stamp_s):\n"
        "    return wall_clock() + stamp_s < time.perf_counter()\n"
        "\n"
        "\n"
        "def age_right(observed_mono):\n"
        "    return time.monotonic() - observed_mono\n"
        "\n"
        "\n"
        "def stamp_right():\n"
        "    return wall_clock() + 30.0\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert [f.line for f in res.findings] == [6, 10]
    assert all("mixes monotonic- and wall-clock" in f.message
               for f in res.findings)


# -- seeded violations: retry-discipline -------------------------------------

def test_retry_discipline_catches_seeded_storms(tmp_path):
    proj = _seed(tmp_path, {"rpc.py": (
        "import time\n"
        "\n"
        "\n"
        "def poll(fetch):\n"
        "    for _ in range(5):\n"
        "        try:\n"
        "            return fetch()\n"
        "        except OSError:\n"
        "            time.sleep(0.05)\n"
        "    raise TimeoutError\n"
        "\n"
        "\n"
        "def forever(fetch):\n"
        "    while True:\n"
        "        try:\n"
        "            fetch()\n"
        "        except OSError:\n"
        "            pass\n")})
    res = run_passes(proj, ids=["retry-discipline"])
    by_line = {f.line: f.message for f in res.findings}
    assert "fixed (unjittered) retry delay" in by_line[9]
    assert "unbounded `while True` retry loop" in by_line[14]
    assert len(res.findings) == 2


def test_retry_discipline_accepts_jittered_bounded_retries(tmp_path):
    """The reference shape — computed full-jitter backoff inside a
    bounded loop, and a ``while True`` that escapes via return/raise —
    stays clean; so does a sleep whose delay is computed, not constant."""
    proj = _seed(tmp_path, {"rpc.py": (
        "import random\n"
        "import time\n"
        "\n"
        "\n"
        "def call(fetch, attempts=3, base=0.05):\n"
        "    for attempt in range(attempts):\n"
        "        try:\n"
        "            return fetch()\n"
        "        except OSError:\n"
        "            time.sleep(random.uniform(0.0, base * 2 ** attempt))\n"
        "    raise TimeoutError\n"
        "\n"
        "\n"
        "def drain(fetch):\n"
        "    while True:\n"
        "        try:\n"
        "            return fetch()\n"
        "        except KeyboardInterrupt:\n"
        "            raise\n")})
    res = run_passes(proj, ids=["retry-discipline"])
    assert res.clean, "\n".join(f.text() for f in res.findings)


# -- seeded violations: event-names ------------------------------------------

def test_event_names_catches_seeded_violations(tmp_path):
    """Every rule of the event-type contract fires on a seeded tree:
    non-literal name, duplicate registration, convention breakage, and
    (with no docs in the tree) undocumented types."""
    proj = _seed(tmp_path, {"emitter.py": (
        "from analytics_zoo_tpu.ops import events\n"
        "\n"
        "_NAME = 'ops' + '.computed'\n"
        "_E_DYN = events.event_type(_NAME, 'computed name')\n"
        "_E_A = events.event_type('serving.thing', 'owned here')\n"
        "_E_B = events.event_type('serving.thing', 'owned here too')\n"
        "_E_BAD = events.event_type('NoDotsOrCase', 'breaks convention')\n")})
    res = run_passes(proj, ids=["event-names"])
    msgs = "\n".join(f.message for f in res.findings)
    assert "event type name must be one string literal" in msgs
    assert "'serving.thing' registered at 2 sites" in msgs
    assert "'NoDotsOrCase'" in msgs and "subsystem.noun" in msgs
    assert "registered but undocumented" in msgs


def test_event_names_resolves_receivers_not_strings(tmp_path):
    """Only events-module aliases count: ``event_type`` on an unrelated
    object is not a registration, and an ``ops_events`` alias is."""
    proj = _seed(tmp_path, {"emitter.py": (
        "from analytics_zoo_tpu.ops import events as ops_events\n"
        "\n"
        "\n"
        "class _Factory:\n"
        "    def event_type(self, name, help=''):\n"
        "        return name\n"
        "\n"
        "\n"
        "factory = _Factory()\n"
        "factory.event_type('not.a_registration')\n"
        "_E = ops_events.event_type('fleet.something', 'real one')\n")})
    import analytics_zoo_tpu.lint.passes.event_names as event_names
    regs, bad = event_names.registrations(proj)
    assert bad == []
    assert set(regs) == {"fleet.something"}


def test_event_names_scanner_sees_known_transitions():
    """The repo scanner must find the load-bearing event types — a
    scanner matching nothing would always pass."""
    import analytics_zoo_tpu.lint.passes.event_names as event_names
    regs, bad = event_names.registrations()
    assert bad == []
    for expected in ("serving.brownout_rung", "fleet.breaker",
                     "cluster.restart", "ops.alert", "ops.incident",
                     "fault.fired"):
        assert expected in regs, expected


def test_event_names_documented_set_is_closed():
    """docs/observability.md's event table covers every registered
    type, and the doc mentions no phantom checks (lint self-clean rides
    repo_result; this pins the docs half specifically)."""
    import analytics_zoo_tpu.lint.passes.event_names as event_names
    assert event_names.undocumented(event_names.registrations()[0]) == []


def test_event_names_matches_runtime_registry():
    """Source-scanned types must match runtime registration once the
    emitting modules are imported (fault.fired registers lazily on first
    fire, so it is exempt from the runtime side)."""
    import analytics_zoo_tpu.cluster.supervisor  # noqa: F401
    import analytics_zoo_tpu.online.promote  # noqa: F401
    import analytics_zoo_tpu.serving.fleet  # noqa: F401
    import analytics_zoo_tpu.serving.server  # noqa: F401
    import analytics_zoo_tpu.lint.passes.event_names as event_names
    from analytics_zoo_tpu.ops import events, incident  # noqa: F401

    runtime = set(events.registered_types())
    scanned = set(event_names.registrations()[0])
    missing = scanned - runtime - {"fault.fired"}
    assert not missing, (
        f"scanned event_type registrations never ran (dead module-level "
        f"code?): {sorted(missing)}")


# -- suppression machinery ----------------------------------------------------

def test_suppression_same_line(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "import time\n"
        "t = time.time()  # zoolint: disable=monotonic-clock — test stamp\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert res.clean and len(res.suppressed) == 1


def test_suppression_standalone_line_above(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "import time\n"
        "# zoolint: disable=monotonic-clock — cross-process stamp\n"
        "t = time.time()\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert res.clean and len(res.suppressed) == 1


def test_suppression_file_level(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "# zoolint: disable-file=monotonic-clock — wall-clock glue module\n"
        "import time\n"
        "a = time.time()\n"
        "b = time.time_ns()\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert res.clean and len(res.suppressed) == 2


def test_stale_waiver_is_flagged(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "# zoolint: disable=monotonic-clock — nothing here anymore\n"
        "x = 1\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert [f.pass_id for f in res.findings] == [UNUSED_SUPPRESSION_ID]
    assert "unused suppression" in res.findings[0].message


def test_waiver_without_justification_is_flagged(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "import time\n"
        "t = time.time()  # zoolint: disable=monotonic-clock\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    # the finding is waived, but the bare waiver itself is a finding
    assert len(res.suppressed) == 1
    assert [f.pass_id for f in res.findings] == [UNUSED_SUPPRESSION_ID]
    assert "no justification" in res.findings[0].message


def test_waiver_naming_unknown_pass_is_flagged(tmp_path):
    proj = _seed(tmp_path, {"s.py": (
        "x = 1  # zoolint: disable=not-a-pass — typo'd id\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert [f.pass_id for f in res.findings] == [UNUSED_SUPPRESSION_ID]
    assert "unknown pass" in res.findings[0].message


def test_waiver_for_unselected_pass_not_reported_stale(tmp_path):
    """Running a pass subset must not flag waivers belonging to passes
    that did not run — they had no chance to match."""
    proj = _seed(tmp_path, {"s.py": (
        "# zoolint: disable=jit-host-sync — belongs to a pass not run here\n"
        "x = 1\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert res.clean, "\n".join(f.text() for f in res.findings)


def test_waiver_inside_string_literal_is_inert(tmp_path):
    """Suppressions are comment tokens; a fixture string seeding one must
    not waive anything."""
    proj = _seed(tmp_path, {"s.py": (
        "import time\n"
        'doc = "t = time.time()  # zoolint: disable=monotonic-clock — no"\n'
        "t = time.time()\n")})
    res = run_passes(proj, ids=["monotonic-clock"])
    assert [f.line for f in res.findings] == [3]
    assert not res.suppressed


# -- acceptance: discovery vs the legacy hand-listed table -------------------

#: the legacy rows that are host-side staging (data-plane iterator cores,
#: batch gathers, the DeviceFeed producer) or one-shot allocation
#: initializers — host code by design, so trace/dispatch discovery cannot
#: and should not find them; they stay policed via the hot-path table seed.
HOST_STAGING_ROWS = {
    "_cached_batches", "_gather", "_produce", "_transformed_batches",
    "eval_iterator", "init_paged_pool", "init_slot_cache",
    "masked_eval_batches", "train_iterator",
    # XShard ETL engine bodies: host-side numpy/pandas shuffle kernels in
    # forked workers — never traced, so jit discovery can't see them
    "_bucket_order", "_exchange_task", "_filter_task", "_gather_dest",
    "_groupby_task", "_handoff_task", "_join_match", "_join_task",
    "_mix64", "_stack_into", "_take_cols_into",
    # fleet router placement scoring: host-side numpy over instance-gauge
    # arrays — never traced, so jit discovery can't see it
    "_score_instances",
}

#: fused embedding kernel rows (ops/embedding_kernels.py): the pallas
#: bodies only trace inside ``pl.pallas_call`` (not a discovery root —
#: the hot-path table polices them instead), and the wrappers are
#: reached through the config-gated ``_fused_kernels()`` module handle,
#: an indirection static call-graph resolution cannot follow. Sourced
#: from the pass's own tuples so the sets cannot drift apart.
EMBED_KERNEL_ROWS = (set(hot_path.EMBED_KERNEL_BODIES)
                     | set(hot_path.EMBED_KERNEL_WRAPPERS))


def test_jit_discovery_covers_legacy_table(discovery):
    disc = discovery
    legacy = hot_path.policed_functions()
    # the full policed surface (auto + seeded) covers every legacy row
    missing = legacy - disc.discovered_names()
    assert not missing, f"policed surface lost legacy rows: {sorted(missing)}"
    # every DEVICE-side legacy row is discovered automatically — no seed:
    # embedding shard_map bodies, slot/paged KV ops, decode/LM/server jits
    auto = disc.traced_names() | disc.dispatch_names()
    assert HOST_STAGING_ROWS <= legacy, "exemption list drifted from table"
    assert EMBED_KERNEL_ROWS <= legacy, "exemption list drifted from table"
    not_auto = (legacy - HOST_STAGING_ROWS - EMBED_KERNEL_ROWS) - auto
    assert not not_auto, (
        f"device-side legacy rows no longer auto-discovered: "
        f"{sorted(not_auto)}")


def test_discovery_traverses_the_package(discovery):
    """Discovery must keep finding a real traced surface — a resolver
    regression that silently found nothing would pass every clean test."""
    disc = discovery
    assert len(disc.traced) >= 100, len(disc.traced)
    assert len(disc.dispatch) >= 15, len(disc.dispatch)
    for name in ("_lookup_body", "paged_attention", "spec_accept_greedy"):
        assert name in disc.traced_names(), name


# -- CLI ----------------------------------------------------------------------

def test_cli_list_exits_zero(capsys):
    assert runner.main(["--list"]) == 0
    out = capsys.readouterr().out
    for pid in ALL_PASS_IDS:
        assert pid in out


def test_cli_clean_repo_exits_zero(capsys):
    """A pass subset keeps this cheap; full-repo cleanliness across ALL
    passes is repo_result's session-scoped assertion."""
    assert runner.main(["--pass", "hot-path-sync",
                        "--pass", "monotonic-clock"]) == 0
    err = capsys.readouterr().err
    assert "zoolint: clean" in err


def test_cli_unknown_pass_exits_two(capsys):
    assert runner.main(["--pass", "bogus"]) == 2
    assert "unknown pass id" in capsys.readouterr().err


def test_cli_findings_exit_one_and_github_format(tmp_path, monkeypatch,
                                                 capsys):
    proj = _seed(tmp_path, {"s.py": "import time\nt = time.time()\n"})
    monkeypatch.setattr(core, "_project", proj)
    assert runner.main(["--pass", "monotonic-clock"]) == 1
    out = capsys.readouterr().out
    assert "[monotonic-clock]" in out
    assert runner.main(["--pass", "monotonic-clock",
                        "--format", "github"]) == 1
    out = capsys.readouterr().out
    assert "::error file=" in out and "title=zoolint/monotonic-clock" in out


def test_finding_formats():
    f = Finding(os.path.join(REPO_ROOT, "x.py"), 3, "demo",
                "50% of\nthis", "do the fix")
    assert f.text() == "x.py:3: [demo] 50% of\nthis  [fix: do the fix]"
    g = f.github()
    assert g.startswith("::error file=x.py,line=3,title=zoolint/demo::")
    assert "50%25 of%0Athis" in g
