"""Tests for the core runtime: config registry, context/mesh, triggers."""
import os

import pytest

from analytics_zoo_tpu.common.config import Config
from analytics_zoo_tpu.common.context import init_tpu_context, reset_context
from analytics_zoo_tpu.common.triggers import (
    And, EveryEpoch, MaxEpoch, MaxIteration, MaxScore, MinLoss, Or,
    SeveralIteration, TrainingState)


class TestConfig:
    def test_default_and_override(self):
        cfg = Config()
        cfg.register("foo.bar", 3, "test flag")
        assert cfg.get("foo.bar") == 3
        cfg.set("foo.bar", 7)
        assert cfg.get("foo.bar") == 7
        cfg.unset("foo.bar")
        assert cfg.get("foo.bar") == 3

    def test_env_layer(self, monkeypatch):
        cfg = Config()
        cfg.register("retry.times", 5)
        monkeypatch.setenv("ZOO_TPU_RETRY_TIMES", "9")
        assert cfg.get("retry.times") == 9
        # programmatic override beats env
        cfg.set("retry.times", 2)
        assert cfg.get("retry.times") == 2

    def test_bool_parsing(self, monkeypatch):
        cfg = Config()
        cfg.register("flagb", False)
        monkeypatch.setenv("ZOO_TPU_FLAGB", "true")
        assert cfg.get("flagb") is True

    def test_file_layer(self, tmp_path):
        cfg = Config()
        cfg.register("a", 1)
        p = tmp_path / "conf.json"
        p.write_text('{"a": 42, "extra": "x"}')
        cfg.load_file(str(p))
        assert cfg.get("a") == 42
        assert cfg.get("extra") == "x"


class TestContext:
    def test_mesh_discovery(self, ctx):
        assert ctx.num_devices == 8
        assert ctx.mesh.axis_names == ("data",)
        assert ctx.local_batch(64) == 64  # single process

    def test_2d_mesh(self):
        reset_context()
        c = init_tpu_context(mesh_shape=(4, 2), force_reinit=True)
        assert c.mesh.devices.shape == (4, 2)
        assert c.mesh.axis_names == ("data", "model")
        reset_context()

    def test_bad_mesh_shape(self):
        reset_context()
        with pytest.raises(ValueError):
            init_tpu_context(mesh_shape=(3,), force_reinit=True)
        reset_context()


class TestTriggers:
    def test_every_epoch(self):
        t = EveryEpoch()
        assert not t(TrainingState(epoch=1, epoch_finished=False))
        assert t(TrainingState(epoch=1, epoch_finished=True))

    def test_several_iteration(self):
        t = SeveralIteration(3)
        fired = [i for i in range(1, 10) if t(TrainingState(iteration=i))]
        assert fired == [3, 6, 9]

    def test_several_iteration_dispatch_width(self):
        # multi-step dispatch: the counter advances by width per check;
        # non-aligned intervals fire at the first check past the boundary
        # (quantized, not skipped)
        t = SeveralIteration(100)
        checks = range(8, 1000, 8)  # iteration after each 8-step dispatch
        fired = [i for i in checks
                 if t(TrainingState(iteration=i, dispatch_width=8))]
        assert fired == [104, 200, 304, 400, 504, 600, 704, 800, 904]
        # aligned interval unchanged: every 96 with width 8
        t2 = SeveralIteration(96)
        fired2 = [i for i in checks
                  if t2(TrainingState(iteration=i, dispatch_width=8))]
        assert fired2 == [96, 192, 288, 384, 480, 576, 672, 768, 864, 960]
        # width never makes it fire twice for one boundary
        assert len(fired) == len(set(i // 100 for i in fired))

    def test_max_epoch_iteration(self):
        assert MaxEpoch(2)(TrainingState(epoch=3))
        assert not MaxEpoch(2)(TrainingState(epoch=2))
        assert MaxIteration(5)(TrainingState(iteration=5))

    def test_score_loss(self):
        assert MaxScore(0.9)(TrainingState(score=0.95))
        assert not MaxScore(0.9)(TrainingState(score=None))
        assert MinLoss(0.1)(TrainingState(loss=0.05))

    def test_compose(self):
        t = And(SeveralIteration(2), MinLoss(0.5))
        assert t(TrainingState(iteration=4, loss=0.4))
        assert not t(TrainingState(iteration=3, loss=0.4))
        t2 = Or(MaxEpoch(1), MaxIteration(100))
        assert t2(TrainingState(epoch=2, iteration=0))


class TestTriggersSliced:
    def test_every_epoch_with_slices(self):
        t = EveryEpoch()
        # 4 slices per epoch: fires only when the finished slice closes the epoch
        fired = [s for s in range(1, 9)
                 if t(TrainingState(num_slices=4, slice_index=s, epoch_finished=True))]
        assert fired == [4, 8]


class TestSummary:
    def test_sequential_summary(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        m = Sequential([Dense(64, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
        text = m.summary(input_shape=(20,), print_fn=None)
        assert "d1 (Dense)" in text and "(None, 64)" in text
        assert "Total params: 1,474" in text

    def test_model_summary_counts_frozen(self):
        import jax
        from analytics_zoo_tpu.keras import Input, Model
        from analytics_zoo_tpu.keras.layers import Dense
        x = Input(shape=(4,))
        h = Dense(8, name="backbone")(x)
        y = Dense(2, name="head")(h)
        model = Model(x, y)
        model.freeze(["backbone"])
        text = model.summary(print_fn=None)
        assert "(frozen)" in text
        assert "trainable: 18" in text  # head: 8*2+2


class TestChromeTrace:
    def test_trace_records_time_it_spans(self, tmp_path):
        import json
        from analytics_zoo_tpu.common.utils import time_it
        from analytics_zoo_tpu.utils.trace import trace

        path = str(tmp_path / "trace.json")
        with trace(path):
            with time_it("phase_a"):
                pass
            with time_it("phase_b"):
                pass
        with time_it("after_session"):  # must NOT be recorded
            pass
        events = json.load(open(path))
        spans = [e for e in events if e.get("ph") == "X"]
        assert {s["name"] for s in spans} == {"phase_a", "phase_b"}
        for s in spans:
            assert s["dur"] >= 0 and "ts" in s and "tid" in s

    def test_trace_captures_training_steps(self, ctx, tmp_path):
        import json
        import numpy as np
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.utils.trace import trace

        x = np.random.rand(64, 4).astype(np.float32)
        y = np.random.rand(64, 1).astype(np.float32)
        m = Sequential([Dense(4), Dense(1)])
        m.compile(optimizer="sgd", loss="mse")
        path = str(tmp_path / "train.json")
        with trace(path):
            m.fit(FeatureSet.from_ndarrays(x, y), batch_size=32, nb_epoch=1)
        spans = [e for e in json.load(open(path)) if e.get("ph") == "X"]
        assert sum(s["name"] == "train_step" for s in spans) == 2


class TestRngImplConfig:
    def test_rng_impl_knob_builds_working_estimator(self):
        import jax
        import numpy as np

        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense, Dropout

        global_config().set("rng.impl", "rbg")
        try:
            model = Sequential([Dense(8, name="d1"), Dropout(0.2),
                                Dense(2, name="d2")])
            est = Estimator(
                model=model,
                loss_fn=objectives.get("sparse_categorical_crossentropy"),
                optimizer=optimizers.SGD(0.05))
            assert jax.dtypes.issubdtype(est.root_rng.dtype, jax.dtypes.prng_key)
            rs = np.random.RandomState(0)
            x = rs.randn(16, 6).astype(np.float32)
            y = rs.randint(0, 2, 16).astype(np.float32)
            r = est.train(FeatureSet.from_ndarrays(x, y), batch_size=8,
                          epochs=1)
            assert r["iterations"] >= 1
        finally:
            global_config().set("rng.impl", "")
