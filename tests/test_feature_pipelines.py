"""ImageSet/TextSet pipelines, NNFrames, XShard tests."""
import numpy as np
import pandas as pd
import pytest


class TestImageTransforms:
    def img(self, h=40, w=60):
        rs = np.random.RandomState(0)
        return rs.randint(0, 255, (h, w, 3)).astype(np.uint8)

    def test_resize_crop_flip(self):
        from analytics_zoo_tpu.feature.image import (
            CenterCrop, HFlip, RandomCrop, Resize)
        img = self.img()
        assert Resize(20, 30).apply(img).shape == (20, 30, 3)
        assert CenterCrop(16, 16).apply(img).shape == (16, 16, 3)
        assert RandomCrop(16, 16, seed=0).apply(img).shape == (16, 16, 3)
        np.testing.assert_array_equal(HFlip().apply(img), img[:, ::-1])

    def test_color_ops(self):
        from analytics_zoo_tpu.feature.image import (
            Brightness, ChannelNormalize, ChannelOrder, ColorJitter, Contrast,
            Hue, Saturation)
        img = self.img().astype(np.float32)
        out = Brightness(10, 10, seed=0).apply(img)
        np.testing.assert_allclose(out, img + 10)
        out = Contrast(2, 2, seed=0).apply(img)
        np.testing.assert_allclose(out, img * 2)
        assert Saturation(seed=0).apply(img).shape == img.shape
        assert Hue(seed=0).apply(img).shape == img.shape
        assert ColorJitter(seed=0).apply(img).shape == img.shape
        norm = ChannelNormalize([1, 2, 3], [2, 2, 2]).apply(img)
        np.testing.assert_allclose(norm, (img - [1, 2, 3]) / 2)
        np.testing.assert_array_equal(ChannelOrder().apply(img),
                                      img[..., ::-1])

    def test_expand_and_random(self):
        from analytics_zoo_tpu.feature.image import (
            Expand, HFlip, RandomPreprocessing)
        img = self.img(10, 10).astype(np.float32)
        out = Expand(max_ratio=2.0, seed=1).apply(img)
        assert out.shape[0] >= 10 and out.shape[1] >= 10
        rp = RandomPreprocessing(HFlip(), prob=0.0, seed=0)
        np.testing.assert_array_equal(rp.apply(img), img)

    def test_chain_and_decode(self, tmp_path):
        import cv2
        from analytics_zoo_tpu.feature.image import (
            ImageSetToSample, PixelBytesToMat, Resize)
        img = self.img()
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        chain = PixelBytesToMat() >> Resize(8, 8) >> ImageSetToSample()
        out = chain.apply(buf.tobytes())
        assert out.shape == (8, 8, 3) and out.dtype == np.float32


class TestImageSet:
    def test_read_with_labels_and_featureset(self, ctx, tmp_path):
        import cv2
        from analytics_zoo_tpu.feature.image import ImageSet, Resize
        rs = np.random.RandomState(0)
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                cv2.imwrite(str(d / f"{i}.png"),
                            rs.randint(0, 255, (12 + i, 10, 3)).astype(np.uint8))
        iset = ImageSet.read(str(tmp_path), with_label=True)
        assert len(iset) == 6
        assert sorted(set(iset.labels.tolist())) == [1.0, 2.0]
        with pytest.raises(ValueError):  # ragged sizes must fail loudly
            iset.to_featureset()
        fs = iset.transform(Resize(8, 8)).to_featureset()
        assert fs.size == 6
        x, y = next(fs.train_iterator(2))
        assert x.shape == (2, 8, 8, 3)


class TestTextSet:
    def test_full_pipeline(self, ctx):
        from analytics_zoo_tpu.feature.text import TextSet
        texts = ["The quick brown fox", "the lazy dog sleeps",
                 "quick quick fox"]
        ts = TextSet.from_texts(texts, labels=[0, 1, 0])
        ts.tokenize().normalize().word2idx().shape_sequence(5)
        wi = ts.get_word_index()
        assert wi["quick"] == 1  # most frequent gets lowest index
        fs = ts.to_featureset(shuffle=False)
        assert fs.size == 3
        x, y = next(fs.train_iterator(3))
        assert x.shape == (3, 5)

    def test_word_index_persistence(self, tmp_path):
        from analytics_zoo_tpu.feature.text import TextSet
        ts = TextSet.from_texts(["a b c", "b c d"]).tokenize().normalize()
        ts.word2idx()
        path = str(tmp_path / "wi.json")
        ts.save_word_index(path)
        ts2 = TextSet.from_texts(["c d e"]).tokenize().normalize()
        ts2.load_word_index(path)
        ts2.word2idx(existing_map=ts2.word_index)
        assert ts2.features[0].indices[0] == ts.word_index["c"]
        assert ts2.features[0].indices[2] == 0  # OOV -> 0

    def test_read_dir_and_relations(self, tmp_path):
        from analytics_zoo_tpu.feature.text import (
            Relation, TextSet, read_relations)
        for cls, text in (("pos", "good great"), ("neg", "bad awful")):
            d = tmp_path / cls
            d.mkdir()
            (d / "a.txt").write_text(text)
        ts = TextSet.read(str(tmp_path))
        assert len(ts) == 2 and {f.label for f in ts.features} == {0, 1}

        rel_file = tmp_path / "rels.csv"
        rel_file.write_text("id1,id2,label\nq1,d1,1\nq1,d2,0\n")
        rels = read_relations(str(rel_file))
        assert rels[0] == Relation("q1", "d1", 1)
        qa = TextSet.from_relation_pairs(
            rels, {"q1": "what is jax"}, {"d1": "jax is nice", "d2": "no"})
        qa.tokenize().normalize().word2idx().shape_sequence(8)
        fs = qa.to_featureset(shuffle=False)
        assert fs.size == 2

    def test_relation_pairs_shaped_for_knrm(self, ctx):
        from analytics_zoo_tpu.feature.text import Relation, TextSet
        rels = [Relation("q1", "d1", 1), Relation("q1", "d2", 0)]
        qa = TextSet.from_relation_pairs(
            rels, {"q1": "what is jax"},
            {"d1": "jax is a nice library", "d2": "no"},
            text1_length=4, text2_length=6)
        assert all(len(f.indices) == 10 for f in qa.features)
        fs = qa.to_featureset(shuffle=False)
        x, y = next(fs.train_iterator(2))
        assert x.shape == (2, 10) and y.tolist() == [1.0, 0.0]
        # feeds KNRM directly
        from analytics_zoo_tpu.models import KNRM
        m = KNRM(4, 6, vocab_size=len(qa.get_word_index()) + 1, embed_size=4,
                 kernel_num=3, target_mode="classification")
        m.default_compile()
        xt = np.tile(x.astype(np.float32), (4, 1))  # 8 rows for the 8-dev mesh
        m.fit(xt, np.tile(y, 4), batch_size=8, nb_epoch=1)

    def test_truncation_modes(self):
        from analytics_zoo_tpu.feature.text import TextSet
        ts = TextSet.from_texts(["a b c d e"]).tokenize().normalize()
        ts.word2idx()
        pre = [f.indices.copy() for f in ts.shape_sequence(3, "pre").features]
        assert len(pre[0]) == 3
        ts2 = TextSet.from_texts(["a b c d e"]).tokenize().normalize()
        ts2.word2idx(existing_map=ts.word_index)
        post = ts2.shape_sequence(3, "post").features[0].indices
        assert not np.array_equal(pre[0], post)


class TestNNFrames:
    def make_df(self, n=48):
        rs = np.random.RandomState(0)
        x = rs.rand(n, 4).astype(np.float32)
        y = (x.sum(1) > 2).astype(np.float32)
        return pd.DataFrame({"features": list(x), "label": y})

    def test_nnestimator_fit_transform(self, ctx):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.nnframes import NNEstimator
        df = self.make_df()
        model = Sequential([Dense(8, activation="relu"), Dense(1)])
        est = (NNEstimator(model, "mse")
               .set_batch_size(16).set_max_epoch(3)
               .set_optim_method("adam"))
        nn_model = est.fit(df)
        out = nn_model.transform(df)
        assert "prediction" in out.columns
        assert len(out) == len(df)

    def test_nnclassifier(self, ctx):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.nnframes import NNClassifier
        df = self.make_df()
        model = Sequential([Dense(8, activation="relu"),
                            Dense(2, activation="softmax")])
        clf = (NNClassifier(model).set_batch_size(16).set_max_epoch(30)
               .set_optim_method("adam").set_learning_rate(0.01))
        fitted = clf.fit(df)
        out = fitted.transform(df)
        assert set(np.unique(out["prediction"])) <= {0.0, 1.0}
        acc = (out["prediction"].to_numpy() == df["label"].to_numpy()).mean()
        assert acc > 0.6

    def test_image_reader(self, ctx, tmp_path):
        import cv2
        from analytics_zoo_tpu.nnframes import NNImageReader
        rs = np.random.RandomState(0)
        for i in range(3):
            cv2.imwrite(str(tmp_path / f"{i}.png"),
                        rs.randint(0, 255, (10, 11, 3)).astype(np.uint8))
        df = NNImageReader.read_images(str(tmp_path), resize_h=8, resize_w=8)
        assert len(df) == 3
        assert df["image"][0].shape == (8, 8, 3)


class TestXShard:
    def test_read_csv_apply_collect(self, ctx, tmp_path):
        from analytics_zoo_tpu.xshard import read_csv
        for i in range(3):
            pd.DataFrame({"a": [i, i + 1], "b": [1.0, 2.0]}).to_csv(
                tmp_path / f"p{i}.csv", index=False)
        shards = read_csv(str(tmp_path))
        assert shards.num_partitions() == 3
        doubled = shards.apply(lambda df: df.assign(a=df.a * 2))
        whole = doubled.concat_to_pandas()
        assert whole["a"].sum() == 2 * sum([0, 1, 1, 2, 2, 3])

    def test_repartition_and_featureset(self, ctx, tmp_path):
        from analytics_zoo_tpu.xshard import read_csv
        pd.DataFrame({"x": np.arange(10, dtype=float),
                      "y": np.arange(10, dtype=float)}).to_csv(
            tmp_path / "data.csv", index=False)
        shards = read_csv(str(tmp_path / "data.csv"), num_shards=4)
        assert shards.num_partitions() == 4
        fs = shards.to_featureset(["x"], ["y"], shuffle=False)
        assert fs.size == 10

    def test_read_partitioned_dataset_dir(self, ctx, tmp_path):
        import pandas as pd
        from analytics_zoo_tpu.xshard import read_parquet
        # hive layout: no top-level *.parquet, pandas reads the dir natively
        for day in range(2):
            sub = tmp_path / f"day={day}"
            sub.mkdir()
            pd.DataFrame({"x": np.arange(3, dtype=float)}).to_parquet(
                sub / "part.parquet")
        shards = read_parquet(str(tmp_path))
        assert shards.num_partitions() == 1
        assert len(shards.concat_to_pandas()) == 6


class TestXShardParquet:
    def test_read_parquet_roundtrip(self, ctx, tmp_path):
        import pandas as pd
        from analytics_zoo_tpu.xshard import read_parquet
        for i in range(2):
            pd.DataFrame({"x": np.arange(5, dtype=float) + 5 * i,
                          "y": np.arange(5, dtype=float)}).to_parquet(
                tmp_path / f"part-{i}.parquet")
        shards = read_parquet(str(tmp_path))
        assert shards.num_partitions() == 2
        whole = shards.concat_to_pandas()
        assert len(whole) == 10 and whole["x"].sum() == sum(range(10))
        fs = shards.to_featureset(["x"], ["y"], shuffle=False)
        assert fs.size == 10

    def test_read_partitioned_dataset_dir(self, ctx, tmp_path):
        import pandas as pd
        from analytics_zoo_tpu.xshard import read_parquet
        # hive layout: no top-level *.parquet, pandas reads the dir natively
        for day in range(2):
            sub = tmp_path / f"day={day}"
            sub.mkdir()
            pd.DataFrame({"x": np.arange(3, dtype=float)}).to_parquet(
                sub / "part.parquet")
        shards = read_parquet(str(tmp_path))
        assert shards.num_partitions() == 1
        assert len(shards.concat_to_pandas()) == 6
