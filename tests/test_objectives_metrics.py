"""Objective and metric golden-value tests."""
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.keras import metrics, objectives


class TestObjectives:
    def test_mse_mae(self):
        t = jnp.array([1.0, 2.0])
        p = jnp.array([2.0, 4.0])
        assert float(objectives.get("mse")(t, p)) == pytest.approx(2.5)
        assert float(objectives.get("mae")(t, p)) == pytest.approx(1.5)

    def test_binary_crossentropy(self):
        t = jnp.array([1.0, 0.0])
        p = jnp.array([0.9, 0.1])
        want = -np.mean([np.log(0.9), np.log(0.9)])
        assert float(objectives.binary_crossentropy(t, p)) == pytest.approx(want, rel=1e-5)
        # logits variant agrees with probability variant
        logits = jnp.log(p / (1 - p))
        assert float(objectives.binary_crossentropy_from_logits(t, logits)) == \
            pytest.approx(want, rel=1e-4)

    def test_categorical_crossentropy(self):
        t = jnp.array([[0.0, 1.0], [1.0, 0.0]])
        p = jnp.array([[0.2, 0.8], [0.6, 0.4]])
        want = -np.mean([np.log(0.8), np.log(0.6)])
        assert float(objectives.categorical_crossentropy(t, p)) == \
            pytest.approx(want, rel=1e-5)
        sp = objectives.sparse_categorical_crossentropy(jnp.array([1, 0]), p)
        assert float(sp) == pytest.approx(want, rel=1e-5)

    def test_hinge_family(self):
        t = jnp.array([1.0, -1.0])
        p = jnp.array([0.5, 0.5])
        assert float(objectives.hinge(t, p)) == pytest.approx((0.5 + 1.5) / 2)
        assert float(objectives.squared_hinge(t, p)) == \
            pytest.approx((0.25 + 2.25) / 2)

    def test_kld_poisson_cosine(self):
        t = jnp.array([[0.5, 0.5]])
        p = jnp.array([[0.25, 0.75]])
        want = 0.5 * np.log(2) + 0.5 * np.log(2 / 3)
        assert float(objectives.kullback_leibler_divergence(t, p)) == \
            pytest.approx(want, rel=1e-4)
        assert float(objectives.cosine_proximity(t, t)) == pytest.approx(-1.0, rel=1e-5)

    def test_rank_hinge(self):
        # pairs: (pos=0.9, neg=0.1) -> 0.2 ; (pos=0.2, neg=0.8) -> 1.6
        p = jnp.array([0.9, 0.1, 0.2, 0.8])
        assert float(objectives.rank_hinge(None, p)) == pytest.approx(0.9, rel=1e-5)

    def test_unknown_loss(self):
        with pytest.raises(ValueError):
            objectives.get("nope")


class TestMetrics:
    def run(self, metric, y_true, y_pred, mask=None):
        y_true = jnp.asarray(y_true)
        y_pred = jnp.asarray(y_pred)
        if mask is None:
            mask = jnp.ones(y_pred.shape[0])
        s = metric.update(metric.init_state(), y_true, y_pred, mask)
        return metric.compute(s)

    def test_binary_accuracy(self):
        acc = self.run(metrics.Accuracy(), [1.0, 0.0, 1.0, 0.0],
                       [0.9, 0.2, 0.3, 0.6])
        assert acc == pytest.approx(0.5)

    def test_categorical_accuracy_with_mask(self):
        y_pred = [[0.9, 0.1], [0.2, 0.8], [0.9, 0.1]]
        acc = self.run(metrics.Accuracy(), [0, 1, 1], y_pred,
                       mask=jnp.array([1.0, 1.0, 0.0]))  # padded row ignored
        assert acc == pytest.approx(1.0)

    def test_topk(self):
        y_pred = [[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]]
        assert self.run(metrics.TopK(2), [1, 0], y_pred) == pytest.approx(0.5)

    def test_mae_streaming(self):
        m = metrics.MAE()
        s = m.init_state()
        s = m.update(s, jnp.array([1.0]), jnp.array([2.0]), jnp.ones(1))
        s = m.update(s, jnp.array([0.0]), jnp.array([4.0]), jnp.ones(1))
        assert m.compute(s) == pytest.approx(2.5)

    def test_auc_perfect_separation(self):
        t = jnp.array([1.0, 1.0, 0.0, 0.0])
        p = jnp.array([0.9, 0.8, 0.2, 0.1])
        auc = self.run(metrics.AUC(), t, p)
        assert auc == pytest.approx(1.0, abs=0.02)
        auc_rand = self.run(metrics.AUC(), t, jnp.array([0.5, 0.5, 0.5, 0.5]))
        assert 0.3 < auc_rand < 0.7


class TestRankingMetrics:
    """NDCG/MAP/HitRatio vs hand-computed values (reference
    Ranker.scala:114-174 formulas)."""

    def test_ndcg_golden(self):
        import numpy as np
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras.metrics import ndcg_score
        # one query, labels ranked by pred = [1, 0, 1]; ideal = [1, 1, 0]
        y_true = jnp.asarray([[1.0, 0.0, 1.0]])
        y_pred = jnp.asarray([[0.9, 0.5, 0.1]])
        dcg = 2.0 / np.log(2.0) + 2.0 / np.log(4.0)
        idcg = 2.0 / np.log(2.0) + 2.0 / np.log(3.0)
        got = float(ndcg_score(y_true, y_pred, k=3)[0])
        assert abs(got - dcg / idcg) < 1e-5
        # k=1: top-ranked is positive -> ndcg 1
        assert abs(float(ndcg_score(y_true, y_pred, k=1)[0]) - 1.0) < 1e-6
        # no positives -> 0
        assert float(ndcg_score(jnp.zeros((1, 3)), y_pred, k=3)[0]) == 0.0

    def test_map_golden(self):
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras.metrics import map_score
        # ranked labels by pred: [1, 0, 1] -> AP = (1/1 + 2/3) / 2
        y_true = jnp.asarray([[1.0, 0.0, 1.0]])
        y_pred = jnp.asarray([[0.9, 0.5, 0.1]])
        assert abs(float(map_score(y_true, y_pred)[0]) - (1.0 + 2 / 3) / 2) < 1e-5

    def test_hit_ratio(self):
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras.metrics import hit_ratio_score
        y_true = jnp.asarray([[0.0, 1.0, 0.0, 0.0],
                              [0.0, 0.0, 0.0, 1.0]])
        y_pred = jnp.asarray([[0.9, 0.8, 0.1, 0.0],
                              [0.9, 0.8, 0.7, 0.0]])
        hits = hit_ratio_score(y_true, y_pred, k=2)
        assert hits.tolist() == [1.0, 0.0]

    def test_streaming_metric_classes(self):
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras import metrics as M
        for name, cls in [("ndcg", M.NDCG), ("map", M.MAP),
                          ("hit_ratio", M.HitRatio)]:
            m = M.get(name)
            assert isinstance(m, cls)
        m = M.NDCG(k=2)
        st = m.init_state()
        y_true = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        y_pred = jnp.asarray([[0.9, 0.1], [0.9, 0.1]])
        st = m.update(st, y_true, y_pred, jnp.ones(2))
        # q1 perfect (1.0), q2 positive at rank 2
        import numpy as np
        want = (1.0 + (2.0 / np.log(3.0)) / (2.0 / np.log(2.0))) / 2
        assert abs(m.compute(st) - want) < 1e-5

    def test_ranker_mixin_on_recommender(self):
        import numpy as np
        from analytics_zoo_tpu.models import NeuralCF
        ncf = NeuralCF(10, 8, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=4)
        ncf.compile("adam", "sparse_categorical_crossentropy")
        rs = np.random.RandomState(0)
        # 4 queries x 5 candidates of (user, item) pairs
        x = np.stack([rs.randint(1, 10, (4, 5)),
                      rs.randint(1, 8, (4, 5))], axis=-1).astype(np.float32)
        y = (rs.rand(4, 5) > 0.5).astype(np.float32)
        ndcg = ncf.evaluate_ndcg(x, y, k=3)
        m = ncf.evaluate_map(x, y)
        hr = ncf.evaluate_hit_ratio(x, y, k=3)
        for v in (ndcg, m, hr):
            assert 0.0 <= v <= 1.0


class TestPrecisionRecallF1:
    def _run(self, metric, y_true, y_pred, mask=None):
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras import metrics as M
        m = M.get(metric)
        state = m.init_state()
        y_true, y_pred = jnp.asarray(y_true), jnp.asarray(y_pred)
        if mask is None:
            mask = jnp.ones(y_true.shape[0])
        state = m.update(state, y_true, y_pred, jnp.asarray(mask))
        return m.compute(state)

    def test_categorical_counts(self):
        # preds (argmax): [1, 1, 0, 1]; true: [1, 0, 1, 1]
        y_pred = np.array([[0.1, 0.9], [0.2, 0.8], [0.7, 0.3], [0.4, 0.6]])
        y_true = np.array([1.0, 0.0, 1.0, 1.0])
        # tp=2, fp=1, fn=1
        assert self._run("precision", y_true, y_pred) == pytest.approx(2 / 3)
        assert self._run("recall", y_true, y_pred) == pytest.approx(2 / 3)
        assert self._run("f1", y_true, y_pred) == pytest.approx(2 / 3)

    def test_binary_threshold_and_mask(self):
        y_pred = np.array([[0.9], [0.8], [0.2], [0.7]])
        y_true = np.array([[1.0], [0.0], [1.0], [1.0]])
        mask = np.array([1.0, 1.0, 1.0, 0.0])  # last row is tail padding
        # rows 0-2: pred [1,1,0] true [1,0,1] -> tp=1 fp=1 fn=1
        assert self._run("precision", y_true, y_pred, mask) == \
            pytest.approx(0.5)
        assert self._run("recall", y_true, y_pred, mask) == pytest.approx(0.5)

    def test_evaluate_through_estimator(self, ctx):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense
        rs = np.random.RandomState(0)
        x = rs.rand(96, 4).astype(np.float32)
        y = (x.sum(1) > 2).astype(np.float32)
        est = Estimator(
            model=Sequential([Dense(8, activation="relu"),
                              Dense(2, activation="softmax")]),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(5e-2),
            metrics=["precision", "recall", "f1", "accuracy"])
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=32, epochs=30)
        res = est.evaluate(FeatureSet.from_ndarrays(x, y, shuffle=False),
                           batch_size=32)
        assert set(res) == {"precision", "recall", "f1", "accuracy"}
        assert res["f1"] > 0.8
        # F1 is the harmonic mean of the reported precision/recall
        p, r = res["precision"], res["recall"]
        assert res["f1"] == pytest.approx(2 * p * r / (p + r), abs=1e-5)
