"""Model-parallel generative tier (docs/parallelism.md): tensor-parallel
TransformerLM training, 1F1B pipelined fit, the MoE exchange parity +
drop-accounting contract, and ring attention vs ``masked_context``.

Parity bar everywhere: the sharded computation must match the
single-device reference through the REAL training path — bitwise where
the arithmetic is shared (MoE exchange engines), documented float
tolerance where the reduction order differs (GSPMD psum placement, the
ring's blockwise streaming softmax).

Op-level pipeline/MoE/TP building blocks are covered in
tests/test_moe_pipeline.py; ring/Ulysses kernels in tests/test_attention.py.
This suite exercises the fused entry points users actually call.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from analytics_zoo_tpu.capture.lm import TransformerLM


def _tokens(n=32, s=12, vocab=32, seed=0):
    return np.random.RandomState(seed).randint(0, vocab, (n, s))


def _flat_spec(arr):
    return tuple(arr.sharding.spec)


class TestTensorParallelFit:
    """``TransformerLM(tensor_parallel=True)``: Megatron column/row rules
    ride the Estimator's param rules — same loss history as the
    replicated layout, with the block kernels genuinely sharded."""

    @pytest.mark.slow  # full Estimator fit x2: the heavyweight parity sweep
    def test_fit_matches_replicated(self, ctx):
        vocab = 32
        toks = _tokens(vocab=vocab)
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))
        kw = dict(vocab_size=vocab, hidden=16, n_block=2, n_head=2,
                  max_len=16, seed=3)
        lm_tp = TransformerLM(mesh=mesh, tensor_parallel=True, **kw)
        lm_ref = TransformerLM(**kw)
        r_tp = lm_tp.fit(toks, batch_size=8, epochs=2)
        r_ref = lm_ref.fit(toks, batch_size=8, epochs=2)
        np.testing.assert_allclose(r_tp["loss_history"],
                                   r_ref["loss_history"], rtol=1e-4)
        # qkv/fc1 column-parallel, attn_out/fc2 row-parallel — actually
        # laid out over the model axis, not just declared
        blk = lm_tp.params["blocks"][0]
        assert _flat_spec(blk["qkv"]["kernel"]) == (None, "model")
        assert _flat_spec(blk["fc1"]["kernel"]) == (None, "model")
        assert _flat_spec(blk["attn_out"]["kernel"])[:1] == ("model",)
        assert _flat_spec(blk["fc2"]["kernel"])[:1] == ("model",)

    def test_head_divisibility_validated(self, ctx):
        mesh = Mesh(np.asarray(jax.devices()), ("model",))  # 8-way
        with pytest.raises(ValueError, match="divisible"):
            TransformerLM(vocab_size=32, hidden=16, n_block=2, n_head=2,
                          max_len=16, mesh=mesh, tensor_parallel=True)

    def test_mesh_must_carry_the_axis(self, ctx):
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        with pytest.raises(ValueError, match="axis"):
            TransformerLM(vocab_size=32, hidden=16, n_block=2, n_head=2,
                          max_len=16, mesh=mesh, tensor_parallel=True)


class TestPipelinedFit:
    """``TransformerLM(pipeline_stages=P)``: the 1F1B schedule must be a
    pure scheduling change — loss history matches the unpipelined fit."""

    @pytest.mark.slow  # full Estimator fit x2: the heavyweight parity sweep
    def test_fit_matches_sequential(self, ctx):
        vocab = 32
        toks = _tokens(vocab=vocab)
        kw = dict(vocab_size=vocab, hidden=16, n_block=2, n_head=2,
                  max_len=16, seed=3)
        lm_pipe = TransformerLM(pipeline_stages=2,
                                pipeline_microbatches=2, **kw)
        lm_ref = TransformerLM(pipeline_stages=0, **kw)
        r_pipe = lm_pipe.fit(toks, batch_size=8, epochs=2)
        r_ref = lm_ref.fit(toks, batch_size=8, epochs=2)
        np.testing.assert_allclose(r_pipe["loss_history"],
                                   r_ref["loss_history"], rtol=1e-4)

    def test_bubble_gauge_published_at_build(self, ctx):
        from analytics_zoo_tpu.parallel.pipeline import (_M_BUBBLE,
                                                         bubble_fraction)
        TransformerLM(vocab_size=32, hidden=16, n_block=4, n_head=2,
                      max_len=16, pipeline_stages=4,
                      pipeline_microbatches=4)
        want = bubble_fraction(4, 4)  # 2(P-1)/(M+2(P-1)) = 0.6
        assert float(_M_BUBBLE.value()) == pytest.approx(want)

    def test_stage_count_must_divide_blocks(self, ctx):
        with pytest.raises(ValueError, match="divisible"):
            TransformerLM(vocab_size=32, hidden=16, n_block=3, n_head=2,
                          max_len=16, pipeline_stages=2)


class TestMoEExchange:
    """The all-to-all expert exchange vs the dense-dispatch einsum:
    bit-identical outputs AND drop counts, with capacity drops drained
    into ``parallel.moe_dropped_tokens_total`` by the Estimator."""

    def test_alltoall_bit_matches_dense(self, ctx):
        from analytics_zoo_tpu.keras.engine import MOE_DROP_KEY
        from analytics_zoo_tpu.parallel import set_default_mesh
        from analytics_zoo_tpu.parallel.moe import MoE

        e, d, h, n_tok, ep = 4, 8, 16, 256, 4
        x = jnp.asarray(
            np.random.RandomState(0).rand(n_tok, d).astype(np.float32))
        rng = jax.random.PRNGKey(0)

        def build(exchange):
            layer = MoE(num_experts=e, hidden_dim=h, k=1,
                        capacity_factor=1.0, group_size=n_tok // ep,
                        exchange=exchange, name="xmoe")
            params, state = layer.build(rng, (None, d))
            return layer, params, state

        dense_layer, params, state = build("dense")
        y_dense, st_dense = jax.jit(dense_layer.call)(params, state, x)

        mesh = Mesh(np.asarray(jax.devices()).reshape(-1, ep),
                    ("data", "expert"))
        set_default_mesh(mesh)
        try:
            a2a_layer, _, _ = build("alltoall")
            y_a2a, st_a2a = jax.jit(a2a_layer.call)(params, state, x)
        finally:
            set_default_mesh(None)

        assert np.array_equal(np.asarray(y_dense), np.asarray(y_a2a))
        assert int(st_dense[MOE_DROP_KEY]) == int(st_a2a[MOE_DROP_KEY])
        # capacity_factor=1.0 on random routing drops SOMETHING — the
        # parity above is vacuous if no token ever overflowed
        assert int(st_dense[MOE_DROP_KEY]) > 0

    def test_alltoall_without_expert_axis_raises(self, ctx):
        from analytics_zoo_tpu.parallel.moe import MoE
        layer = MoE(num_experts=4, hidden_dim=16, group_size=64,
                    exchange="alltoall", name="nomesh")
        params, state = layer.build(jax.random.PRNGKey(0), (None, 8))
        x = jnp.zeros((256, 8), jnp.float32)
        with pytest.raises(ValueError, match="expert"):
            jax.block_until_ready(layer.call(params, state, x)[0])

    def test_drops_drain_into_metric(self, ctx):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import (Sequential, objectives,
                                             optimizers)
        from analytics_zoo_tpu.keras.engine import MOE_DROP_KEY
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.parallel.moe import (MoE, _M_DROPPED,
                                                    moe_sharding_rule)

        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                    ("data", "expert"))
        model = Sequential([
            Dense(8, name="proj"),
            MoE(num_experts=4, hidden_dim=16, capacity_factor=0.25,
                aux_loss_weight=0.0, name="drops"),
            Dense(2, activation="softmax", name="head")])
        est = Estimator(
            model=model,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(1e-2), mesh=mesh,
            param_sharding_rules=[moe_sharding_rule])
        rs = np.random.RandomState(0)
        fs = FeatureSet.from_ndarrays(
            rs.randn(64, 6, 8).astype(np.float32),
            rs.randint(0, 2, (64, 6)).astype(np.float32))
        before = _M_DROPPED.value()
        with mesh:
            est.train(fs, batch_size=16, epochs=2)
        drained = _M_DROPPED.value() - before
        # device-side running total == what reached the counter: the
        # per-epoch drain missed nothing and double-counted nothing
        flat = jax.tree_util.tree_flatten_with_path(est.model_state)[0]
        on_device = sum(
            int(jax.device_get(leaf)) for path, leaf in flat
            if path and str(getattr(path[-1], "key", "")) == MOE_DROP_KEY)
        assert drained == on_device > 0


class TestRingContext:
    """``ring_context``: ``masked_context`` with the KV key axis sharded
    over the ``seq`` ring — documented float32 tolerance, never a
    numerics fork."""

    def _case(self, b, h, t, d, K, seed=0):
        rs = np.random.RandomState(seed)
        q = jnp.asarray(rs.randn(b, h, t, d).astype(np.float32))
        k = jnp.asarray(rs.randn(b, h, K, d).astype(np.float32))
        v = jnp.asarray(rs.randn(b, h, K, d).astype(np.float32))
        # ragged per-row visibility: each query row sees a different
        # prefix of the key axis (the decode-cache mask shape)
        lens = rs.randint(1, K + 1, (b, 1, t, 1))
        visible = jnp.asarray(
            np.arange(K)[None, None, None, :] < lens)
        visible = jnp.broadcast_to(visible, (b, h, t, K))
        return q, k, v, visible, 1.0 / (d ** 0.5)

    def test_matches_masked_context(self, ctx):
        from analytics_zoo_tpu.ops.attention import masked_context
        from analytics_zoo_tpu.parallel.ring_attention import ring_context
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
        q, k, v, visible, scale = self._case(2, 2, 3, 8, K=32)
        ref = masked_context(q, k, v, visible, scale)
        out = ring_context(mesh, q, k, v, visible, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_long_context_exceeding_one_shard(self, ctx):
        """The 100k+-token case the ring exists for: a KV buffer no
        single shard holds in full, still matching the monolithic
        reference."""
        from analytics_zoo_tpu.ops.attention import masked_context
        from analytics_zoo_tpu.parallel.ring_attention import ring_context
        mesh = Mesh(np.asarray(jax.devices()), ("seq",))  # 8-way ring
        q, k, v, visible, scale = self._case(1, 1, 2, 8, K=131072)
        ref = masked_context(q, k, v, visible, scale)
        out = ring_context(mesh, q, k, v, visible, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
