"""Fleet tier: telemetry-driven routing, shed-before-enqueue admission,
and continuation-on-failover (docs/fleet.md).

The load-bearing invariant extends the generative-serving parity rule
across instance death: a stream interrupted mid-flight — its server
killed (health file goes stale) or drained (``handoff``) — must finish on
another instance with EXACTLY the tokens serial ``generate()`` produces,
greedy and sampled, and every request still gets exactly one terminal
result."""
import json
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.utils import wall_clock
from analytics_zoo_tpu.serving import (FleetInstance, FleetRouter,
                                       GenerativeServing, ServingConfig)
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.fleet import (FLEET_SHED_ERROR,
                                             _score_instances,
                                             instance_queue, read_health)
from analytics_zoo_tpu.serving import fleet as _fleet
from analytics_zoo_tpu.serving.queues import FileQueue
from analytics_zoo_tpu.serving.server import DEADLINE_ERROR

from tests.test_generative_serving import _drive, _lm, _src


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _write_health(path, **kw):
    snap = {"state": "running", "time": wall_clock(), "queue_pending": 0,
            "in_flight": 0}
    snap.update(kw)
    with open(path, "w") as f:
        f.write(json.dumps(snap))


def _router(front, insts, **kw):
    kw.setdefault("stale_after_s", 5.0)
    kw.setdefault("health_refresh_s", 0.0)  # refresh every pass in tests
    return FleetRouter(front, insts, **kw)


class TestHealthAge:
    def test_read_health_exposes_age(self, tmp_path):
        p = str(tmp_path / "health.json")
        _write_health(p)
        assert read_health(p)["health_age_s"] < 1.0
        _write_health(p, time=wall_clock() - 60.0)
        assert read_health(p)["health_age_s"] > 59.0

    def test_missing_or_torn_health_is_none(self, tmp_path):
        assert read_health(str(tmp_path / "nope.json")) is None
        p = str(tmp_path / "torn.json")
        with open(p, "w") as f:
            f.write("{not json")
        assert read_health(p) is None

    def test_stale_health_marks_instance_dead(self, tmp_path):
        """A frozen health file must NOT be trusted: the router marks the
        instance dead instead of placing work by its stale gauges."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "h.json")
        _write_health(hp, time=wall_clock() - 60.0, queue_pending=0)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 0  # nowhere to place: parked
        assert router.instances[0].health["health_age_s"] > 59.0
        assert router.stats["backlog"] == 1
        assert router.instances[0].queue.pending_count() == 0


class TestPlacement:
    def test_instance_queue_shares_front_results(self, tmp_path):
        root = str(tmp_path / "f")
        front = FileQueue(root)
        qa = instance_queue(root, "a")
        qa.put_result("u", {"value": [1]})
        assert front.get_result("u")["value"] == [1]

    def test_scoring_is_least_loaded_and_slot_aware(self):
        # one-shot: the shallow queue wins regardless of slots
        est = _score_instances(
            np.array([True, True]), np.array([5.0, 0.0]), np.zeros(2),
            np.zeros(2), np.full(2, -1.0), np.full(2, 0.1),
            np.full(2, 0.02), np.float64(0), np.float64(0))
        assert est[1] < est[0]
        # generative: a free slot beats a busy instance with a deeper
        # queue discount — the stream would wait for a retirement
        est = _score_instances(
            np.array([True, True]), np.array([0.0, 2.0]),
            np.array([2.0, 0.0]), np.array([0.0, 1.0]),
            np.full(2, -1.0), np.full(2, 0.1), np.full(2, 0.02),
            np.float64(8), np.float64(0))
        assert est[1] < est[0]
        # page-aware: the instance whose free pages hold the stream wins
        est = _score_instances(
            np.array([True, True]), np.zeros(2), np.zeros(2),
            np.ones(2), np.array([1.0, 64.0]), np.full(2, 0.1),
            np.full(2, 0.02), np.float64(8), np.float64(4))
        assert est[1] < est[0]
        # dead is unplaceable
        assert np.isinf(_score_instances(
            np.array([False]), np.zeros(1), np.zeros(1), np.ones(1),
            np.full(1, -1.0), np.ones(1), np.ones(1),
            np.float64(1), np.float64(0))[0])

    def test_routes_one_shot_to_least_loaded(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        insts = []
        for name, pending in (("a", 5), ("b", 0), ("c", 9)):
            hp = str(tmp_path / f"{name}.json")
            _write_health(hp, queue_pending=pending,
                          service_time_s_ewma=0.01)
            insts.append(FleetInstance(name, instance_queue(root, name),
                                       hp))
        router = _router(front, insts)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 1
        assert insts[1].queue.pending_count() == 1
        assert insts[0].queue.pending_count() == 0
        assert insts[2].queue.pending_count() == 0
        assert router.stats["assigned"] == 1

    def test_sheds_before_enqueue_when_deadline_unmeetable(self, tmp_path):
        """Admission control answers NOW: a request no instance can finish
        inside its deadline gets the shed error without ever touching an
        instance queue."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, queue_pending=1000, service_time_s_ewma=1.0)
        insts = [FleetInstance("a", instance_queue(root, "a"), hp)]
        router = _router(front, insts)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock(),
                             "deadline_ms": 200})
        router.route_once()
        res = front.get_result("r0")
        assert res is not None and res["error"] == FLEET_SHED_ERROR
        assert insts[0].queue.pending_count() == 0
        assert router.stats["assigned"] == 0

    def test_expired_request_answers_deadline_error(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock() - 10.0,
                             "deadline_ms": 100})
        router.route_once()
        res = front.get_result("r0")
        assert res is not None and res["error"] == DEADLINE_ERROR

    def test_route_fault_parks_request_never_lost(self, tmp_path):
        """The ``fleet.route`` chaos site: a failed placement pass must
        park the request in the backlog and place it on the next pass —
        exactly one copy ever reaches an instance."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp)
        insts = [FleetInstance("a", instance_queue(root, "a"), hp)]
        router = _router(front, insts)
        faults.arm("fleet.route", at=1)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 0
        assert router.stats["backlog"] == 1
        assert insts[0].queue.pending_count() == 0
        assert router.route_once() == 1  # retried, placed exactly once
        assert router.stats["backlog"] == 0
        assert insts[0].queue.pending_count() == 1
        assert faults.fire_count("fleet.route") == 1

    def test_scale_signals_track_demand(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, queue_pending=10, in_flight=2)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp, slots=2)],
            scale_headroom=1.25)
        router.route_once()
        assert int(_fleet._M_ALIVE.value()) == 1
        # 12 outstanding items x 1.25 headroom / 2 slots -> wants 8
        assert int(_fleet._M_DESIRED.value()) >= 2

    def test_router_stop_returns_backlog_to_front(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, time=wall_clock() - 60.0)  # dead: nothing places
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        router.route_once()
        assert router.stats["backlog"] == 1
        assert front.pending_count() == 0
        router.stop()
        assert front.pending_count() == 1  # never taken to the grave


class TestContinuationOnFailover:
    def _fleet_pair(self, tmp_path, lm, budget, **cfg_kw):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        qa, qb = instance_queue(root, "a"), instance_queue(root, "b")
        ha, hb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=ha,
                          health_interval_s=0.001, **cfg_kw),
            lm, queue=qa)
        b = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=hb,
                          health_interval_s=0.001, **cfg_kw),
            lm, queue=qb)
        router = _router(
            front, [FleetInstance("a", qa, ha, slots=2),
                    FleetInstance("b", qb, hb, slots=2)],
            stale_after_s=0.35)
        return root, front, a, b, router

    def _run_failover(self, tmp_path, lm, prompt, budget, seed=None,
                      **cfg_kw):
        """Route a stream to instance A, freeze A mid-stream (its health
        file goes stale), fail the stream over, finish it on B; return
        the terminal result."""
        root, front, a, b, router = self._fleet_pair(tmp_path, lm, budget,
                                                     **cfg_kw)
        a.serve_step()       # writes fresh health: A is alive
        b.serve_step()
        inq = InputQueue(root)
        inq.enqueue_prompt("s0", prompt, seed=seed)
        assert router.route_once() == 1
        assert a.queue.pending_count() == 1  # equal gauges: first wins
        # A decodes until a partial (the failover prefix) exists, then
        # "dies": we stop stepping it, so its health file freezes
        partial = None
        for _ in range(200):
            a.serve_step()
            partial = front.get_result("s0")
            if partial is not None and len(partial.get("stream") or []) >= 2:
                break
        assert partial is not None and partial.get("done") is False
        k = len(partial["stream"])
        assert 0 < k < budget
        time.sleep(0.45)     # A's health ages past stale_after_s
        b.serve_step()       # B's stays fresh
        router.route_once()  # detects the orphan, re-routes with prefix
        assert b.queue.pending_count() == 1
        _drive(b)
        res = front.get_result("s0")
        assert res is not None and res.get("done") is True
        return res, k

    def test_greedy_failover_bit_identical(self, ctx, tmp_path):
        lm = _lm()
        prompt = np.random.RandomState(7).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        fo_before = int(_fleet._M_FAILOVERS.value())
        res, k = self._run_failover(tmp_path, lm, prompt, budget)
        assert res["value"] == want, (
            f"adopted stream diverged after {k} pre-kill tokens")
        assert int(_fleet._M_FAILOVERS.value()) == fo_before + 1

    @pytest.mark.slow  # paged+sharded decode compile on two instances
    def test_failover_onto_sharded_kv_pool(self, ctx, tmp_path):
        """Both instances serve from paged pools sharded over the mesh
        (``kv_shard``): the adopted stream re-prefills into B's SHARDED
        pool and must still finish with exactly serial generate's tokens
        — failover continuation composes with KV sharding."""
        lm = _lm()
        prompt = np.random.RandomState(9).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        res, k = self._run_failover(tmp_path, lm, prompt, budget,
                                    kv_pages=16, kv_page_len=8,
                                    kv_shard=2)
        assert res["value"] == want, (
            f"sharded-pool adoption diverged after {k} pre-kill tokens")

    def test_sampled_failover_bit_identical(self, ctx, tmp_path):
        """The adopting server resumes the ORIGINAL key schedule: keys are
        split over the full budget and indexed by len(tokens), so token k
        uses the same key whether or not the stream was interrupted."""
        lm = _lm()
        prompt = np.random.RandomState(8).randint(0, 16, (4,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]), max_new_tokens=budget,
                           temperature=0.9, top_k=8, seed=123)[0].tolist()
        res, k = self._run_failover(tmp_path, lm, prompt, budget,
                                    seed=123, temperature=0.9, top_k=8)
        assert res["value"] == want, (
            f"sampled continuation diverged after {k} pre-kill tokens")

    def test_drain_handoff_continues_token_identically(self, ctx,
                                                       tmp_path):
        """``handoff()`` — the cooperative half of failover: a draining
        server re-enqueues its live streams (prefix + seed) itself
        instead of waiting to be declared dead. No partials needed."""
        lm = _lm()
        prompt = np.random.RandomState(9).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        src = _src(tmp_path)
        a = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=budget,
                          stream_interval=100), lm)
        b = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=budget,
                          stream_interval=100), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("d0", prompt)
        for _ in range(4):
            a.serve_step()
        assert a.health_snapshot()["slots_occupied"] == 1
        assert a.handoff(a.queue) == 1
        snap = a.health_snapshot()
        assert snap["state"] == "drained"
        assert snap["slots_occupied"] == 0 and snap["in_flight"] == 0
        _drive(b)
        res = outq.query("d0", timeout_s=5)
        assert res is not None and res["value"] == want

    def test_finished_budget_on_adoption_settles_immediately(self, ctx,
                                                             tmp_path):
        """A prefix that already covers the budget has nothing left to
        decode: the adopter posts the terminal without taking a slot."""
        lm = _lm()
        src = _src(tmp_path)
        b = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("f0", [3, 1, 2], prefix=[5, 4, 3, 2])
        b.serve_step()
        res = outq.query("f0", timeout_s=5)
        assert res is not None and res["value"] == [5, 4, 3, 2]
        assert b.health_snapshot()["slots_occupied"] == 0

    @pytest.mark.slow
    def test_exactly_one_terminal_per_stream_under_failover(self, ctx,
                                                            tmp_path):
        """Kill A with 2 resident streams + 2 still queued in its spool:
        all four must finish on B, each with exactly the serial tokens —
        re-routed streams included."""
        lm = _lm()
        rs = np.random.RandomState(11)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 5, 3, 6)]
        budget = 10
        want = [lm.generate(np.asarray([p]),
                            max_new_tokens=budget)[0].tolist()
                for p in prompts]
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        qa, qb = instance_queue(root, "a"), instance_queue(root, "b")
        ha, hb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=ha,
                          health_interval_s=0.001), lm, queue=qa)
        b = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=hb,
                          health_interval_s=0.001), lm, queue=qb)
        router = _router(
            front, [FleetInstance("a", qa, ha, slots=2),
                    FleetInstance("b", qb, hb, slots=2)],
            stale_after_s=0.35)
        a.serve_step()  # A alive; B has no health yet -> everything to A
        inq = InputQueue(root)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"m{i}", p)
        router.route_once()
        assert qa.pending_count() == 4  # all placed on A, none claimed yet
        for _ in range(6):  # a few tokens into the resident streams
            a.serve_step()
        time.sleep(0.45)    # A dies
        b.serve_step()      # B comes up fresh
        router.route_once()  # steal spool + fail over residents
        _drive(b, steps=400)
        for i, w in enumerate(want):
            res = front.get_result(f"m{i}")
            assert res is not None and res.get("done") is True, f"m{i}"
            assert res["value"] == w, f"stream m{i} diverged"
