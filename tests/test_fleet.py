"""Fleet tier: telemetry-driven routing, shed-before-enqueue admission,
and continuation-on-failover (docs/fleet.md).

The load-bearing invariant extends the generative-serving parity rule
across instance death: a stream interrupted mid-flight — its server
killed (health file goes stale) or drained (``handoff``) — must finish on
another instance with EXACTLY the tokens serial ``generate()`` produces,
greedy and sampled, and every request still gets exactly one terminal
result."""
import json
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.common.utils import wall_clock
from analytics_zoo_tpu.serving import (FleetInstance, FleetRouter,
                                       GenerativeServing, ServingConfig)
from analytics_zoo_tpu.serving.client import (InputQueue, OutputQueue,
                                              ResilientClient)
from analytics_zoo_tpu.serving.fleet import (BREAKER_CLOSED,
                                             BREAKER_HALF_OPEN, BREAKER_OPEN,
                                             FLEET_SHED_ERROR, _Breaker,
                                             _score_instances,
                                             instance_queue, read_health)
from analytics_zoo_tpu.serving import fleet as _fleet
from analytics_zoo_tpu.serving.queues import FileQueue, RedisQueue
from analytics_zoo_tpu.serving.server import DEADLINE_ERROR, SHED_ERROR

from tests.test_generative_serving import _drive, _lm, _src


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _write_health(path, **kw):
    snap = {"state": "running", "time": wall_clock(), "queue_pending": 0,
            "in_flight": 0}
    snap.update(kw)
    with open(path, "w") as f:
        f.write(json.dumps(snap))


def _router(front, insts, **kw):
    kw.setdefault("stale_after_s", 5.0)
    kw.setdefault("health_refresh_s", 0.0)  # refresh every pass in tests
    return FleetRouter(front, insts, **kw)


class TestHealthAge:
    def test_read_health_exposes_age(self, tmp_path):
        p = str(tmp_path / "health.json")
        _write_health(p)
        assert read_health(p)["health_age_s"] < 1.0
        _write_health(p, time=wall_clock() - 60.0)
        assert read_health(p)["health_age_s"] > 59.0

    def test_missing_or_torn_health_is_none(self, tmp_path):
        assert read_health(str(tmp_path / "nope.json")) is None
        p = str(tmp_path / "torn.json")
        with open(p, "w") as f:
            f.write("{not json")
        assert read_health(p) is None

    def test_stale_health_marks_instance_dead(self, tmp_path):
        """A frozen health file must NOT be trusted: the router marks the
        instance dead instead of placing work by its stale gauges."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "h.json")
        _write_health(hp, time=wall_clock() - 60.0, queue_pending=0)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 0  # nowhere to place: parked
        assert router.instances[0].health["health_age_s"] > 59.0
        assert router.stats["backlog"] == 1
        assert router.instances[0].queue.pending_count() == 0


class TestPlacement:
    def test_instance_queue_shares_front_results(self, tmp_path):
        root = str(tmp_path / "f")
        front = FileQueue(root)
        qa = instance_queue(root, "a")
        qa.put_result("u", {"value": [1]})
        assert front.get_result("u")["value"] == [1]

    def test_scoring_is_least_loaded_and_slot_aware(self):
        # one-shot: the shallow queue wins regardless of slots
        est = _score_instances(
            np.array([True, True]), np.array([5.0, 0.0]), np.zeros(2),
            np.zeros(2), np.full(2, -1.0), np.full(2, 0.1),
            np.full(2, 0.02), np.float64(0), np.float64(0))
        assert est[1] < est[0]
        # generative: a free slot beats a busy instance with a deeper
        # queue discount — the stream would wait for a retirement
        est = _score_instances(
            np.array([True, True]), np.array([0.0, 2.0]),
            np.array([2.0, 0.0]), np.array([0.0, 1.0]),
            np.full(2, -1.0), np.full(2, 0.1), np.full(2, 0.02),
            np.float64(8), np.float64(0))
        assert est[1] < est[0]
        # page-aware: the instance whose free pages hold the stream wins
        est = _score_instances(
            np.array([True, True]), np.zeros(2), np.zeros(2),
            np.ones(2), np.array([1.0, 64.0]), np.full(2, 0.1),
            np.full(2, 0.02), np.float64(8), np.float64(4))
        assert est[1] < est[0]
        # dead is unplaceable
        assert np.isinf(_score_instances(
            np.array([False]), np.zeros(1), np.zeros(1), np.ones(1),
            np.full(1, -1.0), np.ones(1), np.ones(1),
            np.float64(1), np.float64(0))[0])

    def test_routes_one_shot_to_least_loaded(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        insts = []
        for name, pending in (("a", 5), ("b", 0), ("c", 9)):
            hp = str(tmp_path / f"{name}.json")
            _write_health(hp, queue_pending=pending,
                          service_time_s_ewma=0.01)
            insts.append(FleetInstance(name, instance_queue(root, name),
                                       hp))
        router = _router(front, insts)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 1
        assert insts[1].queue.pending_count() == 1
        assert insts[0].queue.pending_count() == 0
        assert insts[2].queue.pending_count() == 0
        assert router.stats["assigned"] == 1

    def test_sheds_before_enqueue_when_deadline_unmeetable(self, tmp_path):
        """Admission control answers NOW: a request no instance can finish
        inside its deadline gets the shed error without ever touching an
        instance queue."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, queue_pending=1000, service_time_s_ewma=1.0)
        insts = [FleetInstance("a", instance_queue(root, "a"), hp)]
        router = _router(front, insts)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock(),
                             "deadline_ms": 200})
        router.route_once()
        res = front.get_result("r0")
        assert res is not None and res["error"] == FLEET_SHED_ERROR
        assert insts[0].queue.pending_count() == 0
        assert router.stats["assigned"] == 0

    def test_expired_request_answers_deadline_error(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock() - 10.0,
                             "deadline_ms": 100})
        router.route_once()
        res = front.get_result("r0")
        assert res is not None and res["error"] == DEADLINE_ERROR

    def test_route_fault_parks_request_never_lost(self, tmp_path):
        """The ``fleet.route`` chaos site: a failed placement pass must
        park the request in the backlog and place it on the next pass —
        exactly one copy ever reaches an instance."""
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp)
        insts = [FleetInstance("a", instance_queue(root, "a"), hp)]
        router = _router(front, insts)
        faults.arm("fleet.route", at=1)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 0
        assert router.stats["backlog"] == 1
        assert insts[0].queue.pending_count() == 0
        assert router.route_once() == 1  # retried, placed exactly once
        assert router.stats["backlog"] == 0
        assert insts[0].queue.pending_count() == 1
        assert faults.fire_count("fleet.route") == 1

    def test_scale_signals_track_demand(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, queue_pending=10, in_flight=2)
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp, slots=2)],
            scale_headroom=1.25)
        router.route_once()
        assert int(_fleet._M_ALIVE.value()) == 1
        # 12 outstanding items x 1.25 headroom / 2 slots -> wants 8
        assert int(_fleet._M_DESIRED.value()) >= 2

    def test_router_stop_returns_backlog_to_front(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp, time=wall_clock() - 60.0)  # dead: nothing places
        router = _router(front, [
            FleetInstance("a", instance_queue(root, "a"), hp)])
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        router.route_once()
        assert router.stats["backlog"] == 1
        assert front.pending_count() == 0
        router.stop()
        assert front.pending_count() == 1  # never taken to the grave


class TestContinuationOnFailover:
    def _fleet_pair(self, tmp_path, lm, budget, **cfg_kw):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        qa, qb = instance_queue(root, "a"), instance_queue(root, "b")
        ha, hb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=ha,
                          health_interval_s=0.001, **cfg_kw),
            lm, queue=qa)
        b = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=hb,
                          health_interval_s=0.001, **cfg_kw),
            lm, queue=qb)
        router = _router(
            front, [FleetInstance("a", qa, ha, slots=2),
                    FleetInstance("b", qb, hb, slots=2)],
            stale_after_s=0.35)
        return root, front, a, b, router

    def _run_failover(self, tmp_path, lm, prompt, budget, seed=None,
                      **cfg_kw):
        """Route a stream to instance A, freeze A mid-stream (its health
        file goes stale), fail the stream over, finish it on B; return
        the terminal result."""
        root, front, a, b, router = self._fleet_pair(tmp_path, lm, budget,
                                                     **cfg_kw)
        a.serve_step()       # writes fresh health: A is alive
        b.serve_step()
        inq = InputQueue(root)
        inq.enqueue_prompt("s0", prompt, seed=seed)
        assert router.route_once() == 1
        assert a.queue.pending_count() == 1  # equal gauges: first wins
        # A decodes until a partial (the failover prefix) exists, then
        # "dies": we stop stepping it, so its health file freezes
        partial = None
        for _ in range(200):
            a.serve_step()
            partial = front.get_result("s0")
            if partial is not None and len(partial.get("stream") or []) >= 2:
                break
        assert partial is not None and partial.get("done") is False
        k = len(partial["stream"])
        assert 0 < k < budget
        time.sleep(0.45)     # A's health ages past stale_after_s
        b.serve_step()       # B's stays fresh
        router.route_once()  # detects the orphan, re-routes with prefix
        assert b.queue.pending_count() == 1
        _drive(b)
        res = front.get_result("s0")
        assert res is not None and res.get("done") is True
        return res, k

    def test_greedy_failover_bit_identical(self, ctx, tmp_path):
        lm = _lm()
        prompt = np.random.RandomState(7).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        fo_before = int(_fleet._M_FAILOVERS.value())
        res, k = self._run_failover(tmp_path, lm, prompt, budget)
        assert res["value"] == want, (
            f"adopted stream diverged after {k} pre-kill tokens")
        assert int(_fleet._M_FAILOVERS.value()) == fo_before + 1

    @pytest.mark.slow  # paged+sharded decode compile on two instances
    def test_failover_onto_sharded_kv_pool(self, ctx, tmp_path):
        """Both instances serve from paged pools sharded over the mesh
        (``kv_shard``): the adopted stream re-prefills into B's SHARDED
        pool and must still finish with exactly serial generate's tokens
        — failover continuation composes with KV sharding."""
        lm = _lm()
        prompt = np.random.RandomState(9).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        res, k = self._run_failover(tmp_path, lm, prompt, budget,
                                    kv_pages=16, kv_page_len=8,
                                    kv_shard=2)
        assert res["value"] == want, (
            f"sharded-pool adoption diverged after {k} pre-kill tokens")

    def test_sampled_failover_bit_identical(self, ctx, tmp_path):
        """The adopting server resumes the ORIGINAL key schedule: keys are
        split over the full budget and indexed by len(tokens), so token k
        uses the same key whether or not the stream was interrupted."""
        lm = _lm()
        prompt = np.random.RandomState(8).randint(0, 16, (4,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]), max_new_tokens=budget,
                           temperature=0.9, top_k=8, seed=123)[0].tolist()
        res, k = self._run_failover(tmp_path, lm, prompt, budget,
                                    seed=123, temperature=0.9, top_k=8)
        assert res["value"] == want, (
            f"sampled continuation diverged after {k} pre-kill tokens")

    def test_drain_handoff_continues_token_identically(self, ctx,
                                                       tmp_path):
        """``handoff()`` — the cooperative half of failover: a draining
        server re-enqueues its live streams (prefix + seed) itself
        instead of waiting to be declared dead. No partials needed."""
        lm = _lm()
        prompt = np.random.RandomState(9).randint(0, 16, (5,)).tolist()
        budget = 10
        want = lm.generate(np.asarray([prompt]),
                           max_new_tokens=budget)[0].tolist()
        src = _src(tmp_path)
        a = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=budget,
                          stream_interval=100), lm)
        b = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=budget,
                          stream_interval=100), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("d0", prompt)
        for _ in range(4):
            a.serve_step()
        assert a.health_snapshot()["slots_occupied"] == 1
        assert a.handoff(a.queue) == 1
        snap = a.health_snapshot()
        assert snap["state"] == "drained"
        assert snap["slots_occupied"] == 0 and snap["in_flight"] == 0
        _drive(b)
        res = outq.query("d0", timeout_s=5)
        assert res is not None and res["value"] == want

    def test_finished_budget_on_adoption_settles_immediately(self, ctx,
                                                             tmp_path):
        """A prefix that already covers the budget has nothing left to
        decode: the adopter posts the terminal without taking a slot."""
        lm = _lm()
        src = _src(tmp_path)
        b = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("f0", [3, 1, 2], prefix=[5, 4, 3, 2])
        b.serve_step()
        res = outq.query("f0", timeout_s=5)
        assert res is not None and res["value"] == [5, 4, 3, 2]
        assert b.health_snapshot()["slots_occupied"] == 0

    @pytest.mark.slow
    def test_exactly_one_terminal_per_stream_under_failover(self, ctx,
                                                            tmp_path):
        """Kill A with 2 resident streams + 2 still queued in its spool:
        all four must finish on B, each with exactly the serial tokens —
        re-routed streams included."""
        lm = _lm()
        rs = np.random.RandomState(11)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 5, 3, 6)]
        budget = 10
        want = [lm.generate(np.asarray([p]),
                            max_new_tokens=budget)[0].tolist()
                for p in prompts]
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        qa, qb = instance_queue(root, "a"), instance_queue(root, "b")
        ha, hb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=ha,
                          health_interval_s=0.001), lm, queue=qa)
        b = GenerativeServing(
            ServingConfig(data_src=root, slots=2, max_new_tokens=budget,
                          stream_interval=2, health_path=hb,
                          health_interval_s=0.001), lm, queue=qb)
        router = _router(
            front, [FleetInstance("a", qa, ha, slots=2),
                    FleetInstance("b", qb, hb, slots=2)],
            stale_after_s=0.35)
        a.serve_step()  # A alive; B has no health yet -> everything to A
        inq = InputQueue(root)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"m{i}", p)
        router.route_once()
        assert qa.pending_count() == 4  # all placed on A, none claimed yet
        for _ in range(6):  # a few tokens into the resident streams
            a.serve_step()
        time.sleep(0.45)    # A dies
        b.serve_step()      # B comes up fresh
        router.route_once()  # steal spool + fail over residents
        _drive(b, steps=400)
        for i, w in enumerate(want):
            res = front.get_result(f"m{i}")
            assert res is not None and res.get("done") is True, f"m{i}"
            assert res["value"] == w, f"stream m{i} diverged"


class TestCircuitBreaker:
    """Per-instance breakers (docs/fleet.md "Overload survival"): error
    streaks and persistent latency outliers trip an instance OPEN, a
    cooldown later exactly ONE half-open probe decides whether it rejoins
    the fleet — all while the router parks (never loses) unplaceable
    work."""

    def test_unit_trip_halfopen_probe_close(self):
        br = _Breaker(failures=3, latency_ratio=4.0, cooldown_s=10.0)
        now = 100.0
        br.record_result("u0", True, now)
        br.record_result("u1", False, now)  # a success resets the streak
        br.record_result("u2", True, now)
        br.record_result("u3", True, now)
        assert br.state == BREAKER_CLOSED
        br.record_result("u4", True, now)   # third consecutive error
        assert br.state == BREAKER_OPEN
        assert not br.placeable(now + 9.9)      # still cooling down
        assert br.placeable(now + 10.0)         # cooldown over -> half-open
        assert br.state == BREAKER_HALF_OPEN
        br.note_placed("probe")
        assert not br.placeable(now + 11.0)     # one probe at a time
        # a stale non-probe terminal arriving now must not move the machine
        br.record_result("bystander", True, now + 11.0)
        assert br.state == BREAKER_HALF_OPEN
        br.record_result("probe", False, now + 12.0)
        assert br.state == BREAKER_CLOSED

    def test_unit_failed_probe_reopens(self):
        br = _Breaker(failures=1, latency_ratio=4.0, cooldown_s=5.0)
        br.record_result("u0", True, 0.0)
        assert br.state == BREAKER_OPEN
        assert br.placeable(5.0)
        br.note_placed("probe")
        br.record_result("probe", True, 6.0)
        assert br.state == BREAKER_OPEN         # re-opened: fresh cooldown
        assert not br.placeable(10.9)
        assert br.placeable(11.0)               # measured from the re-open

    def test_unit_latency_trip_needs_persistence(self):
        br = _Breaker(failures=3, latency_ratio=4.0, cooldown_s=1.0)
        br.record_latency(0.5, 0.1, 0.0)
        br.record_latency(0.5, 0.1, 0.0)
        br.record_latency(0.01, 0.1, 0.0)  # one healthy refresh resets
        br.record_latency(0.5, 0.1, 0.0)
        br.record_latency(0.5, 0.1, 0.0)
        assert br.state == BREAKER_CLOSED
        br.record_latency(0.5, 0.1, 0.0)   # third consecutive slow refresh
        assert br.state == BREAKER_OPEN
        # a zero fleet median (empty/cold fleet) never trips anyone
        br2 = _Breaker(failures=1, latency_ratio=4.0, cooldown_s=1.0)
        br2.record_latency(99.0, 0.0, 0.0)
        assert br2.state == BREAKER_CLOSED

    def _one_instance_router(self, tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        hp = str(tmp_path / "a.json")
        _write_health(hp)
        inst = FleetInstance("a", instance_queue(root, "a"), hp)
        return front, inst, _router(front, [inst])

    def _req(self, front, uri):
        front.enqueue(uri, {"uri": uri, "tensor": [1],
                            "enqueue_t": wall_clock()})

    def test_error_streak_trips_and_clean_probe_closes(self, tmp_path):
        cfg = global_config()
        cfg.set("fleet.breaker_cooldown_s", 0.05)
        try:
            front, inst, router = self._one_instance_router(tmp_path)
            for i in range(3):
                self._req(front, f"r{i}")
            assert router.route_once() == 3
            # the "server" claims the spool and answers every one with an
            # error: three settled failures in a row trip the breaker
            assert len(inst.queue.claim_batch(10)) == 3
            for i in range(3):
                inst.queue.put_result(f"r{i}",
                                      {"error": "predict failed: boom"})
            router.route_once()
            assert router.breaker_states()["a"] == BREAKER_OPEN
            # while OPEN: nothing places, work parks, the counter ticks
            nc0 = int(_fleet._M_NO_CAPACITY.value())
            self._req(front, "r3")
            assert router.route_once() == 0
            assert router.stats["backlog"] == 1
            assert int(_fleet._M_NO_CAPACITY.value()) > nc0
            assert inst.queue.pending_count() == 0
            time.sleep(0.08)                     # past the cooldown
            assert router.route_once() == 1      # half-open: ONE probe
            assert router.breaker_states()["a"] == BREAKER_HALF_OPEN
            assert inst.queue.pending_count() == 1
            # a second request must NOT ride the outstanding probe
            self._req(front, "r4")
            assert router.route_once() == 0
            assert router.stats["backlog"] == 1
            # the probe comes back clean -> the breaker closes and the
            # parked request is re-placed on the next passes
            assert len(inst.queue.claim_batch(10)) == 1
            inst.queue.put_result("r3", {"value": [1]})
            placed = 0
            for _ in range(3):
                placed += router.route_once()
            assert router.breaker_states()["a"] == BREAKER_CLOSED
            assert placed == 1 and router.stats["backlog"] == 0
            assert inst.queue.pending_count() == 1
        finally:
            cfg.unset("fleet.breaker_cooldown_s")

    def test_failed_probe_reopens_router_breaker(self, tmp_path):
        cfg = global_config()
        cfg.set("fleet.breaker_cooldown_s", 0.05)
        cfg.set("fleet.breaker_failures", 1)
        try:
            front, inst, router = self._one_instance_router(tmp_path)
            self._req(front, "r0")
            assert router.route_once() == 1
            assert len(inst.queue.claim_batch(10)) == 1
            inst.queue.put_result("r0", {"error": "predict failed: boom"})
            router.route_once()
            assert router.breaker_states()["a"] == BREAKER_OPEN
            time.sleep(0.08)
            self._req(front, "r1")
            for _ in range(3):
                if router.breaker_states()["a"] == BREAKER_HALF_OPEN:
                    break
                router.route_once()
            assert router.breaker_states()["a"] == BREAKER_HALF_OPEN
            assert len(inst.queue.claim_batch(10)) == 1
            inst.queue.put_result("r1", {"error": "predict failed: again"})
            router.route_once()
            assert router.breaker_states()["a"] == BREAKER_OPEN
        finally:
            cfg.unset("fleet.breaker_cooldown_s")
            cfg.unset("fleet.breaker_failures")

    def test_flag_fault_trips_instance_and_traffic_avoids_it(self,
                                                            tmp_path):
        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        insts = []
        for name in ("a", "b"):
            hp = str(tmp_path / f"{name}.json")
            _write_health(hp)
            insts.append(FleetInstance(name, instance_queue(root, name),
                                       hp))
        router = _router(front, insts)
        # the chaos site force-opens the FIRST instance refreshed; traffic
        # must flow around it without a single lost or parked request
        faults.arm("fleet.breaker", p=1.0, budget=1)
        front.enqueue("r0", {"uri": "r0", "tensor": [1],
                             "enqueue_t": wall_clock()})
        assert router.route_once() == 1
        states = router.breaker_states()
        assert states["a"] == BREAKER_OPEN
        assert states["b"] == BREAKER_CLOSED
        assert insts[0].queue.pending_count() == 0
        assert insts[1].queue.pending_count() == 1
        assert faults.fire_count("fleet.breaker") == 1

    def test_all_breakers_open_parks_never_raises(self, tmp_path):
        cfg = global_config()
        cfg.set("fleet.breaker_cooldown_s", 30.0)
        try:
            front, inst, router = self._one_instance_router(tmp_path)
            faults.arm("fleet.breaker", p=1.0, budget=1)
            nc0 = int(_fleet._M_NO_CAPACITY.value())
            self._req(front, "r0")
            assert router.route_once() == 0
            assert router.stats["backlog"] == 1
            assert int(_fleet._M_NO_CAPACITY.value()) == nc0 + 1
            # stop() returns the parked request to the front queue
            router.stop()
            assert front.pending_count() == 1
        finally:
            cfg.unset("fleet.breaker_cooldown_s")


class TestCriticalityLanes:
    """Admission classes ride priority lanes end to end: claims drain
    critical -> default -> sheddable (FIFO within a lane), and shed
    consumes the lanes in REVERSE — on both queue backends."""

    LOAD = (("s0", "sheddable"), ("d1", "default"), ("c2", "critical"),
            ("s3", "sheddable"), ("d4", "default"), ("c5", "critical"))

    def _load(self, q):
        for uri, lane in self.LOAD:
            q.enqueue(uri, {"tensor": [1], "criticality": lane})

    def _redis_queue(self):
        from tests.test_redis_serving import FakeRedis
        FakeRedis.instances.clear()
        return RedisQueue(client=FakeRedis("lanes-test", 1, 0))

    def test_file_queue_claim_priority_order(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"))
        self._load(q)
        assert [u for u, _ in q.claim_batch(10)] == [
            "c2", "c5", "d1", "d4", "s0", "s3"]

    def test_file_queue_sheds_sheddable_first(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"))
        self._load(q)
        assert sorted(q.shed(4)) == ["s0", "s3"]
        res = q.get_result("s0")
        assert res["error"] == SHED_ERROR and res["retriable"] is True
        assert sorted(q.shed(2)) == ["d1", "d4"]
        # the critical class is the last to lose work
        assert [u for u, _ in q.claim_batch(10)] == ["c2", "c5"]

    def test_redis_queue_claim_priority_order(self):
        q = self._redis_queue()
        self._load(q)
        assert q.pending_count() == 6
        assert [u for u, _ in q.claim_batch(10)] == [
            "c2", "c5", "d1", "d4", "s0", "s3"]

    def test_redis_queue_sheds_sheddable_first(self):
        q = self._redis_queue()
        self._load(q)
        assert sorted(q.shed(4)) == ["s0", "s3"]
        res = q.get_result("s0")
        assert res["error"] == SHED_ERROR and res["retriable"] is True
        assert sorted(q.shed(2)) == ["d1", "d4"]
        assert [u for u, _ in q.claim_batch(10)] == ["c2", "c5"]

    def test_unknown_criticality_degrades_to_default(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"))
        q.enqueue("x0", {"tensor": [1], "criticality": "page-me-at-3am"})
        q.enqueue("c1", {"tensor": [1], "criticality": "critical"})
        assert [u for u, _ in q.claim_batch(10)] == ["c1", "x0"]


class TestClientResilience:
    """ResilientClient: budgeted, jittered retries keyed on the terminal's
    ``retriable`` flag; hedged queries that surface exactly one terminal;
    and the bounded-retry stance on transient result-store errors."""

    def _client(self, tmp_path, **kw):
        kw.setdefault("backoff_s", 0.001)
        return ResilientClient(str(tmp_path / "q"), **kw)

    def test_retriable_shed_is_retried_to_success(self, tmp_path):
        client = self._client(tmp_path)
        q = client.outputs.queue
        sent = []

        def enqueue(uri):
            sent.append(uri)
            if len(sent) == 1:
                q.put_result(uri, {"error": SHED_ERROR, "retriable": True})
            else:
                q.put_result(uri, {"value": [7]})

        res = client.call("u0", enqueue, timeout_s=5.0)
        assert res["value"] == [7]
        assert sent == ["u0", "u0~r1"]  # fresh uri per attempt
        assert client.requests_sent == 1 and client.attempts_sent == 2

    def test_non_retriable_error_returns_immediately(self, tmp_path):
        client = self._client(tmp_path)
        q = client.outputs.queue
        sent = []

        def enqueue(uri):
            sent.append(uri)
            q.put_result(uri, {"error": DEADLINE_ERROR, "retriable": False})

        res = client.call("u1", enqueue, timeout_s=5.0)
        assert res["error"] == DEADLINE_ERROR
        assert sent == ["u1"] and client.attempts_sent == 1

    def test_retry_budget_bounds_amplification(self, tmp_path):
        client = self._client(tmp_path, budget_ratio=0.1, attempts=3,
                              backoff_s=0.0)
        q = client.outputs.queue

        def always_shed(uri):
            q.put_result(uri, {"error": SHED_ERROR, "retriable": True})

        for i in range(30):
            res = client.call(f"u{i}", always_shed, timeout_s=2.0)
            assert res["error"] == SHED_ERROR
        # 100% shed is the worst case: the token bucket caps retries at
        # ratio x offered load (+ the single bootstrap token)
        assert client.requests_sent == 30
        assert client.attempts_sent <= 30 + int(30 * 0.1) + 1

    def test_hedged_query_exactly_one_terminal(self, tmp_path):
        client = self._client(tmp_path)
        q = client.outputs.queue
        sent = []

        def enqueue(uri):
            sent.append(uri)
            if uri.endswith("~h"):
                q.put_result(uri, {"value": [42]})  # the hedge answers

        res = client.query_any("h0", enqueue, timeout_s=5.0,
                               hedge_delay_s=0.01)
        assert res["value"] == [42]
        assert sent == ["h0", "h0~h"]
        assert client.requests_sent == 1 and client.attempts_sent == 2
        # the losing copy lands late: reaped, never surfaced, no leak
        q.put_result("h0", {"value": [41]})
        assert client.reap_pending() == 1
        assert q.get_result("h0") is None

    def test_hedge_not_sent_when_primary_is_fast(self, tmp_path):
        client = self._client(tmp_path)
        q = client.outputs.queue
        sent = []

        def enqueue(uri):
            sent.append(uri)
            q.put_result(uri, {"value": [1]})

        res = client.query_any("p0", enqueue, timeout_s=5.0,
                               hedge_delay_s=0.25)
        assert res["value"] == [1]
        assert sent == ["p0"] and client.attempts_sent == 1

    def test_output_query_absorbs_transient_errors(self, tmp_path,
                                                   monkeypatch):
        cfg = global_config()
        cfg.set("failure.io_retries", 3)
        cfg.set("failure.io_backoff_s", 0.001)
        try:
            out = OutputQueue(str(tmp_path / "q"))
            out.queue.put_result("u0", {"value": [1]})
            real = out.queue.get_result
            calls = {"n": 0}

            def flaky(uri):
                calls["n"] += 1
                if calls["n"] <= 2:
                    raise OSError("transient backend hiccup")
                return real(uri)

            monkeypatch.setattr(out.queue, "get_result", flaky)
            assert out.query("u0", timeout_s=2.0)["value"] == [1]
            assert calls["n"] == 3
        finally:
            cfg.unset("failure.io_retries")
            cfg.unset("failure.io_backoff_s")

    def test_output_query_fatal_error_raises(self, tmp_path, monkeypatch):
        out = OutputQueue(str(tmp_path / "q"))

        def denied(uri):
            raise PermissionError("result store acl")

        monkeypatch.setattr(out.queue, "get_result", denied)
        with pytest.raises(PermissionError):
            out.query("u0", timeout_s=0.5)
