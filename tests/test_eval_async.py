"""Async eval/predict pipeline tests.

Parity contract: with ``eval.async`` on (DeviceFeed prefetch, on-device
accumulation, one host sync per pass) every evaluate/predict path must
reproduce the synchronous per-batch loops (``estimator/sync_eval.py``)
BIT-FOR-BIT — same f32 per-batch values, same f64 host accumulation order —
on multi-batch and padded/ragged-tail cases, including the
``direct_eval_per_example_fn`` exact path. Plus DeviceFeed lifecycle:
finite-iterator drain, close() mid-stream, producer-exception surfacing.
"""
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import DeviceFeed, FeatureSet
from analytics_zoo_tpu.feature.device_feed import (masked_eval_batches,
                                                   shard_payload)
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Dense


@contextmanager
def flag(name, value):
    cfg = global_config()
    had = name in cfg._overrides
    saved = cfg.get(name)
    cfg.set(name, value)
    try:
        yield
    finally:
        if had:
            cfg.set(name, saved)
        else:
            cfg.unset(name)


def make_regression(n=100, d=4, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, 1).astype(np.float32)
    x = rs.randn(n, d).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def make_direct_estimator(with_per_example=False):
    """Capture-style estimator: loss sees the raw batch, params installed
    by hand (the pod_workers.py convention)."""
    def direct_loss(params, state, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred[:, 0] - y) ** 2), state

    def per_example(params, state, rng, x, y):
        pred = x @ params["w"]
        return (pred[:, 0] - y) ** 2

    est = Estimator(
        model=None, loss_fn=None, optimizer=optimizers.SGD(0.1),
        direct_loss_fn=direct_loss,
        direct_eval_per_example_fn=per_example if with_per_example else None)
    est.params = jax.device_put({"w": jnp.asarray(np.ones((3, 1), np.float32))})
    est.model_state = {}
    est._state_resolved = True
    return est


class TestEvalParity:
    def test_metrics_eval_bit_identical(self, ctx):
        """Metric-path evaluate: multi-batch + padded tail (100 % 32 != 0),
        async == sync exactly."""
        x, y = make_regression(n=100)
        model = Sequential([Dense(8, activation="tanh"), Dense(1)])
        est = Estimator(model=model, loss_fn=objectives.get("mse"),
                        optimizer=optimizers.Adam(1e-2),
                        metrics=["mae", "mse"])
        fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
        est.train(FeatureSet.from_ndarrays(x, y, seed=1), batch_size=32,
                  epochs=2)
        with flag("eval.async", False):
            sync_scores = est.evaluate(fs, batch_size=32)
        with flag("eval.async", True):
            async_scores = est.evaluate(fs, batch_size=32)
        assert set(sync_scores) == {"mae", "mse"}
        assert sync_scores == async_scores  # bit-identical floats

    def test_direct_eval_bit_identical(self, ctx):
        """Batch-mean capture path: full batches sharded + UNPADDED tail
        (11 % 8 != 0) through its true-size compile."""
        rs = np.random.RandomState(3)
        x = rs.randn(11, 3).astype(np.float32)
        y = rs.randn(11).astype(np.float32)
        fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
        est = make_direct_estimator()
        with flag("eval.async", False):
            sync_res = est.evaluate(fs, batch_size=8)
        with flag("eval.async", True):
            async_res = est.evaluate(fs, batch_size=8)
        assert sync_res == async_res
        expect = float(np.sum(((x @ np.ones((3, 1)))[:, 0] - y) ** 2)) / 11
        assert async_res["loss"] == pytest.approx(expect, rel=1e-5)

    def test_direct_exact_eval_bit_identical(self, ctx):
        """Per-example exact path: padded tails masked out on device, one
        device_get drains the pass; async == sync exactly."""
        rs = np.random.RandomState(4)
        x = rs.randn(19, 3).astype(np.float32)
        y = rs.randn(19).astype(np.float32)
        fs = FeatureSet.from_ndarrays(x, y, shuffle=False, shard=False)
        est = make_direct_estimator(with_per_example=True)
        with flag("eval.async", False):
            sync_res = est.evaluate(fs, batch_size=8)
        with flag("eval.async", True):
            async_res = est.evaluate(fs, batch_size=8)
        assert sync_res == async_res
        expect = float(np.sum(((x @ np.ones((3, 1)))[:, 0] - y) ** 2)) / 19
        assert async_res["loss"] == pytest.approx(expect, rel=1e-5)

    def test_empty_validation_set_still_raises(self, ctx):
        model = Sequential([Dense(4), Dense(1)])
        est = Estimator(model=model, loss_fn=objectives.get("mse"),
                        optimizer=optimizers.Adam(1e-2), metrics=["mae"])
        x, y = make_regression(n=8)
        est.train(FeatureSet.from_ndarrays(x, y), batch_size=8, epochs=1)
        empty = FeatureSet.from_ndarrays(np.zeros((0, 4), np.float32),
                                         np.zeros((0, 1), np.float32),
                                         shuffle=False)
        with pytest.raises(ValueError, match="no batches"):
            est.evaluate(empty, batch_size=8)


class TestPredictParity:
    def _trained(self, ctx, n=100):
        x, y = make_regression(n=n)
        model = Sequential([Dense(8, activation="tanh"), Dense(1)])
        est = Estimator(model=model, loss_fn=objectives.get("mse"),
                        optimizer=optimizers.Adam(1e-2))
        est.train(FeatureSet.from_ndarrays(x, y, seed=1), batch_size=32,
                  epochs=1)
        return est, x

    def test_predict_bit_identical_with_ragged_tail(self, ctx):
        est, x = self._trained(ctx)
        with flag("eval.async", False):
            sync_preds = est.predict(x, batch_size=32)
        with flag("eval.async", True):
            async_preds = est.predict(x, batch_size=32)
        assert np.asarray(async_preds).shape == (100, 1)
        np.testing.assert_array_equal(np.asarray(sync_preds),
                                      np.asarray(async_preds))

    def test_predict_window_sizes_agree(self, ctx):
        """The in-flight window K only changes WHEN results are fetched,
        never what they are."""
        est, x = self._trained(ctx)
        with flag("eval.predict_window", 1):
            w1 = est.predict(x, batch_size=16)
        with flag("eval.predict_window", 8):
            w8 = est.predict(x, batch_size=16)
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w8))


class TestDeviceFeedLifecycle:
    def _fs(self, n=64):
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        y = np.arange(n, dtype=np.float32)
        return FeatureSet.from_ndarrays(x, y, shuffle=False)

    def test_finite_iterator_full_drain(self, ctx):
        """A finite masked eval feed drains every batch then StopIterates;
        metadata (valid counts) rides along host-side untouched."""
        fs = self._fs(20)
        host_it = masked_eval_batches(
            fs.eval_iterator(8, pad_remainder=True), 8)
        with DeviceFeed(host_it, ctx.mesh, shard_fn=shard_payload) as feed:
            items = list(feed)
        assert [v for _, v in items] == [8, 8, 4]
        (x, y, mask), valid = items[-1]
        assert isinstance(valid, int)
        assert x.shape == (8, 4)  # padded static shape, sharded
        np.testing.assert_array_equal(
            np.asarray(mask), [1, 1, 1, 1, 0, 0, 0, 0])
        with pytest.raises(StopIteration):
            next(feed)

    def test_close_mid_epoch_stops_producer(self, ctx):
        fs = self._fs(64)
        feed = DeviceFeed(fs.train_iterator(16), ctx.mesh, prefetch=2)
        next(feed)
        next(feed)
        feed.close()
        feed.close()  # idempotent
        assert not feed._thread.is_alive()
        with pytest.raises(StopIteration):
            next(feed)

    def test_context_manager_closes_on_break(self, ctx):
        fs = self._fs(64)
        with DeviceFeed(fs.train_iterator(16), ctx.mesh) as feed:
            next(feed)
        feed._thread.join(timeout=5)
        assert not feed._thread.is_alive()

    def test_producer_exception_surfaces(self, ctx):
        def bad_batches():
            yield np.ones((8, 4), np.float32), np.ones(8, np.float32)
            raise RuntimeError("decode failed mid-stream")

        with DeviceFeed(bad_batches(), ctx.mesh) as feed:
            next(feed)  # first batch is fine
            with pytest.raises(RuntimeError, match="decode failed"):
                while True:
                    next(feed)
