"""Paged KV scheduler: parity with the contiguous engine and with serial
generate, copy-on-write shared prefixes (prefilled ONCE), speculative
draft/verify token-identity, page-pool exhaustion chaos
(``serving.page_alloc``), and the paged metrics plane.

Op-level paged invariants live in tests/test_paged_kv.py; the contiguous
scheduler's own parity suite is tests/test_generative_serving.py.
"""
import uuid

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import metrics as _metrics
from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.server import PAGE_SHED_ERROR


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


pytestmark = pytest.mark.slow  # scheduler-level suite; tier-1 covers the op layer

_LM_CACHE = {}


def _lm(max_len=32, seed=0):
    lm = _LM_CACHE.get((max_len, seed))
    if lm is None:
        from analytics_zoo_tpu.capture.lm import TransformerLM
        rs = np.random.RandomState(seed)
        lm = TransformerLM(vocab_size=16, hidden=16, n_block=2, n_head=2,
                           max_len=max_len, seed=seed)
        lm.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
        _LM_CACHE[(max_len, seed)] = lm
    return lm


def _src(tmp_path):
    return f"dir://{tmp_path}/{uuid.uuid4().hex[:8]}"


def _drive(srv, steps=200):
    idle = 0
    for _ in range(steps):
        if srv.serve_step() == 0:
            idle += 1
            if idle >= 3:
                return
        else:
            idle = 0


def _paged_cfg(src, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_new_tokens", 8)
    kw.setdefault("kv_pages", 16)
    kw.setdefault("kv_page_len", 8)
    return ServingConfig(data_src=src, **kw)


class TestPagedParity:
    @pytest.mark.slow
    def test_greedy_bit_identical_with_midstream_joins(self, ctx, tmp_path):
        # 5 requests through 2 slots: the page pool sees mid-stream joins
        # reusing pages freed by earlier retirements
        lm = _lm()
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 1, 6, 3, 5)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"r{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"r{i}", timeout_s=5)
            assert res is not None and res.get("done") is True
            assert res["value"] == want, f"stream r{i} diverged"
        snap = srv.health_snapshot()
        assert snap["slots_occupied"] == 0
        # every page returned to the pool after the last retirement
        assert snap["kv_pages_free"] == 15

    @pytest.mark.slow
    def test_sampled_bit_identical_per_request_seed(self, ctx, tmp_path):
        lm = _lm()
        rs = np.random.RandomState(4)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (5, 2, 1, 7)]
        seeds = [11, 22, 33, 44]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8,
                              temperature=0.9, top_k=8, seed=s)[0].tolist()
                  for p, s in zip(prompts, seeds)]
        src = _src(tmp_path)
        srv = GenerativeServing(
            _paged_cfg(src, temperature=0.9, top_k=8), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, (p, s) in enumerate(zip(prompts, seeds)):
            inq.enqueue_prompt(f"r{i}", p, seed=s)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"r{i}", timeout_s=5)
            assert res is not None and res["value"] == want

    @pytest.mark.slow
    def test_int8_kv_token_parity(self, ctx, tmp_path):
        """int8 pool error (bounded at the op level) is far inside the
        tiny model's logit margins, so the token streams stay equal."""
        lm = _lm()
        rs = np.random.RandomState(5)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 6)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src, kv_int8=True), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"q{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"q{i}", timeout_s=5)
            assert res is not None and res["value"] == want


class TestSharedPrefixCoW:
    @pytest.mark.slow
    def test_prefix_prefilled_once_and_bit_identical(self, ctx, tmp_path,
                                                     monkeypatch):
        lm = _lm()
        prefix = [3, 7, 2, 9, 5]                        # 5 tokens: CoW tail
        lasts = [1, 4, 8, 12]
        prompts = [prefix + [t] for t in lasts]
        # serial references FIRST — the call counter below must only see
        # the scheduler's traffic
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        calls = []
        orig = lm.prefill_kv
        monkeypatch.setattr(
            lm, "prefill_kv",
            lambda params, tokens: (calls.append(tokens.shape), orig(
                params, tokens))[1])
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src), lm)
        free0 = srv.health_snapshot()["kv_pages_free"]
        srv.register_prefix(prefix)
        assert srv.health_snapshot()["kv_pages_free"] == free0 - 1
        # prompt = prefix + one token joins with NO suffix forward at all:
        # decode reads the registered pages through a CoW tail copy, so
        # the streams are bit-identical to serial generate
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"c{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"c{i}", timeout_s=5)
            assert res is not None and res["value"] == want
        # the common prefix went through the transformer EXACTLY once
        # (register time); joins never re-prefilled it
        assert len(calls) == 1
        # registry keeps its permanent page across all retirements
        assert srv.health_snapshot()["kv_pages_free"] == free0 - 1

    @pytest.mark.slow
    def test_divergent_suffixes_only_prefill_the_suffix(self, ctx, tmp_path,
                                                        monkeypatch):
        lm = _lm()
        rs = np.random.RandomState(6)
        prefix = rs.randint(0, 16, (6,)).tolist()
        prompts = [prefix + rs.randint(0, 16, (n,)).tolist()
                   for n in (3, 5, 2)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=6)[0].tolist()
                  for p in prompts]
        calls, scalls = [], []
        orig, sorig = lm.prefill_kv, lm.prefill_kv_suffix
        monkeypatch.setattr(
            lm, "prefill_kv",
            lambda params, tokens: (calls.append(tokens.shape), orig(
                params, tokens))[1])
        monkeypatch.setattr(
            lm, "prefill_kv_suffix",
            lambda params, tokens, pref, plen: (
                scalls.append(tokens.shape), sorig(params, tokens, pref,
                                                   plen))[1])
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src, max_new_tokens=6), lm)
        srv.register_prefix(prefix)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"s{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"s{i}", timeout_s=5)
            assert res is not None and res["value"] == want
        assert len(calls) == 1          # the register-time prefix forward
        assert len(scalls) >= 1         # joins ran the SUFFIX path only


class TestSpeculative:
    @pytest.mark.slow
    def test_spec_token_identical_to_serial_greedy(self, ctx, tmp_path):
        lm = _lm()
        draft = _lm(max_len=64, seed=1)   # different weights: a REAL draft
        rs = np.random.RandomState(7)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 1, 6)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src, spec_k=3), lm,
                                draft_lm=draft)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"v{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"v{i}", timeout_s=5)
            assert res is not None and res.get("done") is True
            assert res["value"] == want, f"stream v{i} diverged"
        snap = srv.health_snapshot()
        assert snap["spec_accept_ratio"] is not None
        assert 0.0 <= snap["spec_accept_ratio"] <= 1.0

    @pytest.mark.slow
    def test_spec_eos_terminates_streams(self, ctx, tmp_path):
        lm = _lm()
        draft = _lm(max_len=64, seed=1)
        eos = 1
        rs = np.random.RandomState(8)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 3)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=10,
                              eos_id=eos)[0].tolist() for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(
            _paged_cfg(src, max_new_tokens=10, spec_k=3, eos_id=eos), lm,
            draft_lm=draft)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"e{i}", p)
        _drive(srv)
        for i, row in enumerate(serial):
            want = row[:row.index(eos) + 1] if eos in row else row
            res = outq.query(f"e{i}", timeout_s=5)
            assert res is not None and res["value"] == want

    def test_spec_requires_paged_and_greedy(self, ctx, tmp_path):
        lm = _lm()
        draft = _lm(max_len=64, seed=1)
        src = _src(tmp_path)
        with pytest.raises(ValueError, match="paged"):
            GenerativeServing(
                ServingConfig(data_src=src, slots=2, spec_k=2), lm,
                draft_lm=draft)
        with pytest.raises(ValueError, match="greedy"):
            GenerativeServing(_paged_cfg(src, spec_k=2, temperature=0.8),
                              lm, draft_lm=draft)


class TestPagePoolChaos:
    def test_page_alloc_fault_sheds_join_keeps_serving(self, ctx, tmp_path):
        """The armed ``serving.page_alloc`` site simulates pool exhaustion
        at join: the victim is SHED with its one terminal result and the
        resident stream keeps decoding to its serial-identical end."""
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        serial = lm.generate(np.asarray([[2, 3, 5]]),
                             max_new_tokens=8)[0].tolist()
        inq.enqueue_prompt("alive", [2, 3, 5])
        srv.serve_step()                      # resident stream joins first
        faults.arm("serving.page_alloc", at=1)
        inq.enqueue_prompt("victim", [4, 1])
        _drive(srv)
        assert faults.fire_count("serving.page_alloc") == 1
        res = outq.query("victim", timeout_s=5)
        assert res is not None and res["error"] == PAGE_SHED_ERROR
        assert srv.counters["shed"] == 1
        # the resident stream was untouched by the shed
        assert outq.query("alive", timeout_s=5)["value"] == serial
        # and the NEXT request (fault budget spent) decodes normally
        inq.enqueue_prompt("after", [2, 3, 5])
        _drive(srv)
        assert outq.query("after", timeout_s=5)["value"] == serial

    @pytest.mark.slow
    def test_real_exhaustion_sheds_then_recovers_after_retire(
            self, ctx, tmp_path):
        # 4 usable pages, 2 per stream: the third concurrent join finds
        # an empty pool and is shed; retirement refunds the pages and the
        # next request sails through
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src, slots=3, kv_pages=5), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        serial = lm.generate(np.asarray([[2, 3]]),
                             max_new_tokens=8)[0].tolist()
        for i in range(3):
            inq.enqueue_prompt(f"x{i}", [2, 3])
        _drive(srv)
        errors = [outq.query(f"x{i}", timeout_s=5) for i in range(3)]
        shed = [r for r in errors if r.get("error") == PAGE_SHED_ERROR]
        done = [r for r in errors if r.get("value") == serial]
        assert len(shed) == 1 and len(done) == 2
        assert srv.counters["shed"] == 1
        snap = srv.health_snapshot()
        assert snap["kv_pages_free"] == 4   # refunded at retirement
        inq.enqueue_prompt("x3", [2, 3])
        _drive(srv)
        assert outq.query("x3", timeout_s=5)["value"] == serial

    def test_paged_metrics_exposed(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src), lm)
        inq = InputQueue(src)
        inq.enqueue_prompt("m0", [5, 2, 8])
        _drive(srv)
        text = _metrics.expose_text()
        for name in ("serving_kv_pages_free",
                     "serving_kv_page_evictions_total",
                     "serving_spec_accept_ratio"):
            assert name in text
        # the retirement refunded this stream's pages as evictions
        snap = srv.health_snapshot()
        assert snap["kv_pages_free"] == 15
        assert snap["spec_accept_ratio"] is None   # not a spec server


class TestShardedPool:
    """``kv_shard``: the page pool's PAGE axis spread across devices —
    decode gathers each stream's pages to the compute device, so the
    sharded scheduler is TOKEN-identical to serial generate (and hence
    to ``kv_shard=1``), while health reports per-shard capacity
    (docs/parallelism.md#sharded-kv-serving)."""

    def test_sharded_decode_token_identical(self, ctx, tmp_path):
        lm = _lm()
        rs = np.random.RandomState(11)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 1, 6, 3, 5)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(_paged_cfg(src, kv_shard=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"r{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"r{i}", timeout_s=5)
            assert res is not None and res.get("done") is True
            assert res["value"] == want, f"sharded stream r{i} diverged"
        snap = srv.health_snapshot()
        assert snap["kv_shards"] == 4
        assert snap["slots_occupied"] == 0
        # every page back in the free list (page 0 stays reserved as the
        # null page) -> shard 0 reports 3 free, the other shards 4
        assert snap["kv_pages_free"] == 15
        assert snap["kv_pages_free_min_shard"] == 3

    def test_shard_must_divide_pool(self, ctx, tmp_path):
        lm = _lm()
        with pytest.raises(ValueError, match="kv shard"):
            GenerativeServing(
                _paged_cfg(_src(tmp_path), kv_pages=15, kv_shard=4), lm)
