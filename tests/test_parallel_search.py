"""ParallelSearchEngine: process-parallel trials matching the sequential
engine's search space, plus the estimator's record-weighted direct eval."""
import os

import numpy as np
import pytest

from analytics_zoo_tpu.automl import hp
from analytics_zoo_tpu.automl.config.recipe import Recipe
from analytics_zoo_tpu.automl.search import (
    LocalSearchEngine, ParallelSearchEngine)


class _GridRecipe(Recipe):
    def search_space(self, feature_cols=None):
        return {"lr": hp.Grid([0.1, 0.01, 0.001]), "units": hp.Grid([4, 8])}

    def search_algorithm(self):
        return "grid"

    def runtime_params(self):
        return {"num_samples": 1}


def _quadratic_trial(config, data):
    # deterministic objective: workers and the local engine must agree
    return (config["lr"] - 0.01) ** 2 + (config["units"] - 8) ** 2 / 100.0


class TestParallelSearch:
    def test_matches_sequential_results(self):
        seq = LocalSearchEngine(seed=0)
        seq.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_quadratic_trial)
        seq_trials = seq.run()

        par = ParallelSearchEngine(num_workers=3, seed=0)
        par.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_quadratic_trial)
        par_trials = par.run()

        assert len(par_trials) == len(seq_trials) == 6
        assert {(t.config["lr"], t.config["units"]) for t in par_trials} \
            == {(t.config["lr"], t.config["units"]) for t in seq_trials}
        best = par.get_best_trials(1)[0]
        assert best.config["lr"] == 0.01 and best.config["units"] == 8

    def test_trials_run_in_worker_processes(self):
        par = ParallelSearchEngine(num_workers=2, seed=0)
        par.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_pid_trial)
        pids = {int(t.metric) for t in par.run()}
        # really ran in worker processes (how many grab work is up to the
        # pool's scheduling, so only the "not in-process" half is stable)
        assert os.getpid() not in pids

    def test_unpicklable_trainable_rejected(self):
        par = ParallelSearchEngine(num_workers=2, seed=0)
        par.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=lambda c, d: 0.0)
        with pytest.raises(ValueError, match="picklable"):
            par.run()


def _pid_trial(config, data):
    return float(os.getpid())


class TestPodSearch:
    def test_matches_sequential_best_config(self):
        from analytics_zoo_tpu.automl.search import PodSearchEngine
        seq = LocalSearchEngine(seed=0)
        seq.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_quadratic_trial)
        seq_trials = seq.run()

        pod = PodSearchEngine(num_workers=2, seed=0, timeout=300)
        pod.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_quadratic_trial)
        pod_trials = pod.run()

        assert [(t.config["lr"], t.config["units"]) for t in pod_trials] \
            == [(t.config["lr"], t.config["units"]) for t in seq_trials]
        best = pod.get_best_trials(1)[0]
        seq_best = seq.get_best_trials(1)[0]
        assert best.config == seq_best.config
        assert best.metric == pytest.approx(seq_best.metric)

    def test_distinct_trials_per_worker(self):
        from analytics_zoo_tpu.automl.search import PodSearchEngine
        pod = PodSearchEngine(num_workers=2, seed=0, timeout=300)
        pod.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=_pid_trial)
        trials = pod.run()
        pids = {int(t.metric) for t in trials}
        assert os.getpid() not in pids
        assert len(pids) == 2, "expected trials spread over 2 pod workers"
        # stride placement: trial i runs on worker i % 2
        assert len({int(t.metric) for t in trials[0::2]}) == 1
        assert len({int(t.metric) for t in trials[1::2]}) == 1

    def test_lambda_trainable_works_via_cloudpickle(self):
        from analytics_zoo_tpu.automl.search import PodSearchEngine
        pod = PodSearchEngine(num_workers=2, seed=0, timeout=300)
        pod.compile(data=None, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse",
                    fit_fn=lambda c, d: (c["lr"] - 0.01) ** 2)
        trials = pod.run()
        assert pod.get_best_trials(1)[0].config["lr"] == 0.01
        assert len(trials) == 6

    def test_unserializable_rejected(self):
        import threading

        from analytics_zoo_tpu.automl.search import PodSearchEngine
        lock = threading.Lock()
        pod = PodSearchEngine(num_workers=2, seed=0)
        pod.compile(data=lock, model_create_fn=None, recipe=_GridRecipe(),
                    metric="mse", fit_fn=lambda c, d: 0.0)
        with pytest.raises(ValueError, match="serializable"):
            pod.run()


class TestParallelPredictor:
    def test_time_sequence_parallel_search(self):
        """The end-user path: AutoTS-style predictor with parallel trials."""
        import pandas as pd
        from analytics_zoo_tpu.automl import SmokeRecipe, TimeSequencePredictor
        rs = np.random.RandomState(0)
        df = pd.DataFrame({
            "datetime": pd.date_range("2024-01-01", periods=80, freq="h"),
            "value": np.sin(np.arange(80) / 6) + 0.05 * rs.randn(80),
        })
        tsp = TimeSequencePredictor(future_seq_len=1)
        pipeline = tsp.fit(df, recipe=SmokeRecipe(), metric="mse",
                           search_engine="parallel", num_workers=2)
        res = pipeline.evaluate(df, metrics=["mse"])
        assert np.isfinite(res["mse"])


class TestWeightedDirectEval:
    def _setup(self, n):
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        model = Sequential([Dense(1, name="d")])

        def direct_loss(params, state, rng, x, y):
            pred, _ = model.call(params, state, x)
            return jnp.mean((pred[:, 0] - y) ** 2), state

        est = Estimator(model=model, loss_fn=None, optimizer=None,
                        direct_loss_fn=direct_loss)
        rs = np.random.RandomState(0)
        x = rs.randn(n, 3).astype(np.float32)
        y = rs.randn(n).astype(np.float32)
        return est, FeatureSet.from_ndarrays(x, y, shuffle=False), x, y

    def _expected(self, est, x, y):
        import jax.numpy as jnp
        est._ensure_initialized(x)
        params = est.get_params()
        pred = x @ params["d"]["kernel"] + params["d"]["bias"]
        return float(np.mean((pred[:, 0] - y) ** 2))

    def test_tail_records_counted(self):
        est, fs, x, y = self._setup(20)  # batch 16 → one full + tail of 4
        result = est.evaluate(fs, batch_size=16)
        assert result["loss"] == pytest.approx(self._expected(est, x, y),
                                               rel=1e-4)

    def test_tiny_validation_set_works(self):
        est, fs, x, y = self._setup(3)  # smaller than one device batch
        result = est.evaluate(fs, batch_size=64)
        assert result["loss"] == pytest.approx(self._expected(est, x, y),
                                               rel=1e-4)


class TestPodPredictor:
    def test_time_sequence_pod_search(self):
        """AutoTS-style predictor with pod-distributed trials."""
        import pandas as pd

        from analytics_zoo_tpu.automl import SmokeRecipe, TimeSequencePredictor
        rs = np.random.RandomState(0)
        df = pd.DataFrame({
            "datetime": pd.date_range("2024-01-01", periods=80, freq="h"),
            "value": np.sin(np.arange(80) / 6) + 0.05 * rs.randn(80),
        })
        tsp = TimeSequencePredictor(future_seq_len=1)
        pipeline = tsp.fit(df, recipe=SmokeRecipe(), metric="mse",
                           search_engine="pod", num_workers=2)
        res = pipeline.evaluate(df, metrics=["mse"])
        assert np.isfinite(res["mse"])

    def test_unknown_engine_rejected(self):
        import pandas as pd

        from analytics_zoo_tpu.automl import SmokeRecipe, TimeSequencePredictor
        df = pd.DataFrame({
            "datetime": pd.date_range("2024-01-01", periods=20, freq="h"),
            "value": np.arange(20.0)})
        with pytest.raises(ValueError, match="local/parallel/pod"):
            TimeSequencePredictor(future_seq_len=1).fit(
                df, recipe=SmokeRecipe(), search_engine="ray")
