"""Layer unit tests: forward shapes + golden values (reference test strategy:
per-layer forward/backward numerical checks, SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.keras import Input, Model, Sequential, init_model
from analytics_zoo_tpu.keras.layers import (
    Activation, BatchNormalization, Bidirectional, Conv1D, Conv2D, Dense,
    Dropout, Embedding, Flatten, GlobalAveragePooling2D, GRU,
    LayerNormalization, LSTM, MaxPooling2D, Merge, Reshape, SimpleRNN,
    WordEmbedding, merge)

RNG = jax.random.PRNGKey(0)


def run_layer(layer, x, training=False, rng=None):
    params, state = layer.build(RNG, (None,) + x.shape[1:])
    y, new_state = layer.call(params, state, jnp.asarray(x),
                              training=training, rng=rng)
    return y, params, new_state


class TestCoreLayers:
    def test_dense_forward_and_shape(self):
        x = np.ones((2, 3), np.float32)
        layer = Dense(4, activation="relu")
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 4)
        expected = jax.nn.relu(x @ np.asarray(params["kernel"]))
        np.testing.assert_allclose(y, expected, rtol=1e-6)
        assert layer.compute_output_shape((None, 3)) == (None, 4)

    def test_dense_grad(self):
        x = jnp.ones((2, 3))
        layer = Dense(4)
        params, _ = layer.build(RNG, (None, 3))
        g = jax.grad(lambda p: layer.call(p, {}, x)[0].sum())(params)
        assert g["kernel"].shape == (3, 4)
        np.testing.assert_allclose(g["bias"], 2.0 * np.ones(4), rtol=1e-6)

    def test_dropout_train_vs_infer(self):
        x = np.ones((4, 10), np.float32)
        layer = Dropout(0.5)
        y_inf, _, _ = run_layer(layer, x, training=False)
        np.testing.assert_array_equal(y_inf, x)
        y_tr, _, _ = run_layer(layer, x, training=True, rng=jax.random.PRNGKey(1))
        assert float(jnp.sum(y_tr == 0.0)) > 0  # some dropped
        kept = np.asarray(y_tr)[np.asarray(y_tr) != 0]
        np.testing.assert_allclose(kept, 2.0)  # scaled by 1/keep

    def test_flatten_reshape(self):
        x = np.zeros((2, 3, 4), np.float32)
        y, _, _ = run_layer(Flatten(), x)
        assert y.shape == (2, 12)
        y2, _, _ = run_layer(Reshape((4, 3)), x)
        assert y2.shape == (2, 4, 3)

    def test_merge_modes(self):
        a = jnp.ones((2, 3))
        b = 2 * jnp.ones((2, 3))
        for mode, want in [("sum", 3.0), ("mul", 2.0), ("max", 2.0), ("ave", 1.5)]:
            layer = Merge(mode)
            y, _ = layer.call({}, {}, [a, b])
            np.testing.assert_allclose(y, want * np.ones((2, 3)), rtol=1e-6)
        y, _ = Merge("concat").call({}, {}, [a, b])
        assert y.shape == (2, 6)
        y, _ = Merge("dot").call({}, {}, [a, b])
        np.testing.assert_allclose(y, 6 * np.ones((2, 1)), rtol=1e-6)


class TestEmbeddingNorm:
    def test_embedding(self):
        x = np.array([[0, 2], [1, 1]], np.int32)
        layer = Embedding(5, 8)
        y, params, _ = run_layer(layer, x)
        assert y.shape == (2, 2, 8)
        np.testing.assert_allclose(y[0, 1], params["embeddings"][2], rtol=1e-6)

    def test_word_embedding_frozen(self):
        table = np.arange(12, dtype=np.float32).reshape(4, 3)
        layer = WordEmbedding(table, trainable=False)
        params, state = layer.build(RNG, (None, 2))
        assert params == {}  # frozen: lives in state, excluded from grads
        y, _ = layer.call(params, state, jnp.array([[3, 0]]))
        np.testing.assert_allclose(y[0, 0], table[3])

    def test_batchnorm_train_updates_stats(self):
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32) * 3 + 1
        layer = BatchNormalization(momentum=0.9)
        params, state = layer.build(RNG, (None, 4))
        y, new_state = layer.call(params, state, jnp.asarray(x), training=True)
        np.testing.assert_allclose(np.mean(y, axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(np.std(y, axis=0), 1.0, atol=1e-2)
        assert not np.allclose(new_state["moving_mean"], 0.0)
        # inference path uses moving stats
        y_inf, s2 = layer.call(params, new_state, jnp.asarray(x), training=False)
        assert s2 is new_state or np.allclose(
            s2["moving_mean"], new_state["moving_mean"])

    def test_layernorm(self):
        x = np.random.RandomState(1).randn(3, 7).astype(np.float32)
        layer = LayerNormalization()
        y, _, _ = run_layer(layer, x)
        np.testing.assert_allclose(np.mean(y, axis=-1), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.std(y, axis=-1), 1.0, atol=1e-2)


class TestConvPool:
    def test_conv2d_shapes(self):
        x = np.zeros((2, 8, 8, 3), np.float32)
        layer = Conv2D(16, 3, 3)
        y, _, _ = run_layer(layer, x)
        assert y.shape == (2, 6, 6, 16)
        same = Conv2D(16, 3, 3, border_mode="same", subsample=(2, 2))
        y2, _, _ = run_layer(same, x)
        assert y2.shape == (2, 4, 4, 16)
        assert same.compute_output_shape((None, 8, 8, 3)) == (None, 4, 4, 16)

    def test_conv2d_known_value(self):
        x = np.ones((1, 3, 3, 1), np.float32)
        layer = Conv2D(1, 2, 2, init="ones", bias=False)
        y, _, _ = run_layer(layer, x)
        np.testing.assert_allclose(y, 4 * np.ones((1, 2, 2, 1)), rtol=1e-6)

    def test_conv1d(self):
        x = np.zeros((2, 10, 4), np.float32)
        y, _, _ = run_layer(Conv1D(8, 3), x)
        assert y.shape == (2, 8, 8)

    def test_pooling(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
        y, _, _ = run_layer(MaxPooling2D((2, 2)), x)
        assert y.shape == (1, 2, 2, 1)
        np.testing.assert_allclose(y[0, :, :, 0], [[5, 7], [13, 15]])
        g, _, _ = run_layer(GlobalAveragePooling2D(), x)
        np.testing.assert_allclose(g, [[7.5]])


class TestRecurrent:
    def test_lstm_shapes(self):
        x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
        y, _, _ = run_layer(LSTM(7), x)
        assert y.shape == (2, 7)
        y2, _, _ = run_layer(LSTM(7, return_sequences=True), x)
        assert y2.shape == (2, 5, 7)

    def test_gru_simple_rnn(self):
        x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
        assert run_layer(GRU(4), x)[0].shape == (2, 4)
        assert run_layer(SimpleRNN(4), x)[0].shape == (2, 4)

    def test_lstm_numerics_vs_manual(self):
        # golden check: 1 step of LSTM == hand-computed gates
        x = np.ones((1, 1, 2), np.float32)
        layer = LSTM(2)
        params, _ = layer.build(RNG, (None, 1, 2))
        y, _ = layer.call(params, {}, jnp.asarray(x))
        k = np.asarray(params["kernel"])
        b = np.asarray(params["bias"])
        z = np.concatenate([x[0, 0], np.zeros(2)]) @ k + b
        i, f, g, o = np.split(z, 4)
        c = 1 / (1 + np.exp(-i)) * np.tanh(g)
        h = 1 / (1 + np.exp(-o)) * np.tanh(c)
        np.testing.assert_allclose(np.asarray(y)[0], h, rtol=1e-5)

    def test_bidirectional(self):
        x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
        y, _, _ = run_layer(Bidirectional(LSTM(4)), x)
        assert y.shape == (2, 8)


class TestContainers:
    def test_sequential(self):
        model = Sequential([Dense(8, activation="relu"), Dense(2)])
        params, state = init_model(model, RNG, np.zeros((4, 3), np.float32))
        y, _ = model.call(params, state, jnp.zeros((4, 3)))
        assert y.shape == (4, 2)
        assert model.compute_output_shape((None, 3)) == (None, 2)

    def test_functional_graph_two_towers(self):
        a = Input((4,))
        b = Input((4,))
        ha = Dense(8, activation="relu")(a)
        hb = Dense(8, activation="relu")(b)
        m = merge([ha, hb], mode="concat")
        out = Dense(1, activation="sigmoid")(m)
        model = Model([a, b], out)
        params, state = model.build(RNG)
        y, _ = model.call(params, state, [jnp.ones((2, 4)), jnp.ones((2, 4))])
        assert y.shape == (2, 1)

    def test_shared_layer(self):
        shared = Dense(6)
        a = Input((3,))
        b = Input((3,))
        out = merge([shared(a), shared(b)], mode="sum")
        model = Model([a, b], out)
        params, _ = model.build(RNG)
        assert len([k for k in params if k.startswith("dense")]) == 1  # shared

    def test_symbolic_operators(self):
        a = Input((4,))
        b = Input((4,))
        out = (a + b) * 2.0 - 1.0
        model = Model([a, b], out)
        params, state = model.build(RNG)
        y, _ = model.call(params, state, [jnp.ones((2, 4)), jnp.ones((2, 4))])
        np.testing.assert_allclose(y, 3.0 * np.ones((2, 4)), rtol=1e-6)

    def test_jit_forward(self):
        model = Sequential([Dense(8, activation="tanh"), Dense(2)])
        params, state = init_model(model, RNG, np.zeros((4, 3), np.float32))
        fwd = jax.jit(lambda p, x: model.call(p, state, x)[0])
        y = fwd(params, jnp.ones((4, 3)))
        assert y.shape == (4, 2)
