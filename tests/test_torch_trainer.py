"""TorchTrainer: foreign-framework (torch) data-parallel training over the
pod launcher — the reference's MXNet-on-Ray role
(``pyzoo/zoo/ray/mxnet/mxnet_trainer.py:26``) with gloo allreduce standing in
for the KVStore."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from analytics_zoo_tpu.cluster import TorchTrainer  # noqa: E402
from tests import torch_creators as tc  # noqa: E402


class TestTorchTrainer:
    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_two_worker_convergence(self, tmp_path):
        trainer = TorchTrainer(tc.make_model, tc.make_optimizer, tc.make_loss,
                               tc.make_data, num_workers=2,
                               log_dir=str(tmp_path))
        history = trainer.train(epochs=40, timeout=600)
        assert len(history) == 40
        assert history[-1] < history[0] * 0.05  # linear problem: big drop

        state = trainer.state_dict()
        w = state["weight"].numpy()
        b = state["bias"].numpy()
        np.testing.assert_allclose(w, tc.W_TRUE.T, atol=0.15)
        np.testing.assert_allclose(b, [0.5], atol=0.15)

        model = trainer.load_into(tc.make_model())
        pred = model(torch.tensor([[1.0, 1.0]])).detach().numpy()
        np.testing.assert_allclose(pred, [[2.0 - 3.0 + 0.5]], atol=0.3)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_allreduce_matches_single_worker_fullbatch(self, tmp_path):
        """2 workers averaging grads over disjoint half-shards must equal 1
        worker seeing the concatenated data — the sync-SGD contract."""
        t1 = TorchTrainer(tc.make_model, tc.make_optimizer, tc.make_loss,
                          tc.data_full, num_workers=1,
                          log_dir=str(tmp_path / "w1"))
        t1.train(epochs=3, timeout=600)
        t2 = TorchTrainer(tc.make_model, tc.make_optimizer, tc.make_loss,
                          tc.data_halves, num_workers=2,
                          log_dir=str(tmp_path / "w2"))
        hist2 = t2.train(epochs=3, timeout=600)
        for k, v in t1.state_dict().items():
            np.testing.assert_allclose(v.numpy(), t2.state_dict()[k].numpy(),
                                       rtol=1e-5, atol=1e-6)
        assert hist2[-1] < hist2[0]
