"""Tests for the flow-tracing half of the telemetry plane: session
nesting, thread labels, pid-correct spans from forked workers, serving
request-lifecycle flow chains, and the health_snapshot() registry view."""
import json
import os

import numpy as np
import pytest

from analytics_zoo_tpu.common.utils import time_it
from analytics_zoo_tpu.utils import trace as trace_mod
from analytics_zoo_tpu.utils.trace import (
    flow_point, new_trace_id, set_thread_label, trace)

_STAGES = {"serving.enqueue", "serving.claim", "serving.decode",
           "serving.dispatch", "serving.result"}


def _spans(path):
    return [e for e in json.load(open(path)) if e.get("ph") == "X"]


class TestSessionSemantics:
    def test_nested_sessions_merge(self, tmp_path):
        """Satellite: the outer session must keep recording during an
        inner one (the old recorder silently dropped those spans)."""
        outer_p = str(tmp_path / "outer.json")
        inner_p = str(tmp_path / "inner.json")
        with trace(outer_p):
            with time_it("before_inner"):
                pass
            with trace(inner_p):
                with time_it("during_inner"):
                    pass
            with time_it("after_inner"):
                pass
        outer = {s["name"] for s in _spans(outer_p)}
        inner = {s["name"] for s in _spans(inner_p)}
        assert {"before_inner", "during_inner", "after_inner"} <= outer
        assert inner == {"during_inner"}

    def test_not_tracing_outside_sessions(self, tmp_path):
        assert not trace_mod.tracing()
        with trace(str(tmp_path / "t.json")):
            assert trace_mod.tracing()
        assert not trace_mod.tracing()
        # flow_point outside a session is a cheap no-op, not an error
        flow_point(new_trace_id(), "serving.enqueue", "s")

    def test_spans_carry_real_pid(self, tmp_path):
        p = str(tmp_path / "t.json")
        with trace(p):
            with time_it("pid_probe"):
                pass
        (span,) = [s for s in _spans(p) if s["name"] == "pid_probe"]
        assert span["pid"] == os.getpid()  # not the old hardcoded 0

    def test_thread_rows_named_by_role(self, tmp_path):
        """Satellite: thread meta rows use live thread names / the
        set_thread_label() helper, not thread-0..n."""
        import threading
        p = str(tmp_path / "t.json")
        with trace(p):
            def work():
                set_thread_label("producer")
                with time_it("labeled_work"):
                    pass
            t = threading.Thread(target=work)
            t.start()
            t.join()
        events = json.load(open(p))
        names = {e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert "producer" in names


class TestForkedWorkerSpans:
    def test_worker_pool_spans_merge_pid_correct(self, tmp_path):
        """Tentpole: spans from forked transform workers land in the
        dumped trace with THEIR pid — worker activity is visible on the
        same timeline as the consumer."""
        from analytics_zoo_tpu.feature.worker_pool import (
            TransformWorkerPool, fork_available)
        if not fork_available():
            pytest.skip("no fork on this platform")

        class Chain:
            def apply(self, rec):
                return rec + 1.0

        feats = np.arange(32, dtype=np.float32).reshape(8, 4)
        p = str(tmp_path / "workers.json")
        with trace(p):
            with time_it("parent_span"):
                pass
            pool = TransformWorkerPool(feats, Chain(), rows=4, slots=2,
                                       num_workers=2)
            try:
                batches = [np.arange(4), np.arange(4, 8)]
                for idx, view in pool.map_index_batches(iter(batches)):
                    assert np.allclose(view, feats[idx] + 1.0)
            finally:
                pool.close()
        spans = _spans(p)
        worker_spans = [s for s in spans if s["name"] == "worker.task"]
        assert worker_spans, "forked worker spans missing from the trace"
        assert all(s["pid"] != os.getpid() for s in worker_spans)
        assert len({s["pid"] for s in spans}) >= 2  # parent + worker(s)


class TestServingFlowChain:
    def test_full_lifecycle_chain(self, ctx, tmp_path):
        """A traced serving pass draws at least one COMPLETE
        enqueue→claim→decode→dispatch→result flow chain, every anchor
        slice tagged with the request's trace_id."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True),
            {})
        root = str(tmp_path / "spool")
        os.makedirs(root)
        src = f"dir://{root}"
        cfg = ServingConfig(data_src=src, image_shape=(8,), batch_size=4,
                            batch_wait_ms=5, input_dtype="float32")
        serving = ClusterServing(cfg, model=im)
        inq, outq = InputQueue(src), OutputQueue(src)
        p = str(tmp_path / "serve.json")
        with trace(p):
            for i in range(6):
                inq.enqueue_tensor(f"r{i}", np.arange(8, dtype=np.float32))
            done = 0
            while done < 6:
                done += serving.serve_once()
        results = outq.dequeue()
        assert len(results) == 6
        chains = {}
        for s in _spans(p):
            tid_ = (s.get("args") or {}).get("trace_id")
            if tid_ is not None:
                chains.setdefault(tid_, set()).add(s["name"])
        complete = [c for c in chains.values() if _STAGES <= c]
        assert len(complete) == 6, chains
        # flow-phase events present and bindable (s at enqueue, f at end)
        phases = [e.get("ph") for e in json.load(open(p))
                  if e.get("cat") == trace_mod.FLOW_CAT]
        assert "s" in phases and "f" in phases and "t" in phases

    def test_health_snapshot_is_registry_view(self, ctx, tmp_path):
        """health_snapshot() counters/latency come from the shared metrics
        registry; p50/p99 are null (not 0.0) on an empty window."""
        from analytics_zoo_tpu.common import metrics as zoo_metrics
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
        from analytics_zoo_tpu.serving.client import InputQueue

        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True),
            {})
        root = str(tmp_path / "spool2")
        os.makedirs(root)
        cfg = ServingConfig(data_src=f"dir://{root}", image_shape=(8,),
                            batch_size=4, batch_wait_ms=5,
                            input_dtype="float32")
        serving = ClusterServing(cfg, model=im)
        snap = serving.health_snapshot()
        # satellite: empty latency window reads null, never 0.0
        assert snap["latency_ms"]["p50"] is None
        assert snap["latency_ms"]["p99"] is None
        assert snap["latency_ms"]["window"] == 0

        inq = InputQueue(f"dir://{root}")
        for i in range(4):
            inq.enqueue_tensor(f"r{i}", np.arange(8, dtype=np.float32))
        while serving.serve_once() == 0:
            pass
        snap = serving.health_snapshot()
        assert snap["latency_ms"]["window"] == 4
        assert snap["latency_ms"]["p50"] is not None
        # the same numbers are visible through the registry exposition
        reg = zoo_metrics.metrics_snapshot()
        label = f"server={serving.metrics_label}"
        assert reg["serving.request_latency_seconds"]["series"][label][
            "count"] == 4
        assert reg["serving.records_total"]["series"][label] == 4
        text = zoo_metrics.expose_text()
        assert ("zoo_serving_records_total{server=\""
                + serving.metrics_label + "\"} 4") in text

    def test_metrics_prom_written_next_to_health(self, ctx, tmp_path):
        """The serving health loop drops Prometheus text at metrics.prom
        beside health.json."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.serving import ClusterServing, ServingConfig

        im = InferenceModel().load_jax(
            lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True),
            {})
        root = str(tmp_path / "spool3")
        os.makedirs(root)
        cfg = ServingConfig(data_src=f"dir://{root}", image_shape=(8,),
                            batch_size=4, batch_wait_ms=5,
                            input_dtype="float32",
                            health_path=os.path.join(root, "health.json"))
        serving = ClusterServing(cfg, model=im)
        serving._write_health()
        prom = os.path.join(root, "metrics.prom")
        assert os.path.exists(prom)
        text = open(prom).read()
        assert "# TYPE zoo_serving_shed_total counter" in text
        health = json.load(open(os.path.join(root, "health.json")))
        assert health["state"] == "idle"
