"""Parallel host data plane: multiprocess shared-memory transform workers,
lazy/streaming FeatureSet.transform, the one-shot memmap replay cache, and
zero-alloc batch staging.

The contract under test everywhere: every new execution tier (lazy loop /
thread / mp, cached replay, staging rings) is BIT-IDENTICAL to the eager
per-record loop — the parity reference — including padded eval tails; and
the worker pool's lifecycle is airtight (errors surface in the consumer,
shutdown leaves no live children and no leaked /dev/shm segments).
"""
import multiprocessing
import os

import numpy as np
import pytest

from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.feature import (
    FeatureSet, HostDataset, Lambda, LazyTransformFeatureSet,
    TransformWorkerError, TransformWorkerPool)
from analytics_zoo_tpu.feature.preprocessing import BatchLambda


def double_plus_head(r):
    # shape-changing deterministic record transform: [d] -> [d + 1]
    return np.concatenate([r * 2, r[:1] + 1]).astype(np.float32)


def make_fs(n=20, d=4, shuffle=False, seed=0):
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.float32)
    return FeatureSet.from_ndarrays(x, y, shuffle=shuffle, seed=seed)


def batches_equal(a, b):
    ax, bx = a[0], b[0]
    if isinstance(ax, tuple):
        if not all(np.array_equal(p, q) for p, q in zip(ax, bx)):
            return False
    elif not np.array_equal(np.asarray(ax), np.asarray(bx)):
        return False
    if (a[1] is None) != (b[1] is None):
        return False
    if a[1] is not None and not np.array_equal(np.asarray(a[1]),
                                               np.asarray(b[1])):
        return False
    return list(a[2:]) == list(b[2:])


class TestEagerTiers:
    """transform(): loop (parity reference) vs thread vs mp vs batched."""

    def test_thread_and_mp_match_loop(self, ctx):
        p = Lambda(double_plus_head)
        ref = make_fs().transform(p, mode="loop")
        thr = make_fs().transform(p, num_workers=3, mode="thread")
        mp_ = make_fs().transform(p, num_workers=2, mode="mp")
        np.testing.assert_array_equal(np.asarray(ref.features),
                                      np.asarray(thr.features))
        np.testing.assert_array_equal(np.asarray(ref.features),
                                      np.asarray(mp_.features))
        assert np.asarray(ref.features).shape == (20, 5)

    def test_mp_tuple_records(self, ctx):
        x = (np.arange(16, dtype=np.float32).reshape(8, 2),
             np.ones((8, 3), np.float32))
        p = Lambda(lambda r: (r[0] * 2, r[1] + r[0][:1]))
        ref = FeatureSet.from_ndarrays(x, shuffle=False).transform(
            p, mode="loop")
        mp_ = FeatureSet.from_ndarrays(x, shuffle=False).transform(
            p, num_workers=2, mode="mp")
        for a, b in zip(ref.features, mp_.features):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_eager_chunked_loop_still_reference(self, ctx):
        # the chunked fill-into-preallocated-tree rewrite must equal a
        # naive stack of per-record applications
        p = Lambda(double_plus_head)
        fs = make_fs(n=1030)  # > chunk size: exercises multiple chunks
        got = np.asarray(fs.transform(p, mode="loop").features)
        want = np.stack([double_plus_head(r) for r in
                         np.arange(1030 * 4, dtype=np.float32
                                   ).reshape(1030, 4)])
        np.testing.assert_array_equal(got, want)

    def test_mp_rejects_object_outputs(self, ctx):
        fs = make_fs(n=4)
        obj = Lambda(lambda r: np.asarray([None, r], dtype=object))
        with pytest.raises(ValueError, match="numeric"):
            fs.transform(obj, num_workers=2, mode="mp")


class TestLazyParity:
    """lazy=True engines vs the eager loop, train + padded eval tails."""

    @pytest.mark.parametrize("mode,nw", [("loop", 0), ("thread", 3),
                                         ("mp", 2)])
    def test_eval_iterator_parity_with_padded_tail(self, ctx, mode, nw):
        p = Lambda(double_plus_head)
        ref = make_fs().transform(p, mode="loop")
        lz = make_fs().transform(p, num_workers=nw, mode=mode, lazy=True)
        assert isinstance(lz, LazyTransformFeatureSet)
        assert isinstance(lz, HostDataset)
        try:
            for pad in (False, True):
                got = [(np.asarray(x).copy(), None if y is None
                        else np.asarray(y).copy(), v)
                       for x, y, v in lz.eval_iterator(8, pad_remainder=pad)]
                want = list(ref.eval_iterator(8, pad_remainder=pad))
                assert len(got) == len(want)
                assert all(batches_equal(g, w)
                           for g, w in zip(got, want))
        finally:
            lz.close()

    @pytest.mark.parametrize("mode,nw", [("loop", 0), ("mp", 2)])
    def test_train_iterator_parity_same_rng_stream(self, ctx, mode, nw):
        p = Lambda(double_plus_head)
        ref = make_fs(shuffle=True, seed=7).transform(p, mode="loop")
        lz = make_fs(shuffle=True, seed=7).transform(
            p, num_workers=nw, mode=mode, lazy=True)
        try:
            ri, li = ref.train_iterator(8), lz.train_iterator(8)
            for _ in range(5):  # crosses an epoch boundary (2 batches/epoch)
                (rx, ry), (lx, ly) = next(ri), next(li)
                np.testing.assert_array_equal(rx, np.asarray(lx))
                np.testing.assert_array_equal(ry, np.asarray(ly))
        finally:
            lz.close()

    def test_batched_transform_lazy_parity(self, ctx):
        p = BatchLambda(lambda b: b * 3 + 1)
        ref = make_fs().transform(p)
        lz = make_fs().transform(p, lazy=True)
        got = list(lz.eval_iterator(8, pad_remainder=True))
        want = list(ref.eval_iterator(8, pad_remainder=True))
        assert all(batches_equal(g, w) for g, w in zip(got, want))
        assert lz.stats["engine"] == "batched"

    def test_data_state_roundtrip_delegates(self, ctx):
        lz = make_fs(shuffle=True, seed=3).transform(
            Lambda(double_plus_head), mode="loop", lazy=True)
        state = lz.data_state()
        it = lz.train_iterator(8)
        first = np.asarray(next(it)[0]).copy()
        lz.set_data_state(state)  # rewind the shuffle RNG
        it2 = lz.train_iterator(8)
        np.testing.assert_array_equal(first, np.asarray(next(it2)[0]))


class TestReplayCache:
    def test_second_epoch_skips_transform(self, ctx, tmp_path):
        calls = []

        def counting(r):
            calls.append(1)
            return r * 3

        lz = make_fs().transform(Lambda(counting), mode="loop", lazy=True,
                                 cache=True, cache_dir=str(tmp_path))
        first = [np.asarray(b[0]).copy() for b in lz.eval_iterator(8)]
        after_first = len(calls)
        assert after_first >= 20  # every record transformed once (+ probe)
        second = [np.asarray(b[0]).copy() for b in lz.eval_iterator(8)]
        assert len(calls) == after_first  # pure memmap replay
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)
        # files actually live in the requested cache dir
        assert any(f.endswith(".mmap") for f in os.listdir(tmp_path))

    def test_cached_mp_parity(self, ctx, tmp_path):
        p = Lambda(double_plus_head)
        ref = make_fs().transform(p, mode="loop")
        lz = make_fs().transform(p, num_workers=2, mode="mp", lazy=True,
                                 cache=True, cache_dir=str(tmp_path))
        try:
            for _ in range(2):  # first pass fills, second replays
                got = list(lz.eval_iterator(8, pad_remainder=True))
                want = list(ref.eval_iterator(8, pad_remainder=True))
                assert all(batches_equal(g, w)
                           for g, w in zip(got, want))
        finally:
            lz.close()

    def test_shuffled_train_fills_cache_incrementally(self, ctx):
        p = Lambda(double_plus_head)
        ref = make_fs(shuffle=True, seed=5).transform(p, mode="loop")
        lz = make_fs(shuffle=True, seed=5).transform(p, mode="loop",
                                                     lazy=True, cache=True)
        ri, li = ref.train_iterator(5), lz.train_iterator(5)
        for _ in range(9):  # > 2 epochs: replay epochs must stay identical
            (rx, ry), (lx, ly) = next(ri), next(li)
            np.testing.assert_array_equal(rx, np.asarray(lx))
            np.testing.assert_array_equal(ry, np.asarray(ly))
        assert lz._all_covered  # a full epoch covers every record


class TestWorkerPoolLifecycle:
    def test_error_in_worker_surfaces_in_consumer(self, ctx):
        def explode_late(r):
            if r[0] >= 40:  # record 10 of 20 — probe (record 0) succeeds
                raise ValueError("transform exploded mid-stream")
            return r * 2

        lz = make_fs().transform(Lambda(explode_late), num_workers=2,
                                 mode="mp", lazy=True)
        try:
            with pytest.raises(TransformWorkerError,
                               match="exploded mid-stream"):
                for _ in lz.eval_iterator(4):
                    pass
        finally:
            lz.close()

    def test_shutdown_leaves_no_children_or_shm(self, ctx):
        from multiprocessing import shared_memory
        lz = make_fs().transform(Lambda(double_plus_head), num_workers=2,
                                 mode="mp", lazy=True)
        it = lz.train_iterator(4)
        next(it)
        (pool,) = lz._all_pools
        procs, names = list(pool._procs), [s.name for s in pool._shms]
        assert any(p.is_alive() for p in procs)
        it.close()  # interrupt mid-stream with tasks in flight
        lz.close()
        assert not any(p.is_alive() for p in procs)
        for name in names:  # segment names must be gone from /dev/shm
            with pytest.raises(FileNotFoundError):
                shared_memory.SharedMemory(name=name)
        ours = [p for p in multiprocessing.active_children()
                if p.name.startswith("zoo-transform-worker")]
        assert ours == []

    def test_concurrent_train_and_eval_streams_same_set(self, ctx):
        """A train iterator suspended mid-epoch must not deadlock a
        validation pass streaming the SAME lazy set (the mid-epoch
        validation_trigger shape): the busy pool gets a forked sibling."""
        p = Lambda(double_plus_head)
        lz = make_fs().transform(p, num_workers=2, mode="mp", lazy=True)
        try:
            ti = lz.train_iterator(4)
            t1 = np.asarray(next(ti)[0]).copy()  # stream 1 active, suspended
            evals = [np.asarray(b[0]).copy()
                     for b in lz.eval_iterator(4)]  # stream 2, same size
            t2 = np.asarray(next(ti)[0]).copy()  # stream 1 resumes
            want = [np.asarray(b[0]) for b in
                    make_fs().transform(p, mode="loop").eval_iterator(4)]
            for a, b in zip(evals, want):
                np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(t1, want[0])  # shuffle=False
            np.testing.assert_array_equal(t2, want[1])
            assert len(lz._all_pools) == 2  # busy pool ⇒ fresh sibling
        finally:
            lz.close()

    def test_pool_reusable_after_abandoned_iterator(self, ctx):
        lz = make_fs().transform(Lambda(lambda r: r + 1), num_workers=2,
                                 mode="mp", lazy=True)
        try:
            it = lz.train_iterator(4)
            next(it)
            it.close()  # slots still in flight
            x, _, _ = next(lz.eval_iterator(4))  # drains, then reuses slots
            np.testing.assert_array_equal(
                np.asarray(x), np.arange(16, dtype=np.float32
                                         ).reshape(4, 4) + 1)
        finally:
            lz.close()

    def test_eager_transform_all_leaves_nothing_behind(self, ctx):
        fs = make_fs().transform(Lambda(double_plus_head), num_workers=2,
                                 mode="mp")
        np.testing.assert_array_equal(
            np.asarray(fs.features)[0],
            double_plus_head(np.arange(4, dtype=np.float32)))
        ours = [p for p in multiprocessing.active_children()
                if p.name.startswith("zoo-transform-worker")]
        assert ours == []


class TestSelfHealing:
    """Dead-child recovery: a worker SIGKILLed mid-batch must not hang the
    consumer — the pool respawns it and resubmits the lost task (within
    the ``data.worker_respawns`` budget), or surfaces TransformWorkerError
    promptly once the budget is spent. Transient task failures burn
    ``data.task_retries`` before surfacing."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.common.config import global_config
        faults.reset()
        yield
        faults.reset()
        global_config().unset("data.worker_respawns")
        global_config().unset("data.task_retries")

    def test_sigkilled_child_respawns_and_results_stay_exact(self, ctx):
        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.feature.worker_pool import TransformWorkerPool
        x = np.arange(80, dtype=np.float32).reshape(20, 4)
        faults.arm("worker.kill", at=2, budget=1)
        pool = TransformWorkerPool(x, Lambda(lambda r: r * 2), rows=4,
                                   slots=3, num_workers=2)
        try:
            idx_batches = [np.arange(i * 4, (i + 1) * 4) for i in range(5)]
            got = [np.array(view) for _, view in
                   pool.map_index_batches(iter(idx_batches))]
        finally:
            pool.close()
        assert faults.fire_count("worker.kill") == 1
        np.testing.assert_array_equal(np.concatenate(got), x * 2)

    def test_exhausted_respawn_budget_surfaces_promptly(self, ctx):
        import time

        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.feature.worker_pool import TransformWorkerPool
        global_config().set("data.worker_respawns", 0)
        faults.arm("worker.kill", at=1, budget=1)
        x = np.arange(80, dtype=np.float32).reshape(20, 4)
        pool = TransformWorkerPool(x, Lambda(lambda r: r * 2), rows=4,
                                   slots=2, num_workers=2)
        try:
            t0 = time.monotonic()
            with pytest.raises(TransformWorkerError, match="worker died"):
                for _ in pool.map_index_batches(iter([np.arange(4)])):
                    pass
            # promptly: seconds, not the 300s result-collection timeout
            assert time.monotonic() - t0 < 10
        finally:
            pool.close()

    def test_task_retries_absorb_transient_faults(self, ctx):
        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.feature.worker_pool import transform_all
        global_config().set("data.task_retries", 2)
        faults.arm("worker.task", at=1, budget=1)
        x = np.arange(80, dtype=np.float32).reshape(20, 4)
        tree, keepalive = transform_all(x, 20, Lambda(lambda r: r * 2),
                                        num_workers=2)
        assert faults.fire_count("worker.task") == 1
        np.testing.assert_array_equal(np.array(tree), x * 2)

    def test_task_retry_budget_exhausts_to_error(self, ctx):
        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.common.config import global_config
        from analytics_zoo_tpu.feature.worker_pool import TransformWorkerPool
        global_config().set("data.task_retries", 1)
        faults.arm("worker.task", p=1.0, budget=100)
        x = np.arange(80, dtype=np.float32).reshape(20, 4)
        pool = TransformWorkerPool(x, Lambda(lambda r: r * 2), rows=4,
                                   slots=2, num_workers=2)
        try:
            with pytest.raises(TransformWorkerError, match="injected fault"):
                for _ in pool.map_index_batches(iter([np.arange(4)])):
                    pass
        finally:
            pool.close()

    def test_respawned_pool_keeps_streaming_through_training(self, ctx):
        """End-to-end: the eager mp transform behind an estimator survives
        a killed worker and the trained params match the loop tier."""
        from analytics_zoo_tpu.common import faults
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense

        def run(kill):
            faults.reset()
            if kill:
                faults.arm("worker.kill", at=1, budget=1)
            fs = make_fs(n=40).transform(Lambda(double_plus_head),
                                         num_workers=2, mode="mp")
            est = Estimator(
                model=Sequential([Dense(4, name="d1"), Dense(1, name="d2")]),
                loss_fn=objectives.get("mse"),
                optimizer=optimizers.SGD(0.01))
            est.train(fs, batch_size=8, epochs=2)
            return est.get_params()

        pa, pb = run(kill=False), run(kill=True)
        np.testing.assert_array_equal(pa["d1"]["kernel"], pb["d1"]["kernel"])
        np.testing.assert_array_equal(pa["d2"]["kernel"], pb["d2"]["kernel"])


class TestZeroAllocStaging:
    def test_gather_out_buffers_are_reused_and_correct(self, ctx):
        cfg = global_config()
        cfg.set("data.staging_slots", 4)
        try:
            fs = make_fs(n=40, shuffle=False)
            it = fs.train_iterator(5)
            seen = [next(it) for _ in range(4)]
            ids = [id(x) for x, _ in seen]
            assert len(set(ids)) == 4  # distinct ring entries...
            x5, _ = next(it)
            assert id(x5) == ids[0]  # ...then the ring wraps
            np.testing.assert_array_equal(
                x5, np.arange(80, 100, dtype=np.float32).reshape(5, 4))
        finally:
            cfg.unset("data.staging_slots")

    def test_staging_parity_with_fresh_alloc(self, ctx):
        cfg = global_config()
        fs1 = make_fs(n=40, shuffle=True, seed=11)
        plain = [np.asarray(x).copy() for (x, _), _ in
                 zip(fs1.train_iterator(8), range(10))]
        cfg.set("data.staging_slots", 4)
        try:
            fs2 = make_fs(n=40, shuffle=True, seed=11)
            ring = [np.asarray(x).copy() for (x, _), _ in
                    zip(fs2.train_iterator(8), range(10))]
        finally:
            cfg.unset("data.staging_slots")
        for a, b in zip(plain, ring):
            np.testing.assert_array_equal(a, b)

    def test_masked_eval_batches_reuses_full_mask(self, ctx):
        from analytics_zoo_tpu.feature.device_feed import masked_eval_batches
        fs = make_fs(n=20, shuffle=False)
        items = list(masked_eval_batches(
            fs.eval_iterator(8, pad_remainder=True), 8))
        masks = [m for (_, _, m), _ in items]
        valids = [v for _, v in items]
        assert valids == [8, 8, 4]
        assert masks[0] is masks[1]  # full-batch mask allocated once
        np.testing.assert_array_equal(masks[2],
                                      (np.arange(8) < 4).astype(np.float32))


class TestEstimatorWireThrough:
    """Lazy/mp sets flow through Estimator.train/evaluate end to end."""

    def _estimator(self):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense
        return Estimator(
            model=Sequential([Dense(8, activation="relu", name="a"),
                              Dense(2, name="b")]),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.05), metrics=["accuracy"])

    def test_train_and_evaluate_on_lazy_loop_set(self, ctx):
        rs = np.random.RandomState(0)
        x = rs.rand(64, 6).astype(np.float32)
        y = (x.sum(1) > 3).astype(np.float32)
        p = Lambda(lambda r: (r - 0.5).astype(np.float32))
        ref = FeatureSet.from_ndarrays(x, y, shuffle=True, seed=1
                                       ).transform(p, mode="loop")
        lz = FeatureSet.from_ndarrays(x, y, shuffle=True, seed=1
                                      ).transform(p, mode="loop", lazy=True)
        e1, e2 = self._estimator(), self._estimator()
        out1 = e1.train(ref, batch_size=16, epochs=2)
        out2 = e2.train(lz, batch_size=16, epochs=2)
        assert out1["iterations"] == out2["iterations"] == 8
        # identical data order + identical init seed ⇒ identical history
        np.testing.assert_allclose(out1["loss_history"],
                                   out2["loss_history"], rtol=1e-6)
        r1 = e1.evaluate(ref, batch_size=16)
        r2 = e2.evaluate(lz, batch_size=16)
        assert r1 == r2

    def test_train_on_mp_set_runs_and_shuts_down(self, ctx):
        rs = np.random.RandomState(1)
        x = rs.rand(64, 6).astype(np.float32)
        y = (x.sum(1) > 3).astype(np.float32)
        lz = FeatureSet.from_ndarrays(x, y, shuffle=True, seed=2).transform(
            Lambda(lambda r: (r * 2).astype(np.float32)),
            num_workers=2, mode="mp", lazy=True)
        try:
            est = self._estimator()
            out = est.train(lz, batch_size=16, epochs=2)
            assert out["iterations"] == 8
            assert np.isfinite(out["loss_history"]).all()
            scores = est.evaluate(lz, batch_size=16)
            assert 0.0 <= scores["accuracy"] <= 1.0
        finally:
            lz.close()
        ours = [p for p in multiprocessing.active_children()
                if p.name == "zoo-transform-worker"]
        assert ours == []
