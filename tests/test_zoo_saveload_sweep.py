"""Zoo-wide save/load round-trip sweep (reference strategy: the reflective
serializer sweep, ``SerializerSpecHelper.scala`` — SURVEY §4, applied at
the model-zoo level).

Every registered ZooModel family: construct a tiny config, compile,
initialize via predict, ``save_model`` to disk, ``ZooModel.load_model``
back through the registry, and assert bit-comparable predictions. Catches
config keys missing from ``get_config``, registry gaps, and weight trees
that don't survive the round trip.
"""
import numpy as np
import pytest

from analytics_zoo_tpu.models import ZooModel


def _rs(seed=0):
    return np.random.RandomState(seed)


def _ncf():
    from analytics_zoo_tpu.models import NeuralCF
    m = NeuralCF(10, 8, 2, user_embed=4, item_embed=4, hidden_layers=[8],
                 mf_embed=4)
    x = np.stack([_rs().randint(1, 11, 8), _rs().randint(1, 9, 8)],
                 1).astype(np.float32)
    return m, x


def _wide_deep():
    from analytics_zoo_tpu.models import ColumnFeatureInfo, WideAndDeep
    info = ColumnFeatureInfo(
        wide_base_cols=["a"], wide_base_dims=[5],
        indicator_cols=["c"], indicator_dims=[4],
        embed_cols=["d"], embed_in_dims=[10], embed_out_dims=[6],
        continuous_cols=["x1"])
    m = WideAndDeep("wide_n_deep", num_classes=2, column_info=info,
                    hidden_layers=[8, 4])
    rs = _rs()
    x = [rs.randint(0, 5, (8, 1)).astype(np.float32),
         rs.randint(0, 4, (8, 1)).astype(np.float32),
         rs.randint(0, 10, (8, 1)).astype(np.float32),
         rs.rand(8, 1).astype(np.float32)]
    return m, x


def _session():
    from analytics_zoo_tpu.models import SessionRecommender
    m = SessionRecommender(item_count=12, item_embed=6,
                           rnn_hidden_layers=[8], session_length=5)
    x = _rs().randint(1, 13, (8, 5)).astype(np.float32)
    return m, x


def _anomaly():
    from analytics_zoo_tpu.models import AnomalyDetector
    m = AnomalyDetector(feature_shape=(8, 1), hidden_layers=[8, 4],
                        dropouts=[0.2, 0.2])
    return m, _rs().rand(8, 8, 1).astype(np.float32)


def _text_classifier():
    from analytics_zoo_tpu.models import TextClassifier
    m = TextClassifier(class_num=3, token_length=8, sequence_length=10,
                       encoder="cnn", encoder_output_dim=8, vocab_size=30)
    return m, _rs().randint(0, 30, (8, 10)).astype(np.float32)


def _knrm():
    from analytics_zoo_tpu.models import KNRM
    m = KNRM(4, 6, 25, embed_size=8, kernel_num=5)
    return m, _rs().randint(0, 25, (8, 10)).astype(np.float32)


def _seq2seq():
    from analytics_zoo_tpu.models import Seq2seq
    m = Seq2seq(rnn_type="gru", num_layers=1, hidden_size=4,
                generator_dim=2)
    rs = _rs()
    return m, [rs.rand(8, 4, 2).astype(np.float32),
               rs.rand(8, 3, 2).astype(np.float32)]


def _image_classifier():
    from analytics_zoo_tpu.models.image.imageclassification import (
        ImageClassifier)
    m = ImageClassifier("squeezenet", num_classes=3,
                        input_shape=(32, 32, 3))
    return m, _rs().rand(4, 32, 32, 3).astype(np.float32)


def _tagger():
    from analytics_zoo_tpu.models import NER
    m = NER(num_tags=5, word_vocab_size=40, char_vocab_size=20,
            sequence_length=6, word_length=4, word_emb_dim=8,
            char_emb_dim=4, char_lstm_dim=4, tagger_lstm_dim=8)
    rs = _rs()
    return m, [rs.randint(1, 40, (8, 6)).astype(np.float32),
               rs.randint(1, 20, (8, 6, 4)).astype(np.float32)]


def _intent_entity():
    from analytics_zoo_tpu.models import IntentEntity
    m = IntentEntity(num_intents=3, num_entities=5, word_vocab_size=40,
                     char_vocab_size=20, sequence_length=6, word_length=4,
                     word_emb_dim=8, char_emb_dim=4, char_lstm_dim=4,
                     tagger_lstm_dim=8)
    rs = _rs()
    return m, [rs.randint(1, 40, (8, 6)).astype(np.float32),
               rs.randint(1, 20, (8, 6, 4)).astype(np.float32)]


CASES = {
    "NeuralCF": _ncf,
    "WideAndDeep": _wide_deep,
    "SessionRecommender": _session,
    "AnomalyDetector": _anomaly,
    "TextClassifier": _text_classifier,
    "KNRM": _knrm,
    "Seq2seq": _seq2seq,
    "ImageClassifier": _image_classifier,
    "NER": _tagger,
    "IntentEntity": _intent_entity,
}


def _tree(o):
    return o if isinstance(o, (list, tuple)) else [o]


@pytest.mark.parametrize("name", sorted(CASES), ids=sorted(CASES))
def test_save_load_roundtrip(name, ctx, tmp_path):
    model, x = CASES[name]()
    model.default_compile()
    before = _tree(model.predict(x, batch_size=8))
    path = str(tmp_path / name)
    model.save_model(path)
    loaded = ZooModel.load_model(path)
    assert type(loaded).__name__ == name
    after = _tree(loaded.predict(x, batch_size=8))
    assert len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
