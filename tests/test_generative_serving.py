"""Continuous-batching generative serving: decode parity + per-token SLOs.

The load-bearing invariant is BIT-IDENTITY: N requests decoded through the
slot-batched scheduler — with mid-stream joins and evictions — must produce
exactly the token streams serial ``TransformerLM.generate()`` produces,
greedy and sampled. Everything else (per-token deadlines, drain, step
chaos, streaming client, metrics) layers on the exactly-one-terminal rule
ClusterServing established.
"""
import time
import uuid

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import metrics as _metrics
from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.server import DEADLINE_ERROR


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


#: one fitted model per max_len, shared across the file — every test reads
#: params / generates, nothing mutates the model, and reusing it keeps the
#: serial-reference executables warm between tests
_LM_CACHE = {}


def _lm(max_len=32, seed=0):
    lm = _LM_CACHE.get((max_len, seed))
    if lm is None:
        from analytics_zoo_tpu.capture.lm import TransformerLM
        rs = np.random.RandomState(seed)
        lm = TransformerLM(vocab_size=16, hidden=16, n_block=2, n_head=2,
                           max_len=max_len, seed=seed)
        lm.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
        _LM_CACHE[(max_len, seed)] = lm
    return lm


def _src(tmp_path):
    return f"dir://{tmp_path}/{uuid.uuid4().hex[:8]}"


def _drive(srv, steps=200):
    """Manual stepping until the scheduler goes idle (deterministic —
    no background thread in the parity tests)."""
    idle = 0
    for _ in range(steps):
        if srv.serve_step() == 0:
            idle += 1
            if idle >= 3:
                return
        else:
            idle = 0


class TestDecodeParity:
    @pytest.mark.slow
    def test_greedy_bit_identical_with_midstream_joins(self, ctx, tmp_path):
        # 5 requests through 2 slots: requests 3..5 join slots mid-run as
        # earlier streams finish and are evicted — the continuous-batching
        # case, not just a static batch
        lm = _lm()
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 1, 6, 3, 5)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8)[0].tolist()
                  for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=8), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"r{i}", p)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"r{i}", timeout_s=5)
            assert res is not None and res.get("done") is True
            assert res["value"] == want, f"stream r{i} diverged"
        assert srv.health_snapshot()["slots_occupied"] == 0

    @pytest.mark.slow
    def test_sampled_bit_identical_per_request_seed(self, ctx, tmp_path):
        lm = _lm()
        rs = np.random.RandomState(4)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (5, 2, 1, 7)]
        seeds = [11, 22, 33, 44]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=8,
                              temperature=0.9, top_k=8, seed=s)[0].tolist()
                  for p, s in zip(prompts, seeds)]
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=8,
                          temperature=0.9, top_k=8), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, (p, s) in enumerate(zip(prompts, seeds)):
            inq.enqueue_prompt(f"r{i}", p, seed=s)
        _drive(srv)
        for i, want in enumerate(serial):
            res = outq.query(f"r{i}", timeout_s=5)
            assert res is not None and res["value"] == want

    def test_eos_terminates_stream_bit_identically(self, ctx, tmp_path):
        # serial generate pads finished rows with eos; the scheduler
        # retires the stream at its first eos — the stream must equal the
        # serial row truncated one past the first eos
        lm = _lm()
        eos = 1  # the tiny model's attractor token (seen in every run)
        rs = np.random.RandomState(5)
        prompts = [rs.randint(0, 16, (n,)).tolist() for n in (4, 3)]
        serial = [lm.generate(np.asarray([p]), max_new_tokens=10,
                              eos_id=eos)[0].tolist() for p in prompts]
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=10,
                          eos_id=eos), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i, p in enumerate(prompts):
            inq.enqueue_prompt(f"e{i}", p)
        _drive(srv)
        for i, row in enumerate(serial):
            want = row[:row.index(eos) + 1] if eos in row else row
            res = outq.query(f"e{i}", timeout_s=5)
            assert res is not None and res["value"] == want


class TestPerTokenSLO:
    @pytest.mark.slow
    def test_deadline_mid_stream_exactly_one_terminal(self, ctx, tmp_path):
        lm = _lm(max_len=64)
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=40), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        # warm the prefill-bucket and step compiles so the doomed stream's
        # clock measures decode steps, not tracing
        inq.enqueue_prompt("warmup", [1, 2, 3])
        _drive(srv)
        inq.enqueue_prompt("doomed", [3, 5, 2], deadline_ms=1500)
        # a few tokens stream out before the deadline...
        for _ in range(3):
            srv.serve_step()
        partial = outq.query("doomed")
        assert partial is not None and partial.get("done") is False
        assert len(partial["stream"]) >= 1
        # ...then the per-step deadline check evicts the stream mid-flight
        time.sleep(1.6)
        _drive(srv, steps=10)
        res = outq.query("doomed", timeout_s=2)
        assert res is not None and res["error"] == DEADLINE_ERROR
        assert srv.counters["expired"] == 1
        # exactly one terminal: further steps must not resurrect it
        _drive(srv, steps=5)
        assert outq.query("doomed")["error"] == DEADLINE_ERROR
        assert srv.health_snapshot()["in_flight"] == 0

    def test_expired_at_claim_never_occupies_a_slot(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("stale", [2, 4], deadline_ms=1)
        time.sleep(0.05)
        srv.serve_step()
        res = outq.query("stale", timeout_s=2)
        assert res is not None and res["error"] == DEADLINE_ERROR
        assert srv.health_snapshot()["slots_occupied"] == 0

    def test_over_budget_request_errors_immediately(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=1, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("huge", [1] * 30, max_new_tokens=30)
        srv.serve_step()
        res = outq.query("huge", timeout_s=2)
        assert res is not None and "out of range" in res["error"]
        assert srv.counters["errors"] == 1

    def test_drain_finishes_in_flight_streams(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=6), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        for i in range(3):
            inq.enqueue_prompt(f"d{i}", [2, 3, 4])
        srv.start()
        try:
            assert outq.query("d0", timeout_s=30) is not None
            srv.drain(timeout_s=30)
            for i in range(3):
                res = outq.query(f"d{i}", timeout_s=5)
                assert res is not None and res.get("done") is True
                assert len(res["value"]) == 6
        finally:
            srv.stop() if srv._thread is not None else None
        assert srv.health_snapshot()["state"] == "drained"


class TestChaosAndStreaming:
    def test_decode_step_fault_errors_streams_keeps_serving(self, ctx,
                                                            tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("hit", [2, 3])
        faults.arm("serving.decode_step", at=1)
        srv.serve_step()  # the armed step fails: the stream gets its one
        res = outq.query("hit", timeout_s=2)  # terminal — an error result
        assert res is not None and "FaultInjected" in res["error"]
        assert srv.counters["errors"] == 1
        assert srv.health_snapshot()["slots_occupied"] == 0
        # the scheduler survives: the NEXT request decodes normally
        serial = lm.generate(np.asarray([[2, 3]]),
                             max_new_tokens=4)[0].tolist()
        inq.enqueue_prompt("after", [2, 3])
        _drive(srv)
        assert outq.query("after", timeout_s=5)["value"] == serial

    def test_client_stream_yields_each_token_once(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=1, max_new_tokens=6), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        serial = lm.generate(np.asarray([[4, 2, 7]]),
                             max_new_tokens=6)[0].tolist()
        inq.enqueue_prompt("s0", [4, 2, 7])
        srv.start()
        try:
            got = list(outq.stream("s0", timeout_s=30))
        finally:
            srv.drain(timeout_s=30)
        assert got == serial

    def test_stream_raises_on_error_terminal(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=1, max_new_tokens=4), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("bad", [1, 2], deadline_ms=1)
        time.sleep(0.05)
        srv.serve_step()
        with pytest.raises(RuntimeError, match="deadline exceeded"):
            list(outq.stream("bad", timeout_s=5))

    def test_metrics_ttft_tokens_slots(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=2, max_new_tokens=5), lm)
        inq = InputQueue(src)
        for i in range(2):
            inq.enqueue_prompt(f"m{i}", [3, 1, 4])
        srv.serve_step()
        # both streams produced their first token: TTFT observed, gauge up
        snap = srv.health_snapshot()
        assert snap["slots_occupied"] == 2
        assert snap["ttft_ms"]["window"] == 2
        _drive(srv)
        snap = srv.health_snapshot()
        assert snap["tokens_total"] == 10
        assert snap["slots_occupied"] == 0
        text = _metrics.expose_text()
        for name in ("serving_ttft_seconds", "serving_tokens_total",
                     "serving_slots_occupied"):
            assert name in text

    def test_shutdown_errors_active_streams(self, ctx, tmp_path):
        lm = _lm()
        src = _src(tmp_path)
        srv = GenerativeServing(
            ServingConfig(data_src=src, slots=1, max_new_tokens=20), lm)
        inq, outq = InputQueue(src), OutputQueue(src)
        inq.enqueue_prompt("cut", [2, 5])
        srv.serve_step()  # stream is mid-flight
        srv.stop()
        res = outq.query("cut", timeout_s=2)
        assert res is not None and "shut down" in res["error"]
