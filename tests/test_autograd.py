"""Autograd variable algebra: symbolic math ops, Parameter variables,
CustomLoss expressions (reference autograd/math.scala + CustomLoss.scala)."""
import jax
import numpy as np
import pytest

from analytics_zoo_tpu.keras import Input, Model, Sequential, autograd as A
from analytics_zoo_tpu.keras.layers import Dense


def _run(expr_builder, *input_shapes):
    """Build Model(inputs → expr), run on random data, return (out, arrays)."""
    rs = np.random.RandomState(0)
    syms = [Input(shape=s) for s in input_shapes]
    out_sym = expr_builder(*syms)
    model = Model(syms if len(syms) > 1 else syms[0], out_sym)
    params, state = model.build(jax.random.PRNGKey(0))
    arrays = [rs.randn(3, *s).astype(np.float32) for s in input_shapes]
    out, _ = model.call(params, state,
                        arrays if len(arrays) > 1 else arrays[0])
    return np.asarray(out), arrays


class TestOps:
    def test_unary_suite(self):
        for fn, ref in [(A.abs, np.abs), (A.exp, np.exp),
                        (A.square, np.square), (A.neg, lambda v: -v),
                        (A.tanh, np.tanh), (A.relu, lambda v: np.maximum(v, 0))]:
            out, (x,) = _run(fn, (4,))
            np.testing.assert_allclose(out, ref(x), rtol=1e-5, atol=1e-6)

    def test_sqrt_log_on_positive(self):
        out, (x,) = _run(lambda s: A.sqrt(A.abs(s) + 1.0), (4,))
        np.testing.assert_allclose(out, np.sqrt(np.abs(x) + 1), rtol=1e-5)

    def test_clip(self):
        out, (x,) = _run(lambda s: A.clip(s, -0.5, 0.5), (6,))
        np.testing.assert_allclose(out, np.clip(x, -0.5, 0.5))

    def test_reductions(self):
        out, (x,) = _run(lambda s: A.mean(s, axis=1), (5,))
        np.testing.assert_allclose(out, x.mean(axis=1), rtol=1e-6)
        out, (x,) = _run(lambda s: A.sum(s, axis=1, keepdims=True), (5,))
        np.testing.assert_allclose(out, x.sum(axis=1, keepdims=True),
                                   rtol=1e-5)

    def test_binary_and_pairwise(self):
        out, (a, b) = _run(lambda x, y: A.maximum(x, y), (4,), (4,))
        np.testing.assert_allclose(out, np.maximum(a, b))
        out, (a, b) = _run(lambda x, y: x * y + 2.0, (4,), (4,))
        np.testing.assert_allclose(out, a * b + 2, rtol=1e-6)

    def test_shape_ops(self):
        out, (x,) = _run(lambda s: A.expand_dims(s, 1), (4,))
        assert out.shape == (3, 1, 4)
        out, (x,) = _run(lambda s: A.reshape(s, [2, 3]), (6,))
        np.testing.assert_allclose(out, x.reshape(3, 2, 3))
        out, (x,) = _run(lambda s: A.transpose(s, [2, 1]), (2, 5))
        np.testing.assert_allclose(out, np.transpose(x, (0, 2, 1)))

    def test_stack_concat_select(self):
        out, (a, b) = _run(lambda x, y: A.stack([x, y], axis=1), (4,), (4,))
        assert out.shape == (3, 2, 4)
        out, (a, b) = _run(lambda x, y: A.concat([x, y], axis=-1), (4,), (2,))
        assert out.shape == (3, 6)
        out, (x,) = _run(lambda s: A.index_select(s, 1, 2), (4,))
        np.testing.assert_allclose(out, x[:, 2])
        out, (x,) = _run(lambda s: A.slice(s, 1, 1, 2), (5,))
        np.testing.assert_allclose(out, x[:, 1:3])

    def test_mm_and_l2_normalize(self):
        out, (a, b) = _run(lambda x, y: A.mm(x, y), (2, 3), (3, 4))
        np.testing.assert_allclose(out, a @ b, rtol=1e-5, atol=1e-5)
        out, (x,) = _run(lambda s: A.l2_normalize(s, axis=-1), (4,))
        np.testing.assert_allclose(np.linalg.norm(out, axis=-1),
                                   np.ones(3), rtol=1e-5)


class TestParameter:
    def test_parameter_trains(self):
        """y = w*x with w a bare Parameter: fitting recovers the slope."""
        x = Input(shape=(1,))
        w = A.Parameter([1], init="ones", name="slope")
        model = Model(x, x * w)
        model.compile(optimizer="sgd", loss="mse")
        rs = np.random.RandomState(0)
        xs = rs.randn(64, 1).astype(np.float32)
        ys = 3.0 * xs
        model.fit(xs, ys, batch_size=16, nb_epoch=40)
        west = float(np.asarray(model.get_weights()["slope"]["weight"])[0])
        assert west == pytest.approx(3.0, abs=0.2)

    def test_non_trainable_parameter_frozen(self):
        x = Input(shape=(1,))
        w = A.Parameter([1], init="ones", trainable=False, name="fixed")
        model = Model(x, x * w)
        model.compile(optimizer="sgd", loss="mse")
        xs = np.ones((16, 1), np.float32)
        model.fit(xs, 5 * xs, batch_size=16, nb_epoch=3)
        assert float(np.asarray(
            model.get_weights()["fixed"]["weight"])[0]) == 1.0


class TestCustomLoss:
    def test_custom_mae_matches_builtin(self):
        def mae(y_true, y_pred):
            return A.mean(A.abs(y_true - y_pred), axis=1)

        loss = A.CustomLoss(mae, [2])
        rs = np.random.RandomState(1)
        yt = rs.randn(8, 2).astype(np.float32)
        yp = rs.randn(8, 2).astype(np.float32)
        got = float(loss(yt, yp))
        assert got == pytest.approx(float(np.mean(np.abs(yt - yp))), rel=1e-5)

    def test_custom_loss_trains_model(self):
        def huber(y_true, y_pred):
            err = A.abs(y_true - y_pred)
            return A.mean(A.minimum(0.5 * err * err, err - 0.5), axis=1)

        model = Sequential([Dense(1, name="d")])
        model.compile(optimizer="adam", loss=A.CustomLoss(huber, [1]))
        rs = np.random.RandomState(2)
        xs = rs.randn(64, 3).astype(np.float32)
        ys = (xs @ np.asarray([[1.0], [-2.0], [0.5]], np.float32))
        r = model.fit(xs, ys, batch_size=16, nb_epoch=5)
        assert r["loss_history"][-1] < r["loss_history"][0]

    def test_parameterized_expression_rejected(self):
        with pytest.raises(ValueError, match="parameter-free"):
            A.CustomLoss(lambda yt, yp: Dense(1)(yp - yt), [2])


class TestNewImageTransforms:
    def test_filler_and_vflip(self):
        from analytics_zoo_tpu.feature.image import Filler, VFlip
        img = np.zeros((4, 4, 3), np.float32)
        out = Filler(0.5, 0.0, 1.0, 0.5, value=9).apply(img)
        assert out[0, 3, 0] == 9 and out[3, 0, 0] == 0
        np.testing.assert_array_equal(VFlip().apply(out), out[::-1])

    def test_channel_scaled_and_pixel_normalizer(self):
        from analytics_zoo_tpu.feature.image import (
            ChannelScaledNormalizer, PixelNormalizer)
        img = np.full((2, 2, 3), 10.0, np.float32)
        out = ChannelScaledNormalizer(1, 2, 3, scale=0.5).apply(img)
        np.testing.assert_allclose(out[0, 0], [4.5, 4.0, 3.5])
        means = np.ones((2, 2, 3), np.float32)
        np.testing.assert_allclose(PixelNormalizer(means).apply(img),
                                   img - 1)

    def test_random_resize_and_aspect_scale(self):
        from analytics_zoo_tpu.feature.image import (
            RandomAspectScale, RandomResize)
        img = np.zeros((20, 10, 3), np.uint8)
        out = RandomResize(5, 8, seed=0).apply(img)
        assert 5 <= out.shape[0] <= 8 and out.shape[0] == out.shape[1]
        out = RandomAspectScale([12], max_size=30, seed=0).apply(img)
        assert min(out.shape[:2]) == 12  # short side scaled to target
        # long-side cap: with max_size=20 the scale clamps to 1.0
        out = RandomAspectScale([12], max_size=20, seed=0).apply(img)
        assert out.shape[:2] == (20, 10)

    def test_grayscale(self):
        from analytics_zoo_tpu.feature.image import Grayscale
        img = np.random.RandomState(0).rand(3, 3, 3).astype(np.float32)
        out = Grayscale().apply(img)
        assert out.shape == (3, 3, 3)
        np.testing.assert_allclose(out[..., 0], out[..., 1])
