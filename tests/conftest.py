"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference tests distributed semantics on a single host with multi-partition
``local[n]`` Spark masters (SURVEY.md §4). The TPU equivalent is an 8-device
virtual CPU mesh via ``xla_force_host_platform_device_count``, set before jax
initializes.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def ctx():
    from analytics_zoo_tpu.common.context import init_tpu_context, reset_context
    reset_context()
    context = init_tpu_context(force_reinit=True)
    yield context
    reset_context()
