"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference tests distributed semantics on a single host with multi-partition
``local[n]`` Spark masters (SURVEY.md §4). The TPU equivalent is an 8-device
virtual CPU mesh via ``xla_force_host_platform_device_count``, set before jax
initializes.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import inspect  # noqa: E402

import pytest  # noqa: E402

#: wall-seconds of the tier-1 870s budget (ROADMAP verify command) that
#: non-slow multi-process tests may collectively declare — the rest
#: belongs to the single-process suite. Breaching this fails COLLECTION,
#: so a new pod test that would blow the CI budget is caught before it
#: runs, not after CI times out.
_POD_BUDGET_CAP_S = 420.0

#: names whose presence in a test's source means it spawns worker
#: subprocesses and must carry @pytest.mark.pod(budget_s=...)
_POD_SPAWNERS = ("PodLauncher", "run_pod(", "ElasticSupervisor",
                 "FleetSupervisor")


def pytest_collection_modifyitems(config, items):
    total, unbudgeted, unmarked = 0.0, [], []
    for item in items:
        mark = item.get_closest_marker("pod")
        if mark is None:
            fn = getattr(item, "function", None)
            try:
                src = inspect.getsource(fn) if fn else ""
            except (OSError, TypeError):
                src = ""
            if any(s in src for s in _POD_SPAWNERS):
                unmarked.append(item.nodeid)
            continue
        if item.get_closest_marker("slow") is not None:
            continue  # tier-2: outside the 870s budget
        budget = float(mark.kwargs.get("budget_s", 0.0))
        if budget <= 0:
            unbudgeted.append(item.nodeid)
        total += budget
    problems = []
    if unmarked:
        problems.append(
            f"multi-process tests must declare a wall budget with "
            f"@pytest.mark.pod(budget_s=...): {unmarked}")
    if unbudgeted:
        problems.append(
            f"pod marker without a positive budget_s: {unbudgeted}")
    if total > _POD_BUDGET_CAP_S:
        problems.append(
            f"non-slow pod tests declare {total:.0f}s of wall budget, "
            f"over the {_POD_BUDGET_CAP_S:.0f}s cap — mark the heaviest "
            f"soaks slow or shrink them")
    if problems:
        raise pytest.UsageError("; ".join(problems))


@pytest.fixture()
def ctx():
    from analytics_zoo_tpu.common.context import init_tpu_context, reset_context
    reset_context()
    context = init_tpu_context(force_reinit=True)
    yield context
    reset_context()
