"""int8 training convolution (ops/int8_training.py): forward numerics vs
the float conv, STE gradient sanity, and end-to-end convergence of an
int8-conv network — the experimental byte-cut lever past the bf16 HBM
roofline (new TPU-native capability; the reference's int8 is
inference-only, ``examples/vnni/openvino/Perf.scala:1``)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.int8_training import int8_train_conv


class TestInt8TrainConv:
    def _pair(self, seed=0, shape=(2, 8, 8, 16), cout=32, k=3):
        rs = np.random.RandomState(seed)
        x = jnp.asarray(rs.randn(*shape).astype(np.float32))
        w = jnp.asarray(rs.randn(k, k, shape[-1], cout).astype(np.float32)
                        * 0.1)
        return x, w

    def test_forward_close_to_float(self):
        x, w = self._pair()
        got = int8_train_conv(x, w, (1, 1), "SAME", (1, 1), 1)
        want = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        err = float(jnp.max(jnp.abs(got - want))
                    / jnp.max(jnp.abs(want)))
        # two int8 quantizations: ~1% relative error expected
        assert err < 0.05, err

    def test_ste_gradients_close_to_float(self):
        x, w = self._pair(seed=1)

        def loss_q(x, w):
            return jnp.sum(int8_train_conv(x, w, (2, 2), "SAME",
                                           (1, 1), 1) ** 2)

        def loss_f(x, w):
            return jnp.sum(jax.lax.conv_general_dilated(
                x, w, (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) ** 2)

        gq = jax.grad(loss_q, argnums=(0, 1))(x, w)
        gf = jax.grad(loss_f, argnums=(0, 1))(x, w)
        for q, f in zip(gq, gf):
            q, f = np.asarray(q, np.float32), np.asarray(f, np.float32)
            denom = max(float(np.max(np.abs(f))), 1e-6)
            assert float(np.max(np.abs(q - f))) / denom < 0.08
            assert np.isfinite(q).all()

    def test_grad_dtype_follows_inputs(self):
        x, w = self._pair(seed=2)
        xb = x.astype(jnp.bfloat16)

        def loss(x_, w_):
            return jnp.sum(int8_train_conv(x_, w_, (1, 1), "SAME",
                                           (1, 1), 1)
                           .astype(jnp.float32))

        dx, dw = jax.grad(loss, argnums=(0, 1))(xb, w)
        assert dx.dtype == jnp.bfloat16
        assert dw.dtype == jnp.float32

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_int8_network_converges(self, ctx):
        """A small int8-conv classifier must train (loss decreasing into
        the same ballpark as the float version)."""
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import (Input, Model, objectives,
                                             optimizers)
        from analytics_zoo_tpu.keras.layers import (Convolution2D, Dense,
                                                    GlobalAveragePooling2D)

        rs = np.random.RandomState(0)
        n = 256
        x = rs.rand(n, 12, 12, 3).astype(np.float32)
        # learnable rule: mean brightness of a quadrant decides the class
        y = (x[:, :6, :6].mean(axis=(1, 2, 3)) > 0.5).astype(np.float32)

        def build(int8):
            inp = Input((12, 12, 3), name="img")
            h = Convolution2D(16, 3, 3, activation="relu",
                              border_mode="same", int8_training=int8,
                              name="c1")(inp)
            h = Convolution2D(16, 3, 3, activation="relu",
                              border_mode="same", int8_training=int8,
                              name="c2")(h)
            h = GlobalAveragePooling2D(name="gap")(h)
            out = Dense(2, activation="softmax", name="logits")(h)
            return Model(inp, out)

        losses = {}
        for tag, int8 in (("float", False), ("int8", True)):
            est = Estimator(
                model=build(int8),
                loss_fn=objectives.get("sparse_categorical_crossentropy"),
                optimizer=optimizers.Adam(5e-3))
            hist = est.train(FeatureSet.from_ndarrays(x, y, shuffle=False),
                             batch_size=64, epochs=60)
            losses[tag] = hist["loss_history"]
        assert losses["int8"][-1] < losses["int8"][0] * 0.75
        # tracks the float trajectory (measured: 0.492 vs 0.470 at the
        # same step count — quantization noise, not brokenness)
        assert losses["int8"][-1] < losses["float"][-1] * 1.15 + 0.02
