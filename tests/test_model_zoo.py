"""Model-zoo tests (reference strategy, SURVEY.md §4: construct, fit 1-2
iterations on tiny random data, predict/evaluate, save/load round-trip)."""
import numpy as np
import pytest

from analytics_zoo_tpu.models import (
    AnomalyDetector, ColumnFeatureInfo, KNRM, Seq2seq, SessionRecommender,
    TextClassifier, WideAndDeep, ZooModel, detect_anomalies, unroll)


def fit_little(model, x, y, batch=8):
    model.default_compile()
    return model.fit(x, y, batch_size=batch, nb_epoch=1)


class TestWideAndDeep:
    def make_data(self, n=32):
        rs = np.random.RandomState(0)
        wide = np.stack([rs.randint(0, 5, n), 5 + rs.randint(0, 7, n)],
                        1).astype(np.float32)
        ind = rs.randint(0, 4, (n, 1)).astype(np.float32)
        emb = rs.randint(0, 10, (n, 1)).astype(np.float32)
        cont = rs.rand(n, 2).astype(np.float32)
        y = rs.randint(0, 2, n).astype(np.float32)
        return [wide, ind, emb, cont], y

    def make_model(self, model_type="wide_n_deep"):
        info = ColumnFeatureInfo(
            wide_base_cols=["a"], wide_base_dims=[5],
            wide_cross_cols=["ab"], wide_cross_dims=[7],
            indicator_cols=["c"], indicator_dims=[4],
            embed_cols=["d"], embed_in_dims=[10], embed_out_dims=[6],
            continuous_cols=["x1", "x2"])
        return WideAndDeep(model_type, num_classes=2, column_info=info,
                           hidden_layers=[8, 4])

    def test_fit_predict(self, ctx):
        x, y = self.make_data()
        wnd = self.make_model()
        hist = fit_little(wnd, x, y)
        assert hist["iterations"] >= 1
        preds = wnd.predict(x, batch_size=8)
        assert preds.shape == (32, 2)
        np.testing.assert_allclose(np.asarray(preds).sum(1), 1, atol=1e-4)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_criteo_scale_vocab(self, ctx):
        """The sparse wide/embed path must survive Criteo-scale vocabularies
        (SURVEY §7 hard part (b)): 2M-entry wide table + 1M-entry embedding.
        A one-hot densification would materialize [B, 2e6] activations and
        grads; the gather + scatter-add design keeps this cheap."""
        wide_dim, embed_dim = 2_000_000, 1_000_000
        rs = np.random.RandomState(1)
        n = 64
        wide = rs.randint(0, wide_dim, (n, 2)).astype(np.float32)
        emb = rs.randint(0, embed_dim, (n, 1)).astype(np.float32)
        cont = rs.rand(n, 2).astype(np.float32)
        y = rs.randint(0, 2, n).astype(np.float32)
        info = ColumnFeatureInfo(
            wide_base_cols=["a", "b"], wide_base_dims=[wide_dim // 2] * 2,
            embed_cols=["d"], embed_in_dims=[embed_dim],
            embed_out_dims=[16], continuous_cols=["x1", "x2"])
        wnd = WideAndDeep("wide_n_deep", num_classes=2, column_info=info,
                          hidden_layers=[16, 8])
        wnd.default_compile()
        ind = np.zeros((n, 0), np.float32)  # no indicator columns
        hist = wnd.fit([wide, ind, emb, cont], y, batch_size=32, nb_epoch=1)
        assert np.isfinite(hist["loss_history"]).all()
        preds = wnd.predict([wide, ind, emb, cont], batch_size=32)
        assert preds.shape == (n, 2)

    def test_wide_only_and_deep_only(self, ctx):
        x, y = self.make_data(16)
        for mt in ("wide", "deep"):
            m = self.make_model(mt)
            fit_little(m, x, y)
            assert m.predict(x, batch_size=8).shape == (16, 2)

    def test_save_load(self, ctx, tmp_path):
        x, y = self.make_data(16)
        wnd = self.make_model()
        fit_little(wnd, x, y)
        p1 = wnd.predict(x, batch_size=8)
        path = str(tmp_path / "wnd")
        wnd.save_model(path)
        loaded = ZooModel.load_model(path)
        np.testing.assert_allclose(np.asarray(loaded.predict(x, batch_size=8)),
                                   np.asarray(p1), atol=1e-5)

    def test_features_from_dataframe(self):
        import pandas as pd
        from analytics_zoo_tpu.models import features_from_dataframe
        df = pd.DataFrame({"a": [0, 1], "ab": [2, 3], "c": [1, 0],
                           "d": [4, 5], "x1": [0.1, 0.2], "x2": [1.0, 2.0],
                           "label": [0, 1]})
        info = ColumnFeatureInfo(
            wide_base_cols=["a"], wide_base_dims=[5],
            wide_cross_cols=["ab"], wide_cross_dims=[7],
            indicator_cols=["c"], indicator_dims=[4],
            embed_cols=["d"], embed_in_dims=[10], embed_out_dims=[6],
            continuous_cols=["x1", "x2"])
        feats, labels = features_from_dataframe(df, info)
        assert feats[0].shape == (2, 2)
        assert feats[0][0, 1] == 5 + 2  # offset applied
        assert labels.tolist() == [0.0, 1.0]

    def test_cross_columns_matches_per_value_crc32(self):
        # the vectorized unique+gather hash must be bit-identical to the
        # per-value crc32 loop it replaced (train/serve bucket stability)
        import zlib

        import pandas as pd
        from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
            cross_columns)
        rs = np.random.RandomState(3)
        df = pd.DataFrame({
            "s": rs.choice(["alpha", "beta", "gamma", "delta"], 5000),
            "i": rs.randint(0, 50, 5000),
            "f": rs.choice([0.5, 1.25, 7.0], 5000),
        })
        # NaN must hash as crc32("nan"), not gather a sentinel bucket
        df.loc[::7, "s"] = np.nan
        df.loc[::11, "f"] = np.nan
        got = cross_columns(df, ["s", "i", "f"], 1 << 20)
        acc = np.zeros(len(df), dtype=np.int64)
        for c in ["s", "i", "f"]:
            acc = acc * 1000003 + np.asarray(
                [zlib.crc32(str(v).encode()) for v in df[c]], dtype=np.int64)
        np.testing.assert_array_equal(got, np.abs(acc) % (1 << 20))


class TestSessionRecommender:
    def test_session_only(self, ctx):
        rs = np.random.RandomState(1)
        n, slen, items = 24, 6, 20
        x = rs.randint(1, items + 1, (n, slen)).astype(np.float32)
        y = rs.randint(0, items, n).astype(np.float32)
        m = SessionRecommender(items, item_embed=8, rnn_hidden_layers=[8, 4],
                               session_length=slen)
        fit_little(m, x, y)
        recs = m.recommend_for_session(x[:4], max_items=3)
        assert len(recs) == 4 and len(recs[0]) == 3
        assert all(0 <= i < items for i, p in recs[0])

    def test_with_history(self, ctx):
        rs = np.random.RandomState(2)
        n, slen, hlen, items = 16, 5, 4, 15
        x = [rs.randint(1, items + 1, (n, slen)).astype(np.float32),
             rs.randint(1, items + 1, (n, hlen)).astype(np.float32)]
        y = rs.randint(0, items, n).astype(np.float32)
        m = SessionRecommender(items, item_embed=8, rnn_hidden_layers=[8],
                               session_length=slen, include_history=True,
                               mlp_hidden_layers=[8], history_length=hlen)
        fit_little(m, x, y)
        preds = m.predict(x, batch_size=8)
        assert preds.shape == (n, items)


class TestAnomalyDetector:
    def test_unroll_and_detect(self):
        series = np.arange(20, dtype=np.float32)
        x, y = unroll(series, unroll_length=4)
        assert x.shape == (16, 4, 1)
        assert y[0] == 4.0  # first window [0..3] predicts 4
        report = detect_anomalies(np.zeros(10), np.r_[np.zeros(9), 5.0],
                                  anomaly_size=1)
        assert report[9][3] and not report[0][3]

    def test_fit_predict(self, ctx):
        rs = np.random.RandomState(3)
        series = np.sin(np.arange(80) / 5) + rs.rand(80) * 0.1
        x, y = unroll(series.astype(np.float32), unroll_length=8)
        m = AnomalyDetector(feature_shape=(8, 1), hidden_layers=[8, 4],
                            dropouts=[0.2, 0.2])
        fit_little(m, x, y)
        preds = m.predict(x, batch_size=16)
        assert preds.shape == (len(x), 1)


class TestTextClassifier:
    @pytest.mark.parametrize("encoder", ["cnn", "lstm", "gru"])
    def test_encoders(self, ctx, encoder):
        rs = np.random.RandomState(4)
        n, seq, vocab = 16, 10, 30
        x = rs.randint(0, vocab, (n, seq)).astype(np.float32)
        y = rs.randint(0, 3, n).astype(np.float32)
        m = TextClassifier(class_num=3, token_length=8, sequence_length=seq,
                           encoder=encoder, encoder_output_dim=16,
                           vocab_size=vocab)
        fit_little(m, x, y)
        preds = m.predict(x, batch_size=8)
        assert preds.shape == (n, 3)
        np.testing.assert_allclose(np.asarray(preds).sum(1), 1, atol=1e-4)

    def test_pretrained_frozen_embedding(self, ctx):
        rs = np.random.RandomState(5)
        vocab, dim = 12, 6
        weights = rs.rand(vocab, dim).astype(np.float32)
        m = TextClassifier(class_num=2, token_length=dim, sequence_length=5,
                           encoder="cnn", encoder_output_dim=8,
                           vocab_size=vocab, embedding_weights=weights,
                           train_embedding=False)
        x = rs.randint(0, vocab, (8, 5)).astype(np.float32)
        y = rs.randint(0, 2, 8).astype(np.float32)
        fit_little(m, x, y)
        est = m.model.get_estimator()
        assert "embedding" not in est.params  # frozen table lives in state
        assert "embedding" in est.model_state


class TestKNRM:
    def test_ranking_and_classification(self, ctx):
        rs = np.random.RandomState(6)
        q_len, d_len, vocab = 4, 6, 25
        n = 16
        x = rs.randint(0, vocab, (n, q_len + d_len)).astype(np.float32)
        y = rs.rand(n).astype(np.float32)
        m = KNRM(q_len, d_len, vocab, embed_size=8, kernel_num=5,
                 target_mode="ranking")
        m.compile("adam", "mse")
        m.fit(x, y, batch_size=8, nb_epoch=1)
        s = m.predict(x, batch_size=8)
        assert s.shape == (n, 1)

        mc = KNRM(q_len, d_len, vocab, embed_size=8, kernel_num=5,
                  target_mode="classification")
        mc.default_compile()
        mc.fit(x, (y > 0.5).astype(np.float32), batch_size=8, nb_epoch=1)
        p = np.asarray(mc.predict(x, batch_size=8))
        assert ((0 <= p) & (p <= 1)).all()


class TestSeq2seq:
    def test_fit_and_infer(self, ctx):
        rs = np.random.RandomState(7)
        n, in_seq, out_seq, dim = 16, 6, 5, 3
        enc = rs.rand(n, in_seq, dim).astype(np.float32)
        dec = rs.rand(n, out_seq, dim).astype(np.float32)
        target = rs.rand(n, out_seq, dim).astype(np.float32)
        m = Seq2seq(rnn_type="lstm", num_layers=2, hidden_size=8,
                    bridge="dense", generator_dim=dim)
        m.default_compile()
        m.fit([enc, dec], target, batch_size=8, nb_epoch=1)
        preds = m.predict([enc, dec], batch_size=8)
        assert preds.shape == (n, out_seq, dim)
        gen = m.infer(enc[:2], start_sign=np.zeros(dim, np.float32),
                      max_seq_len=4)
        assert gen.shape == (2, 4, dim)

    def test_gru_passthrough(self, ctx):
        rs = np.random.RandomState(8)
        enc = rs.rand(8, 4, 2).astype(np.float32)
        dec = rs.rand(8, 3, 2).astype(np.float32)
        target = rs.rand(8, 3, 2).astype(np.float32)
        m = Seq2seq(rnn_type="gru", num_layers=1, hidden_size=4,
                    generator_dim=2)
        m.default_compile()
        m.fit([enc, dec], target, batch_size=8, nb_epoch=1)
        assert m.predict([enc, dec], batch_size=8).shape == (8, 3, 2)


class TestImageClassifierBackbones:
    """Construct + forward for the classifier config family (reference
    ImageClassifier per-model configs: inception-v1/vgg/squeezenet/densenet)."""

    @pytest.mark.parametrize("name", [
        # inception forward is a ~18s compile — slow tier (870s budget)
        pytest.param("inception-v1", marks=pytest.mark.slow),
        "squeezenet"])
    def test_forward(self, ctx, name):
        from analytics_zoo_tpu.models.image.imageclassification import (
            ImageClassifier)
        clf = ImageClassifier(name, num_classes=3, input_shape=(64, 64, 3))
        clf.default_compile()
        probs = np.asarray(clf.predict(
            np.random.rand(4, 64, 64, 3).astype(np.float32), batch_size=4))
        assert probs.shape == (4, 3)
        assert np.allclose(probs.sum(-1), 1.0, atol=1e-3)

    def test_construct_only(self):
        from analytics_zoo_tpu.models.image.imageclassification import (
            densenet, vgg)
        assert vgg(19, 5, (32, 32, 3), fc_dim=16).name == "vgg19"
        assert densenet(121, 5, (64, 64, 3)).name == "densenet121"
        with pytest.raises(ValueError):
            vgg(13, 5, (32, 32, 3))

    def test_predict_image_set_labels(self, ctx):
        from analytics_zoo_tpu.feature.image import ImageSet
        from analytics_zoo_tpu.models.image.imageclassification import (
            ImageClassifier)
        # ragged input sizes: the model's preprocessing chain must resize
        imgs = [np.random.randint(0, 255, (h, w, 3), np.uint8)
                for h, w in [(70, 50), (64, 64), (50, 70), (80, 90)]]
        clf = ImageClassifier("squeezenet", num_classes=3,
                              input_shape=(32, 32, 3),
                              labels=["cat", "dog", "fish"])
        clf.default_compile()
        out = clf.predict_image_set(ImageSet.from_arrays(imgs), top_k=2)
        assert len(out) == 4 and all(len(r) == 2 for r in out)
        for r in out:
            assert all(lbl in ("cat", "dog", "fish") for lbl, _ in r)
            assert r[0][1] >= r[1][1]


class TestSequenceTaggers:
    """Word+char taggers (reference tfpark/text/keras NER/POS/IntentEntity)."""

    def _data(self, B=8, S=10, W=6, seed=9):
        rs = np.random.RandomState(seed)
        words = rs.randint(1, 40, (B, S)).astype(np.float32)
        chars = rs.randint(1, 20, (B, S, W)).astype(np.float32)
        tags = rs.randint(0, 5, (B, S)).astype(np.float32)
        return words, chars, tags

    def test_ner_fit_predict(self, ctx):
        from analytics_zoo_tpu.models import NER
        words, chars, tags = self._data()
        ner = NER(num_tags=5, word_vocab_size=40, char_vocab_size=20,
                  sequence_length=10, word_length=6, word_emb_dim=16,
                  char_emb_dim=8, char_lstm_dim=8, tagger_lstm_dim=16)
        ner.default_compile()
        ner.fit([words, chars], tags, batch_size=8, nb_epoch=1)
        p = np.asarray(ner.predict([words, chars], batch_size=8))
        assert p.shape == (8, 10, 5)
        np.testing.assert_allclose(p.sum(-1), 1, atol=1e-4)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_intent_entity_joint(self, ctx):
        from analytics_zoo_tpu.models import IntentEntity
        words, chars, tags = self._data()
        rs = np.random.RandomState(1)
        intents = rs.randint(0, 3, 8).astype(np.float32)
        ie = IntentEntity(num_intents=3, num_entities=5, word_vocab_size=40,
                          char_vocab_size=20, sequence_length=10,
                          word_length=6, word_emb_dim=16, char_emb_dim=8,
                          char_lstm_dim=8, tagger_lstm_dim=16)
        ie.default_compile()
        ie.fit([words, chars], (intents, tags), batch_size=8, nb_epoch=1)
        ip, sp = ie.predict([words, chars], batch_size=8)
        assert np.asarray(ip).shape == (8, 3)
        assert np.asarray(sp).shape == (8, 10, 5)

    def test_save_load_roundtrip(self, ctx, tmp_path):
        from analytics_zoo_tpu.models import SequenceTagger, ZooModel
        words, chars, tags = self._data()
        st = SequenceTagger(num_tags=5, word_vocab_size=40,
                            char_vocab_size=20, sequence_length=10,
                            word_length=6, word_emb_dim=16, char_emb_dim=8,
                            char_lstm_dim=8, tagger_lstm_dim=16)
        st.default_compile()
        st.fit([words, chars], tags, batch_size=8, nb_epoch=1)
        p1 = np.asarray(st.predict([words, chars], batch_size=8))
        path = str(tmp_path / "tagger")
        st.save_model(path)
        st2 = ZooModel.load_model(path)
        p2 = np.asarray(st2.predict([words, chars], batch_size=8))
        np.testing.assert_allclose(p1, p2, atol=1e-5)

    def test_pad_masked_tag_loss(self, ctx):
        import jax.numpy as jnp
        from analytics_zoo_tpu.models import NER
        ner = NER(num_tags=4, word_vocab_size=40, char_vocab_size=20,
                  sequence_length=6, word_length=4, pad_tag=-1)
        loss_fn = ner.tag_loss()
        # two tokens real, one pad (-1): pad position must not contribute
        y_true = jnp.asarray([[0.0, 1.0, -1.0]])
        good = jnp.asarray([[[0.97, 0.01, 0.01, 0.01],
                             [0.01, 0.97, 0.01, 0.01],
                             [0.25, 0.25, 0.25, 0.25]]])
        bad_pad = jnp.asarray([[[0.97, 0.01, 0.01, 0.01],
                                [0.01, 0.97, 0.01, 0.01],
                                [0.97, 0.01, 0.01, 0.01]]])
        assert float(loss_fn(y_true, good)) == pytest.approx(
            float(loss_fn(y_true, bad_pad)))  # pad prob irrelevant
        # and real positions still matter
        wrong = jnp.asarray([[[0.01, 0.97, 0.01, 0.01],
                              [0.97, 0.01, 0.01, 0.01],
                              [0.25, 0.25, 0.25, 0.25]]])
        assert float(loss_fn(y_true, wrong)) > float(loss_fn(y_true, good))

    def test_padded_fit(self, ctx):
        from analytics_zoo_tpu.models import IntentEntity
        rs = np.random.RandomState(2)
        B, S, W = 8, 10, 6
        words = rs.randint(1, 40, (B, S)).astype(np.float32)
        words[:, 6:] = 0  # pad tail positions
        chars = rs.randint(1, 20, (B, S, W)).astype(np.float32)
        chars[:, 6:] = 0
        tags = rs.randint(0, 5, (B, S)).astype(np.float32)
        tags[:, 6:] = -1  # pad label
        intents = rs.randint(0, 3, B).astype(np.float32)
        ie = IntentEntity(num_intents=3, num_entities=5, word_vocab_size=40,
                          char_vocab_size=20, sequence_length=S,
                          word_length=W, word_emb_dim=16, char_emb_dim=8,
                          char_lstm_dim=8, tagger_lstm_dim=16, pad_tag=-1)
        ie.default_compile()
        h = ie.fit([words, chars], (intents, tags), batch_size=8, nb_epoch=1)
        assert np.isfinite(h["loss_history"]).all()

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_crf_head_learns_transitions(self, ctx):
        """CRF tagger on a task where TRANSITIONS carry the signal: the tag
        alternates 1,2,1,2,... regardless of input. A per-token head can't
        beat chance; the CRF transition matrix nails it."""
        from analytics_zoo_tpu.models import NER
        rs = np.random.RandomState(5)
        B, S, W = 32, 8, 4
        words = rs.randint(1, 30, (B, S)).astype(np.float32)
        chars = rs.randint(1, 12, (B, S, W)).astype(np.float32)
        tags = np.tile(np.resize([1.0, 2.0], S), (B, 1)).astype(np.float32)
        ner = NER(num_tags=3, word_vocab_size=30, char_vocab_size=12,
                  sequence_length=S, word_length=W, word_emb_dim=8,
                  char_emb_dim=4, char_lstm_dim=4, tagger_lstm_dim=8,
                  crf=True)
        from analytics_zoo_tpu.keras import optimizers
        from analytics_zoo_tpu.keras.layers.crf import crf_nll
        ner.compile(optimizer=optimizers.Adam(3e-2), loss=crf_nll())
        ner.fit([words, chars], tags, batch_size=16, nb_epoch=60)
        decoded = ner.decode([words, chars], batch_size=16)
        acc = (decoded == tags).mean()
        assert acc > 0.95, acc

    def test_crf_nll_matches_bruteforce(self):
        import itertools
        import jax.numpy as jnp
        from analytics_zoo_tpu.keras.layers.crf import crf_decode, crf_nll
        rs = np.random.RandomState(0)
        B, S, T = 2, 4, 3
        emis = rs.randn(B, S, T).astype(np.float32)
        trans = rs.randn(T, T).astype(np.float32)
        start = rs.randn(T).astype(np.float32)
        pot = emis[:, :, None, :] + trans[None, None]
        pot[:, 0] = np.broadcast_to(emis[:, 0, None, :] + start[None, None],
                                    (B, T, T))

        def score(b, p):
            s = emis[b, 0, p[0]] + start[p[0]]
            for k in range(1, S):
                s += emis[b, k, p[k]] + trans[p[k - 1], p[k]]
            return s

        y = rs.randint(0, T, (B, S)).astype(np.float32)
        got = float(crf_nll()(jnp.asarray(y), jnp.asarray(pot)))
        ref, best = 0.0, []
        paths = list(itertools.product(range(T), repeat=S))
        for b in range(B):
            scores = [score(b, p) for p in paths]
            ref += (np.logaddexp.reduce(scores)
                    - score(b, [int(t) for t in y[b]])) / B
            best.append(list(paths[int(np.argmax(scores))]))
        assert got == pytest.approx(ref, abs=1e-4)
        assert np.asarray(crf_decode(jnp.asarray(pot))).tolist() == best
