"""Elastic pod supervisor (docs/cluster.md): lease-based membership,
survive-a-dead-host training, and a fleet actuator that actually actuates.

The capstone invariant is the reference's ``failure.retryTimes`` story
made checkable: a 4-process CPU-mesh fit that loses one rank to SIGKILL
mid-epoch AND one rank to a hung host (frozen lease, live pid) must
complete with params BIT-IDENTICAL to a fault-free run — elasticity that
changes the math is not fault tolerance. On the serving side the fleet
supervisor closes the loop on ``fleet.desired_instances`` with real
server subprocesses, and a mid-scale-out SIGKILL must leave every request
with exactly one terminal (audited at ``put_result``)."""
import collections
import json
import os
import signal
import time

import numpy as np
import pytest

from analytics_zoo_tpu.cluster.supervisor import (ElasticSupervisor,
                                                  FileLeaseStore,
                                                  FleetSupervisor,
                                                  LeaseHeartbeat,
                                                  LeaseTracker,
                                                  PodSupervisorError,
                                                  RedisLeaseStore,
                                                  make_lease_store)
from analytics_zoo_tpu.cluster.supervisor import _M_RESTARTS
from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.serving.fleet import FleetRouter
from analytics_zoo_tpu.serving.queues import FileQueue


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestLeaseStores:
    def test_file_store_roundtrip_and_torn_lease(self, tmp_path):
        store = FileLeaseStore(str(tmp_path / "leases"))
        store.write(0, {"rank": 0, "seq": 1, "generation": 0})
        store.write(3, {"rank": 3, "seq": 7, "generation": 0})
        with open(os.path.join(store.root, "lease-9.json"), "w") as f:
            f.write("{torn mid-re")  # same as absent, never a crash
        leases = store.read_all()
        assert set(leases) == {0, 3}
        assert leases[3]["seq"] == 7
        store.clear()
        assert store.read_all() == {}

    def test_redis_store_roundtrip(self):
        from tests.test_redis_serving import FakeRedis
        FakeRedis.instances.clear()
        store = make_lease_store("redis://localhost:6379/zoo:test-leases",
                                 client=FakeRedis())
        assert isinstance(store, RedisLeaseStore)
        assert store.spec() == "redis://localhost:6379/zoo:test-leases"
        store.write(1, {"rank": 1, "seq": 4, "generation": 2})
        store.write(2, {"rank": 2, "seq": 9, "generation": 2})
        leases = store.read_all()
        assert leases[1]["seq"] == 4 and leases[2]["generation"] == 2
        store.clear()  # tombstones, not DEL — minimal client contract
        assert store.read_all() == {}
        FakeRedis.instances.clear()

    def test_make_lease_store_parses_specs(self, tmp_path):
        fs = make_lease_store(str(tmp_path / "l"))
        assert isinstance(fs, FileLeaseStore)
        from tests.test_redis_serving import FakeRedis
        FakeRedis.instances.clear()
        rs = make_lease_store("redis://somehost:7000/ns",
                              client=FakeRedis())
        assert (rs.host, rs.port, rs.namespace) == ("somehost", 7000, "ns")
        FakeRedis.instances.clear()


class TestLeaseLiveness:
    def test_seq_progress_keeps_lease_alive(self, tmp_path):
        tracker = LeaseTracker([0, 1], expiry_s=0.2, grace_s=0.2)
        lease = lambda seq: {"seq": seq, "generation": 0}  # noqa: E731
        assert tracker.update({0: lease(1), 1: lease(1)}, 0) == []
        time.sleep(0.3)
        # rank 0 progressed, rank 1 froze: only 1 expires — expiry is the
        # supervisor's OWN monotonic age since it last SAW progress
        assert tracker.update({0: lease(2), 1: lease(1)}, 0) == [1]
        assert tracker.alive() == 1

    def test_stale_generation_lease_is_ignored(self):
        """A dead rank's generation-0 lease file must not shadow its
        generation-1 replacement: old-generation seqs read as absent."""
        tracker = LeaseTracker([0], expiry_s=0.15, grace_s=0.15)
        assert tracker.update({0: {"seq": 99, "generation": 0}}, 1) == []
        time.sleep(0.2)
        assert tracker.update({0: {"seq": 100, "generation": 0}}, 1) == [0]

    def test_unregistered_rank_gets_spawn_grace(self):
        tracker = LeaseTracker([0], expiry_s=10.0, grace_s=0.15)
        assert tracker.update({}, 0) == []  # interpreter still starting
        time.sleep(0.2)
        assert tracker.update({}, 0) == [0]  # never arrived: expired

    def test_heartbeat_pumps_seq_and_freezes_on_chaos(self, tmp_path):
        """The ``cluster.heartbeat`` site models a hung host: the process
        lives, the lease freezes — beat_once returns False and the pump
        thread stops, so seq never advances again."""
        store = FileLeaseStore(str(tmp_path / "leases"))
        hb = LeaseHeartbeat(store, rank=2, generation=1, heartbeat_s=0.02)
        assert hb.beat_once() is True
        assert store.read_all()[2]["seq"] == 1
        assert store.read_all()[2]["generation"] == 1
        faults.arm("cluster.heartbeat", at=1)
        assert hb.beat_once() is False
        assert store.read_all()[2]["seq"] == 1  # frozen, not torn
        assert faults.fire_count("cluster.heartbeat") == 1


class TestRespawnBudget:
    @pytest.mark.pod(budget_s=5)
    def test_worker_restart_fault_consumes_budget(self):
        """``cluster.worker_restart`` firing on every spawn attempt must
        exhaust ``cluster.respawns`` and surface PodSupervisorError —
        without ever launching a process."""
        sup = ElasticSupervisor(target="tests.pod_workers:train_worker",
                                num_processes=2, respawns=1,
                                restart_backoff_s=0.01)
        faults.arm("cluster.worker_restart", p=1.0, budget=10)
        before = _M_RESTARTS.labels(reason="respawn").value()
        with pytest.raises(PodSupervisorError, match="respawn budget"):
            sup.run(timeout=30)
        assert faults.fire_count("cluster.worker_restart") == 2
        assert _M_RESTARTS.labels(reason="respawn").value() == before + 1


class _StubRouter:
    """desired_instances()-only router for actuation-chaos tests."""

    def __init__(self, desired):
        self.desired = desired
        self.registered, self.removed = [], []

    def desired_instances(self):
        return self.desired

    def register_instance(self, inst):
        self.registered.append(inst.name)

    def remove_instance(self, name):
        self.removed.append(name)


class TestFleetActuationChaos:
    @pytest.mark.pod(budget_s=5)
    def test_scale_actuate_fault_defers_to_next_tick(self, tmp_path):
        """``fleet.scale_actuate`` firing mid-tick must leave the fleet
        consistent — no half-spawn, no phantom router registration — and
        the tick simply retried on the next cadence."""
        router = _StubRouter(desired=1)
        sup = FleetSupervisor(router, str(tmp_path), "unused:factory",
                              min_instances=0, max_instances=4,
                              scale_interval_s=0.01)
        faults.arm("fleet.scale_actuate", at=1)
        assert sup.step() is None  # actuation aborted by the fault
        assert faults.fire_count("fleet.scale_actuate") == 1
        assert sup.instance_names() == []
        assert router.registered == []
        # a desired of 0 on the retry tick means no actuation is needed —
        # the failed tick did not leak any intent
        router.desired = 0
        time.sleep(0.02)
        assert sup.step() is None
        assert sup.instance_names() == []


class TestElasticTraining:
    def _run(self, workdir, chaos):
        sup = ElasticSupervisor(
            target="tests.pod_workers:elastic_train_worker",
            num_processes=4, devices_per_process=1, platform="cpu",
            args=[str(workdir), 3, chaos], workdir=str(workdir / "sup"),
            heartbeat_s=0.25, lease_expiry_s=3.0, respawns=3,
            restart_backoff_s=0.2)
        return sup.run(timeout=420)

    @pytest.mark.pod(budget_s=120)
    def test_chaos_restart_bit_identical(self, tmp_path):
        """The capstone: generation 0 loses rank 2 to SIGKILL mid-epoch-2
        (restart reason ``exit``), the respawn itself fails once
        (``cluster.worker_restart`` -> reason ``respawn``), generation 1
        loses rank 1 to a frozen lease with a live pid (reason ``lease``,
        detected purely by monotonic lease age), and generation 2 resumes
        from the sealed epoch-1 snapshot and finishes — with final params
        on every rank BIT-IDENTICAL to a run that saw no faults at all."""
        ref = tmp_path / "ref"
        ref.mkdir()
        ref_result = self._run(ref, "")
        assert ref_result.generations == 1 and ref_result.restarts == 0
        assert [r.returncode for r in ref_result.results] == [0] * 4

        restarts_before = {
            r: _M_RESTARTS.labels(reason=r).value()
            for r in ("exit", "lease", "respawn")}
        faulty = tmp_path / "faulty"
        faulty.mkdir()
        # call #1 = generation-0 spawn (clean); call #2 = the respawn
        # after the SIGKILL — THAT one fails, is retried within budget
        faults.arm("cluster.worker_restart", at=2)
        result = self._run(faulty, "kill+hang")
        assert result.generations == 3  # gen0 killed, gen1 hung, gen2 ran
        assert result.restarts == 3     # exit + respawn + lease
        assert [r.returncode for r in result.results] == [0] * 4
        assert faults.fire_count("cluster.worker_restart") == 1
        for reason in ("exit", "lease", "respawn"):
            assert (_M_RESTARTS.labels(reason=reason).value()
                    == restarts_before[reason] + 1), reason

        for rank in range(4):
            a = np.load(str(faulty / f"params_rank{rank}.npz"))
            b = np.load(str(ref / f"params_rank{rank}.npz"))
            assert set(a.files) == set(b.files) and a.files
            for key in a.files:
                np.testing.assert_array_equal(
                    a[key], b[key],
                    err_msg=f"rank {rank} param {key} diverged from the "
                            f"fault-free run")


class TestFleetScaling:
    @pytest.mark.pod(budget_s=60)
    def test_scale_out_kill_scale_in_exactly_one_terminal(self, tmp_path):
        """Close the loop 1 -> 3 -> 2 with REAL server subprocesses:
        demand scales the fleet out, one instance is SIGKILLed mid-scale-
        out (before it claims work — its respawn keeps capacity on
        target), the queue drains with every request answered, and the
        audit journals at ``put_result`` show exactly one terminal per
        request across the whole fleet."""
        from analytics_zoo_tpu.serving.client import InputQueue

        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        router = FleetRouter(front, [], stale_after_s=0.6,
                             health_refresh_s=0.05,
                             default_service_s=0.25 / 4)
        sup = FleetSupervisor(router, root,
                              "tests.pod_workers:fleet_predict_factory",
                              min_instances=1, max_instances=3, slots=1,
                              scale_interval_s=0.05, ready_timeout_s=120)
        events = []
        try:
            ev = sup.step()  # bootstrap to min_instances
            assert ev == "out:inst0"
            events.append(ev)

            n = 96
            vec = np.random.RandomState(0).rand(16).astype(np.float32)
            inq = InputQueue(f"dir://{root}")
            for i in range(n):
                inq.enqueue_tensor(f"r{i}", vec)
            res_dir = os.path.join(root, "results")

            def n_results():
                try:
                    return sum(1 for f in os.listdir(res_dir)
                               if not f.startswith("."))
                except FileNotFoundError:
                    return 0

            killed = False
            deadline = time.monotonic() + 120
            while n_results() < n:
                assert time.monotonic() < deadline, (
                    f"only {n_results()}/{n} answered; events={events}")
                router.route_once()
                ev = sup.step()
                if ev:
                    events.append(ev)
                if ev == "out:inst1" and not killed:
                    # mid-scale-out chaos: the instance that JUST came up
                    # dies before the ramp to 3 finishes — the supervisor
                    # must reap it and respawn capacity, the router must
                    # never wedge on its frozen health file
                    os.kill(sup._procs["inst1"].pid, signal.SIGKILL)
                    killed = True
            assert killed, f"scale-out never reached inst1: {events}"

            # scale-in: demand collapsed, so the supervisor drains back
            # down — stop observing once the fleet passes through 2
            deadline = time.monotonic() + 60
            while not (any(e.startswith("in:") for e in events)
                       and sup.alive_count() <= 2):
                assert time.monotonic() < deadline, events
                router.route_once()
                ev = sup.step()
                if ev:
                    events.append(ev)

            outs = [e for e in events if e.startswith("out:")]
            assert len(outs) >= 4, events  # inst0..inst3: ramp + respawn
            for i in range(n):
                res = front.get_result(f"r{i}")
                assert res is not None and "value" in res, f"r{i}: {res}"

            # the exactly-one-terminal audit, taken at put_result in every
            # server subprocess: the union of the per-instance journals
            # covers every request exactly once — nothing dropped, nothing
            # answered twice, SIGKILL and drains included
            terminals = collections.Counter()
            audit_dir = os.path.join(root, "audit")
            for name in os.listdir(audit_dir):
                with open(os.path.join(audit_dir, name)) as f:
                    terminals.update(line.strip() for line in f
                                     if line.strip())
            assert set(terminals) == {f"r{i}" for i in range(n)}
            dups = {u: c for u, c in terminals.items() if c != 1}
            assert not dups, f"multiple terminals: {dups}"
        finally:
            sup.shutdown()

    @pytest.mark.slow
    @pytest.mark.pod(budget_s=240)
    def test_generative_drain_handoff_token_identical(self, tmp_path):
        """Scale-in of a generative instance mid-decode: the draining
        subprocess hands its unfinished streams (prefix + key schedule)
        back to the FRONT spool, the router re-places them, and every
        stream finishes on the survivor with EXACTLY serial generate()'s
        tokens — the continuation invariant surviving real process
        boundaries."""
        from analytics_zoo_tpu.capture.lm import TransformerLM
        from analytics_zoo_tpu.serving.client import InputQueue

        rs = np.random.RandomState(0)
        lm = TransformerLM(vocab_size=16, hidden=16, n_block=2, n_head=2,
                           max_len=32, seed=0)
        lm.fit(rs.randint(0, 16, (32, 12)), batch_size=8, epochs=1)
        prs = np.random.RandomState(11)
        prompts = [prs.randint(0, 16, (k,)).tolist() for k in (4, 5, 3, 6)]
        want = [lm.generate(np.asarray([p]),
                            max_new_tokens=10)[0].tolist()
                for p in prompts]

        root = str(tmp_path / "fleet")
        front = FileQueue(root)
        router = FleetRouter(front, [], stale_after_s=5.0,
                             health_refresh_s=0.05)
        sup = FleetSupervisor(router, root,
                              "tests.pod_workers:fleet_generative_factory",
                              min_instances=2, max_instances=2, slots=2,
                              scale_interval_s=0.01, ready_timeout_s=180)
        try:
            deadline = time.monotonic() + 300
            while sup.alive_count() < 2:
                assert time.monotonic() < deadline, "fleet never reached 2"
                sup.step()
            inq = InputQueue(f"dir://{root}")
            for i, p in enumerate(prompts):
                inq.enqueue_prompt(f"s{i}", p)
            for _ in range(10):
                router.route_once()
                time.sleep(0.02)
            # drain the newest instance while streams are in flight: its
            # handoff() re-enqueues them to the front with their prefix
            sup.min_instances = sup.max_instances = 1
            deadline = time.monotonic() + 180
            ev = None
            while ev is None:
                assert time.monotonic() < deadline
                ev = sup.step()
            assert ev.startswith("in:")
            done = 0
            while done < len(prompts):
                assert time.monotonic() < deadline, "streams never settled"
                router.route_once()
                sup.step()
                done = sum(
                    1 for i in range(len(prompts))
                    if (front.get_result(f"s{i}") or {}).get("done"))
                time.sleep(0.02)
            for i, w in enumerate(want):
                res = front.get_result(f"s{i}")
                assert res["value"] == w, (
                    f"stream s{i} diverged after subprocess handoff")
        finally:
            sup.shutdown()
