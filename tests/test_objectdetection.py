"""SSD object detection tests (reference test strategy: construct, fit a
step, predict boxes, evaluate mAP on a toy set — SSDSpec.scala model)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.models.image.objectdetection import (
    ObjectDetector, SSD, decode_boxes, decode_detections, encode_targets,
    generate_anchors, iou_matrix, multibox_loss, Visualizer)
from analytics_zoo_tpu.models.image.evaluation import MeanAveragePrecision

_SSD300_ARGS = dict(
    fmap_sizes=[38, 19, 10, 5, 3, 1],
    image_size=300,
    min_sizes=[30, 60, 111, 162, 213, 264],
    max_sizes=[60, 111, 162, 213, 264, 315],
    aspect_ratios=[[2], [2, 3], [2, 3], [2, 3], [2], [2]],
)


class TestAnchors:
    def test_ssd300_anchor_count(self):
        a = generate_anchors(**_SSD300_ARGS)
        assert a.shape == (8732, 4)  # the canonical SSD300 anchor count
        assert np.all(a >= 0) and np.all(a <= 1)

    def test_iou(self):
        a = np.array([[0, 0, 1, 1]], np.float32)
        b = np.array([[0, 0, 1, 1], [0.5, 0.5, 1.5, 1.5], [2, 2, 3, 3]],
                     np.float32)
        ious = iou_matrix(a, b)[0]
        np.testing.assert_allclose(ious, [1.0, 0.25 / 1.75, 0.0], rtol=1e-5)

    def test_encode_decode_roundtrip(self):
        a = generate_anchors(**_SSD300_ARGS)
        gt = np.array([[0.2, 0.3, 0.6, 0.8]], np.float32)
        loc_t, cls_t = encode_targets(gt, np.array([5]), a)
        pos = cls_t > 0
        assert pos.sum() >= 1
        decoded = np.asarray(decode_boxes(jnp.asarray(loc_t), jnp.asarray(a)))
        np.testing.assert_allclose(decoded[pos], np.tile(gt, (pos.sum(), 1)),
                                   atol=1e-5)

    def test_empty_gt(self):
        a = generate_anchors(**_SSD300_ARGS)
        loc_t, cls_t = encode_targets(np.zeros((0, 4), np.float32),
                                      np.zeros((0,)), a)
        assert (cls_t == 0).all() and (loc_t == 0).all()


class TestMultiBoxLoss:
    def test_perfect_prediction_low_loss(self):
        a = generate_anchors(**_SSD300_ARGS)
        gt = np.array([[0.2, 0.3, 0.6, 0.8]], np.float32)
        loc_t, cls_t = encode_targets(gt, np.array([1]), a)
        loss_fn = multibox_loss()
        A = a.shape[0]
        y = (jnp.asarray(loc_t)[None], jnp.asarray(cls_t)[None])
        # logits strongly favoring the target class
        logits = jnp.full((1, A, 3), -10.0)
        logits = logits.at[..., 0].set(10.0)
        pos_idx = np.nonzero(cls_t > 0)[0]
        logits = logits.at[0, pos_idx, 0].set(-10.0)
        logits = logits.at[0, pos_idx, 1].set(10.0)
        good = float(loss_fn(y, [jnp.asarray(loc_t)[None], logits]))
        bad = float(loss_fn(y, [jnp.zeros((1, A, 4)),
                                jnp.zeros((1, A, 3))]))
        assert good < 0.01 < bad

    def test_hard_negative_mining_ratio(self):
        # with all-background targets there are no positives; loss is finite
        loss_fn = multibox_loss()
        y = (jnp.zeros((2, 100, 4)), jnp.zeros((2, 100), jnp.int32))
        out = float(loss_fn(y, [jnp.zeros((2, 100, 4)),
                                jnp.zeros((2, 100, 5))]))
        assert np.isfinite(out)


class TestNMS:
    def test_decode_detections_suppresses_overlaps(self):
        anchors = np.array([[0.3, 0.3, 0.2, 0.2],
                            [0.31, 0.31, 0.2, 0.2],
                            [0.7, 0.7, 0.2, 0.2]], np.float32)
        loc = jnp.zeros((1, 3, 4))  # boxes == anchors
        logits = jnp.asarray(
            [[[0.0, 5.0], [0.0, 4.0], [0.0, 3.0]]])  # 2 classes (bg + 1)
        boxes, scores, classes = decode_detections(
            loc, logits, anchors, num_classes=2, score_threshold=0.1,
            iou_threshold=0.5, max_detections=3)
        kept = np.asarray(scores[0]) > 0
        # anchors 0 and 1 overlap heavily: one suppressed; anchor 2 kept
        assert kept.sum() == 2

    def test_visualizer_draws(self):
        img = np.zeros((50, 50, 3), np.float32)
        out = Visualizer(score_threshold=0.1).draw(
            img, np.array([[0.1, 0.1, 0.6, 0.6]]), np.array([0.9]),
            np.array([1]))
        assert out.sum() > 0 and img.sum() == 0  # drew, without mutating input


class TestMeanAveragePrecision:
    def test_perfect_detections(self):
        m = MeanAveragePrecision(num_classes=3)
        gt_b = np.array([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.8, 0.8]])
        gt_l = np.array([1, 2])
        m.add(gt_b, np.array([0.9, 0.8]), gt_l, gt_b, gt_l)
        res = m.compute()
        assert res["mAP"] == pytest.approx(1.0)

    def test_false_positive_halves_precision(self):
        m = MeanAveragePrecision(num_classes=2)
        gt_b = np.array([[0.1, 0.1, 0.3, 0.3]])
        dets = np.array([[0.1, 0.1, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9]])
        m.add(dets, np.array([0.9, 0.8]), np.array([1, 1]), gt_b,
              np.array([1]))
        res = m.compute()
        # 1 TP at rank 1 (p=1, r=1), FP after: AP stays 1.0 (recall saturated)
        assert res["mAP"] == pytest.approx(1.0)
        # reversed scores: FP first -> precision at recall 1 is 0.5
        m2 = MeanAveragePrecision(num_classes=2)
        m2.add(dets, np.array([0.5, 0.8]), np.array([1, 1]), gt_b,
               np.array([1]))
        assert m2.compute()["mAP"] == pytest.approx(0.5)

    def test_voc2007_interpolation(self):
        m = MeanAveragePrecision(num_classes=2, use_voc2007=True)
        gt_b = np.array([[0.1, 0.1, 0.3, 0.3]])
        m.add(gt_b, np.array([0.9]), np.array([1]), gt_b, np.array([1]))
        assert m.compute()["mAP"] == pytest.approx(1.0)


class TestSSDEndToEnd:
    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_ssd_mobilenet_fit_and_detect(self, ctx):
        det = ObjectDetector(class_num=3, backbone="mobilenet", resolution=300)
        det._ensure_built()
        det.compile("adam", multibox_loss())
        rs = np.random.RandomState(0)
        n = 8
        imgs = rs.rand(n, 300, 300, 3).astype(np.float32)
        gt_boxes = [np.array([[0.2, 0.2, 0.6, 0.6]], np.float32)] * n
        gt_labels = [np.array([1])] * n
        loc_t, cls_t = det.encode_batch(gt_boxes, gt_labels)
        assert loc_t.shape == (n, 8732, 4) and cls_t.shape == (n, 8732)
        hist = det.fit(imgs, (loc_t, cls_t), batch_size=8, nb_epoch=1)
        assert hist["iterations"] >= 1
        boxes, scores, classes = det.detect(imgs[:8], batch_size=8,
                                            max_detections=10)
        assert boxes.shape == (8, 10, 4)
        assert scores.shape == (8, 10)
        # mAP machinery runs over the detections
        m = MeanAveragePrecision(num_classes=3)
        for i in range(4):
            m.add(boxes[i], scores[i], classes[i], gt_boxes[i], gt_labels[i])
        res = m.compute()
        assert 0.0 <= res["mAP"] <= 1.0

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_ssd_vgg16_builds(self, ctx):
        model, anchors = SSD(21, 300, "vgg16")
        assert anchors.shape == (8732, 4)
        params, state = model.build(jax.random.PRNGKey(0))
        x = jnp.zeros((1, 300, 300, 3))
        (loc, conf), _ = model.call(params, state, x)
        assert loc.shape == (1, 8732, 4)
        assert conf.shape == (1, 8732, 21)


class TestDetectionAugmentation:
    """Box-aware augmentation ops (reference SSD RandomSampler/expand/flip
    roi transforms)."""

    def _record(self, seed=0):
        rs = np.random.RandomState(seed)
        img = rs.rand(60, 80, 3).astype(np.float32)
        boxes = np.array([[0.25, 0.25, 0.5, 0.5],
                          [0.6, 0.1, 0.9, 0.4]], np.float32)
        labels = np.array([1, 2])
        return img, boxes, labels

    def test_hflip_boxes(self):
        from analytics_zoo_tpu.feature.image import RandomHFlipWithBoxes
        img, boxes, labels = self._record()
        out_img, out_boxes, _ = RandomHFlipWithBoxes(p=1.0).apply(
            (img, boxes, labels))
        np.testing.assert_allclose(out_img, img[:, ::-1])
        np.testing.assert_allclose(out_boxes[0], [0.5, 0.25, 0.75, 0.5],
                                   atol=1e-6)
        # widths preserved, order x0 < x1 kept
        assert (out_boxes[:, 2] > out_boxes[:, 0]).all()

    def test_expand_keeps_boxes_on_content(self):
        from analytics_zoo_tpu.feature.image import ExpandWithBoxes
        img, boxes, labels = self._record()
        out_img, out_boxes, _ = ExpandWithBoxes(max_ratio=3.0, p=1.0,
                                                seed=0).apply(
            (img, boxes, labels))
        assert out_img.shape[0] >= img.shape[0]
        assert (out_boxes >= 0).all() and (out_boxes <= 1).all()
        # box area shrinks by the expand ratio squared
        a0 = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        a1 = (out_boxes[:, 2] - out_boxes[:, 0]) * \
            (out_boxes[:, 3] - out_boxes[:, 1])
        assert (a1 < a0).all()

    def test_random_sample_crop_keeps_centered_boxes(self):
        from analytics_zoo_tpu.feature.image import RandomSampleCrop
        img, boxes, labels = self._record()
        op = RandomSampleCrop(min_ious=(0.1,), seed=3)
        out_img, out_boxes, out_labels = op.apply((img, boxes, labels))
        assert len(out_boxes) >= 1 and len(out_boxes) == len(out_labels)
        assert (out_boxes >= -1e-6).all() and (out_boxes <= 1 + 1e-6).all()
        assert out_img.ndim == 3 and out_img.shape[2] == 3

    def test_chain_into_encode(self, ctx):
        from analytics_zoo_tpu.feature.image import (
            ExpandWithBoxes, RandomHFlipWithBoxes, RandomSampleCrop,
            ResizeWithBoxes)
        chain = (RandomHFlipWithBoxes(p=0.5, seed=0)
                 >> ExpandWithBoxes(p=0.5, seed=1)
                 >> RandomSampleCrop(seed=2)
                 >> ResizeWithBoxes(120, 120))
        imgs, all_boxes, all_labels = [], [], []
        for i in range(4):
            img, boxes, labels = chain.apply(self._record(seed=i))
            assert img.shape == (120, 120, 3)
            imgs.append(img)
            all_boxes.append(boxes)
            all_labels.append(labels)
        det = ObjectDetector(class_num=3, backbone="mobilenet",
                             resolution=300)
        # encode the augmented ground truth against SSD anchors
        loc_t, cls_t = det.encode_batch(all_boxes, all_labels)
        assert loc_t.shape[0] == 4 and cls_t.shape[0] == 4


class TestSSD512:
    def test_build_and_anchor_consistency(self, ctx):
        model, anchors = SSD(21, 512, "vgg16")
        assert anchors.shape == (24564, 4)  # canonical SSD512 anchor count
        assert model.name == "ssd512_vgg16"
        loc_shape, conf_shape = [o.shape for o in model.outputs]
        assert loc_shape[1] == conf_shape[1] == 24564
        assert loc_shape[2] == 4 and conf_shape[2] == 21
        assert np.all(anchors >= 0) and np.all(anchors <= 1)

    def test_unsupported_resolution_raises(self, ctx):
        with pytest.raises(ValueError, match="300 or 512"):
            SSD(21, 400, "vgg16")

    def test_encode_against_512_anchors(self, ctx):
        from analytics_zoo_tpu.models.image.objectdetection import (
            generate_anchors, _SSD512)
        a = generate_anchors(image_size=512, **_SSD512)
        gt = np.array([[0.1, 0.1, 0.4, 0.5]], np.float32)
        loc_t, cls_t = encode_targets(gt, np.array([3]), a)
        assert loc_t.shape == (24564, 4) and (cls_t > 0).sum() >= 1
        pos = cls_t > 0
        decoded = np.asarray(decode_boxes(jnp.asarray(loc_t), jnp.asarray(a)))
        np.testing.assert_allclose(decoded[pos],
                                   np.tile(gt, (pos.sum(), 1)), atol=1e-5)
