"""Fault-injection framework unit tests: deterministic schedules, seeded
probabilistic draws, shared budgets (including across forked children), the
config-plan string, and the site registry contract."""
import multiprocessing
import os

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.config import global_config


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()
    global_config().unset("faults.plan")


def fire_pattern(site, calls):
    out = []
    for _ in range(calls):
        try:
            out.append(1 if faults.inject(site) else 0)
        except faults.FaultInjected:
            out.append(1)
    return out


class TestSchedules:
    def test_at_n_fires_exactly_once_on_nth_call(self):
        faults.arm("train.step", at=3)
        assert fire_pattern("train.step", 6) == [0, 0, 1, 0, 0, 0]
        assert faults.fire_count("train.step") == 1

    def test_raise_kind_raises_fault_injected(self):
        faults.arm("train.step", at=1)
        with pytest.raises(faults.FaultInjected, match="train.step"):
            faults.inject("train.step")

    def test_fault_injected_is_oserror(self):
        # retry layers classify OSError as transient; injected faults must
        # ride the same path as a real flaky backend
        assert issubclass(faults.FaultInjected, OSError)

    def test_flag_kind_returns_true(self):
        faults.arm("worker.kill", at=1)
        assert faults.inject("worker.kill") is True
        assert faults.inject("worker.kill") is False

    def test_probability_is_seeded_deterministic(self):
        faults.arm("io.remote", p=0.3, budget=100, seed=11)
        a = fire_pattern("io.remote", 200)
        faults.reset()
        faults.arm("io.remote", p=0.3, budget=100, seed=11)
        assert fire_pattern("io.remote", 200) == a
        faults.reset()
        faults.arm("io.remote", p=0.3, budget=100, seed=12)
        assert fire_pattern("io.remote", 200) != a  # seed actually matters
        assert 30 <= sum(a) <= 100  # plausibly ~0.3, budget-capped

    def test_budget_caps_total_fires(self):
        faults.arm("io.remote", p=1.0, budget=4)
        assert sum(fire_pattern("io.remote", 10)) == 4
        assert faults.fire_count("io.remote") == 4

    def test_unknown_site_rejected_everywhere(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.inject("no.such.site")
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.arm("no.such.site", at=1)

    def test_arm_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            faults.arm("train.step")
        with pytest.raises(ValueError, match="exactly one"):
            faults.arm("train.step", at=1, p=0.5)
        with pytest.raises(ValueError, match="1-based"):
            faults.arm("train.step", at=0)
        with pytest.raises(ValueError):
            faults.arm("train.step", p=1.5)

    def test_idle_site_is_silent(self):
        assert fire_pattern("train.step", 50) == [0] * 50


class TestPlanString:
    def test_plan_parses_at_probability_and_budget(self):
        global_config().set(
            "faults.plan", "train.step:2,io.remote:1.0@3,worker.kill:1")
        assert fire_pattern("train.step", 4) == [0, 1, 0, 0]
        assert sum(fire_pattern("io.remote", 10)) == 3
        assert faults.inject("worker.kill") is True

    def test_plan_unknown_site_fails_loudly(self):
        global_config().set("faults.plan", "bogus.site:1")
        with pytest.raises(ValueError, match="unknown site"):
            faults.inject("train.step")

    def test_reset_disarms_plan(self):
        global_config().set("faults.plan", "train.step:1")
        with pytest.raises(faults.FaultInjected):
            faults.inject("train.step")
        global_config().unset("faults.plan")
        faults.reset()
        assert fire_pattern("train.step", 3) == [0, 0, 0]


class TestForkSharing:
    def test_budget_shared_with_forked_children(self):
        """budget=1 armed before a fork must mean ONE firing across the
        whole process tree — the 'kill exactly one worker' contract."""
        faults.arm("worker.kill", at=1, budget=1)
        ctx = multiprocessing.get_context("fork")
        q = ctx.SimpleQueue()

        def child():
            q.put(bool(faults.inject("worker.kill")))

        procs = [ctx.Process(target=child) for _ in range(4)]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=10)
        fired = [q.get() for _ in range(4)]
        assert sum(fired) == 1
        assert faults.fire_count("worker.kill") == 1  # visible in parent


class TestRegistry:
    def test_registry_covers_all_layers(self):
        # the spine of the chaos layer: estimator, checkpointing, IO,
        # worker pool, device feed, serving
        assert {"train.step", "train.preempt", "ckpt.write", "ckpt.corrupt",
                "io.remote", "worker.task", "worker.kill", "feed.produce",
                "serving.decode", "serving.writeback"} <= set(faults.REGISTRY)

    def test_describe_lists_kinds(self):
        desc = faults.describe()
        assert desc["worker.kill"].startswith("flag:")
        assert desc["train.step"].startswith("raise:")

    def test_tear_snapshot_flips_a_data_file(self, tmp_path):
        d = tmp_path / "snap"
        d.mkdir()
        (d / "data.bin").write_bytes(bytes(range(100)))
        (d / "meta.json").write_text("{}")
        before = (d / "data.bin").read_bytes()
        faults.tear_snapshot(str(d))
        assert (d / "data.bin").read_bytes() != before
        assert (d / "meta.json").read_text() == "{}"  # metadata untouched
