"""Capture-style API (TFPark equivalent) + inference engine tests."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

RNG = jax.random.PRNGKey(0)


def linreg_data(n=64, d=4, noise=0.0, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, d).astype(np.float32)
    w = np.arange(1, d + 1, dtype=np.float32)
    y = (x @ w + noise * rs.randn(n)).astype(np.float32)[:, None]
    return x, y


class TestGraphModel:
    def test_from_loss(self, ctx):
        from analytics_zoo_tpu.capture import GraphModel
        x, y = linreg_data()

        def init_params(rng, sample_x):
            return {"w": jnp.zeros((sample_x.shape[-1], 1)),
                    "b": jnp.zeros((1,))}

        def loss_fn(params, bx, by):
            pred = bx @ params["w"] + params["b"]
            return jnp.mean((pred - by) ** 2)

        gm = GraphModel.from_loss(loss_fn, init_params, optimizer="adam")
        hist = gm.fit(x, y, batch_size=16, epochs=30)
        assert hist["loss_history"][-1] < hist["loss_history"][0]
        res = gm.evaluate(x, y, batch_size=16)
        assert "loss" in res
        w = gm.get_weights()["w"]
        assert w.shape == (4, 1)

    def test_from_loss_per_example_exact_eval(self, ctx):
        """per_example_loss_fn makes ragged-size eval EXACT: batch 16 over
        37 rows (2 full batches + tail 5) must equal plain numpy."""
        from analytics_zoo_tpu.capture import GraphModel
        rs = np.random.RandomState(3)
        x = rs.randn(37, 4).astype(np.float32)
        y = rs.randn(37, 1).astype(np.float32)

        def init_params(rng, sample_x):
            return {"w": jnp.ones((sample_x.shape[-1], 1))}

        def loss_fn(params, bx, by):
            return jnp.mean((bx @ params["w"] - by) ** 2)

        def per_example(params, bx, by):
            return jnp.mean((bx @ params["w"] - by) ** 2, axis=-1)

        gm = GraphModel.from_loss(loss_fn, init_params,
                                  per_example_loss_fn=per_example)
        gm.predict  # built lazily; evaluate initializes
        res = gm.evaluate(x, y, batch_size=16)
        expect = float(np.mean((x @ np.ones((4, 1)) - y) ** 2))
        assert res["loss"] == pytest.approx(expect, abs=1e-6)

    def test_from_forward(self, ctx):
        from analytics_zoo_tpu.capture import GraphModel
        x, y = linreg_data()

        def init_params(rng, sample_x):
            return {"w": jax.random.normal(rng, (sample_x.shape[-1], 1)) * 0.1}

        def forward(params, bx):
            return bx @ params["w"]

        gm = GraphModel.from_forward(forward, init_params, loss="mse",
                                     optimizer="sgd")
        hist = gm.fit(x, y, batch_size=16, epochs=20)
        assert hist["loss_history"][-1] < hist["loss_history"][0]
        preds = gm.predict(x, batch_size=16)
        assert preds.shape == (64, 1)

    def test_from_flax(self, ctx):
        import flax.linen as nn
        from analytics_zoo_tpu.capture import GraphModel

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Dense(8)(x)
                x = nn.relu(x)
                return nn.Dense(1)(x)

        x, y = linreg_data()
        gm = GraphModel.from_flax(MLP(), loss="mse", optimizer="adam")
        hist = gm.fit(x, y, batch_size=16, epochs=10)
        assert hist["loss_history"][-1] < hist["loss_history"][0]
        assert gm.predict(x, batch_size=16).shape == (64, 1)

    def test_checkpoint_roundtrip(self, ctx, tmp_path):
        from analytics_zoo_tpu.capture import GraphModel
        x, y = linreg_data()

        def init_params(rng, sx):
            return {"w": jnp.zeros((sx.shape[-1], 1))}

        gm = GraphModel.from_forward(lambda p, bx: bx @ p["w"], init_params)
        gm.fit(x, y, batch_size=16, epochs=5)
        p1 = gm.predict(x, batch_size=16)
        gm.save_checkpoint(str(tmp_path / "ckpt"))
        gm2 = GraphModel.from_forward(lambda p, bx: bx @ p["w"], init_params)
        gm2.fit(x, y, batch_size=16, epochs=1)  # init shapes
        gm2.load_checkpoint(str(tmp_path / "ckpt"))
        np.testing.assert_allclose(gm2.predict(x, batch_size=16), p1,
                                   atol=1e-5)


class TestFnEstimator:
    def test_modes(self, ctx):
        from analytics_zoo_tpu.capture import FnEstimator, ModeKeys
        x, y = linreg_data()

        def init_fn(rng, sx):
            return {"w": jnp.zeros((sx.shape[-1], 1))}

        def model_fn(params, features, labels, mode, rng):
            pred = features @ params["w"]
            if mode == ModeKeys.PREDICT:
                return pred
            return jnp.mean((pred - labels) ** 2)

        est = FnEstimator(model_fn, init_fn, optimizer="adam")
        h = est.train(lambda mode: (x, y), batch_size=16, epochs=20)
        assert h["loss_history"][-1] < h["loss_history"][0]
        res = est.evaluate(lambda mode: (x, y), batch_size=16)
        assert res["loss"] < h["loss_history"][0]
        preds = est.predict(lambda mode: x, batch_size=16)
        assert preds.shape == (64, 1)


class TestGAN:
    def test_gan_trains(self, ctx):
        from analytics_zoo_tpu.capture import GANEstimator
        rs = np.random.RandomState(0)
        real = (rs.randn(256, 2) * 0.3 + np.array([2.0, -1.0])).astype(
            np.float32)

        def gen_init(rng, noise):
            k1, k2 = jax.random.split(rng)
            return {"w1": jax.random.normal(k1, (noise.shape[-1], 16)) * 0.1,
                    "b1": jnp.zeros((16,)),
                    "w2": jax.random.normal(k2, (16, 2)) * 0.1,
                    "b2": jnp.zeros((2,))}

        def gen_fn(p, z):
            h = jax.nn.relu(z @ p["w1"] + p["b1"])
            return h @ p["w2"] + p["b2"]

        def disc_init(rng, x):
            k1, k2 = jax.random.split(rng)
            return {"w1": jax.random.normal(k1, (x.shape[-1], 16)) * 0.1,
                    "b1": jnp.zeros((16,)),
                    "w2": jax.random.normal(k2, (16, 1)) * 0.1}

        def disc_fn(p, x):
            h = jax.nn.relu(x @ p["w1"] + p["b1"])
            return h @ p["w2"]

        def g_loss(fake_logits):
            return jnp.mean(jax.nn.softplus(-fake_logits))

        def d_loss(real_logits, fake_logits):
            return jnp.mean(jax.nn.softplus(-real_logits)) + \
                jnp.mean(jax.nn.softplus(fake_logits))

        from analytics_zoo_tpu.keras import optimizers
        gan = GANEstimator(gen_fn, disc_fn, g_loss, d_loss, gen_init,
                           disc_init,
                           generator_optimizer=optimizers.Adam(1e-2),
                           discriminator_optimizer=optimizers.Adam(1e-2),
                           noise_dim=4, d_steps=1, g_steps=2)
        hist = gan.train(real, batch_size=64, steps=150)
        assert hist["iterations"] == 150
        samples = gan.generate(128)
        assert samples.shape == (128, 2)
        # generator should move toward the real mode at (2, -1) from ~N(0, .1)
        assert samples.mean(0)[0] > 0.8 and samples.mean(0)[1] < -0.3


class TestBERTEstimators:
    def test_bert_classifier(self, ctx):
        from analytics_zoo_tpu.capture import BERTClassifier
        rs = np.random.RandomState(1)
        tokens = rs.randint(1, 50, (16, 10))
        labels = rs.randint(0, 2, 16)
        clf = BERTClassifier(2, bert_config=dict(
            vocab=50, hidden_size=16, n_block=1, n_head=2,
            max_position_len=10, intermediate_size=32))
        h = clf.fit(tokens, labels, batch_size=8, epochs=1)
        assert h["iterations"] >= 1
        p = clf.predict(tokens, batch_size=8)
        assert p.shape == (16, 2)

    def test_bert_ner(self, ctx):
        from analytics_zoo_tpu.capture import BERTNER
        rs = np.random.RandomState(2)
        tokens = rs.randint(1, 40, (8, 6))
        tags = rs.randint(0, 3, (8, 6))
        ner = BERTNER(3, bert_config=dict(
            vocab=40, hidden_size=16, n_block=1, n_head=2,
            max_position_len=6, intermediate_size=32))
        ner.fit(tokens, tags, batch_size=8, epochs=1)
        p = ner.predict(tokens, batch_size=8)
        assert p.shape == (8, 6, 3)

    def test_bert_squad(self, ctx):
        from analytics_zoo_tpu.capture import BERTSQuAD
        rs = np.random.RandomState(3)
        tokens = rs.randint(1, 40, (8, 6))
        spans = np.stack([rs.randint(0, 6, 8), rs.randint(0, 6, 8)], 1)
        qa = BERTSQuAD(bert_config=dict(
            vocab=40, hidden_size=16, n_block=1, n_head=2,
            max_position_len=6, intermediate_size=32))
        qa.fit(tokens, spans, batch_size=8, epochs=1)
        start, end = qa.predict(tokens, batch_size=8)
        assert start.shape == (8, 6) and end.shape == (8, 6)


class TestInferenceModel:
    def _simple_forward(self):
        def forward(params, x):
            return x @ params["w"] + params["b"]
        params = {"w": jnp.asarray(np.eye(3, 2, dtype=np.float32)),
                  "b": jnp.ones((2,))}
        return forward, params

    def test_load_jax_and_bucketing(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        fwd, params = self._simple_forward()
        im = InferenceModel(concurrent_num=2).load_jax(fwd, params)
        x = np.random.rand(5, 3).astype(np.float32)  # pads to bucket 8
        y = im.predict(x)
        assert y.shape == (5, 2)
        np.testing.assert_allclose(y, x @ np.eye(3, 2) + 1, atol=1e-5)
        y2 = im.predict(np.random.rand(7, 3).astype(np.float32))
        assert y2.shape == (7, 2)  # same bucket (8) reused by jit's cache
        y3 = im.predict(np.random.rand(20, 3).astype(np.float32),
                        batch_size=8)
        assert y3.shape == (20, 2)

    def test_pool_concurrency(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        fwd, params = self._simple_forward()
        im = InferenceModel(concurrent_num=4).load_jax(fwd, params)
        batches = [np.random.rand(4, 3).astype(np.float32) for _ in range(8)]
        outs = im.predict_many(batches)
        assert len(outs) == 8 and all(o.shape == (4, 2) for o in outs)

    def test_quantize_bf16_int8(self, ctx):
        from analytics_zoo_tpu.inference import InferenceModel
        rs = np.random.RandomState(0)
        w = rs.randn(8, 4).astype(np.float32)

        def forward(params, x):
            return x @ params["w"]

        x = rs.rand(4, 8).astype(np.float32)
        ref = x @ w
        for dtype, tol in (("bf16", 0.1), ("int8", 0.2)):
            im = InferenceModel().load_jax(forward, {"w": jnp.asarray(w)})
            im.quantize(dtype)
            y = im.predict(x)
            np.testing.assert_allclose(y, ref, atol=tol)

    def test_load_zoo_model(self, ctx, tmp_path):
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.models import NeuralCF
        ncf = NeuralCF(10, 8, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=4)
        ncf.default_compile()
        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 11, 16), rs.randint(1, 9, 16)],
                     1).astype(np.float32)
        y = rs.randint(0, 2, 16).astype(np.float32)
        ncf.fit(x, y, batch_size=8, nb_epoch=1)
        path = str(tmp_path / "ncf")
        ncf.save_model(path)
        im = InferenceModel().load_zoo(path)
        p = im.predict(x)
        np.testing.assert_allclose(
            p, np.asarray(ncf.predict(x, batch_size=16)), atol=1e-5)

    def test_load_savedmodel(self, ctx, tmp_path):
        tf = pytest.importorskip("tensorflow")
        from analytics_zoo_tpu.inference import InferenceModel

        class M(tf.Module):
            @tf.function(input_signature=[
                tf.TensorSpec([None, 3], tf.float32)])
            def __call__(self, x):
                return {"out": 2.0 * x}

        path = str(tmp_path / "sm")
        tf.saved_model.save(M(), path)
        im = InferenceModel().load_savedmodel(path)
        x = np.random.rand(4, 3).astype(np.float32)
        np.testing.assert_allclose(im.predict(x), 2 * x, atol=1e-5)

    def test_savedmodel_stablehlo_roundtrip_serves_without_tf(self, ctx,
                                                              tmp_path):
        # VERDICT r2 weak#7: the SERVED path must not need TF — export the
        # imported SavedModel to StableHLO buckets, then predict from the
        # artifact in a subprocess where importing tensorflow is a hard
        # error
        tf = pytest.importorskip("tensorflow")
        import subprocess
        import sys

        from analytics_zoo_tpu.inference import InferenceModel

        class M(tf.Module):
            @tf.function(input_signature=[
                tf.TensorSpec([None, 3], tf.float32)])
            def __call__(self, x):
                return {"out": 3.0 * x + 1.0}

        sm = str(tmp_path / "sm")
        tf.saved_model.save(M(), sm)
        art = str(tmp_path / "aot")
        x = np.random.RandomState(0).rand(4, 3).astype(np.float32)
        im = InferenceModel().load_savedmodel(sm)
        im.export_compiled(art, x, batch_sizes=(4,), platforms=("cpu",))
        np.save(str(tmp_path / "x.npy"), x)
        code = f"""
import sys
sys.modules["tensorflow"] = None  # any TF import now raises
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax; jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
from analytics_zoo_tpu.inference import InferenceModel
x = np.load({str(tmp_path / 'x.npy')!r})
im = InferenceModel().load_compiled({art!r})
got = np.asarray(im.predict(x))
np.testing.assert_allclose(got, 3.0 * x + 1.0, atol=1e-5)
print("TF_FREE_SERVE_OK")
"""
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "TF_FREE_SERVE_OK" in proc.stdout

    def test_savedmodel_multi_output_artifact_keeps_keys(self, ctx,
                                                         tmp_path):
        # dict-output signatures must serve the SAME dict from the TF-free
        # artifact as from the live call_tf path
        tf = pytest.importorskip("tensorflow")
        from analytics_zoo_tpu.inference import InferenceModel

        class M(tf.Module):
            @tf.function(input_signature=[
                tf.TensorSpec([None, 3], tf.float32)])
            def __call__(self, x):
                return {"scores": 2.0 * x, "bias": x + 1.0}

        sm = str(tmp_path / "sm")
        tf.saved_model.save(M(), sm)
        x = np.random.RandomState(1).rand(4, 3).astype(np.float32)
        im = InferenceModel().load_savedmodel(sm)
        live = im.predict(x)
        assert set(live) == {"scores", "bias"}
        art = str(tmp_path / "art")
        im.export_compiled(art, x, batch_sizes=(4,), platforms=("cpu",))
        got = InferenceModel().load_compiled(art).predict(x)
        assert set(got) == {"scores", "bias"}
        np.testing.assert_allclose(got["scores"], 2.0 * x, atol=1e-5)
        np.testing.assert_allclose(got["bias"], x + 1.0, atol=1e-5)

    def test_reused_model_does_not_export_stale_savedmodel(self, ctx,
                                                           tmp_path):
        tf = pytest.importorskip("tensorflow")
        import jax.numpy as jnp

        from analytics_zoo_tpu.inference import InferenceModel

        class M(tf.Module):
            @tf.function(input_signature=[
                tf.TensorSpec([None, 3], tf.float32)])
            def __call__(self, x):
                return {"out": 9.0 * x}

        sm = str(tmp_path / "sm")
        tf.saved_model.save(M(), sm)
        im = InferenceModel().load_savedmodel(sm)
        im.load_jax(lambda p, x: x @ p["w"], {"w": jnp.eye(3)})
        x = np.random.RandomState(2).rand(2, 3).astype(np.float32)
        art = str(tmp_path / "art2")
        im.export_compiled(art, x, batch_sizes=(2,), platforms=("cpu",))
        got = np.asarray(InferenceModel().load_compiled(art).predict(x))
        np.testing.assert_allclose(got, x, atol=1e-5)  # NOT 9*x

    def test_load_torch(self, ctx, tmp_path):
        torch = pytest.importorskip("torch")
        from analytics_zoo_tpu.inference import InferenceModel

        class Net(torch.nn.Module):
            def forward(self, x):
                return x * 3.0

        path = str(tmp_path / "net.pt")
        torch.jit.script(Net()).save(path)
        im = InferenceModel().load_torch(path)
        x = np.random.rand(4, 3).astype(np.float32)
        np.testing.assert_allclose(im.predict(x), 3 * x, atol=1e-5)


class TestImportedModelServing:
    def test_load_onnx_into_pool(self, tmp_path):
        from test_net import _mlp_onnx
        rs = np.random.RandomState(0)
        data, (w1, b1, w2, b2) = _mlp_onnx(rs)
        path = tmp_path / "m.onnx"
        path.write_bytes(data)
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel(concurrent_num=2).load_onnx(str(path))
        x = rs.randn(4, 4).astype(np.float32)
        out = np.asarray(im.predict(x))
        expected = np.maximum(x @ w1 + b1, 0) @ w2 + b2
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-4)

    def test_load_caffe_into_pool(self, tmp_path):
        pt = tmp_path / "net.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "p1" type: "Pooling" bottom: "data" top: "p1"
        pooling_param { pool: AVE kernel_size: 2 stride: 2 } }
""")
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_caffe(str(pt))
        x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
        out = np.asarray(im.predict(x))
        assert out.shape == (1, 2, 2, 1)
        assert out[0, 0, 0, 0] == x[0, :2, :2, 0].mean()


class TestAOTExport:
    """Serialized ahead-of-time compiled artifacts (the OpenVINO IR role):
    export on one process, serve from the artifact with zero JIT compiles."""

    def _make_pool(self, ctx):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        rs = np.random.RandomState(0)
        w = rs.randn(6, 3).astype(np.float32)

        def fwd(params, x):
            return jnp.tanh(x @ params["w"])

        return InferenceModel(concurrent_num=2).load_jax(
            fwd, {"w": jnp.asarray(w)}), w

    def test_export_load_roundtrip(self, ctx, tmp_path):
        from analytics_zoo_tpu.inference import InferenceModel
        pool, w = self._make_pool(ctx)
        x = np.random.RandomState(1).rand(20, 6).astype(np.float32)
        ref = np.asarray(pool.predict(x))
        path = str(tmp_path / "aot")
        pool.export_compiled(path, x[:1], batch_sizes=(4, 16, 32))
        served = InferenceModel(concurrent_num=2).load_compiled(path)
        out = np.asarray(served.predict(x))  # pads 20 -> bucket 32
        np.testing.assert_allclose(out, ref, atol=1e-5)
        # larger than the biggest bucket: chunked through bucket 32
        x_big = np.random.RandomState(2).rand(70, 6).astype(np.float32)
        out_big = np.asarray(served.predict(x_big))
        np.testing.assert_allclose(out_big, np.tanh(x_big @ w), atol=1e-5)

    def test_artifact_is_self_contained(self, ctx, tmp_path):
        import os
        pool, _ = self._make_pool(ctx)
        path = str(tmp_path / "aot")
        pool.export_compiled(path, np.zeros((1, 6), np.float32),
                             batch_sizes=(8,))
        files = sorted(os.listdir(path))
        assert files == ["aot_meta.json", "batch-8.stablehlo"]
        # params are frozen inside the artifact: nothing else needed
        assert os.path.getsize(os.path.join(path, "batch-8.stablehlo")) > 0

    def test_multi_input_and_empty_batch(self, ctx, tmp_path):
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference import InferenceModel
        w = np.random.RandomState(3).randn(4, 2).astype(np.float32)

        def fwd(params, xs):  # list-of-inputs calling convention
            a, b = xs
            return (a + b) @ params["w"]

        pool = InferenceModel().load_jax(fwd, {"w": jnp.asarray(w)})
        ex = [np.zeros((1, 4), np.float32), np.zeros((1, 4), np.float32)]
        path = str(tmp_path / "aot_multi")
        pool.export_compiled(path, ex, batch_sizes=(4,))
        served = InferenceModel().load_compiled(path)
        a = np.random.RandomState(4).rand(3, 4).astype(np.float32)
        b = np.random.RandomState(5).rand(3, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(served.predict([a, b])),
                                   (a + b) @ w, atol=1e-5)
        # empty batch trims to zero rows through the bucket-1..4 program
        empty = np.zeros((0, 4), np.float32)
        out = np.asarray(served.predict([empty, empty]))
        assert out.shape == (0, 2)
        # batch_size chunking still honored on the AOT path
        big_a = np.random.RandomState(6).rand(10, 4).astype(np.float32)
        big_b = np.random.RandomState(7).rand(10, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(served.predict([big_a, big_b], batch_size=3)),
            (big_a + big_b) @ w, atol=1e-5)


class TestTransformerLM:
    def test_fit_and_cached_generation(self, ctx):
        from analytics_zoo_tpu.capture import TransformerLM
        V, S = 12, 16
        lm = TransformerLM(vocab_size=V, hidden=32, n_block=2, n_head=2,
                           max_len=64)
        rs = np.random.RandomState(0)
        starts = rs.randint(0, V, 256)
        data = (starts[:, None] + np.arange(S)[None]) % V  # cyclic counting
        r = lm.fit(data, batch_size=32, epochs=40)
        assert r["loss_history"][-1] < 0.1
        prompt = data[:2, :5]
        gen = lm.generate(prompt, max_new_tokens=6)
        expect = np.stack([(p[-1] + 1 + np.arange(6)) % V for p in prompt])
        np.testing.assert_array_equal(gen, expect)

    def test_generation_consistent_with_full_forward(self, ctx):
        """Prefill+cached decode must pick the same argmax as the full
        forward on an UNTRAINED model (exactness of the cache path)."""
        import jax.numpy as jnp
        from analytics_zoo_tpu.capture import TransformerLM
        lm = TransformerLM(vocab_size=9, hidden=16, n_block=2, n_head=2,
                           max_len=32, seed=3)
        rs = np.random.RandomState(1)
        prompt = rs.randint(0, 9, (2, 6))
        lm.fit(prompt.repeat(4, 0), batch_size=8, epochs=1)  # init params
        gen1 = lm.generate(prompt, max_new_tokens=1)[:, 0]
        logits = np.asarray(lm.logits(prompt))  # [B, S, V]
        full_next = logits[:, -1].argmax(-1)
        np.testing.assert_array_equal(gen1, full_next)
        # beam_size=1-equivalent best beam matches greedy on a peaked model
        beam = lm.generate(prompt, max_new_tokens=1, beam_size=3)[:, 0]
        np.testing.assert_array_equal(beam, full_next)

    def test_prompt_budget_enforced(self, ctx):
        from analytics_zoo_tpu.capture import TransformerLM
        lm = TransformerLM(vocab_size=5, hidden=16, n_block=1, n_head=2,
                           max_len=8)
        lm.fit(np.zeros((8, 8)), batch_size=8, epochs=1)
        with pytest.raises(ValueError, match="exceeds max_len"):
            lm.generate(np.zeros((1, 6), np.int32), max_new_tokens=4)
