"""Tests for the step-phase attribution profiler (common/profiler.py):
the disabled sub-microsecond no-op contract, exact fake-clock phase
accounting (sum(phases) == wall, remainder booked as ``other``), graceful
memory sampling on backends without ``memory_stats``, the ``zoo_build_info``
info-style gauge, and jax.profiler capture windows (step-bounded,
config-armed, SLO-breach-armed, broken-profiler degrade)."""
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common import metrics as zoo_metrics
from analytics_zoo_tpu.common import profiler
from analytics_zoo_tpu.common.config import global_config


@pytest.fixture(autouse=True)
def _profiler_reset():
    """Every test leaves the profiler as it found it: disabled, no open
    capture window, config arming unconsumed."""
    yield
    profiler.set_enabled(False)
    profiler._reset_capture_for_tests()


@pytest.fixture()
def fake_capture(monkeypatch):
    """Replace the jax.profiler start/stop entry points with recorders so
    window mechanics are testable without a real trace backend."""
    calls = {"start": [], "stop": 0}
    monkeypatch.setattr(profiler, "_profiler_start",
                        lambda out_dir: calls["start"].append(out_dir))

    def _stop():
        calls["stop"] += 1

    monkeypatch.setattr(profiler, "_profiler_stop", _stop)
    profiler._reset_capture_for_tests()
    return calls


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _phase_sum(loop):
    return sum(profiler._M_PHASE.labels(loop=loop, phase=p).sum()
               for p in profiler.PHASES)


class TestDisabledOverhead:
    def test_record_phase_disabled_is_sub_microsecond(self):
        """The observability bar: a disabled record call must cost less
        than 1µs over an empty loop (median of rounds vs a bare loop, the
        same protocol as the metrics registry's overhead test)."""
        profiler.set_enabled(False)
        n = 2000

        def bare():
            t0 = time.perf_counter()
            for _ in range(n):
                pass
            return (time.perf_counter() - t0) / n

        def with_record():
            t0 = time.perf_counter()
            for _ in range(n):
                profiler.record_phase("t_off", "dispatch", 0.001)
            return (time.perf_counter() - t0) / n

        bare_s = sorted(bare() for _ in range(5))[2]
        rec_s = sorted(with_record() for _ in range(5))[2]
        added = rec_s - bare_s
        assert added < 1e-6, f"disabled record_phase added {added * 1e9:.0f}ns"

    def test_disabled_step_profiler_records_nothing(self):
        profiler.set_enabled(False)
        sp = profiler.StepProfiler("t_off2")
        before = _phase_sum("t_off2")
        sp.step_start()
        sp.add("dispatch", 1.0)
        assert sp.phase("fetch") is profiler._NULL_SPAN
        with sp.phase("fetch"):
            pass
        sp.step_end()
        assert _phase_sum("t_off2") == before
        assert profiler._M_WALL.labels(loop="t_off2").count() == 0


class TestPhaseAccounting:
    def test_fake_clock_phase_sum_equals_wall(self):
        """The accounting invariant: per-step phase sums equal the step
        wall exactly; unattributed time lands in phase=other."""
        profiler.set_enabled(True)
        clk = _FakeClock()
        sp = profiler.StepProfiler("t_fake", clock=clk)
        p_before = _phase_sum("t_fake")
        w_before = profiler._M_WALL.labels(loop="t_fake").sum()
        o_before = profiler._M_PHASE.labels(loop="t_fake",
                                            phase="other").sum()

        sp.step_start()
        clk.advance(0.02)
        sp.add("host_input", 0.02)
        clk.advance(0.03)
        sp.add("dispatch", 0.03)
        with sp.phase("execute"):
            clk.advance(0.05)
        clk.advance(0.01)  # unattributed: bookkeeping, triggers, ...
        sp.step_end()

        wall = profiler._M_WALL.labels(loop="t_fake").sum() - w_before
        assert wall == pytest.approx(0.11)
        assert _phase_sum("t_fake") - p_before == pytest.approx(wall)
        other = (profiler._M_PHASE.labels(loop="t_fake", phase="other").sum()
                 - o_before)
        assert other == pytest.approx(0.01)

    def test_multi_window_phase_accumulates_within_step(self):
        profiler.set_enabled(True)
        clk = _FakeClock()
        sp = profiler.StepProfiler("t_acc", clock=clk)
        before = profiler._M_PHASE.labels(loop="t_acc",
                                          phase="fetch").sum()
        sp.step_start()
        for _ in range(3):
            with sp.phase("fetch"):
                clk.advance(0.004)
        sp.step_end()
        got = profiler._M_PHASE.labels(loop="t_acc", phase="fetch").sum()
        assert got - before == pytest.approx(0.012)
        # three windows, ONE observation: accumulation happens per step
        assert profiler._M_PHASE.labels(loop="t_acc",
                                        phase="fetch").count() == 1

    def test_train_loop_lands_phases_in_exposition(self, ctx):
        """End to end on the CPU mesh: one profiled epoch produces train
        phase series and step walls in the Prometheus exposition."""
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense
        rs = np.random.RandomState(0)
        x = rs.randn(256, 8).astype(np.float32)
        y = rs.randn(256, 1).astype(np.float32)
        wall_before = profiler._M_WALL.labels(loop="train").count()
        profiler.set_enabled(True)
        try:
            est = Estimator(
                model=Sequential([Dense(8, activation="tanh"), Dense(1)]),
                loss_fn=objectives.get("mse"),
                optimizer=optimizers.Adam(1e-2))
            est.train(FeatureSet.from_ndarrays(x, y, seed=1),
                      batch_size=64, epochs=1)
        finally:
            profiler.set_enabled(False)
        assert profiler._M_WALL.labels(loop="train").count() > wall_before
        text = zoo_metrics.expose_text()
        assert "zoo_profile_phase_seconds" in text
        assert 'loop="train"' in text
        for phase in ("host_input", "dispatch", "execute", "fetch"):
            assert f'phase="{phase}"' in text

    def test_enable_midrun_on_warm_estimator_records_phases(self, ctx):
        """Flipping the profiler on between train calls must attribute the
        next epoch. ``epochs=`` is a cumulative MaxEpoch trigger, so the
        follow-up call asks for one MORE epoch via an explicit trigger —
        ``train(epochs=1)`` again would be a zero-step no-op and the
        profiler would (correctly) record nothing."""
        from analytics_zoo_tpu.common.triggers import MaxEpoch
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import Dense
        rs = np.random.RandomState(1)
        x = rs.randn(256, 8).astype(np.float32)
        y = rs.randn(256, 1).astype(np.float32)
        est = Estimator(
            model=Sequential([Dense(8, activation="tanh"), Dense(1)]),
            loss_fn=objectives.get("mse"),
            optimizer=optimizers.Adam(1e-2))
        fs = FeatureSet.from_ndarrays(x, y, seed=1)
        est.train(fs, batch_size=64, epochs=1)  # profiler off: warm compile
        step_before = est.global_step
        wall_before = profiler._M_WALL.labels(loop="train").count()
        profiler.set_enabled(True)
        try:
            est.train(fs, batch_size=64, end_trigger=MaxEpoch(est.epoch))
        finally:
            profiler.set_enabled(False)
        assert est.global_step > step_before  # the epoch actually stepped
        assert profiler._M_WALL.labels(loop="train").count() > wall_before
        for phase in ("host_input", "dispatch", "execute"):
            assert profiler._M_PHASE.labels(
                loop="train", phase=phase).count() > 0


class TestMemoryAndBuildInfo:
    def test_sample_memory_never_raises_without_memory_stats(self):
        """CPU backends report no memory_stats: the sample degrades the
        HBM fields to None and still lands host RSS."""
        out = profiler.sample_memory()
        assert set(out) == {"hbm_used_bytes", "hbm_limit_bytes",
                            "host_rss_bytes"}
        assert out["host_rss_bytes"] is not None
        assert out["host_rss_bytes"] > 0

    def test_build_info_gauge_exposed(self):
        info = profiler.ensure_build_info()
        assert info is profiler.ensure_build_info()  # memoized
        assert info["jax_version"] not in ("", None)
        assert len(info["git_sha"]) >= 7 or info["git_sha"] == "unknown"
        text = zoo_metrics.expose_text()
        assert "zoo_build_info{" in text
        assert 'git_sha="' in text


class TestCaptureWindows:
    OUT = "/tmp/zoo-profiler-test-trace"

    def test_step_window_closes_after_n_boundaries(self, fake_capture):
        profiler.set_enabled(True)
        before = profiler._M_CAPTURES.labels(trigger="manual").value()
        assert profiler.arm_capture(steps=2, out_dir=self.OUT)
        assert profiler.capture_active()
        assert fake_capture["start"] == [self.OUT]
        # a second arm while a window is open is refused, not queued
        assert not profiler.arm_capture(steps=1, out_dir=self.OUT)
        profiler.step_boundary()
        assert profiler.capture_active()
        profiler.step_boundary()
        assert not profiler.capture_active()
        assert fake_capture["stop"] == 1
        got = profiler._M_CAPTURES.labels(trigger="manual").value()
        assert got == before + 1

    def test_arm_without_bound_or_dir_is_refused(self, fake_capture):
        assert not profiler.arm_capture(out_dir=self.OUT)  # no bound
        assert not profiler.arm_capture(steps=3)           # no dir
        assert fake_capture["start"] == []

    def test_config_armed_window(self, fake_capture):
        cfg = global_config()
        cfg.set("profile.capture_steps", 1)
        cfg.set("profile.capture_dir", self.OUT)
        try:
            profiler.set_enabled(True)
            profiler.step_boundary()  # first boundary consumes the arming
            assert profiler.capture_active()
            profiler.step_boundary()  # counts the one armed step down
            assert not profiler.capture_active()
            assert fake_capture["stop"] == 1
        finally:
            cfg.unset("profile.capture_steps")
            cfg.unset("profile.capture_dir")

    def test_slo_breach_arms_once_and_time_window_closes(self, fake_capture):
        cfg = global_config()
        cfg.set("profile.capture_on_breach", True)
        cfg.set("profile.capture_dir", self.OUT)
        cfg.set("profile.capture_seconds", 0.01)
        try:
            before = profiler._M_CAPTURES.labels(trigger="breach").value()
            profiler.on_slo_breach("shed")
            assert profiler.capture_active()
            profiler.on_slo_breach("expired")  # one capture per process
            got = profiler._M_CAPTURES.labels(trigger="breach").value()
            assert got == before + 1
            time.sleep(0.02)
            profiler.maybe_stop_capture()  # the health-cadence closer
            assert not profiler.capture_active()
            assert fake_capture["stop"] == 1
        finally:
            cfg.unset("profile.capture_on_breach")
            cfg.unset("profile.capture_dir")
            cfg.unset("profile.capture_seconds")

    def test_breach_without_optin_is_a_noop(self, fake_capture):
        profiler.on_slo_breach("shed")
        assert not profiler.capture_active()
        assert fake_capture["start"] == []

    def test_broken_profiler_degrades_permanently(self, monkeypatch):
        profiler._reset_capture_for_tests()

        def boom(out_dir):
            raise RuntimeError("no trace backend")

        monkeypatch.setattr(profiler, "_profiler_start", boom)
        assert not profiler.arm_capture(steps=1, out_dir=self.OUT)
        assert not profiler.capture_active()
        # broken stays broken (warn once, then silent no-ops) until reset
        assert not profiler.arm_capture(steps=1, out_dir=self.OUT)
