"""Image3D volume transforms + the Keras-2 naming API."""
import math

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D, CenterCrop3D, Crop3D, RandomCrop3D, Rotate3D)


class TestCrop3D:
    def test_fixed_crop(self):
        vol = np.arange(4 * 5 * 6, dtype=np.float32).reshape(4, 5, 6)
        out = Crop3D([1, 2, 3], [2, 2, 2]).apply(vol)
        np.testing.assert_array_equal(out, vol[1:3, 2:4, 3:5])

    def test_channel_axis_preserved(self):
        vol = np.zeros((4, 5, 6, 1), np.float32)
        assert Crop3D([0, 0, 0], [2, 2, 2]).apply(vol).shape == (2, 2, 2, 1)

    def test_out_of_bounds_rejected(self):
        with pytest.raises(ValueError):
            Crop3D([3, 0, 0], [2, 2, 2]).apply(np.zeros((4, 4, 4)))

    def test_center_crop(self):
        vol = np.arange(6 ** 3, dtype=np.float32).reshape(6, 6, 6)
        out = CenterCrop3D(2, 2, 2).apply(vol)
        np.testing.assert_array_equal(out, vol[2:4, 2:4, 2:4])

    def test_random_crop_shape(self):
        out = RandomCrop3D(3, 4, 5).apply(np.zeros((8, 8, 8)))
        assert out.shape == (3, 4, 5)


class TestAffine3D:
    def test_identity(self):
        vol = np.random.RandomState(0).rand(5, 6, 7).astype(np.float32)
        out = AffineTransform3D(np.eye(3)).apply(vol)
        np.testing.assert_allclose(out, vol, atol=1e-5)

    def test_translation_shifts(self):
        vol = np.zeros((5, 5, 5), np.float32)
        vol[2, 2, 2] = 1.0
        # translation moves the sampled source coordinate by -t, i.e. the
        # CONTENT moves by +t along each axis
        out = AffineTransform3D(np.eye(3), translation=(1, 0, 0),
                                clamp_mode="padding").apply(vol)
        assert out[3, 2, 2] == pytest.approx(1.0)
        assert out[2, 2, 2] == pytest.approx(0.0)

    def test_padding_mode_fills(self):
        vol = np.ones((4, 4, 4), np.float32)
        out = AffineTransform3D(np.eye(3), translation=(2, 0, 0),
                                clamp_mode="padding", pad_val=-7).apply(vol)
        assert out[0, 0, 0] == pytest.approx(-7)

    def test_clamp_rejects_pad_val(self):
        with pytest.raises(ValueError):
            AffineTransform3D(np.eye(3), clamp_mode="clamp", pad_val=1.0)

    def test_rotate_90_yaw(self):
        """Reference convention (Rotation.scala:47-48): the yaw matrix acts
        on (z, y, x) coordinate vectors mixing the first two components, so
        a 90-degree yaw rotates the z-y plane and leaves x invariant. A unit
        mass at offset (0, -1, 0) from center moves to offset (-1, 0, 0)."""
        vol = np.zeros((3, 5, 5), np.float32)
        vol[1, 1, 2] = 1.0  # center (1,2,2) + offset (0,-1,0)
        out = Rotate3D([math.pi / 2, 0, 0]).apply(vol)
        assert out.sum() == pytest.approx(1.0, abs=1e-5)
        assert out[1, 1, 2] == pytest.approx(0.0, abs=1e-5)
        assert out[0, 2, 2] == pytest.approx(1.0, abs=1e-5)

    def test_rotate_roundtrip(self):
        # smooth volume: double trilinear interpolation stays accurate
        g = np.linspace(-1, 1, 12)
        zz, yy, xx = np.meshgrid(g, g, g, indexing="ij")
        vol = np.exp(-(zz ** 2 + yy ** 2 + xx ** 2) * 2).astype(np.float32)
        ang = [0.3, -0.2, 0.5]
        once = Rotate3D(ang).apply(vol)
        out = AffineTransform3D(np.linalg.inv(Rotate3D(ang).mat)).apply(once)
        # interpolation loses a little at the borders; interior must agree
        # two trilinear passes over a curved field cost a few percent
        np.testing.assert_allclose(out[3:9, 3:9, 3:9], vol[3:9, 3:9, 3:9],
                                   atol=0.1)


class TestKeras2:
    def test_dense_conv_names(self):
        import jax
        from analytics_zoo_tpu.keras2 import Input, Model
        from analytics_zoo_tpu.keras2.layers import (
            Conv2D, Dense, Dropout, Flatten, MaxPooling2D)
        x = Input(shape=(8, 8, 3))
        h = Conv2D(4, kernel_size=3, strides=1, padding="same",
                   activation="relu", name="c1")(x)
        h = MaxPooling2D(pool_size=2)(h)
        h = Flatten()(h)
        h = Dropout(rate=0.5)(h)
        y = Dense(units=2, use_bias=True, name="head")(h)
        model = Model(x, y)
        params, state = model.build(jax.random.PRNGKey(0))
        out, _ = model.call(params, state,
                            np.zeros((2, 8, 8, 3), np.float32))
        assert np.asarray(out).shape == (2, 2)
        # identical param-tree contract as keras-1
        assert params["c1"]["kernel"].shape == (3, 3, 3, 4)
        assert params["head"]["kernel"].shape == (4 * 4 * 4, 2)

    def test_keras1_keras2_interchangeable(self):
        """Same weights, same answers across the two namespaces."""
        import jax
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense as Dense1
        from analytics_zoo_tpu.keras2.layers import Dense as Dense2
        m1 = Sequential([Dense1(5, name="d")])
        m2 = Sequential([Dense2(units=5, name="d")])
        p, s = m1.build(jax.random.PRNGKey(0), (None, 3))
        x = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        y1, _ = m1.call(p, s, x)
        y2, _ = m2.call(p, s, x)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))

    def test_merge_functions(self):
        import jax
        from analytics_zoo_tpu.keras2 import Input, Model
        from analytics_zoo_tpu.keras2.layers import average, maximum
        a, b = Input(shape=(4,)), Input(shape=(4,))
        model = Model([a, b], maximum([a, b]))
        p, s = model.build(jax.random.PRNGKey(0))
        xa = np.asarray([[1, 5, 2, 0]], np.float32)
        xb = np.asarray([[3, 1, 2, 4]], np.float32)
        out, _ = model.call(p, s, [xa, xb])
        np.testing.assert_array_equal(np.asarray(out), [[3, 5, 2, 4]])
