"""Transfer learning + model import: ONNX loader, torch weights, freezing,
graph surgery (reference NetUtils.scala / onnx_loader.py behavior)."""
import struct

import numpy as np
import pytest

from analytics_zoo_tpu.net import Net, load_onnx, load_torch_state_dict

# ---------------------------------------------------------------------------
# minimal protobuf wire ENCODER (test-side twin of net/onnx_wire.py's decoder)
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(fno: int, wt: int) -> bytes:
    return _varint((fno << 3) | wt)


def _len_field(fno: int, payload: bytes) -> bytes:
    return _tag(fno, 2) + _varint(len(payload)) + payload


def _str_field(fno: int, s: str) -> bytes:
    return _len_field(fno, s.encode())


def _int_field(fno: int, v: int) -> bytes:
    return _tag(fno, 0) + _varint(v & ((1 << 64) - 1))


def _float_field(fno: int, v: float) -> bytes:
    return _tag(fno, 5) + struct.pack("<f", v)


def _tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = {np.float32: 1, np.int64: 7, np.int32: 6}[arr.dtype.type]
    body = b"".join(_int_field(1, d) for d in arr.shape)
    body += _int_field(2, dt)
    body += _str_field(8, name)
    body += _len_field(9, arr.tobytes())
    return body


def _attr_int(name: str, v: int) -> bytes:
    return _str_field(1, name) + _int_field(3, v) + _int_field(20, 2)


def _attr_float(name: str, v: float) -> bytes:
    return _str_field(1, name) + _float_field(2, v) + _int_field(20, 1)


def _attr_ints(name: str, vs) -> bytes:
    body = _str_field(1, name)
    body += b"".join(_int_field(8, v) for v in vs)
    return body + _int_field(20, 7)


def _attr_tensor(name: str, arr: np.ndarray) -> bytes:
    return _str_field(1, name) + _len_field(5, _tensor("", arr)) \
        + _int_field(20, 4)


def _node(op: str, inputs, outputs, name: str = "", attrs=()) -> bytes:
    body = b"".join(_str_field(1, i) for i in inputs)
    body += b"".join(_str_field(2, o) for o in outputs)
    if name:
        body += _str_field(3, name)
    body += _str_field(4, op)
    body += b"".join(_len_field(5, a) for a in attrs)
    return body


def _value_info(name: str, shape) -> bytes:
    dims = b""
    for d in shape:
        if d is None:
            dims += _len_field(1, _str_field(2, "N"))
        else:
            dims += _len_field(1, _int_field(1, d))
    tensor_type = _int_field(1, 1) + _len_field(2, dims)
    return _str_field(1, name) + _len_field(2, _len_field(1, tensor_type))


def _graph(nodes, inputs, outputs, initializers) -> bytes:
    body = b"".join(_len_field(1, n) for n in nodes)
    body += _str_field(2, "g")
    body += b"".join(_len_field(5, t) for t in initializers)
    body += b"".join(_len_field(11, v) for v in inputs)
    body += b"".join(_len_field(12, v) for v in outputs)
    return body


def _model(graph: bytes) -> bytes:
    return (_int_field(1, 8) + _str_field(2, "testgen")
            + _len_field(7, graph)
            + _len_field(8, _str_field(1, "") + _int_field(2, 13)))


def _mlp_onnx(rs):
    w1 = rs.randn(4, 16).astype(np.float32)
    b1 = rs.randn(16).astype(np.float32)
    w2 = rs.randn(16, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)
    nodes = [
        _node("Gemm", ["x", "w1", "b1"], ["h"], "fc1",
              attrs=[_attr_int("transB", 0)]),
        _node("Relu", ["h"], ["hr"], "relu1"),
        _node("Gemm", ["hr", "w2t", "b2"], ["y"], "fc2",
              attrs=[_attr_int("transB", 1)]),
    ]
    graph = _graph(
        nodes,
        inputs=[_value_info("x", [None, 4])],
        outputs=[_value_info("y", [None, 3])],
        initializers=[_tensor("w1", w1), _tensor("b1", b1),
                      _tensor("w2t", w2.T.copy()), _tensor("b2", b2)])
    return _model(graph), (w1, b1, w2, b2)


class TestOnnxMLP:
    def test_forward_matches_numpy(self):
        rs = np.random.RandomState(0)
        data, (w1, b1, w2, b2) = _mlp_onnx(rs)
        model, params, state = load_onnx(data)
        x = rs.randn(8, 4).astype(np.float32)
        import jax
        y, _ = model.call(params, state, x, training=False)
        expected = np.maximum(x @ w1 + b1, 0) @ w2 + b2
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-4)

    def test_finetune_frozen_backbone(self):
        """The VERDICT item-4 'done' bar: load ONNX MLP, freeze the
        backbone, fine-tune the head — backbone params must not move."""
        rs = np.random.RandomState(1)
        data, _ = _mlp_onnx(rs)
        model, params, state = load_onnx(data)
        model.compile(optimizer="adam", loss="mse")
        model.freeze(["fc1"])
        est = model.get_estimator()
        est.set_params(params)
        est.set_model_state(state)
        x = rs.randn(32, 4).astype(np.float32)
        y = rs.randn(32, 3).astype(np.float32)
        before = est.get_params()
        model.fit(x, y, batch_size=16, nb_epoch=2)
        after = est.get_params()
        np.testing.assert_array_equal(before["fc1"]["kernel"],
                                      after["fc1"]["kernel"])
        assert np.abs(after["fc2"]["kernel"]
                      - before["fc2"]["kernel"]).max() > 1e-6

    def test_unfreeze_resumes_updates(self):
        rs = np.random.RandomState(2)
        data, _ = _mlp_onnx(rs)
        model, params, state = load_onnx(data)
        model.compile(optimizer="sgd", loss="mse")
        model.freeze()  # everything
        est = model.get_estimator()
        est.set_params(params)
        x = rs.randn(16, 4).astype(np.float32)
        y = rs.randn(16, 3).astype(np.float32)
        before = est.get_params()
        r1 = model.fit(x, y, batch_size=16, nb_epoch=1)
        assert r1["iterations"] >= 1
        mid = est.get_params()
        np.testing.assert_array_equal(before["fc1"]["kernel"],
                                      mid["fc1"]["kernel"])
        np.testing.assert_array_equal(before["fc2"]["kernel"],
                                      mid["fc2"]["kernel"])
        model.unfreeze()
        # nb_epoch is a cumulative MaxEpoch trigger (BigDL semantics): the
        # first fit ended at epoch 2, so train up to epoch 2 now
        r2 = model.fit(x, y, batch_size=16, nb_epoch=2)
        assert r2["iterations"] >= 1
        after = est.get_params()
        assert np.abs(after["fc1"]["kernel"]
                      - mid["fc1"]["kernel"]).max() > 1e-8


class TestOnnxCNN:
    def _cnn_onnx(self, torch_model, h=8, w=8):
        """Hand-encode the ONNX equivalent of a small torch CNN, weights
        taken from the live module — validates conv layout conversion and
        the flatten→Gemm row permutation against torch's NCHW output."""
        sd = {k: v.detach().numpy() for k, v in torch_model.state_dict().items()}
        conv_w = sd["0.weight"]          # OIHW (8,3,3,3)
        conv_b = sd["0.bias"]
        bn_g, bn_b = sd["1.weight"], sd["1.bias"]
        bn_m, bn_v = sd["1.running_mean"], sd["1.running_var"]
        fc_w = sd["5.weight"]            # (5, 8*4*4) torch layout
        fc_b = sd["5.bias"]
        nodes = [
            _node("Conv", ["x", "conv_w", "conv_b"], ["c1"], "conv1", attrs=[
                _attr_ints("kernel_shape", [3, 3]),
                _attr_ints("strides", [1, 1]),
                _attr_ints("pads", [1, 1, 1, 1])]),
            _node("BatchNormalization",
                  ["c1", "bn_g", "bn_b", "bn_m", "bn_v"], ["b1"], "bn1",
                  attrs=[_attr_float("epsilon", 1e-5)]),
            _node("Relu", ["b1"], ["r1"], "relu1"),
            _node("MaxPool", ["r1"], ["p1"], "pool1", attrs=[
                _attr_ints("kernel_shape", [2, 2]),
                _attr_ints("strides", [2, 2])]),
            _node("Flatten", ["p1"], ["f1"], "flat1",
                  attrs=[_attr_int("axis", 1)]),
            _node("Gemm", ["f1", "fc_w", "fc_b"], ["y"], "fc1",
                  attrs=[_attr_int("transB", 1)]),
        ]
        graph = _graph(
            nodes,
            inputs=[_value_info("x", [None, 3, h, w])],
            outputs=[_value_info("y", [None, 5])],
            initializers=[
                _tensor("conv_w", conv_w), _tensor("conv_b", conv_b),
                _tensor("bn_g", bn_g), _tensor("bn_b", bn_b),
                _tensor("bn_m", bn_m), _tensor("bn_v", bn_v),
                _tensor("fc_w", fc_w), _tensor("fc_b", fc_b)])
        return _model(graph)

    def test_cnn_matches_torch(self):
        torch = pytest.importorskip("torch")
        nn = torch.nn
        torch.manual_seed(0)
        m = nn.Sequential(nn.Conv2d(3, 8, 3, padding=1), nn.BatchNorm2d(8),
                          nn.ReLU(), nn.MaxPool2d(2), nn.Flatten(),
                          nn.Linear(8 * 4 * 4, 5))
        m.eval()
        with torch.no_grad():  # fold some running stats in so BN is nontrivial
            m[1].running_mean.uniform_(-0.5, 0.5)
            m[1].running_var.uniform_(0.5, 1.5)
        data = self._cnn_onnx(m)
        model, params, state = load_onnx(data)
        x = np.random.RandomState(3).randn(4, 3, 8, 8).astype(np.float32)
        with torch.no_grad():
            expected = m(torch.from_numpy(x)).numpy()
        # our model is NHWC
        y, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)),
                          training=False)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-3,
                                   atol=1e-4)


class TestOnnxNumericEdges:
    def test_averagepool_excludes_padding(self):
        """ONNX default count_include_pad=0: border windows divide by the
        number of REAL elements, not the full kernel area."""
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        nodes = [_node("AveragePool", ["x"], ["y"], "ap", attrs=[
            _attr_ints("kernel_shape", [3, 3]),
            _attr_ints("strides", [1, 1]),
            _attr_ints("pads", [1, 1, 1, 1])])]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 1, 4, 4])],
                       outputs=[_value_info("y", [None, 1, 4, 4])],
                       initializers=[])
        model, params, state = load_onnx(_model(graph))
        y, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)))
        # corner (0,0): mean of the 2x2 real block {0,1,4,5} = 2.5 (not /9)
        assert np.isclose(np.asarray(y)[0, 0, 0, 0], 2.5)
        # center (1,1): full 3x3 window mean
        assert np.isclose(np.asarray(y)[0, 1, 1, 0],
                          x[0, 0, 0:3, 0:3].mean())

    def test_reducemean_axes_follow_layout(self):
        """ReduceMean(axes=[2,3]) after a conv = spatial mean in NCHW; the
        NHWC-converted graph must reduce (1,2), yielding (N, C)."""
        rs = np.random.RandomState(7)
        conv_w = rs.randn(5, 3, 1, 1).astype(np.float32)
        fc_w = rs.randn(5, 2).astype(np.float32)
        nodes = [
            _node("Conv", ["x", "w"], ["c"], "conv", attrs=[
                _attr_ints("kernel_shape", [1, 1]),
                _attr_ints("strides", [1, 1])]),
            _node("ReduceMean", ["c"], ["g"], "gap", attrs=[
                _attr_ints("axes", [2, 3]), _attr_int("keepdims", 0)]),
            _node("MatMul", ["g", "fc"], ["y"], "head"),
        ]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 3, 4, 4])],
                       outputs=[_value_info("y", [None, 2])],
                       initializers=[_tensor("w", conv_w),
                                     _tensor("fc", fc_w)])
        model, params, state = load_onnx(_model(graph))
        x = rs.randn(2, 3, 4, 4).astype(np.float32)
        y, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)))
        # NCHW reference: 1x1 conv = einsum over channels, then spatial mean
        conv_ref = np.einsum("nchw,oc->nohw", x, conv_w[:, :, 0, 0])
        expected = conv_ref.mean(axis=(2, 3)) @ fc_w
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-4)

    def test_clip_zero_min_survives_wire(self):
        """proto3 drops zero scalars from the wire; Clip(min=0) must still
        clip at zero (ReLU6 pattern)."""
        nodes = [_node("Clip", ["x"], ["y"], "clip", attrs=[
            _attr_float("min", 0.0), _attr_float("max", 6.0)])]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 4])],
                       outputs=[_value_info("y", [None, 4])],
                       initializers=[])
        model, params, state = load_onnx(_model(graph))
        x = np.array([[-5.0, -0.5, 3.0, 9.0]], dtype=np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_array_equal(np.asarray(y),
                                      [[0.0, 0.0, 3.0, 6.0]])


class TestOnnxExtendedOps:
    def test_shape_gather_concat_reshape_idiom(self):
        """The standard exporter flatten: Reshape(x, Concat(Gather(Shape(x),
        0), [-1])) must fold statically and flatten correctly."""
        rs = np.random.RandomState(0)
        w = rs.randn(5, 12, 2).astype(np.float32)  # conv-free: 3D input
        nodes = [
            _node("Shape", ["x"], ["shp"], "shape0"),
            _node("Gather", ["shp", "zero"], ["b"], "gather0",
                  attrs=[_attr_int("axis", 0)]),
            _node("Unsqueeze", ["b"], ["b1"], "unsq0",
                  attrs=[_attr_ints("axes", [0])]),
            _node("Concat", ["b1", "minus1"], ["tgt"], "cat0",
                  attrs=[_attr_int("axis", 0)]),
            _node("Reshape", ["x", "tgt"], ["flat"], "reshape0"),
            _node("Gemm", ["flat", "wT", "bias"], ["y"], "fc",
                  attrs=[_attr_int("transB", 1)]),
        ]
        fc_w = rs.randn(3, 10).astype(np.float32)
        fc_b = rs.randn(3).astype(np.float32)
        graph = _graph(
            nodes, inputs=[_value_info("x", [None, 5, 2])],
            outputs=[_value_info("y", [None, 3])],
            initializers=[_tensor("zero", np.asarray(0, np.int64)),
                          _tensor("minus1", np.asarray([-1], np.int64)),
                          _tensor("wT", fc_w), _tensor("bias", fc_b)])
        model, params, state = load_onnx(_model(graph))
        x = rs.randn(4, 5, 2).astype(np.float32)
        y, _ = model.call(params, state, x)
        expected = x.reshape(4, 10) @ fc_w.T + fc_b
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-4)

    def test_elementwise_and_reductions(self):
        rs = np.random.RandomState(1)
        nodes = [
            _node("Abs", ["x"], ["a"], "abs0"),
            _node("Sqrt", ["a"], ["s"], "sqrt0"),
            _node("ReduceSum", ["s"], ["r"], "rsum",
                  attrs=[_attr_ints("axes", [1]), _attr_int("keepdims", 0)]),
            _node("Neg", ["r"], ["y"], "neg0"),
        ]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 6])],
                       outputs=[_value_info("y", [None])], initializers=[])
        model, params, state = load_onnx(_model(graph))
        x = rs.randn(3, 6).astype(np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y),
                                   -np.sqrt(np.abs(x)).sum(axis=1),
                                   rtol=1e-5)

    def test_slice_split_minmax(self):
        rs = np.random.RandomState(2)
        nodes = [
            _node("Slice", ["x"], ["sl"], "slice0", attrs=[
                _attr_ints("starts", [1]), _attr_ints("ends", [5]),
                _attr_ints("axes", [1])]),
            _node("Split", ["sl"], ["p1", "p2"], "split0",
                  attrs=[_attr_int("axis", 1), _attr_ints("split", [2, 2])]),
            _node("Max", ["p1", "p2"], ["y"], "max0"),
        ]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 6])],
                       outputs=[_value_info("y", [None, 2])], initializers=[])
        model, params, state = load_onnx(_model(graph))
        x = rs.randn(3, 6).astype(np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y),
                                   np.maximum(x[:, 1:3], x[:, 3:5]))

    def test_resize_nearest_nhwc(self):
        nodes = [
            _node("Conv", ["x", "w"], ["c"], "conv0", attrs=[
                _attr_ints("kernel_shape", [1, 1]),
                _attr_ints("strides", [1, 1])]),
            _node("Resize", ["c", "roi", "scales"], ["y"], "resize0",
                  attrs=[]),
        ]
        w = np.ones((2, 1, 1, 1), np.float32)
        graph = _graph(
            nodes, inputs=[_value_info("x", [None, 1, 2, 2])],
            outputs=[_value_info("y", [None, 2, 4, 4])],
            initializers=[_tensor("w", w),
                          _tensor("roi", np.zeros(0, np.float32)),
                          _tensor("scales",
                                  np.asarray([1, 1, 2, 2], np.float32))])
        model, params, state = load_onnx(_model(graph))
        x = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
        y, _ = model.call(params, state, x)
        assert np.asarray(y).shape == (1, 4, 4, 2)
        # nearest: each pixel repeats 2x2
        np.testing.assert_array_equal(np.asarray(y)[0, :2, :2, 0],
                                      np.full((2, 2), x[0, 0, 0, 0]))

    def test_strided_and_reversed_slice(self):
        rs = np.random.RandomState(3)
        nodes = [_node("Slice", ["x", "st", "en", "ax", "sp"], ["y"],
                       "slice0")]
        graph = _graph(
            nodes, inputs=[_value_info("x", [None, 6])],
            outputs=[_value_info("y", [None, 3])],
            initializers=[
                _tensor("st", np.asarray([0], np.int64)),
                _tensor("en", np.asarray([6], np.int64)),
                _tensor("ax", np.asarray([1], np.int64)),
                _tensor("sp", np.asarray([2], np.int64))])
        model, params, state = load_onnx(_model(graph))
        x = rs.randn(2, 6).astype(np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y), x[:, ::2])

    def test_expand_rank_extend(self):
        nodes = [_node("Expand", ["x", "tgt"], ["y"], "exp0")]
        graph = _graph(
            nodes, inputs=[_value_info("x", [None, 3])],
            outputs=[_value_info("y", [None, 2, 3])],
            initializers=[_tensor("tgt", np.asarray([2, 2, 3], np.int64))])
        model, params, state = load_onnx(_model(graph))
        x = np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)
        y, _ = model.call(params, state, x)
        assert np.asarray(y).shape == (2, 2, 3)
        # right-aligned: the [2,3] input tiles along the new middle axis
        np.testing.assert_allclose(np.asarray(y)[0], x)
        np.testing.assert_allclose(np.asarray(y)[1], x)

    def test_max_with_constant(self):
        nodes = [_node("Max", ["x", "floor"], ["y"], "max0")]
        graph = _graph(
            nodes, inputs=[_value_info("x", [None, 3])],
            outputs=[_value_info("y", [None, 3])],
            initializers=[_tensor("floor",
                                  np.asarray([0.5], np.float32))])
        model, params, state = load_onnx(_model(graph))
        x = np.asarray([[-1.0, 0.7, 0.2]], np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y), [[0.5, 0.7, 0.5]])

    def test_prelu(self):
        nodes = [_node("PRelu", ["x", "slope"], ["y"], "prelu0")]
        graph = _graph(nodes, inputs=[_value_info("x", [None, 3])],
                       outputs=[_value_info("y", [None, 3])],
                       initializers=[_tensor(
                           "slope", np.asarray([0.1, 0.2, 0.3], np.float32))])
        model, params, state = load_onnx(_model(graph))
        x = np.asarray([[-1.0, -1.0, 2.0]], np.float32)
        y, _ = model.call(params, state, x)
        np.testing.assert_allclose(np.asarray(y), [[-0.1, -0.2, 2.0]],
                                   rtol=1e-5)


class TestGlove:
    def test_read_and_build(self, tmp_path):
        from analytics_zoo_tpu.keras.layers import WordEmbedding
        glove = tmp_path / "glove.txt"
        glove.write_text("the 0.1 0.2 0.3\ncat 0.4 0.5 0.6\nsat 0.7 0.8 0.9\n")
        table, index = WordEmbedding.read_glove(str(glove))
        assert table.shape == (4, 3)  # + padding row 0
        np.testing.assert_allclose(table[index["cat"]], [0.4, 0.5, 0.6])
        np.testing.assert_allclose(table[0], 0.0)

    def test_with_word_index(self, tmp_path):
        from analytics_zoo_tpu.keras.layers import WordEmbedding
        glove = tmp_path / "glove.txt"
        glove.write_text("the 0.1 0.2\ncat 0.4 0.5\n")
        table = WordEmbedding.read_glove(str(glove),
                                         {"cat": 1, "unknown": 2})
        assert table.shape == (3, 2)
        np.testing.assert_allclose(table[1], [0.4, 0.5])
        np.testing.assert_allclose(table[2], 0.0)  # missing word stays zero

    def test_multi_token_words_skipped_not_fatal(self, tmp_path):
        """glove.840B-style files contain '. . . 0.1 0.2' lines; loading
        must not abort (and once dim is known, the vector still parses)."""
        from analytics_zoo_tpu.keras.layers import WordEmbedding
        glove = tmp_path / "glove.txt"
        glove.write_text("the 0.1 0.2\n. . . 0.3 0.4\ncat 0.5 0.6\n")
        table, index = WordEmbedding.read_glove(str(glove))
        np.testing.assert_allclose(table[index["cat"]], [0.5, 0.6])
        np.testing.assert_allclose(table[index[". . ."]], [0.3, 0.4])

    def test_layer_from_glove(self, tmp_path):
        import jax
        from analytics_zoo_tpu.keras.layers import WordEmbedding
        glove = tmp_path / "glove.txt"
        glove.write_text("a 1 0\nb 0 1\n")
        layer = WordEmbedding.from_glove(str(glove), {"a": 1, "b": 2})
        params, state = layer.build(jax.random.PRNGKey(0), (None, 2))
        out, _ = layer.call(params, state, np.asarray([[1, 2]]))
        np.testing.assert_allclose(np.asarray(out),
                                   [[[1, 0], [0, 1]]])


class TestTorchImport:
    def test_mlp_state_dict(self):
        torch = pytest.importorskip("torch")
        nn = torch.nn
        torch.manual_seed(1)
        tm = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 2))
        tm.eval()
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        model = Sequential([Dense(12, name="d1"), Activation("relu"),
                            Dense(2, name="d2")])
        params, state = load_torch_state_dict(model, tm.state_dict())
        x = np.random.RandomState(4).randn(5, 6).astype(np.float32)
        import jax
        rng = jax.random.PRNGKey(0)
        _, st = model.build(rng, (None, 6))
        y, _ = model.call(params, st, x, training=False)
        with torch.no_grad():
            expected = tm(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-5)

    def test_cnn_state_dict_with_bn(self):
        torch = pytest.importorskip("torch")
        nn = torch.nn
        torch.manual_seed(2)
        tm = nn.Sequential(nn.Conv2d(3, 4, 3), nn.BatchNorm2d(4), nn.ReLU(),
                           nn.Flatten(), nn.Linear(4 * 6 * 6, 3))
        tm.eval()
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import (
            Activation, BatchNormalization, Convolution2D, Dense, Flatten)
        model = Sequential([
            Convolution2D(4, 3, 3, name="c1"), BatchNormalization(name="b1"),
            Activation("relu"), Flatten(), Dense(3, name="d1")])
        params, state = load_torch_state_dict(model, tm.state_dict())
        # NHWC flatten order differs from torch's NCHW: permute Dense rows
        h = w = 6
        perm = np.arange(4 * h * w).reshape(4, h, w).transpose(1, 2, 0)
        params["d1"]["kernel"] = params["d1"]["kernel"][perm.reshape(-1)]
        x = np.random.RandomState(5).randn(2, 3, 8, 8).astype(np.float32)
        y, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)),
                          training=False)
        with torch.no_grad():
            expected = tm(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-3,
                                   atol=1e-4)


    def test_nested_container_paths(self):
        """Imported params must nest by container, matching build()'s tree."""
        torch = pytest.importorskip("torch")
        nn = torch.nn
        torch.manual_seed(3)
        tm = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 6),
                           nn.ReLU(), nn.Linear(6, 2))
        tm.eval()
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        block = Sequential([Dense(8, name="b1"), Activation("relu"),
                            Dense(6, name="b2"), Activation("relu")],
                           name="block")
        model = Sequential([block, Dense(2, name="head")])
        params, state = load_torch_state_dict(model, tm.state_dict())
        assert set(params) == {"block", "head"}
        assert set(params["block"]) == {"b1", "b2"}
        import jax
        _, st = model.build(jax.random.PRNGKey(0), (None, 4))
        x = np.random.RandomState(8).randn(3, 4).astype(np.float32)
        y, _ = model.call(params, st, x, training=False)
        with torch.no_grad():
            expected = tm(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-5)


class TestGraphSurgery:
    def _model(self):
        from analytics_zoo_tpu.keras import Input, Model
        from analytics_zoo_tpu.keras.layers import Dense
        x = Input(shape=(4,))
        h1 = Dense(8, activation="relu", name="feat1")(x)
        h2 = Dense(6, activation="relu", name="feat2")(h1)
        y = Dense(2, name="head")(h2)
        return Model(x, y)

    def test_new_graph_truncates(self):
        import jax
        model = self._model()
        params, state = model.build(jax.random.PRNGKey(0))
        feat = model.new_graph("feat2")
        x = np.random.RandomState(6).randn(3, 4).astype(np.float32)
        y, _ = feat.call(params, state, x, training=False)
        assert np.asarray(y).shape == (3, 6)
        # embeddings from the truncated graph match the full graph's
        # intermediate (same layers, same params)
        full_out, _ = model.call(params, state, x, training=False)
        assert np.asarray(full_out).shape == (3, 2)

    def test_freeze_up_to(self):
        model = self._model()
        model.freeze_up_to("feat2")
        assert model.frozen_layers == frozenset({"feat1", "feat2"})
        assert model.trainable_param_names() == ["head"]

    def test_new_graph_preserves_frozen(self):
        model = self._model()
        model.freeze(["feat1"])
        feat = model.new_graph("feat2")
        assert "feat1" in feat.frozen_layers


class TestNetFacade:
    def test_load_roundtrip(self, tmp_path):
        from analytics_zoo_tpu.models import NeuralCF
        ncf = NeuralCF(20, 15, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=2)
        ncf._ensure_built()
        ncf.default_compile()
        path = str(tmp_path / "zoo")
        x = np.stack([np.random.randint(1, 20, 16),
                      np.random.randint(1, 15, 16)], 1).astype(np.float32)
        ncf.model.predict(x)  # force param init
        ncf.save_model(path)
        loaded = Net.load(path)
        assert type(loaded).__name__ == "NeuralCF"
