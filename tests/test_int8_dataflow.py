"""Quantized-dataflow int8 ResNet: op-level gradient correctness, forward
parity with the float mirror, end-to-end Estimator training descent, and
the eval/running-stats path. (Reference parity note: the reference's int8
is OpenVINO inference-only — ``examples/vnni/openvino/Perf.scala`` — so the
bar here is self-consistency against this module's own float reference.)"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from analytics_zoo_tpu.ops import int8_dataflow as d8  # noqa: E402
from analytics_zoo_tpu.ops.int8_dataflow import Int8ResNetDataflow  # noqa: E402


def _cos(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


class TestConvBNOp:
    def test_bwd_matches_float_vjp(self):
        """Hand-written conv+BN+relu backward vs jax.vjp of the same float
        math on the dequantized input (isolates op logic from input quant
        noise); cos similarity must be ~1 for all four gradients."""
        rs = np.random.RandomState(0)
        N, H, W, Cin, Cout, K = 4, 16, 16, 8, 16, 3
        x = jnp.asarray(rs.randn(N, H, W, Cin).astype(np.float32))
        w = jnp.asarray((rs.randn(K, K, Cin, Cout) * 0.2).astype(np.float32))
        gamma = jnp.asarray(1.0 + 0.1 * rs.randn(Cout).astype(np.float32))
        beta = jnp.asarray(0.1 * rs.randn(Cout).astype(np.float32))
        g_out = jnp.asarray(rs.randn(N, H, W, Cout).astype(np.float32))

        sx = jnp.float32(np.abs(np.asarray(x)).max() / 127.0)
        xq = d8._quant(x, sx)
        mid_run = jnp.full((Cout,), 8.0, jnp.float32)
        _, aux, _ = d8._conv_bn_fwd(xq, sx, w, gamma, beta, mid_run,
                                       True, (1, 1), "SAME")
        mid_run = jnp.maximum(0.99 * mid_run, aux[0])  # warmed delayed scale
        y, aux, res = d8._conv_bn_fwd(xq, sx, w, gamma, beta, mid_run,
                                         True, (1, 1), "SAME")
        s_out = d8._scale_of(jnp.asarray(np.abs(np.asarray(y)).max()))
        yq = d8._quant(y, s_out)
        dx, dw, dgam, dbet = d8._conv_bn_bwd(
            res, True, (1, 1), "SAME", yq, g_out.astype(jnp.bfloat16))

        x_deq = d8._deq(xq, sx, jnp.float32)

        def ref(x_, w_, gam, bet):
            f = lax.conv_general_dilated(
                x_, w_, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            mu = jnp.mean(f, axis=(0, 1, 2))
            var = jnp.maximum(jnp.mean(f * f, axis=(0, 1, 2)) - mu * mu, 0.0)
            z = (f - mu) * lax.rsqrt(var + 1e-5) * gam + bet
            return jnp.maximum(z, 0.0)

        _, vjp = jax.vjp(ref, x_deq, w, gamma, beta)
        rdx, rdw, rdgam, rdbet = vjp(g_out)
        assert _cos(dx, rdx) > 0.97
        assert _cos(dw, rdw) > 0.97
        assert _cos(dgam, rdgam) > 0.97
        assert _cos(dbet, rdbet) > 0.95

    def test_maxpool_int8_matches_float(self):
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(2, 8, 8, 4).astype(np.float32))
        s = jnp.float32(np.abs(np.asarray(x)).max() / 127.0)
        q = d8._quant(x, s)
        pooled_q = d8._maxpool_q(q, (3, 3), (2, 2), "SAME")
        ref = lax.reduce_window(d8._deq(q, s, jnp.float32), -jnp.inf,
                                lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        np.testing.assert_allclose(
            np.asarray(d8._deq(pooled_q, s, jnp.float32)), np.asarray(ref),
            rtol=1e-5)


class TestBackbone:
    @pytest.fixture(scope="class")
    def built(self):
        bb = Int8ResNetDataflow(18, (32, 32, 3))
        params, state = bb.init(jax.random.PRNGKey(0))
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(8, 32, 32, 3).astype(np.float32))
        for _ in range(3):  # warm the delayed scales
            _, state = bb.apply(params, state, x, training=True)
        return bb, params, state, x

    def test_forward_close_to_float_mirror(self, built):
        bb, params, state, x = built
        fi, _ = bb.apply(params, state, x, training=True)
        ff = bb.apply_float(params, x)
        mi = float(jnp.mean(jnp.abs(fi.astype(jnp.float32))))
        mf = float(jnp.mean(jnp.abs(ff)))
        assert abs(mi - mf) / max(mf, 1e-6) < 0.15
        assert _cos(fi.astype(jnp.float32), ff) > 0.95

    @pytest.mark.slow
    def test_grads_correlate_with_float_late_layers(self, built):
        """STE grads vs the float mirror: late layers must match tightly;
        early layers accumulate quantization noise through depth (expected
        — the descent test is the training-level check)."""
        bb, params, state, x = built

        def li(p):
            f, _ = bb.apply(p, state, x, training=True)
            return jnp.mean(f.astype(jnp.float32) ** 2)

        def lf(p):
            return jnp.mean(bb.apply_float(p, x) ** 2)

        gi = jax.jit(jax.grad(li))(params)
        gf = jax.jit(jax.grad(lf))(params)
        assert _cos(gi["s4b2_b"]["gamma"], gf["s4b2_b"]["gamma"]) > 0.9
        assert _cos(gi["s4b2_b"]["beta"], gf["s4b2_b"]["beta"]) > 0.9
        assert _cos(gi["s4b2_b"]["kernel"], gf["s4b2_b"]["kernel"]) > 0.6

    def test_state_updates(self, built):
        bb, params, state, x = built
        _, ns = bb.apply(params, state, x, training=True)
        assert float(ns["in_amax"]) > 0
        # running stats move toward batch stats
        assert not np.allclose(np.asarray(ns["stem"]["running_mean"]),
                               np.asarray(state["stem"]["running_mean"]))

    def test_eval_uses_running_stats(self, built):
        bb, params, state, x = built
        f1, s1 = bb.apply(params, state, x, training=False)
        assert s1 is state  # eval mutates nothing
        # eval on a half batch must agree with eval on the full batch
        # (running stats — no batch-size dependence)
        f_half, _ = bb.apply(params, state, x[:4], training=False)
        np.testing.assert_allclose(np.asarray(f1[:4], np.float32),
                                   np.asarray(f_half, np.float32),
                                   rtol=0.05, atol=0.05)


class TestConvergenceParity:
    @pytest.mark.slow
    def test_tracks_float_mirror_training(self):
        """Train the SAME architecture from the SAME init on the SAME data
        twice — once through the int8 dataflow, once through the float
        mirror (jax autodiff) — and require the int8 loss trajectory to
        track the float one: quantization noise may slow it, but it must
        descend to a comparable level (the int8_training op's 'float twin'
        convergence stance, applied to the whole backbone)."""
        import optax

        bb = Int8ResNetDataflow(18, (24, 24, 3))
        params0, state0 = bb.init(jax.random.PRNGKey(1))
        rs = np.random.RandomState(7)
        x = rs.rand(32, 24, 24, 3).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.int32)
        x[y == 1] += 0.4
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        head0 = jnp.asarray(rs.randn(512, 2).astype(np.float32) * 0.05)

        def run(loss_fn, n_steps=10):
            opt = optax.sgd(0.02, momentum=0.9)
            carrier = {"p": params0, "h": head0, "s": state0}
            opt_state = opt.init({"p": carrier["p"], "h": carrier["h"]})

            @jax.jit
            def step(carrier, opt_state):
                def wrapped(tp):
                    l, ns = loss_fn(tp["p"], tp["h"], carrier["s"])
                    return l, ns
                (l, ns), g = jax.value_and_grad(wrapped, has_aux=True)(
                    {"p": carrier["p"], "h": carrier["h"]})
                up, opt_state = opt.update(g, opt_state)
                new = optax.apply_updates(
                    {"p": carrier["p"], "h": carrier["h"]}, up)
                return {"p": new["p"], "h": new["h"], "s": ns}, opt_state, l
            losses = []
            for _ in range(n_steps):
                carrier, opt_state, l = step(carrier, opt_state)
                losses.append(float(l))
            return losses

        def head_loss(feats, head):
            logits = feats.reshape(feats.shape[0], -1).astype(
                jnp.float32) @ head
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        def int8_loss(p, h, s):
            feats, ns = bb.apply(p, s, x, training=True)
            return head_loss(feats, h), ns

        def float_loss(p, h, s):
            return head_loss(bb.apply_float(p, x), h), s

        li = run(int8_loss)
        lf = run(float_loss)
        # both descend; int8 ends within 2x-ish of float's progress
        assert li[-1] < li[0], li
        assert lf[-1] < lf[0], lf
        drop_i = li[0] - min(li)
        drop_f = lf[0] - min(lf)
        assert drop_i > 0.4 * drop_f, (li, lf)


class TestEstimatorIntegration:
    @pytest.mark.slow
    def test_train_descends_and_predicts(self):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import objectives, optimizers
        from analytics_zoo_tpu.models.image.imageclassification import resnet

        model = resnet(18, num_classes=2, input_shape=(32, 32, 3),
                       dataflow="int8")
        est = Estimator(
            model=model,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.01, momentum=0.9),
            compute_dtype=jnp.bfloat16)
        rs = np.random.RandomState(0)
        x = rs.rand(32, 32, 32, 3).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.float32)
        x[y == 1] += 0.3
        fs = FeatureSet.from_ndarrays(x, y)
        r = est.train(fs, batch_size=16, epochs=8)
        h = r["loss_history"]
        assert np.mean(h[-4:]) < np.mean(h[:4])
        out = np.asarray(est.predict(x[:8], batch_size=8))
        assert out.shape == (8, 2)
        assert np.all(np.isfinite(out))
