"""Learning-correctness checks: small models must actually CONVERGE on
planted-signal data, not merely execute steps (the reference's integration
suites assert accuracy, e.g. LeNet/Mnist; SURVEY §4)."""
import numpy as np


class TestConvergence:
    def test_mlp_learns_xor_like_signal(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Activation, Dense
        rs = np.random.RandomState(0)
        x = rs.randn(512, 2).astype(np.float32)
        y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32)  # xor: nonlinear
        # keras-1 convention: CE losses take probabilities, so models end
        # in softmax (the _from_logits objective variants exist too)
        model = Sequential([Dense(16), Activation("relu"),
                            Dense(16), Activation("relu"), Dense(2),
                            Activation("softmax")])
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x, y, batch_size=64, nb_epoch=30)
        acc = float(model.evaluate(x, y, batch_size=128)["accuracy"])
        assert acc > 0.9, f"xor accuracy only {acc}"

    def test_small_convnet_learns(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import (
            Activation, Convolution2D, Dense, Flatten, MaxPooling2D)
        rs = np.random.RandomState(1)
        # planted signal: class = which quadrant holds the bright blob
        n = 256
        x = rs.rand(n, 8, 8, 1).astype(np.float32) * 0.2
        y = rs.randint(0, 2, n).astype(np.float32)
        for i in range(n):
            if y[i]:
                x[i, :4, :4, 0] += 1.0
            else:
                x[i, 4:, 4:, 0] += 1.0
        model = Sequential([
            Convolution2D(8, 3, 3, border_mode="same"), Activation("relu"),
            MaxPooling2D(), Flatten(), Dense(2), Activation("softmax")])
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x, y, batch_size=32, nb_epoch=15)
        acc = float(model.evaluate(x, y, batch_size=64)["accuracy"])
        assert acc > 0.95, f"convnet accuracy only {acc}"

    def test_ncf_ranks_planted_preferences(self):
        from analytics_zoo_tpu.models import NeuralCF
        rs = np.random.RandomState(2)
        users, items, n = 40, 30, 4096
        uid = rs.randint(1, users + 1, n)
        iid = rs.randint(1, items + 1, n)
        label = ((uid % 2) == (iid % 2)).astype(np.float32)  # parity affinity
        ncf = NeuralCF(users, items, 2, user_embed=8, item_embed=8,
                       hidden_layers=[16, 8], mf_embed=4)
        ncf.compile(optimizer="adam",
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        x = np.stack([uid, iid], 1).astype(np.float32)
        ncf.fit(x, label, batch_size=256, nb_epoch=12)
        acc = float(ncf.evaluate(x, label, batch_size=512)["accuracy"])
        assert acc > 0.9, f"ncf accuracy only {acc}"

    def test_lstm_learns_sequence_counting(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import LSTM, Dense
        rs = np.random.RandomState(3)
        # counting task: does the sequence contain more than 4 ones
        x = rs.randint(0, 2, (512, 8, 1)).astype(np.float32)
        y = (x.sum(axis=(1, 2)) > 4).astype(np.float32)
        model = Sequential([LSTM(24), Dense(2, activation="softmax")])
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x, y, batch_size=64, nb_epoch=25)
        acc = float(model.evaluate(x, y, batch_size=128)["accuracy"])
        assert acc > 0.9, f"lstm counting accuracy only {acc}"
