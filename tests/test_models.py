"""Model-zoo tests (reference strategy: construct, fit a few iterations on
synthetic data, predict/evaluate, save/load — e.g. NeuralCFSpec.scala)."""
import numpy as np
import pytest

from analytics_zoo_tpu.models import NeuralCF, ZooModel


def synthetic_ml(n=512, users=50, items=40, seed=0):
    """MovieLens-style implicit-feedback pairs with a learnable pattern."""
    rs = np.random.RandomState(seed)
    u = rs.randint(1, users + 1, n)
    i = rs.randint(1, items + 1, n)
    # label: affinity pattern (same parity -> positive)
    y = ((u + i) % 2).astype(np.float32)
    x = np.stack([u, i], axis=1).astype(np.float32)
    return x, y


class TestNeuralCF:
    def test_fit_predict_evaluate(self, ctx):
        x, y = synthetic_ml()
        ncf = NeuralCF(user_count=50, item_count=40, num_classes=2,
                       user_embed=8, item_embed=8, hidden_layers=[16, 8],
                       mf_embed=4)
        from analytics_zoo_tpu.keras import optimizers
        ncf.compile(optimizer=optimizers.Adam(5e-3),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        result = ncf.fit(x, y, batch_size=128, nb_epoch=60)
        assert result["loss_history"][-1] < result["loss_history"][0]
        scores = ncf.evaluate(x, y, batch_size=128)
        assert scores["accuracy"] > 0.8  # pattern is learnable
        probs = ncf.predict(x[:16])
        assert probs.shape == (16, 2)
        np.testing.assert_allclose(np.sum(probs, axis=1), 1.0, rtol=1e-5)

    def test_no_mf_variant(self, ctx):
        x, y = synthetic_ml(n=128)
        ncf = NeuralCF(50, 40, 2, include_mf=False, hidden_layers=[8])
        ncf.compile("adam", "sparse_categorical_crossentropy")
        ncf.fit(x, y, batch_size=64, nb_epoch=1)
        params = ncf.model.get_weights()
        names = " ".join(params)
        assert "mf_user_table" not in names

    def test_recommend_helpers(self, ctx):
        x, y = synthetic_ml(n=256)
        ncf = NeuralCF(50, 40, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=4)
        ncf.compile("adam", "sparse_categorical_crossentropy")
        ncf.fit(x, y, batch_size=64, nb_epoch=2)
        users = np.array([1, 1, 1, 2, 2, 2])
        items = np.array([1, 2, 3, 1, 2, 3])
        preds = ncf.predict_user_item_pair(users, items)
        assert len(preds) == 6
        u, i, c, p = preds[0]
        assert c in (1, 2) and 0.0 <= p <= 1.0  # 1-based class convention
        recs = ncf.recommend_for_user(users, items, max_items=2)
        assert set(recs) == {1, 2}
        assert len(recs[1]) == 2
        # ranked by the documented key: (class desc, probability desc) —
        # probability only breaks ties WITHIN a class
        keys = [(-c, -p) for _i, c, p in recs[1]]
        assert keys == sorted(keys)
        recs_i = ncf.recommend_for_item(users, items, max_users=1)
        assert set(recs_i) == {1, 2, 3}

    def test_save_load_roundtrip(self, ctx, tmp_path):
        x, y = synthetic_ml(n=128)
        ncf = NeuralCF(50, 40, 2, user_embed=4, item_embed=4,
                       hidden_layers=[8], mf_embed=4)
        ncf.compile("adam", "sparse_categorical_crossentropy")
        ncf.fit(x, y, batch_size=64, nb_epoch=1)
        preds1 = ncf.predict(x[:32])
        path = str(tmp_path / "ncf_model")
        ncf.save_model(path)

        loaded = ZooModel.load_model(path)
        assert isinstance(loaded, NeuralCF)
        assert loaded.hidden_layers == [8]
        preds2 = loaded.predict(x[:32])
        np.testing.assert_allclose(preds1, preds2, rtol=1e-5)
