"""Tier-1 collection-time guard: metrics-registry names must stay literal,
unique, canonical (``subsystem.noun_unit``; counters ``_total``,
histograms ``_seconds``) and documented in docs/observability.md
(``scripts/check_metric_names.py``).

Runs at IMPORT (= pytest collection) so a refactor that duplicates a
metric name, computes one dynamically, or adds one without documenting it
fails the suite even though nothing behavioral notices telemetry rotting."""
import importlib.util
import os

_script = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "check_metric_names.py")
_spec = importlib.util.spec_from_file_location("check_metric_names", _script)
_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_lint)

_problems = _lint.check()
if _problems:  # collection-time failure, with the drifted names
    raise AssertionError(
        "metric-name hygiene drifted: " + "; ".join(_problems))


def test_metric_names_clean():
    assert _lint.check() == []


def test_scanner_sees_known_instrumentation():
    """The AST scanner must actually find the load-bearing metrics — a
    scanner that silently matches nothing would always pass."""
    regs, bad = _lint.registrations()
    assert bad == []
    for expected in ("train.step_seconds", "serving.shed_total",
                     "worker.task_seconds", "fault.fired_total"):
        assert expected in regs, expected


def test_convention_rules_fire():
    """Seed violations through the pure rule helpers (guards against the
    lint rotting into a silent always-pass)."""
    assert not _lint._NAME_RE.match("NoDots")
    assert not _lint._NAME_RE.match("two.dots.deep")
    assert not _lint._NAME_RE.match("Caps.bad_total")
    assert _lint._NAME_RE.match("serving.shed_total")
    assert _lint._UNIT_SUFFIX["counter"] == "_total"
    assert _lint._UNIT_SUFFIX["histogram"] == "_seconds"


def test_registered_names_match_runtime_registry():
    """Every name the scanner found must be importable-time registered in
    the default registry (and vice versa for package modules that were
    imported) — the lint reads source, the registry is runtime truth."""
    # import the heavy modules so their module-level registrations run
    import analytics_zoo_tpu.estimator.estimator  # noqa: F401
    import analytics_zoo_tpu.feature.worker_pool  # noqa: F401
    import analytics_zoo_tpu.inference.inference_model  # noqa: F401
    import analytics_zoo_tpu.serving.server  # noqa: F401
    from analytics_zoo_tpu.common import metrics

    runtime = set(metrics.default_registry().snapshot())
    scanned = set(_lint.registrations()[0])
    missing = scanned - runtime
    assert not missing, (
        f"scanned registrations never ran (dead module-level code?): "
        f"{sorted(missing)}")


def test_documented_set_is_closed():
    """docs/observability.md documents every registered metric."""
    assert _lint.undocumented(_lint.registrations()[0]) == []
